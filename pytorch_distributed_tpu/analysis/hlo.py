"""Compiled-HLO text parsing: the shared matcher layer under shardlint.

XLA's post-optimization module (``jitted.lower(...).compile().as_text()``)
is the ground truth for what actually runs per device: shapes there are
*per-device* (post-SPMD-partitioning) shapes, collectives are explicit
``all-reduce``/``all-gather``/... instructions, and buffer donation shows
up (or silently doesn't) in the module header's ``input_output_alias`` map.
PR 1 found the replicated ``[V, D]`` dE accumulator by hand-grepping this
text; these helpers turn that grep into reusable structure shared by
``analysis/core.py`` and ``scripts/hlo_dy_check.py``.

Nothing here imports jax — it is pure text parsing, unit-testable on
string fixtures without compiling anything.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

Shape = Tuple[str, Tuple[int, ...]]  # (dtype, dims)

DTYPE_BYTES: Dict[str, int] = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    # fp8 families (quantized gradient collectives, ops/qcomm.py)
    "f8e4m3fn": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1, "f8e4m3": 1,
    "f8e5m2": 1, "f8e5m2fnuz": 1, "f8e3m4": 1,
}

# Longer alternatives first — the regex engine takes the first match, so
# `f8e4m3fn` must not be eaten by a shorter `f8e4m3` alternative.
_SHAPE_RE = re.compile(
    r"\b(pred|bf16|f16|f32|f64"
    r"|f8e4m3b11fnuz|f8e4m3fnuz|f8e4m3fn|f8e4m3|f8e5m2fnuz|f8e5m2|f8e3m4"
    r"|s4|s8|s16|s32|s64|u4|u8|u16|u32|u64|c64|c128)"
    r"\[([0-9,]*)\]"
)

# `%name = <type> opcode(...)` — the type may be a tuple; the opcode is the
# first bare word after the (possibly layout-annotated) result type.
_INSTR_RE = re.compile(
    r"^\s*(?P<root>ROOT\s+)?%?(?P<name>[\w.\-]+)\s+=\s+(?P<rhs>.+)$")
_OPCODE_RE = re.compile(r"(?P<opcode>[a-z][a-z0-9\-]*)\(")
_COMPUTATION_RE = re.compile(r"^(?:ENTRY\s+)?%?(?P<name>[\w.\-]+)\s+\(.*\{\s*$")

# Collectives counted toward the per-step budget.  Async pairs count once
# (the -start op carries the payload; -done is bookkeeping).
COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "all-to-all", "reduce-scatter",
    "collective-permute", "collective-broadcast",
)
_COLLECTIVE_SET = frozenset(COLLECTIVE_OPS) | frozenset(
    op + "-start" for op in COLLECTIVE_OPS)


def shape_bytes(shape: Shape) -> int:
    dtype, dims = shape
    n = DTYPE_BYTES.get(dtype, 4)
    for d in dims:
        n *= d
    return n


def iter_shapes(fragment: str) -> Iterator[Shape]:
    """All ``dtype[d0,d1,...]`` tokens in an HLO text fragment, in order."""
    for m in _SHAPE_RE.finditer(fragment):
        dims = tuple(int(d) for d in m.group(2).split(",")) \
            if m.group(2) else ()
        yield (m.group(1), dims)


@dataclasses.dataclass
class Instruction:
    """One parsed HLO instruction (output side only)."""

    name: str
    opcode: str
    shapes: List[Shape]        # result shapes (tuple types contribute all)
    computation: str
    line: str
    is_root: bool = False

    def result_bytes(self) -> int:
        return sum(shape_bytes(s) for s in self.shapes)


def _result_type_and_opcode(rhs: str) -> Optional[Tuple[str, str]]:
    """Split an instruction's RHS into (result-type text, opcode)."""
    if rhs.startswith("("):
        # tuple type: find the matching close paren
        depth = 0
        for i, ch in enumerate(rhs):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    m = _OPCODE_RE.search(rhs, i + 1)
                    return (rhs[:i + 1], m.group("opcode")) if m else None
        return None
    m = _SHAPE_RE.match(rhs)
    if not m:
        return None
    # skip a layout annotation like {1,0} or {1,0:T(8,128)}
    rest = rhs[m.end():]
    if rest.startswith("{"):
        close = rest.find("}")
        rest = rest[close + 1:] if close >= 0 else rest
    om = _OPCODE_RE.match(rest.lstrip())
    if om is None:
        return None
    return rhs[:m.end()], om.group("opcode")


def parse_instructions(hlo_text: str) -> List[Instruction]:
    """Parse every ``%x = type op(...)`` line across all computations."""
    instrs: List[Instruction] = []
    computation = ""
    for raw in hlo_text.splitlines():
        comp = _COMPUTATION_RE.match(raw)
        if comp is not None and "=" not in raw.split("(")[0]:
            computation = comp.group("name")
            continue
        m = _INSTR_RE.match(raw)
        if m is None or "(" not in m.group("rhs"):
            continue
        split = _result_type_and_opcode(m.group("rhs"))
        if split is None:
            continue
        type_text, opcode = split
        instrs.append(Instruction(
            name=m.group("name"),
            opcode=opcode,
            shapes=list(iter_shapes(type_text)),
            computation=computation,
            line=raw.strip(),
            is_root=bool(m.group("root")),
        ))
    return instrs


def entry_computation_name(hlo_text: str) -> str:
    """Name of the module's ENTRY computation ("" when absent).

    ``parse_instructions`` strips the ``ENTRY`` prefix when recording the
    ``computation`` field, so schedule walkers (obs/memory.py) need the
    raw-line scan here to know *which* computation is the entry."""
    for raw in hlo_text.splitlines():
        s = raw.lstrip()
        if not s.startswith("ENTRY"):
            continue
        m = _COMPUTATION_RE.match(s)
        if m is not None:
            return m.group("name")
    return ""


_OPERAND_REF_RE = re.compile(r"%([\w.\-]+)")


def instruction_operands(ins: Instruction) -> List[str]:
    """Operand instruction names of one parsed instruction, in order.

    Post-optimization HLO prints operands as ``type %name`` tokens inside
    the opcode's balanced parens (``dot(f32[8,16]{1,0} %Arg_0.1, ...)``);
    attributes after the close paren (``calls=%fused_computation``,
    ``to_apply=%region``) reference computations, not values, and are
    excluded by the balanced scan.  This is the def-use edge extractor
    under the memory ledger's live-range analysis."""
    m = _INSTR_RE.match(ins.line)
    if m is None:
        return []
    rhs = m.group("rhs")
    split = _result_type_and_opcode(rhs)
    if split is None:
        return []
    type_text, opcode = split
    start = rhs.find(opcode + "(", len(type_text) - 1)
    if start < 0:
        return []
    open_paren = start + len(opcode)
    depth, i = 0, open_paren
    while i < len(rhs):
        if rhs[i] == "(":
            depth += 1
        elif rhs[i] == ")":
            depth -= 1
            if depth == 0:
                break
        i += 1
    region = rhs[open_paren + 1:i]
    return _OPERAND_REF_RE.findall(region)


_PARAM_NUM_RE = re.compile(r"parameter\((\d+)\)")


def parameter_number(ins: Instruction) -> Optional[int]:
    """Entry-parameter number of a ``parameter(N)`` instruction, else None."""
    if ins.opcode != "parameter":
        return None
    m = _PARAM_NUM_RE.search(ins.line)
    return int(m.group(1)) if m else None


def collect_collectives(
    instrs: Iterable[Instruction],
) -> Dict[str, Dict[str, int]]:
    """Per-collective-kind ``{"count", "bytes"}`` (per-device payload)."""
    out: Dict[str, Dict[str, int]] = {}
    for ins in instrs:
        if ins.opcode not in _COLLECTIVE_SET:
            continue
        kind = ins.opcode[:-len("-start")] \
            if ins.opcode.endswith("-start") else ins.opcode
        slot = out.setdefault(kind, {"count": 0, "bytes": 0})
        slot["count"] += 1
        slot["bytes"] += ins.result_bytes()
    return out


# ------------------------------------------------- per-collective details

_IOTA_GROUPS_RE = re.compile(r"replica_groups=\[([0-9,]+)\]<=\[")
_PAIRS_RE = re.compile(r"source_target_pairs=\{")
_METADATA_RE = re.compile(
    r'metadata=\{[^}]*?op_name="(?P<op_name>[^"]*)"'
    r'(?:[^}]*?source_file="(?P<file>[^"]*)")?'
    r'(?:[^}]*?source_line=(?P<line>\d+))?')


def _balanced_braces(text: str, start: int) -> str:
    """Contents of the ``{...}`` block opening at ``text[start] == '{'``."""
    depth, i = 0, start
    while i < len(text):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[start + 1:i]
        i += 1
    return text[start + 1:]


def parse_replica_groups(line: str) -> Tuple[int, int]:
    """``(n_groups, group_size)`` of a collective instruction line.

    Handles both encodings XLA emits: the iota form
    ``replica_groups=[G,S]<=[N]`` (G groups of S devices — leading dims
    multiply into the group count) and the explicit nested-brace form
    ``replica_groups={{0,1},{2,3}}``.  ``collective-permute`` carries
    ``source_target_pairs={{s,t},...}`` instead: each pair is reported as
    a 2-device "group".  Returns ``(1, 1)`` when no group annotation is
    present (a single-device module)."""
    m = _IOTA_GROUPS_RE.search(line)
    if m:
        dims = [int(d) for d in m.group(1).split(",")]
        size = dims[-1] if dims else 1
        groups = 1
        for d in dims[:-1]:
            groups *= d
        return (max(1, groups), max(1, size))
    key = "replica_groups={"
    start = line.find(key)
    if start >= 0:
        block = _balanced_braces(line, start + len(key) - 1)
        groups = [g for g in re.findall(r"\{([0-9,\s]*)\}", block)]
        if groups:
            sizes = [len([t for t in g.split(",") if t.strip()])
                     for g in groups]
            return (len(groups), max(sizes))
        # replica_groups={} — all devices in one group, size unknown here
        return (1, 1)
    m = _PAIRS_RE.search(line)
    if m:
        block = _balanced_braces(line, m.end() - 1)
        pairs = re.findall(r"\{[0-9,\s]*\}", block)
        return (max(1, len(pairs)), 2)
    return (1, 1)


_CHANNEL_ID_RE = re.compile(r"\bchannel_id=(\d+)")


def parse_channel_id(line: str) -> int:
    """``channel_id=N`` of a collective instruction line, or ``-1``.

    Cross-module (multi-process) collectives carry a channel id that must
    match across every participating program — it is the rendezvous key
    NCCL/ICI uses to pair the ops up.  Single-module SPMD collectives may
    omit it; synclint canonicalizes the absent case to ``-1`` so schedule
    digests stay stable either way."""
    m = _CHANNEL_ID_RE.search(line)
    return int(m.group(1)) if m else -1


def parse_replica_group_members(line: str) -> Optional[List[List[int]]]:
    """Explicit device-id membership of each replica group, or ``None``.

    Three encodings appear in post-optimization text:

    - explicit nested braces ``replica_groups={{0,1},{2,3}}`` → member
      lists verbatim;
    - the iota form ``replica_groups=[G,S]<=[N]`` → G sequential groups of
      S ids covering ``range(N)`` (XLA's compressed spelling of the same
      partition), synthesized here so congruence checks see one shape;
    - ``source_target_pairs={{s,t},...}`` (collective-permute) → one
      2-element ``[s, t]`` list per pair (pairs may legitimately repeat a
      device across *different* pairs, so callers must not apply the
      disjoint-partition rule to permutes).

    Returns ``None`` when the line carries no group annotation at all —
    distinct from ``[[...]]`` so callers can tell "no groups" apart from
    "one group of everything"."""
    m = _IOTA_GROUPS_RE.search(line)
    if m:
        dims = [int(d) for d in m.group(1).split(",")]
        size = dims[-1] if dims else 1
        groups = 1
        for d in dims[:-1]:
            groups *= d
        ids = iter(range(groups * size))
        return [[next(ids) for _ in range(size)] for _ in range(groups)]
    key = "replica_groups={"
    start = line.find(key)
    if start >= 0:
        block = _balanced_braces(line, start + len(key) - 1)
        groups = re.findall(r"\{([0-9,\s]*)\}", block)
        if groups:
            return [[int(t) for t in g.split(",") if t.strip()]
                    for g in groups]
        return [[]]  # replica_groups={} — one all-device group
    m = _PAIRS_RE.search(line)
    if m:
        block = _balanced_braces(line, m.end() - 1)
        return [[int(t) for t in pair.split(",") if t.strip()]
                for pair in re.findall(r"\{([0-9,\s]*)\}", block)]
    return None


def parse_op_metadata(line: str) -> Tuple[str, str]:
    """``(op_name, "file:line")`` from an instruction's ``metadata={...}``
    annotation; empty strings when absent.  ``op_name`` is the full jax
    scope path (``jit(step)/jit(main)/.../grad_sync/...``) — the hook that
    lets the comm ledger attribute a collective to the ``trace.scope`` /
    ``named_scope`` phase it lowered under."""
    m = _METADATA_RE.search(line)
    if not m:
        return ("", "")
    src = ""
    if m.group("file"):
        src = m.group("file")
        if m.group("line"):
            src += f":{m.group('line')}"
    return (m.group("op_name"), src)


@dataclasses.dataclass
class CollectiveDetail:
    """One collective instruction with its attribution fields."""

    name: str              # HLO instruction name (all-reduce.13)
    kind: str              # normalized opcode (-start folded in)
    bytes: int             # per-device result payload bytes
    shapes: List[Shape]
    n_groups: int
    group_size: int        # replica-group fan-out (devices per group)
    op_name: str           # full jax scope path from metadata
    source: str            # "file:line" from metadata
    computation: str

    def to_dict(self) -> Dict[str, object]:
        d = dataclasses.asdict(self)
        d["shapes"] = [[dt, list(dims)] for dt, dims in self.shapes]
        return d


def collect_collective_details(hlo_text: str) -> List[CollectiveDetail]:
    """Every collective in the module as an attributed record, in program
    order.  Async pairs count once (the ``-start`` op carries the payload;
    ``-done`` is bookkeeping, skipped)."""
    out: List[CollectiveDetail] = []
    for ins in parse_instructions(hlo_text):
        if ins.opcode not in _COLLECTIVE_SET:
            continue
        kind = ins.opcode[:-len("-start")] \
            if ins.opcode.endswith("-start") else ins.opcode
        n_groups, group_size = parse_replica_groups(ins.line)
        op_name, source = parse_op_metadata(ins.line)
        out.append(CollectiveDetail(
            name=ins.name, kind=kind, bytes=ins.result_bytes(),
            shapes=list(ins.shapes), n_groups=n_groups,
            group_size=group_size, op_name=op_name, source=source,
            computation=ins.computation))
    return out


# ------------------------------------------------------------ module header

_ALIAS_BLOCK_RE = re.compile(r"input_output_alias=\{(.*?)\}\s*[,)]")
_ALIAS_ENTRY_RE = re.compile(
    r"\{([0-9,\s]*)\}:\s*\((\d+),\s*\{([0-9,\s]*)\}")


def parse_input_output_alias(
    hlo_text: str,
) -> List[Tuple[Tuple[int, ...], int, Tuple[int, ...]]]:
    """The header's donation map as ``(output_path, param_num,
    param_path)`` triples; empty when nothing aliases."""
    header = hlo_text.split("\n", 1)[0]
    # the alias map nests braces: grab from `input_output_alias={` to the
    # matching close by scanning (entries themselves contain `{}`).
    key = "input_output_alias={"
    start = header.find(key)
    if start < 0:
        return []
    depth, i = 1, start + len(key)
    while i < len(header) and depth:
        if header[i] == "{":
            depth += 1
        elif header[i] == "}":
            depth -= 1
        i += 1
    block = header[start + len(key):i - 1]

    def path(text: str) -> Tuple[int, ...]:
        text = text.strip()
        return tuple(int(t) for t in text.split(",")) if text else ()

    return [
        (path(m.group(1)), int(m.group(2)), path(m.group(3)))
        for m in _ALIAS_ENTRY_RE.finditer(block)
    ]


def aliased_param_numbers(hlo_text: str) -> List[int]:
    """Entry-parameter numbers that donate their buffer to an output."""
    return sorted({p for _, p, _ in parse_input_output_alias(hlo_text)})


def _entry_layout_parts(hlo_text: str) -> Optional[Tuple[str, str]]:
    """``(params_text, outputs_text)`` of the header's
    ``entry_computation_layout={(...)->...}``, split at the top-level
    ``->`` with balanced brace/paren scanning (layout annotations like
    ``{1,0:T(8,128)}`` nest both delimiters)."""
    header = hlo_text.split("\n", 1)[0]
    key = "entry_computation_layout={"
    start = header.find(key)
    if start < 0:
        return None
    depth, i = 1, start + len(key)
    while i < len(header) and depth:
        if header[i] in "{(":
            depth += 1
        elif header[i] in "})":
            depth -= 1
        i += 1
    block = header[start + len(key):i - 1]
    depth = 0
    for j in range(len(block) - 1):
        if block[j] in "{(":
            depth += 1
        elif block[j] in "})":
            depth -= 1
        elif block[j:j + 2] == "->" and depth == 0:
            return block[:j], block[j + 2:]
    return None


def entry_parameter_shapes(hlo_text: str) -> List[Shape]:
    """Per-device entry parameter shapes, in parameter-number order, from
    the header's ``entry_computation_layout={(...)->...}``."""
    parts = _entry_layout_parts(hlo_text)
    return list(iter_shapes(parts[0])) if parts else []


def entry_output_shapes(hlo_text: str) -> List[Shape]:
    """Per-device entry *output* shapes from the header layout — the other
    half of the donation-opportunity question (an un-donated large input
    only matters if a shape-compatible output exists to alias it to)."""
    parts = _entry_layout_parts(hlo_text)
    return list(iter_shapes(parts[1])) if parts else []


# ------------------------------------------------- materialization matchers

def find_materializations(
    hlo_text: str,
    dtype: str,
    dims: Sequence[int],
    opcodes: Sequence[str] = ("fusion",),
    exclude_root: bool = True,
) -> List[Instruction]:
    """Instructions producing a buffer of exactly ``dtype[dims]``.

    The question scripts/hlo_dy_check.py asks: does XLA *materialize* a
    given intermediate (a fusion writes a buffer of that shape to memory)
    or keep it fused into its consumers?  ``opcodes=None`` matches any
    producer opcode except ``parameter``."""
    want: Shape = (dtype, tuple(int(d) for d in dims))
    out = []
    for ins in parse_instructions(hlo_text):
        if exclude_root and ins.is_root:
            continue
        if opcodes is not None and ins.opcode not in opcodes:
            continue
        if opcodes is None and ins.opcode == "parameter":
            continue
        if want in ins.shapes:
            out.append(ins)
    return out


def count_custom_call_convolutions(hlo_text: str) -> int:
    """Convolutions lowered to backend custom-calls (the CPU/TPU library
    path) — the denominator hlo_dy_check reports its fusion count against."""
    n = 0
    for line in hlo_text.splitlines():
        if "custom-call" in line and "convolution" in line.lower():
            n += 1
        elif "kind=kCustom" in line and "convolution" in line:
            n += 1
    return n


def nonparameter_shape_index(
    instrs: Iterable[Instruction],
) -> Dict[Shape, Instruction]:
    """First non-``parameter`` producer of each result shape — the lookup
    the replicated-tensor detector probes with global jaxpr shapes."""
    index: Dict[Shape, Instruction] = {}
    for ins in instrs:
        if ins.opcode == "parameter":
            continue
        for s in ins.shapes:
            index.setdefault(s, ins)
    return index
