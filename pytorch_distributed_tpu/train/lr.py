"""Learning-rate schedules.

Capability parity with the reference's ``adjust_learning_rate``
(reference distributed.py:374-378): step decay ``lr0 * 0.1 ** (epoch // 30)``.
Here the schedule is a pure function whose value is passed into the jitted
step as a scalar operand, so changing LR never retraces the program.
"""

from __future__ import annotations


def step_decay_lr(
    base_lr: float,
    epoch: int,
    decay_factor: float = 0.1,
    decay_every: int = 30,
) -> float:
    """``lr = base_lr * decay_factor ** (epoch // decay_every)``."""
    return base_lr * (decay_factor ** (epoch // decay_every))


def linear_scaled_lr(base_lr: float, global_batch: int, base_batch: int = 256) -> float:
    """Linear-scaling rule (Goyal et al.) — optional helper, off by default to
    preserve the reference's effective-LR semantics (SURVEY.md §7.4 item 2)."""
    return base_lr * global_batch / base_batch
