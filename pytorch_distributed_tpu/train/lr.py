"""Learning-rate schedules.

Capability parity with the reference's ``adjust_learning_rate``
(reference distributed.py:374-378): step decay ``lr0 * 0.1 ** (epoch // 30)``.
Here the schedule is a pure function whose value is passed into the jitted
step as a scalar operand, so changing LR never retraces the program.
"""

from __future__ import annotations


def step_decay_lr(
    base_lr: float,
    epoch: int,
    decay_factor: float = 0.1,
    decay_every: int = 30,
) -> float:
    """``lr = base_lr * decay_factor ** (epoch // decay_every)``."""
    return base_lr * (decay_factor ** (epoch // decay_every))


def cosine_lr(
    base_lr: float,
    epoch: int,
    total_epochs: int,
    warmup_epochs: int = 0,
    min_lr: float = 0.0,
) -> float:
    """Warmup + cosine decay over epochs — half-cosine from ``base_lr`` at
    the end of warmup to ``min_lr`` at ``total_epochs``.  Same shape as the
    LM twin's per-step ``warmup_cosine_lr`` (train/lm.py), but the ramp here
    ends AT ``warmup_epochs`` (every warmup epoch runs reduced), while the
    LM form's ``(step+1)/warmup_steps`` reaches full LR one step early —
    immaterial at its hundreds-of-steps granularity, degenerate at epoch
    granularity.  Like ``step_decay_lr`` this is a pure host-side function;
    its value enters the jitted step as a scalar operand, so changing LR
    never retraces."""
    import math

    if warmup_epochs > 0 and epoch < warmup_epochs:
        # Ramp reaches base_lr at epoch == warmup_epochs, so every warmup
        # epoch (including warmup_epochs=1) really runs reduced — the
        # (epoch+1)/warmup form makes warmup=1 a silent no-op at epoch
        # granularity (round-4 review finding).
        return base_lr * (epoch + 1) / (warmup_epochs + 1)
    span = max(1, total_epochs - warmup_epochs)
    t = min(max(epoch - warmup_epochs, 0), span) / span
    return min_lr + (base_lr - min_lr) * 0.5 * (1.0 + math.cos(math.pi * t))


def linear_scaled_lr(base_lr: float, global_batch: int, base_batch: int = 256) -> float:
    """Linear-scaling rule (Goyal et al.) — optional helper, off by default to
    preserve the reference's effective-LR semantics (SURVEY.md §7.4 item 2)."""
    return base_lr * global_batch / base_batch
