"""The canonical training harness (reference distributed.py:228-395 parity).

Meters, LR schedule, SGD with torch-exact update semantics, jitted SPMD
train/eval steps, checkpoint save/resume, and the epoch driver.
"""

from pytorch_distributed_tpu.train.meters import AverageMeter, ProgressMeter
from pytorch_distributed_tpu.train.lr import step_decay_lr
from pytorch_distributed_tpu.train.optim import sgd_init, sgd_update

__all__ = [
    "AverageMeter",
    "ProgressMeter",
    "step_decay_lr",
    "sgd_init",
    "sgd_update",
]
