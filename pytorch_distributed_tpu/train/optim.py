"""SGD with exactly the reference optimizer's update semantics.

The reference uses ``torch.optim.SGD(lr, momentum=0.9, weight_decay=1e-4)``
(reference distributed.py:153-156).  Torch semantics, which differ from some
JAX-ecosystem defaults and therefore warrant this ~40-line pure implementation:

- weight decay is *coupled* (added to the gradient): ``g = g + wd * p``
- momentum buffer: ``buf = mu * buf + g`` (dampening 0, no bias correction)
- update: ``p = p - lr * buf``  (LR multiplies the *buffer*, so step-decay LR
  takes effect immediately, mid-momentum — exactly like torch)

Implemented as init/update pure functions over pytrees so the update lives
inside the jitted SPMD step; ``lr`` is a traced scalar operand.  An optax
optimizer can be substituted anywhere the harness accepts ``tx`` — this module
is the default because its numerics are the parity target.

``--zero wus`` (parallel/zero.py) re-implements exactly this ``_upd`` on flat
1/N parameter chunks so the weight-update-sharded step is bit-compatible with
the replicated one: any change to the update math here must be mirrored in
``zero.wus_apply_updates`` (the 3-step parity fence in tests/test_zero.py
catches drift).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

Pytree = Any


def sgd_init(params: Pytree) -> Pytree:
    """Zero momentum buffers shaped like ``params``."""
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def sgd_update(
    grads: Pytree,
    momentum_buf: Pytree,
    params: Pytree,
    lr: jnp.ndarray | float,
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
) -> Tuple[Pytree, Pytree]:
    """One SGD step; returns ``(new_params, new_momentum_buf)``.

    Momentum/weight-decay math runs in the parameter dtype's f32 master copy —
    callers keep params in f32 and cast to bf16 only for compute (the
    apex-recipe-equivalent policy, SURVEY.md §7.1).
    """

    def _upd(g, buf, p):
        g = g + weight_decay * p
        buf = momentum * buf + g
        return p - lr * buf, buf

    flat = jax.tree_util.tree_map(_upd, grads, momentum_buf, params)
    new_params = jax.tree_util.tree_map(lambda t: t[0], flat, is_leaf=lambda t: isinstance(t, tuple))
    new_buf = jax.tree_util.tree_map(lambda t: t[1], flat, is_leaf=lambda t: isinstance(t, tuple))
    return new_params, new_buf
