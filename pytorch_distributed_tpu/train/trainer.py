"""Epoch driver: the reference's ``main_worker`` / ``train`` / ``validate``
harness (reference distributed.py:129-324) rebuilt around compiled SPMD steps.

One Trainer serves every recipe; recipes differ only in driver-level config
(mesh construction, precision, explicit-vs-GSPMD collectives, multi-host
bootstrap) — the TPU-native collapse of the reference's six-script mechanism
diversity (SURVEY.md §7.1).
"""

from __future__ import annotations

import time
from contextlib import nullcontext
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from pytorch_distributed_tpu import models
from pytorch_distributed_tpu.data import (
    DataLoader,
    DeviceFeeder,
    DistributedShardSampler,
    ImageFolder,
    SyntheticImageDataset,
)
from pytorch_distributed_tpu.data.transforms import eval_transform, train_transform
from pytorch_distributed_tpu.obs import (
    HeartbeatWriter,
    MetricsLogger,
    ProfileWindow,
    sample_process_memory,
    scope,
)
from pytorch_distributed_tpu.parallel import DistContext, data_parallel_mesh
from pytorch_distributed_tpu.train.checkpoint import load_checkpoint, save_checkpoint
from pytorch_distributed_tpu.train.config import Config
from pytorch_distributed_tpu.train.lr import cosine_lr, step_decay_lr
from pytorch_distributed_tpu.train.meters import AverageMeter, ProgressMeter, StepMeters
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.steps import make_eval_step, make_train_step
from pytorch_distributed_tpu.utils import EpochCSVLogger


class Trainer:
    def __init__(
        self,
        cfg: Config,
        mesh: Optional[Mesh] = None,
        ctx: Optional[DistContext] = None,
        explicit_collectives: bool = False,
        wire_dtype=None,
        grad_compress: Optional[str] = None,
        zero: Optional[str] = None,
        data_axis: str = "data",
        tx=None,
        preempt=None,
        chaos=None,
    ):
        """``tx``: optional optax GradientTransformation replacing the
        default torch-parity SGD (see train/steps.py docstring).

        ``grad_compress``: gradient wire format for the DP sync
        (none|bf16|int8|fp8, ops/qcomm.py); falls back to
        ``cfg.grad_compress``.  The legacy ``wire_dtype`` argument is the
        deprecated bf16-mode alias.

        ``zero``: ``none|wus`` weight-update sharding (parallel/zero.py);
        falls back to ``cfg.zero``.  Under ``wus`` the optimizer state is
        sharded 1/N over the data axis — stacked chunks on the explicit
        step, ``fsdp_specs`` shardings under GSPMD — and checkpoints keep
        storing the param-shaped momentum, so runs restore across modes.

        ``preempt``: optional ``utils.preempt.PreemptionGuard`` (already
        installed) polled between steps; ``fit()`` installs a guard for
        ``cfg.preempt_signals`` (default SIGTERM) when none is given.

        ``chaos``: optional ``ft.chaos`` injector schedule called once per
        train step (fault-injection drills and the survival tests)."""
        self.cfg = cfg
        self.preempt = preempt
        self.chaos = chaos
        self._agree = None  # built lazily (PreemptionAgreement over the mesh)
        self.ctx = ctx or DistContext(
            jax.process_index(), jax.process_count(), None
        )
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self.data_axis = data_axis

        # Global batch divided across processes (reference distributed.py:146
        # divides by nprocs; we divide by process count — device-level split
        # happens in the sharded feeder, so per-chip batch is global/chips).
        cfg.nprocs = self.ctx.process_count
        if cfg.batch_size % max(1, self.ctx.process_count):
            raise ValueError(
                f"global batch {cfg.batch_size} not divisible by "
                f"{self.ctx.process_count} processes"
            )
        self.local_batch = cfg.batch_size // max(1, self.ctx.process_count)

        # Data first: ImageFolder infers num_classes, which sizes the head.
        self._build_data()

        dtype = jnp.bfloat16 if cfg.precision == "bf16" else jnp.float32
        # --stem / --fused-convbn are ResNet-family knobs; only forwarded
        # when non-default.
        extra = {} if cfg.stem == "conv7" else {"stem": cfg.stem}
        if cfg.fused_convbn:
            extra["fused_convbn"] = True
        if extra and getattr(
            models._REGISTRY.get(cfg.arch), "func", None
        ) is not models.ResNet:
            raise ValueError(
                f"--stem/--fused-convbn only apply to the ResNet family; "
                f"arch {cfg.arch!r} has no such variant"
            )
        if getattr(cfg, "sync_bn", False) and explicit_collectives:
            if cfg.fused_convbn:
                # The fold gate (models/resnet.py _fuse_ok) has no
                # synced-stats kernel and would silently drop the fold —
                # make the conflict loud instead.
                raise ValueError(
                    "--sync-bn and --fused-convbn are mutually exclusive: "
                    "the fused conv+BN backward has no cross-replica "
                    "statistics variant; drop one of the flags")
            # Cross-replica BN moments inside the shard_map step (torch
            # SyncBatchNorm ≙, model-agnostic like torch's): every BN
            # model family threads bn_axis_name into its norm layers.
            # GSPMD already has global-batch semantics, so the flag is a
            # documented no-op there.
            extra["bn_axis_name"] = data_axis
            # Explicit capability check instead of catching the
            # CPython-wording-dependent rejected-kwarg TypeError: a
            # BN-carrying model class declares bn_axis_name as a dataclass
            # field (flax modules are dataclasses), so its absence IS the
            # "no BatchNorm" signal — robust to constructor wrappers and
            # message-wording changes.  (Plain VGG keeps its own in-class
            # check: the class carries the field for the *_bn variants but
            # a BN-free cfg must still refuse at init.)
            import dataclasses as _dc

            ctor = (models._REGISTRY.get(cfg.arch)
                    or models._LM_REGISTRY.get(cfg.arch))
            cls = getattr(ctor, "func", ctor)
            fields = ({f.name for f in _dc.fields(cls)}
                      if _dc.is_dataclass(cls) else set())
            if "bn_axis_name" not in fields:
                raise ValueError(
                    f"--sync-bn: arch {cfg.arch!r} has no BatchNorm layers "
                    f"to synchronize (no bn_axis_name knob)")
        self.model = models.create_model(
            cfg.arch, num_classes=cfg.num_classes, dtype=dtype, **extra
        )

        # Resolve the gradient wire format once (kwarg > cfg; wire_dtype is
        # the deprecated bf16 alias) — the mode decides the error-feedback
        # residual layout carried in TrainState.
        from pytorch_distributed_tpu.ops import qcomm

        gc = grad_compress if grad_compress is not None else cfg.grad_compress
        self.grad_compress, self._grad_cast = qcomm.resolve_mode(
            gc, wire_dtype)

        # Weight-update sharding (kwarg > cfg, like grad_compress) — the
        # mode decides the optimizer-state layout carried in TrainState.
        from pytorch_distributed_tpu.parallel import zero as zero_lib

        self.zero = zero_lib.resolve_zero(
            zero if zero is not None else getattr(cfg, "zero", None))
        if self.zero == "wus" and tx is not None:
            raise ValueError(
                "--zero wus implements the torch-parity SGD on 1/N shards; "
                "an optax tx cannot be chunked — drop one of them")

        seed = cfg.seed if cfg.seed is not None else 0
        # Stashed for _build_for_mesh: an elastic re-mesh rebuilds the
        # jitted steps and feeder against the survivor set.
        self._explicit = explicit_collectives
        self._tx = tx
        self._seed = seed
        rng = jax.random.PRNGKey(seed)
        sample = jnp.zeros((1, cfg.image_size, cfg.image_size, 3), jnp.float32)
        variables = self.model.init(rng, sample, train=False)
        n_data = dict(self.mesh.shape)[self.data_axis]
        self._mom_sharding = None   # non-replicated momentum layout (wus)
        if self.zero == "wus" and explicit_collectives:
            from jax.sharding import NamedSharding, PartitionSpec

            opt0 = zero_lib.init_wus_momentum(
                variables["params"], n_data,
                quantized=self.grad_compress in qcomm.QUANTIZED_MODES)
            self._mom_sharding = NamedSharding(
                self.mesh, PartitionSpec(self.data_axis))
            opt0 = jax.device_put(opt0, self._mom_sharding)
        elif self.zero == "wus":
            from jax.sharding import NamedSharding

            opt0 = sgd_init(variables["params"])
            self._mom_sharding = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s),
                zero_lib.zero_momentum_specs(
                    variables["params"], self.mesh, data_axis=self.data_axis))
            opt0 = jax.device_put(opt0, self._mom_sharding)
        else:
            opt0 = tx.init(variables["params"]) if tx is not None else \
                sgd_init(variables["params"])
        residual = qcomm.init_residual(
            variables["params"], self.grad_compress,
            explicit=explicit_collectives,
            n_data=n_data)
        self.state = TrainState.create(variables, opt0, residual=residual)
        del variables

        if cfg.pretrained:
            self._load_pretrained()

        # Divergence guard + last-good snapshot (ft/): policy over the
        # in-graph nonfinite flag the step emits under --nan-guard.
        self.ft_guard = None
        self._keeper = None
        if getattr(cfg, "nan_guard", False):
            from pytorch_distributed_tpu.ft import DivergenceGuard, StateKeeper

            self._keeper = StateKeeper()
            # obs wired below (constructed later in __init__); attached then.
            self.ft_guard = DivergenceGuard(
                rollback_k=cfg.ft_rollback_k,
                check_every=cfg.ft_check_every,
                lr_backoff=cfg.ft_lr_backoff)

        self.best_acc1 = 0.0
        self._resume_step = 0    # step-in-epoch offset for the first epoch
        self._resume_global = 0
        if cfg.resume:
            self.state, meta = load_checkpoint(cfg.resume, self.state)
            self.best_acc1 = float(meta["best_acc1"])
            ft = meta["ft"]
            self._resume_step = int(ft["step"])
            self._resume_global = int(ft["global_step"])
            if self.ft_guard is not None:
                self.ft_guard.lr_scale = float(ft["lr_scale"])
            if self._resume_step > 0 and int(ft["sampler_seed"]) != (
                    cfg.seed if cfg.seed is not None else 0):
                import warnings

                warnings.warn(
                    f"resuming mid-epoch with --seed "
                    f"{cfg.seed if cfg.seed is not None else 0} but the "
                    f"checkpoint's sampler ran with seed "
                    f"{int(ft['sampler_seed'])}: the shuffle permutation "
                    f"differs, so the resumed epoch will not be "
                    f"sample-exact", stacklevel=2)
            if cfg.start_epoch == 0:
                # Mid-epoch checkpoint (ft step > 0): rerun the SAME epoch
                # from that step; epoch-boundary checkpoint: next epoch.
                cfg.start_epoch = int(meta["epoch"]) + (
                    0 if self._resume_step > 0 else 1)
            print(
                f"=> resumed {meta['arch']} from '{cfg.resume}' "
                f"(epoch {meta['epoch']}, step {self._resume_step}, "
                f"best_acc1 {self.best_acc1:.3f})"
            )

        # Validate accumulation settings BEFORE building the step — an invalid
        # accum_steps inside make_train_step would only surface as a confusing
        # trace-time reshape error (round-1 advisor finding).
        if cfg.accum_steps < 1:
            raise ValueError(f"--accum-steps must be >= 1, got {cfg.accum_steps}")
        if cfg.accum_steps > 1:
            # Each strided microbatch must still cover every data-axis shard
            # evenly, or XLA reshards the input on every scan iteration.
            shards = dict(self.mesh.shape)[self.data_axis]
            micro_global = cfg.batch_size // cfg.accum_steps
            if cfg.batch_size % cfg.accum_steps or micro_global % shards:
                raise ValueError(
                    f"global batch {cfg.batch_size} / --accum-steps "
                    f"{cfg.accum_steps} must be a whole multiple of the "
                    f"'{self.data_axis}' mesh axis ({shards} shards)"
                )
        # Everything mesh-shape-dependent (jitted steps, feeder, the
        # momentum sharding, topology-keyed caches) builds in one place so
        # an elastic re-mesh can rebuild it against the survivor set.
        self._build_for_mesh(self.mesh)
        # One observability entry point (obs/): the epoch CSV registers as
        # an epoch sink, a --telemetry-csv sampler registers in fit(), and
        # per-step structured records land in --metrics-jsonl.
        self.csv = EpochCSVLogger(cfg.epoch_csv)
        self.obs = MetricsLogger(cfg.metrics_jsonl,
                                 process_index=self.ctx.process_index)
        self.obs.register(self.csv)
        self.hb = (HeartbeatWriter(cfg.hb_dir, self.ctx.process_index,
                                   interval_s=cfg.hb_interval_s)
                   if cfg.hb_dir else None)
        if self.ft_guard is not None:
            self.ft_guard.obs = self.obs  # ft_event records → metrics JSONL
        # Efficiency accounting (obs/): per-step MFU/HFU from the analytic
        # FLOPs model, the live goodput ledger, and the recompile watchdog.
        self._mfu = None
        self._mfu_on = bool(getattr(cfg, "mfu", False))
        if self._mfu_on:
            self._build_mfu()
        self._goodput = None
        if getattr(cfg, "goodput", False):
            from pytorch_distributed_tpu.obs.goodput import GoodputTracker

            self._goodput = self.obs.register(GoodputTracker())
        self.watchdog = None
        if getattr(cfg, "watch_recompiles", False):
            from pytorch_distributed_tpu.obs.watchdog import (
                RecompileWatchdog,
            )

            self.watchdog = RecompileWatchdog(obs=self.obs).install()
        # Flight recorder (obs/flightrec.py): bounded per-rank event ring
        # + collective-hang watchdog, dumped on any death path; the
        # signal-dump chain and the watchdog thread start in fit().
        self.flight = None
        self._hang_wd = None
        if getattr(cfg, "flight_rec", None):
            from pytorch_distributed_tpu.obs.flightrec import (
                FlightRecorder,
                HangWatchdog,
                attach_to_metrics,
            )

            self.flight = FlightRecorder(cfg.flight_rec,
                                         rank=self.ctx.process_index)
            self._hang_wd = HangWatchdog(
                self.flight, obs=self.obs,
                timeout=float(getattr(cfg, "hang_timeout", 30.0)))
            # Every ft_event the metrics logger sees (skip/rollback/
            # preempt/remesh, incl. DivergenceGuard's) lands in the ring.
            attach_to_metrics(self.flight, self.obs)
        # Live telemetry plane (obs/export.py + obs/alerts.py): the
        # exporter and the rule engine are both flush-time sinks on the
        # same logger — zero additions to the hot loop.  The exporter is
        # an owned sink (started here, stopped at obs.close()); rank k
        # serves metrics_port + k.
        self._exporter = None
        if int(getattr(cfg, "metrics_port", 0) or 0) > 0:
            from pytorch_distributed_tpu.obs.export import MetricsExporter

            self._exporter = MetricsExporter(
                int(cfg.metrics_port) + self.ctx.process_index,
                rank=self.ctx.process_index)
            self.obs.register(self._exporter)        # lifecycle (start/stop)
            self.obs.register(self._exporter.update)  # per-record sink
        self.alerts = None
        if getattr(cfg, "alerts", None):
            from pytorch_distributed_tpu.obs.alerts import (
                AlertEngine,
                default_rules,
                load_rules,
            )

            rules = (default_rules() if cfg.alerts == "default"
                     else load_rules(cfg.alerts))
            self.alerts = AlertEngine(
                rules, emit=self._emit_alert,
                process_index=self.ctx.process_index)
            self.obs.register(self.alerts)
            if self._exporter is not None:
                self._exporter.engine = self.alerts  # ptd_alert_firing
        # Exact step attribution (obs/stepattr.py, --step-attr): three
        # perf_counter wall windows per step + one explicit block on the
        # step outputs, closing step_time == compute + exposed_comm +
        # host_sync + data_wait + other exactly.  The device-window split
        # starts as a ledger estimate and upgrades to the comm ledger's
        # wire bytes when --comm-ledger runs (same lowering, no extra
        # compile); the static phase roofline books once as a
        # `stepattr_phases` ft_event.
        self.stepattr = None
        self._stepattr_phases_booked = False
        if getattr(cfg, "step_attr", False):
            from pytorch_distributed_tpu.obs.flops import chip_link_bytes
            from pytorch_distributed_tpu.obs.stepattr import StepAttr

            kind = getattr(self.mesh.devices.flat[0], "device_kind", "")
            self.stepattr = StepAttr(link_bytes_per_s=chip_link_bytes(kind))
        # Communication + memory ledgers (obs/comms.py, obs/memory.py):
        # emitted lazily on the first train batch (real shardings in
        # hand), opt-in because the AOT lowering does not share the jit
        # call cache — one extra compile shared by both receipts.
        self._comm_fields: Optional[dict] = None
        # Dominant ledger collective (kind/bytes/name) labelling the flight
        # ring's coll_enter events; None until a ledger lowering runs.
        self._flight_coll: Optional[dict] = None
        # Monotonic logged-train-step counter; a resume restores it so the
        # metrics JSONL step axis continues instead of restarting at 0.
        self._global_step = self._resume_global

        # ---- elastic membership (ft/elastic.py) ----
        from pytorch_distributed_tpu.ft import elastic as elastic_lib

        self.rescale_lr_rule = str(getattr(cfg, "rescale_lr", "none") or "none")
        if self.rescale_lr_rule not in elastic_lib.RESCALE_RULES:
            raise ValueError(
                f"--rescale-lr must be one of {elastic_lib.RESCALE_RULES}, "
                f"got {self.rescale_lr_rule!r}")
        self._elastic_lr_scale = 1.0
        self._membership_epoch = 0
        self.elastic = elastic_lib.elastic_controller_from_config(
            cfg, dict(self.mesh.shape)[self.data_axis])
        if self.elastic is not None and self._keeper is None:
            # Re-meshing re-shards from the same last-good host snapshot
            # the divergence guard rolls back to.
            from pytorch_distributed_tpu.ft import StateKeeper

            self._keeper = StateKeeper()
        if self.hb is not None:
            self.hb.set_membership(dict(self.mesh.shape)[self.data_axis],
                                   self._membership_epoch)
        if self.flight is not None:
            self.flight.set_membership(
                dict(self.mesh.shape)[self.data_axis],
                self._membership_epoch)

    def _emit_alert(self, **fields) -> None:
        """AlertEngine emit hook: book a firing as an ``alert`` ft_event
        in the same JSONL, so goodput/postmortem/obs_report fold it (and
        the flight ring records it via attach_to_metrics)."""
        self.obs.log_event("alert", **fields)

    def _build_for_mesh(self, mesh: Mesh) -> None:
        """Build (or rebuild) every mesh-shape-dependent piece against
        ``mesh``: the momentum sharding, jitted train/eval steps, the
        device feeder, and the topology-keyed caches (preemption
        agreement, comm-ledger fields).  Called once from ``__init__`` and
        again on every elastic ``remesh`` — the mesh-shape-agnostic seam
        that decouples trainer construction from mesh shape."""
        from pytorch_distributed_tpu.ops import qcomm
        from pytorch_distributed_tpu.parallel import zero as zero_lib

        cfg = self.cfg
        self.mesh = mesh
        if self.zero == "wus" and self._explicit:
            from jax.sharding import NamedSharding, PartitionSpec

            self._mom_sharding = NamedSharding(
                mesh, PartitionSpec(self.data_axis))
        elif self.zero == "wus":
            from jax.sharding import NamedSharding

            self._mom_sharding = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s),
                zero_lib.zero_momentum_specs(
                    self.state.params, mesh, data_axis=self.data_axis))
        else:
            self._mom_sharding = None
        self.train_step = make_train_step(
            self.model,
            mesh,
            momentum=cfg.momentum,
            weight_decay=cfg.weight_decay,
            data_axis=self.data_axis,
            wire_dtype=(self._grad_cast
                        if self.grad_compress == "bf16" else None),
            grad_compress=self.grad_compress,
            explicit_collectives=self._explicit,
            seed=self._seed,
            tx=self._tx,
            accum_steps=cfg.accum_steps,
            # In-graph grad/param norms only when a metrics sink consumes
            # them — the reductions lengthen compiles, so observability
            # costs nothing when off.
            log_norms=bool(cfg.metrics_jsonl),
            guard_nonfinite=bool(getattr(cfg, "nan_guard", False)),
            zero=self.zero,
            params=self.state.params,
            # Comm-overlap scheduler (parallel/overlap.py): bucketed
            # backward-overlapped grad sync on the explicit step;
            # make_train_step rejects bucketed-under-GSPMD loudly.
            overlap=getattr(cfg, "overlap", "none"),
            bucket_mb=float(getattr(cfg, "bucket_mb", 4.0)),
        )
        self.eval_step = make_eval_step(
            self.model, mesh, data_axis=self.data_axis,
            residual_sharded=(self._explicit
                              and self.grad_compress in qcomm.QUANTIZED_MODES),
            momentum_sharding=self._mom_sharding)
        self.feeder = DeviceFeeder(mesh, data_axis=self.data_axis)
        self._agree = None        # PreemptionAgreement holds the old mesh
        self._comm_fields = None  # ledger re-emits against the new mesh

    def _build_mfu(self) -> None:
        from pytorch_distributed_tpu.obs.flops import (
            MFUReporter,
            device_peak_flops,
            image_step_cost,
        )

        cfg = self.cfg
        cost = image_step_cost(cfg.arch, cfg.batch_size, cfg.image_size,
                               cfg.num_classes)
        dev = self.mesh.devices.flat[0]
        self._mfu = MFUReporter(cost, n_devices=self.mesh.devices.size,
                                peak_per_chip=device_peak_flops(dev))

    def remesh(self, new_world: int, refresh_snapshot: bool = True) -> int:
        """Re-mesh to ``new_world`` devices on the data axis: rebuild the
        mesh / jitted steps / feeder from the survivor set and re-shard the
        last-good ``StateKeeper`` snapshot onto the new topology.  Returns
        the global step to resume from (the snapshot's step).

        Unlike the LM path, the explicit-collectives layouts bake n_data
        into the state itself, so this is where the layout surgery
        happens: stacked ZeRO-WUS momentum chunks re-grid losslessly
        (flat-concat → truncate → re-chunk, ft/elastic.py) and stacked
        per-rank error-feedback residuals fold their sum into slot 0 —
        the total pending correction is preserved exactly.  Param-shaped
        leaves need no surgery; the jitted step's in_shardings place the
        host snapshot on the next call, exactly like ``_rollback``."""
        from pytorch_distributed_tpu.ft import elastic as elastic_lib
        from pytorch_distributed_tpu.ops import qcomm
        from pytorch_distributed_tpu.parallel import zero as zero_lib
        from pytorch_distributed_tpu.parallel.mesh import MeshSpec, build_mesh

        axes = tuple(self.mesh.axis_names)
        if axes != (self.data_axis,):
            raise ValueError(
                f"elastic re-mesh supports pure data-parallel meshes; "
                f"this trainer's mesh has axes {axes}")
        devs = jax.devices()
        if not 1 <= new_world <= len(devs):
            raise ValueError(
                f"new world {new_world} outside [1, {len(devs)}] devices")
        old_world = dict(self.mesh.shape)[self.data_axis]
        if self._keeper is None:
            from pytorch_distributed_tpu.ft import StateKeeper

            self._keeper = StateKeeper()
        if refresh_snapshot or not self._keeper.has_snapshot:
            self._keeper.update(self.state, self._global_step)
        host = self._keeper.restore()
        resume_global = int(self._keeper.step)
        if self.rescale_lr_rule != "none":
            new_batch = elastic_lib.rescale_batch(
                self.cfg.batch_size, old_world, new_world,
                self.rescale_lr_rule)
            self._elastic_lr_scale *= elastic_lib.rescale_lr(
                1.0, old_world, new_world, self.rescale_lr_rule)
            if new_batch != self.cfg.batch_size:
                # Per-rank batch held constant: loaders re-size (epoch
                # length changes take effect from the resume step).
                self.cfg.batch_size = new_batch
                self.local_batch = new_batch // max(
                    1, self.ctx.process_count)
                self._build_data()
        if self.cfg.batch_size % new_world:
            raise ValueError(
                f"global batch {self.cfg.batch_size} does not divide the "
                f"new data axis ({new_world} devices); pick --min-ranks / "
                "batch so every admissible world divides it")
        new_mesh = build_mesh(MeshSpec((self.data_axis,), (new_world,)),
                              devices=devs[:new_world])
        momentum = host.momentum
        if zero_lib.is_wus_momentum(momentum):
            momentum = elastic_lib.regrid_wus_momentum(
                momentum, host.params, new_world)
        residual = host.residual
        if (self._explicit and self.grad_compress in qcomm.QUANTIZED_MODES
                and residual):
            residual = elastic_lib.regrid_stacked_residual(residual,
                                                           new_world)
        self.state = TrainState(host.step, host.params, host.batch_stats,
                                momentum, residual)
        self._build_for_mesh(new_mesh)
        if self._mom_sharding is not None:
            # The stacked/sharded momentum is placed eagerly (its layout
            # just changed); everything param-shaped re-shards lazily via
            # the step's in_shardings.
            self.state = TrainState(
                self.state.step, self.state.params, self.state.batch_stats,
                jax.device_put(self.state.momentum, self._mom_sharding),
                self.state.residual)
        if self._mfu_on:
            self._build_mfu()  # n_devices (and maybe batch) changed
        self._membership_epoch += 1
        if self.hb is not None:
            self.hb.set_membership(new_world, self._membership_epoch)
        if self.flight is not None:
            self.flight.set_membership(new_world, self._membership_epoch)
        return resume_global

    def _apply_remesh(self, chg, epoch: int) -> int:
        """Act on a committed ``MembershipChange`` inside ``train_epoch``:
        log the ``remesh`` ft_event (goodput books the gap to the first
        step on the new mesh) and rebuild.  Returns the global resume
        step."""
        kind = chg.kind
        old_world = dict(self.mesh.shape)[self.data_axis]
        self.obs.log_event("remesh", step=self._global_step, change=kind,
                           old_world=chg.old.world, new_world=chg.new.world,
                           epoch=chg.new.epoch, reason=chg.reason,
                           rescale=self.rescale_lr_rule, train_epoch=epoch)
        resume = self.remesh(chg.new.world,
                             refresh_snapshot=(kind == "grow"))
        print(f"=> remesh ({kind}) at global step {self._global_step}: "
              f"world {old_world}->{chg.new.world}, epoch {chg.new.epoch}, "
              f"resuming at global step {resume} ({chg.reason})", flush=True)
        return resume

    def _load_pretrained(self) -> None:
        """``--pretrained`` parity (reference distributed.py:134-136 loads zoo
        weights).  TPU pods have no network egress, so weights come from a
        local directory: ``$PTD_TPU_PRETRAINED_DIR/<arch>.msgpack`` — any
        checkpoint this framework saved for the same arch."""
        import os

        d = os.environ.get("PTD_TPU_PRETRAINED_DIR", "pretrained")
        path = os.path.join(d, f"{self.cfg.arch}.msgpack")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"--pretrained: no weights at '{path}'; set "
                "PTD_TPU_PRETRAINED_DIR to a directory containing "
                f"{self.cfg.arch}.msgpack (a checkpoint saved by this framework)"
            )
        self.state, _ = load_checkpoint(path, self.state)
        print(f"=> using pre-trained model '{self.cfg.arch}' from '{path}'")

    # ------------------------------------------------------------------ data
    def _build_data(self) -> None:
        cfg = self.cfg
        world = self.ctx.process_count
        rank = self.ctx.process_index
        seed = cfg.seed if cfg.seed is not None else 0
        if cfg.synthetic:
            self.train_set = SyntheticImageDataset(
                length=cfg.synthetic_length,
                num_classes=cfg.num_classes,
                image_size=cfg.image_size,
                transform=None,
                seed=seed,
            )
            self.val_set = SyntheticImageDataset(
                length=max(cfg.synthetic_length // 10, world * 2),
                num_classes=cfg.num_classes,
                image_size=cfg.image_size,
                transform=None,
                seed=seed + 1,
            )
        elif cfg.wire == "native":
            # Full native host path: C++ JPEG decode + crop/resize, batch
            # flip host-side, uint8 across the wire, normalize on device.
            from pytorch_distributed_tpu.data.native import (
                jpeg_native_available,
            )

            if not jpeg_native_available():
                raise RuntimeError(
                    "--wire native needs the C++ data plane built against "
                    "libjpeg (g++ and libjpeg-dev); use --wire u8 or u8host "
                    "on this host"
                )
            self.train_set = ImageFolder(
                f"{cfg.data}/train", native_decode=True,
                image_size=cfg.image_size, native_augment=True,
            )
            self.val_set = ImageFolder(
                f"{cfg.data}/val", native_decode=True,
                image_size=cfg.image_size, native_augment=False,
            )
            cfg.num_classes = len(self.train_set.classes)
        else:
            if cfg.wire == "f32":
                ttf, vtf = train_transform(cfg.image_size), eval_transform(cfg.image_size)
            else:
                from pytorch_distributed_tpu.data.transforms import (
                    eval_transform_u8,
                    train_transform_u8,
                )

                ttf, vtf = train_transform_u8(cfg.image_size), eval_transform_u8(cfg.image_size)
            self.train_set = ImageFolder(f"{cfg.data}/train", transform=ttf)
            self.val_set = ImageFolder(f"{cfg.data}/val", transform=vtf)
            cfg.num_classes = len(self.train_set.classes)
        self.train_sampler = DistributedShardSampler(
            len(self.train_set), world, rank, shuffle=True, seed=seed
        )
        self.val_sampler = DistributedShardSampler(
            len(self.val_set), world, rank, shuffle=False, seed=seed
        )
        # drop_last on train: XLA needs static shapes, and a zero-padded
        # partial batch would pollute that batch's BatchNorm statistics.  The
        # torch reference trains on a smaller final batch instead (dynamic
        # shapes); with ImageNet-scale epochs the dropped tail is <1 batch.
        # Eval keeps padding + masks so metrics stay exact (SURVEY §7.4 it.3).
        # Synthetic datasets emit f32 directly; wire modes apply to the
        # ImageFolder (u8-transform) path.
        batch_mode = {"f32": "f32", "u8host": "u8_host", "u8": "u8_wire",
                      "native": "u8_wire"}[cfg.wire]
        if cfg.synthetic:
            batch_mode = "f32"
        self.train_loader = DataLoader(
            self.train_set,
            self.local_batch,
            sampler=self.train_sampler,
            num_workers=cfg.workers,
            drop_last=True,
            seed=seed,
            batch_mode=batch_mode,
            random_flip=batch_mode != "f32",
            worker_type=cfg.worker_type,
        )
        self.val_loader = DataLoader(
            self.val_set,
            self.local_batch,
            sampler=self.val_sampler,
            num_workers=cfg.workers,
            seed=seed,
            batch_mode=batch_mode,
            worker_type=cfg.worker_type,
        )

    def _wd_watch(self, label: str, step: Optional[int] = None):
        """Watchdog attribution context for a jitted call (inert when
        --watch-recompiles is off)."""
        if self.watchdog is not None:
            return self.watchdog.watch(label, step=step)
        import contextlib

        return contextlib.nullcontext()

    # ----------------------------------------------------------------- train
    def _ft_record(self, epoch: int, step_in_epoch: int) -> dict:
        return {
            "step": int(step_in_epoch),
            "global_step": int(self._global_step),
            "sampler_seed": int(self.train_sampler.seed),
            "sampler_epoch": int(epoch),
            "lr_scale": (self.ft_guard.lr_scale
                         if self.ft_guard is not None else 1.0),
        }

    def _save_step_checkpoint(self, epoch: int, step_in_epoch: int) -> None:
        """Mid-epoch (step-granular) checkpoint: --save-steps cadence and
        the preemption path.  ``step_in_epoch`` counts *completed* steps of
        ``epoch``; 0 completed steps degrade to the epoch-boundary form
        (previous epoch, step 0) so resume semantics stay uniform."""
        cfg = self.cfg
        if step_in_epoch > 0:
            e, ft = epoch, self._ft_record(epoch, step_in_epoch)
        else:
            e, ft = epoch - 1, self._ft_record(epoch - 1, 0)
        save_checkpoint(
            cfg.checkpoint_dir, self.state, e, cfg.arch, self.best_acc1,
            is_best=False, is_primary=self.ctx.is_primary,
            backend=cfg.ckpt_backend, metric=0.0, ft=ft,
        )
        if self.flight is not None:
            self.flight.event("checkpoint", self._global_step,
                              epoch=e, step_in_epoch=ft["step"])
        if self._keeper is not None:
            self._keeper.update(self.state, self._global_step)

    def _rollback(self, epoch: int, step_in_epoch: int) -> float:
        """Divergence recovery: restore the last-good host snapshot (the
        jitted step's in_shardings re-shard it next call) and back off the
        LR scale.  Returns the new scale for the caller's lr rebuild."""
        restored = None
        if self._keeper is not None and self._keeper.has_snapshot:
            self.state = self._keeper.restore()
            restored = self._keeper.step
        scale = self.ft_guard.note_rollback(self._global_step, restored)
        print(f"=> divergence rollback at epoch {epoch} step "
              f"{step_in_epoch}: restored state from global step "
              f"{restored}, lr scale now {scale:g}", flush=True)
        if self.flight is not None:
            # The rollback itself is forensic: snapshot the ring (the
            # `rollback` ft_event is already in it via attach_to_metrics).
            self.flight.dump("rollback")
        return scale

    def _emit_ledgers(self, batch, lr_arr) -> None:
        """AOT-compile the live train step once against the first batch's
        real shardings and itemize both opt-in receipts off that single
        lowering: the communication ledger (``--comm-ledger``) and the
        static HBM memory ledger (``--mem-ledger``).  The compile goes
        through ``analysis.lowering.aot_ledgers`` so it shares the
        process-wide compile counter (the tier-1 budget fence sees it)
        and, under ``--lowering-cache DIR``, persists the standard
        ``<step>.hlo``/``<step>.json`` artifact pair for post-hoc
        re-analysis; the cached metrics fields ride every subsequent
        ``log_step`` record."""
        from pytorch_distributed_tpu.analysis import lowering
        from pytorch_distributed_tpu.obs import comms

        cfg = self.cfg
        args = (self.state, batch, lr_arr)
        want_comm = bool(getattr(cfg, "comm_ledger", None))
        want_mem = bool(getattr(cfg, "mem_ledger", None))
        ledger, mled = lowering.aot_ledgers(
            self.train_step, args, step="train_step",
            mesh_shape=dict(self.mesh.shape), want_comm=want_comm,
            want_mem=want_mem,
            cache_dir=getattr(cfg, "lowering_cache", None))
        self._comm_fields = {}
        if ledger is not None:
            self._comm_fields.update(ledger.metrics_fields())
            if ledger.entries:
                top = max(ledger.entries, key=lambda e: e.wire_bytes)
                self._flight_coll = {"kind": top.kind, "bytes": top.bytes,
                                     "name": top.name}
            if self.ctx.process_index == 0:
                comms.write_ledgers(cfg.comm_ledger, [ledger])
                print(f"=> wrote comm ledger ({ledger.count} collectives, "
                      f"{ledger.total_bytes} B/step payload) to "
                      f"{cfg.comm_ledger}", flush=True)
        if mled is not None:
            from pytorch_distributed_tpu.obs import memory

            self._comm_fields.update(mled.metrics_fields())
            if self.ctx.process_index == 0:
                memory.write_ledgers(cfg.mem_ledger, [mled])
                print(f"=> wrote mem ledger (peak {mled.peak_bytes} B at "
                      f"instr {mled.peak_index}/{mled.n_instructions}) to "
                      f"{cfg.mem_ledger}", flush=True)

    def _book_stepattr_phases(self) -> None:
        """Feed the attribution recorder the comm ledger's measured wire
        bytes (when one ran — the estimate upgrade costs no compile) and
        book the static per-phase roofline ledger as a one-time
        ``stepattr_phases`` ft_event: per named_scope phase FLOPs/HBM
        bytes from the analytic StepCost plus the chip peaks, so the
        jax-free CLI never touches hardware tables."""
        if self.stepattr is None or self._stepattr_phases_booked:
            return
        self._stepattr_phases_booked = True
        from pytorch_distributed_tpu.obs import flops, stepattr

        cfg = self.cfg
        wire = float((self._comm_fields or {}).get("comm_wire_bytes", 0.0))
        if wire > 0:
            self.stepattr.set_comm_bytes(wire)
        try:
            cost = flops.image_step_cost(cfg.arch, cfg.batch_size,
                                         cfg.image_size, cfg.num_classes)
        except (KeyError, ValueError):
            return  # unregistered arch: attribution still runs, no roofline
        kind = getattr(self.mesh.devices.flat[0], "device_kind", "")
        prof = stepattr.phase_profile(
            cost.breakdown,
            stepattr.split_step_bytes(cost.bytes, cost.params),
            comm_bytes=wire,
            peak_flops=flops.chip_peak_flops(kind),
            hbm_bw=flops.chip_hbm_bw(kind),
            link_bw=flops.chip_link_bytes(kind),
            n_devices=self.mesh.devices.size)
        self.obs.log_event("stepattr_phases",
                           **stepattr.phase_event_fields(prof))

    def train_epoch(
        self, epoch: int, profiler: Optional[ProfileWindow] = None,
        start_step: int = 0,
    ) -> Tuple[int, bool]:
        """One epoch from ``start_step`` (0 except the first epoch of a
        mid-epoch resume).  Returns ``(completed_steps, preempted)`` so the
        epoch driver knows exactly where a preemption landed."""
        cfg = self.cfg
        if cfg.lr_schedule == "cosine":
            lr = cosine_lr(cfg.lr, epoch, cfg.epochs,
                           warmup_epochs=cfg.lr_warmup_epochs)
        elif cfg.lr_schedule == "step":
            lr = step_decay_lr(cfg.lr, epoch)
        else:  # argparse enforces choices; guard programmatic Configs too
            raise ValueError(
                f"unknown lr_schedule {cfg.lr_schedule!r}: "
                "expected 'step' or 'cosine'")
        meters = StepMeters(
            len(self.train_loader),
            [("loss", "Loss", ":.4e"), ("acc1", "Acc@1", ":6.2f"),
             ("acc5", "Acc@5", ":6.2f")],
            prefix=f"Epoch: [{epoch}]",
        )
        self.train_loader.set_epoch(epoch)
        self.val_sampler.set_epoch(epoch)
        scale = self.ft_guard.lr_scale if self.ft_guard is not None else 1.0
        lr_arr = jnp.float32(lr * scale * self._elastic_lr_scale)
        completed = start_step
        if self._keeper is not None and not self._keeper.has_snapshot:
            self._keeper.update(self.state, self._global_step)
        meters.restart_clock()
        # Global step this epoch's step 0 corresponds to — the anchor that
        # maps a StateKeeper (global-step) snapshot back to a step-in-epoch
        # when an elastic rewind lands mid-epoch.
        epoch_base = self._global_step - start_step
        epoch_len = len(self.train_loader)
        batch_iter = self.feeder(self.train_loader.iter_batches(start_step))
        i = start_step
        while i < epoch_len:
            if profiler is not None:
                profiler.step_begin(epoch, i)
            # Polled at print_freq cadence so the agreement collective (a
            # tiny any-rank-flagged all-reduce every rank runs at the same
            # step — signal skew across hosts must not break ranks at
            # different boundaries) stays off the per-step hot path.
            if (self.preempt is not None and i % cfg.print_freq == 0
                    and self._preempt_agreed()):
                return completed, True
            if self.chaos is not None:
                self.chaos.on_step(self, i)
            if self.elastic is not None:
                # Membership epochs are committed by the coordinator and
                # read by every rank at the same step — an agreed value,
                # not a local probe (synclint would otherwise flag the
                # re-mesh below as a rank-divergent collective path).
                chg = self.elastic.poll(self._global_step)  # synclint: agreement
                if chg is not None:
                    # Membership changed: rebuild against the survivor set
                    # and rewind to the snapshot step (the sampler's
                    # (seed, epoch) permutation regenerates the identical
                    # index stream, so replayed steps see the same data).
                    batch_iter.close()
                    resume_global = self._apply_remesh(chg, epoch)
                    self._global_step = resume_global
                    completed = i = max(0, resume_global - epoch_base)
                    epoch_len = len(self.train_loader)  # batch rescale
                    batch_iter = self.feeder(
                        self.train_loader.iter_batches(i))
                    lr_arr = jnp.float32(
                        lr * scale * self._elastic_lr_scale)
                    meters.restart_clock()
                    continue
            # Attribution windows (--step-attr): data_wait wraps batch
            # acquisition *and* the chaos on_batch hook, so an injected
            # loader delay (chaoskit drill slow-loader) lands in the
            # measured component by design.
            sa = self.stepattr
            _dw = sa.data_wait if sa is not None else nullcontext
            with _dw():
                batch = next(batch_iter, None)
            if batch is None:
                break
            if self.chaos is not None:
                with _dw():
                    batch = self.chaos.on_batch(i, batch)
            n = self.cfg.batch_size
            if ((getattr(cfg, "comm_ledger", None)
                    or getattr(cfg, "mem_ledger", None))
                    and self._comm_fields is None):
                self._emit_ledgers(batch, lr_arr)
            if self.flight is not None:
                # Ring: step window + collective region (labelled with the
                # ledger's dominant entry when the AOT lowering ran) —
                # two deque appends, no sync/I/O.
                self.flight.step_begin(self._global_step)
                fc = self._flight_coll or {}
                self.flight.coll_enter(self._global_step,
                                       kind=fc.get("kind"),
                                       bytes=fc.get("bytes"),
                                       name=fc.get("name"))
            if self.chaos is not None:
                self.chaos.on_collective(self, self._global_step)
            _dev = sa.device if sa is not None else nullcontext
            _hs = sa.host_sync if sa is not None else nullcontext
            with scope("train_step"), self._wd_watch("train_step",
                                                     self._global_step), \
                    _dev():
                self.state, metrics = self.train_step(self.state, batch, lr_arr)
                if sa is not None:
                    # The step's blocking transfer: without it, async
                    # dispatch smears step N's device time into N+1's
                    # windows and the identity stops meaning anything.
                    # Only when --step-attr opted in; overhead fenced
                    # <2% p50 in RESULTS_stepattr.json.
                    jax.block_until_ready(metrics)  # shardlint: allow-sync
            if self.flight is not None:
                self.flight.coll_exit(self._global_step)
                self.flight.step_end(self._global_step)
            completed = i + 1
            # Unready device scalars: meters and the metrics logger convert
            # lazily, so no per-step host sync (SURVEY.md §7.4 item 1).
            with _hs():
                dt = meters.update(metrics, n)
            extra = {"epoch": epoch}
            if self._mfu is not None:
                extra.update(self._mfu.fields(dt))
            if self._comm_fields:
                extra.update(self._comm_fields)
            if sa is not None:
                extra.update(sa.fields(dt))
            # The lazy-flush scalar drain inside log_step accrues to the
            # *next* step's host_sync window (its dt covers this wall
            # time), keeping the identity aligned.
            with _hs():
                self.obs.log_step(
                    self._global_step, step_time=dt, n_items=n, lr=lr,
                    scalars=dict(metrics),  # incl. norms when --metrics-jsonl
                    extra=extra,
                )
            # booked after the first step's record so the event's
            # timestamp cannot widen the post-hoc goodput wall span back
            # across the step-0 compile
            if sa is not None and not self._stepattr_phases_booked:
                self._book_stepattr_phases()
            if self.hb is not None:
                self.hb.beat(self._global_step, step_time_ema=self.obs.ema,
                             last_ft=self.obs.last_event_kind,
                             mem_bytes=sample_process_memory(),
                             data_wait_ms=(sa.data_wait_ema_ms
                                           if sa is not None else None))
                if self.flight is not None:
                    self.flight.heartbeat(
                        {"step": self._global_step,
                         "last_ft": self.obs.last_event_kind})
            self._global_step += 1
            meters.maybe_display(i, cfg.print_freq)
            at_save = (cfg.save_steps > 0 and completed % cfg.save_steps == 0
                       and completed < len(self.train_loader))
            if self.ft_guard is not None:
                # Flags buffer unconverted; drained every ft_check_every
                # steps (one amortized host sync) — forced before a
                # snapshot so it never races an undetected divergence.
                rollback = self.ft_guard.observe(
                    self._global_step - 1, metrics.get("nonfinite"))
                if at_save:
                    # The drained flag is the in-step all-reduced nonfinite
                    # count: every rank drains the identical value, so the
                    # rollback decision below is bulk-synchronous.
                    rollback = self.ft_guard.drain() or rollback  # synclint: agreement
                if rollback:
                    lr_arr = jnp.float32(lr * self._rollback(epoch, i)
                                         * self._elastic_lr_scale)
                # A flagged streak means the current state is suspect —
                # don't refresh the last-good snapshot/checkpoint from it.
                at_save = at_save and self.ft_guard.consecutive == 0
            if at_save:
                self._save_step_checkpoint(epoch, completed)
                meters.restart_clock()  # exclude checkpoint I/O from meter
            i += 1
        if self.ft_guard is not None and self.ft_guard.drain():  # synclint: agreement
            # Trailing flags (buffered past the last cadence point) must be
            # resolved before the epoch-end checkpoint can capture them.
            # Agreed: the flag drains an in-step all-reduced scalar.
            self._rollback(epoch, completed)
        return completed, False

    # ------------------------------------------------------------------ eval
    def validate(self) -> float:
        cfg = self.cfg
        batch_time = AverageMeter("Time", ":6.3f")
        losses = AverageMeter("Loss", ":.4e")
        top1 = AverageMeter("Acc@1", ":6.2f")
        top5 = AverageMeter("Acc@5", ":6.2f")
        progress = ProgressMeter(
            len(self.val_loader), [batch_time, losses, top1, top5], prefix="Test: "
        )
        totals = {"loss_sum": 0.0, "correct1": 0.0, "correct5": 0.0, "count": 0.0}
        end = time.time()
        for i, batch in enumerate(self.feeder(iter(self.val_loader))):
            with self._wd_watch("eval_step"):
                sums = self.eval_step(self.state, batch)
            c = float(sums["count"])
            if c > 0:
                losses.update(float(sums["loss_sum"]) / c, int(c))
                top1.update(float(sums["correct1"]) * 100.0 / c, int(c))
                top5.update(float(sums["correct5"]) * 100.0 / c, int(c))
            for k in totals:
                totals[k] += float(sums[k])
            batch_time.update(time.time() - end)
            end = time.time()
            if i % cfg.print_freq == 0:
                progress.display(i)
        count = max(totals["count"], 1.0)
        acc1 = totals["correct1"] * 100.0 / count
        acc5 = totals["correct5"] * 100.0 / count
        # Reference summary line (distributed.py:321-322).
        print(f" * Acc@1 {acc1:.3f} Acc@5 {acc5:.3f}", flush=True)
        return acc1

    # ------------------------------------------------------------------- fit
    def fit(self) -> float:
        """Train/eval driver with the unified observability surface (obs/):
        per-step meters + structured --metrics-jsonl records, per-epoch CSV,
        optional in-process device telemetry, per-process heartbeats
        (--hb-dir), and an optional XPlane profiler trace windowed by
        --profile-epochs/--profile-steps (the TPU-native upgrade of
        nvidia-smi sampling — open in TensorBoard's profile plugin)."""
        cfg = self.cfg
        if cfg.evaluate:
            return self.validate()
        if cfg.telemetry_csv and not getattr(self, "_telemetry_on", False):
            from pytorch_distributed_tpu.utils.telemetry import TelemetrySampler

            # Registered (not started ad hoc): obs.close() stops it.
            self.obs.register(TelemetrySampler(cfg.telemetry_csv))
            self._telemetry_on = True
        import threading

        from pytorch_distributed_tpu.utils.preempt import (
            PreemptionGuard,
            parse_signals,
        )

        # Default guard: cfg.preempt_signals (SIGTERM, the pod-reclaim
        # grace signal, by default; '--preempt-signals term,int' adds
        # Ctrl-C for interactive runs) triggers a checkpoint-and-exit at
        # the next safe boundary (SURVEY §5.3 upgrade).  Callers may pass
        # their own guard to Trainer().  Signal handlers are
        # main-thread-only in Python, so off-main-thread fit() callers
        # simply run unguarded unless they pass one in.
        installed = (self.preempt is None
                     and threading.current_thread() is threading.main_thread())
        if installed:
            self.preempt = PreemptionGuard(
                signals=parse_signals(cfg.preempt_signals)).install()
        if self.watchdog is not None:
            self.watchdog.install()  # idempotent (re-fit after a fit)
        if self._exporter is not None and not self._exporter.running:
            # A prior fit's obs.close() stopped the owned exporter;
            # re-register so this fit serves (and tears down) again.
            self.obs.register(self._exporter)
        # Flight recorder death paths: signal-dump chain (installed after
        # the preemption guard so the dump happens first, then chains to
        # it) + the collective-hang watchdog daemon.
        flight_sig = None
        if self.flight is not None:
            if threading.current_thread() is threading.main_thread():
                from pytorch_distributed_tpu.obs.flightrec import (
                    FlightSignalDump,
                )

                flight_sig = FlightSignalDump(
                    self.flight,
                    signals=parse_signals(cfg.preempt_signals)).install()
            if self._hang_wd is not None:
                self._hang_wd.start()
        try:
            return self._fit_epochs()
        except BaseException as e:
            if self.flight is not None:
                from pytorch_distributed_tpu.ft.integrity import (
                    CheckpointCorruptError,
                )

                self.flight.record("exception", self._global_step,
                                   error=type(e).__name__)
                self.flight.dump("checkpoint_corrupt"
                                 if isinstance(e, CheckpointCorruptError)
                                 else f"exception:{type(e).__name__}")
            raise
        finally:
            if installed:
                self.preempt.uninstall()
                self.preempt = None
            if self._hang_wd is not None:
                self._hang_wd.stop()
            if flight_sig is not None:
                flight_sig.uninstall()
            if self.watchdog is not None:
                self.watchdog.uninstall()
            if self.hb is not None:
                self.hb.close(max(0, self._global_step - 1),
                              step_time_ema=self.obs.ema,
                              last_ft=self.obs.last_event_kind,
                              mem_bytes=sample_process_memory(),
                              data_wait_ms=(self.stepattr.data_wait_ema_ms
                                            if self.stepattr is not None
                                            else None))
            self.obs.flush()
            if self._goodput is not None:
                print(f"=> {self._goodput.format_summary()}", flush=True)
            self.obs.close()  # flush JSONL, stop registered telemetry
            self._telemetry_on = False

    def _preempt_agreed(self) -> bool:
        """Cross-process 'any rank flagged?' — see utils/preempt.py.  Every
        rank must call this at the same loop boundary (it runs a collective
        on multi-process meshes)."""
        if self._agree is None:
            from pytorch_distributed_tpu.utils.preempt import (
                PreemptionAgreement,
            )

            self._agree = PreemptionAgreement(self.mesh, self.data_axis)
        return self._agree(self.preempt.triggered)

    def _fit_epochs(self) -> float:
        cfg = self.cfg
        profiler = ProfileWindow(cfg.profile_dir, epochs=cfg.profile_epochs,
                                 steps=cfg.profile_steps,
                                 start_epoch=cfg.start_epoch)
        for epoch in range(cfg.start_epoch, cfg.epochs):
            self.obs.epoch_start()
            profiler.epoch_begin(epoch)
            # Mid-epoch resume: the first epoch starts at the checkpointed
            # step offset — the sampler's (seed, epoch) permutation
            # regenerates the identical index stream, and the loader skips
            # the already-trained prefix by index arithmetic.
            start_step = (self._resume_step
                          if epoch == cfg.start_epoch else 0)
            completed, preempted = self.train_epoch(epoch, profiler,
                                                    start_step=start_step)
            jax.block_until_ready(self.state.params)
            if profiler.epoch_end():
                print(f"=> wrote profiler trace to '{cfg.profile_dir}'")
            if not preempted and (self.preempt is not None
                                  and self._preempt_agreed()):
                preempted = True  # signal landed between last poll and here
            if preempted:
                # Step-granular preemption checkpoint: the ft record pins
                # the exact completed step, so --resume continues from it —
                # no epoch rerun (the pre-FT behavior threw away up to a
                # whole epoch here).
                print(f"=> preemption signal: checkpointing at epoch "
                      f"{epoch} step {completed} and exiting", flush=True)
                self.obs.log_event("preempt", step=self._global_step,
                                   epoch=epoch, step_in_epoch=completed)
                self._save_step_checkpoint(epoch, completed)
                break
            acc1 = self.validate()
            elapsed = self.obs.epoch_end()  # drives the registered epoch CSV
            print(f"Epoch {epoch} took {elapsed:.1f}s", flush=True)
            is_best = acc1 > self.best_acc1
            self.best_acc1 = max(acc1, self.best_acc1)
            save_checkpoint(
                cfg.checkpoint_dir,
                self.state,
                epoch,
                cfg.arch,
                self.best_acc1,
                is_best,
                is_primary=self.ctx.is_primary,
                backend=cfg.ckpt_backend,
                metric=acc1,  # this epoch's own score (orbax best retention)
                ft=self._ft_record(epoch, 0),
            )
            if self._keeper is not None:
                self._keeper.update(self.state, self._global_step)
        if cfg.ckpt_backend == "orbax":
            from pytorch_distributed_tpu.train.checkpoint import (
                wait_for_async_saves,
            )

            wait_for_async_saves()
        return self.best_acc1
