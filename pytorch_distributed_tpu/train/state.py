"""Training state: params + BN statistics + SGD momentum, as one pytree.

The TPU-native analogue of the reference's (model, optimizer) pair
(reference distributed.py:134-156): a single immutable pytree that flows
through the jitted step function and is donated each step, so parameter
updates happen in-place in device memory.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax.numpy as jnp

Pytree = Any


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray          # global step counter (int32 scalar)
    params: Pytree             # f32 master weights
    batch_stats: Pytree        # BatchNorm running mean/var (f32)
    momentum: Pytree           # SGD momentum buffers (f32, params-shaped)

    @classmethod
    def create(cls, variables: Pytree, momentum: Pytree) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=variables["params"],
            batch_stats=variables.get("batch_stats", {}),
            momentum=momentum,
        )
