"""Training state: params + BN statistics + SGD momentum, as one pytree.

The TPU-native analogue of the reference's (model, optimizer) pair
(reference distributed.py:134-156): a single immutable pytree that flows
through the jitted step function and is donated each step, so parameter
updates happen in-place in device memory.
"""

from __future__ import annotations

from typing import Any

import dataclasses

import flax.struct
import jax.numpy as jnp

Pytree = Any


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray          # global step counter (int32 scalar)
    params: Pytree             # f32 master weights
    batch_stats: Pytree        # BatchNorm running mean/var (f32)
    # SGD momentum buffers.  Three layouts flow through this field:
    # params-shaped f32 (replicated DP, and GSPMD --zero wus where only the
    # sharding changes); the explicit --zero wus stacked-chunk dict
    # {"buf": (n_data, chunk) leaves[, "agerr": ...]} sharded P("data")
    # (parallel/zero.py — checkpoints always store the param-shaped view);
    # or an optax opt_state when a tx is supplied.
    momentum: Pytree
    # Error-feedback residuals for quantized gradient sync (ops/qcomm.py):
    # empty for grad_compress none/bf16; params-shaped f32 under GSPMD
    # emulation; stacked (n_data, *shape) sharded over the data axis under
    # explicit collectives.  Defaulted so positional construction and old
    # checkpoints keep working.
    residual: Pytree = dataclasses.field(default_factory=dict)

    @classmethod
    def create(cls, variables: Pytree, momentum: Pytree,
               residual: Pytree = None) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=variables["params"],
            batch_stats=variables.get("batch_stats", {}),
            momentum=momentum,
            residual={} if residual is None else residual,
        )
