"""CLI / config: the reference's 15-flag surface as one dataclass.

Flag names, shorthands, and defaults mirror reference distributed.py:25-102
(``--data -a -j --epochs --start-epoch -b --lr --momentum --wd -p -e
--pretrained --seed``), with the reference's per-recipe extras available as
opt-ins (``--dist-file`` from distributed_slurm_main.py:102-105) and
TPU-native additions the recipes need:

- ``--precision {fp32,bf16}``   — the apex-AMP slot (SURVEY.md §7.1)
- ``--synthetic``               — synthetic dataset (no ImageNet on disk)
- ``--image-size``              — train crop size (default 224)
- ``--resume PATH``             — the load path the reference lacks (§5.3)
- ``--checkpoint-dir``          — where checkpoints land

Like the reference, the global batch is divided by world size in the driver
(reference distributed.py:146), not here.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional

from pytorch_distributed_tpu import models


@dataclasses.dataclass
class Config:
    data: str = "/home/zhangzhi/Data/exports/ImageNet2012"
    arch: str = "resnet18"
    workers: int = 4
    worker_type: str = "thread"   # "thread" | "process" (GIL-proof PIL path)
    epochs: int = 90
    start_epoch: int = 0
    batch_size: int = 3200        # GLOBAL batch (reference semantics)
    lr: float = 0.1
    # "step" = the reference's adjust_learning_rate (0.1x every 30 epochs,
    # distributed.py:374-378); "cosine" = warmup+cosine over --epochs.
    lr_schedule: str = "step"
    lr_warmup_epochs: int = 0
    momentum: float = 0.9
    weight_decay: float = 1e-4
    print_freq: int = 10
    evaluate: bool = False
    pretrained: bool = False
    seed: Optional[int] = None
    # per-recipe extras / TPU-native additions
    dist_file: Optional[str] = None
    # None = "recipe decides" (apex/tpu_native default to bf16); an explicit
    # --precision flag always wins over the recipe default.
    precision: Optional[str] = None
    synthetic: bool = False
    synthetic_length: int = 1280
    wire: str = "f32"
    # Gradient wire format for the DP sync (ops/qcomm.py): bf16 casts the
    # psum operand (the old wire_dtype knob); int8/fp8 run the per-block
    # quantized all-reduce with error feedback.  None = "recipe decides"
    # (horovod defaults to bf16), mirroring the precision convention.
    grad_compress: Optional[str] = None
    # ZeRO-style weight-update sharding (parallel/zero.py): "wus" shards the
    # SGD momentum 1/N over the data axis, reduce-scatters gradients, and
    # all-gathers the parameter delta once per step — (N-1)/N of the
    # optimizer+synced-gradient bytes reclaimed per device at equal wire
    # cost.  None = "recipe decides" (all recipes currently default to the
    # replicated-DP "none"), mirroring the grad_compress convention.
    zero: Optional[str] = None
    # Comm-overlap scheduler (parallel/overlap.py): "bucketed" splits the
    # explicit grad sync into ~bucket_mb-MiB reverse-autodiff buckets so
    # each bucket's collective can run concurrently with the remaining
    # backward (bit-equal numerics; requires the explicit-collectives step).
    overlap: str = "none"
    bucket_mb: float = 4.0
    accum_steps: int = 1
    local_rank: int = -1  # launch-line parity only; unused on TPU
    image_size: int = 224
    num_classes: int = 1000
    # ResNet stem variant: "space_to_depth" is the MLPerf-style packed stem
    # (identical math/params, faster MXU tiling); other archs ignore it.
    stem: str = "conv7"
    # Fold BN-backward dx into the 1x1 dgrad/wgrad via the Pallas fused
    # kernel (ops/fused_conv_bn.py); ResNet bottleneck family only.
    fused_convbn: bool = False
    # Cross-replica SyncBN for the explicit-collectives (shard_map) step:
    # psum the BN moments over the data axis so statistics cover the
    # global batch, matching GSPMD's implicit semantics.  ≙ torch
    # nn.SyncBatchNorm — the capability torch users reach for at small
    # per-device batch.  No effect under GSPMD (already synced).
    sync_bn: bool = False
    # LM-family loss head (recipes/lm_pretrain.py forwards these): chunked
    # fused tied-head+CE (ops/fused_ce.py) and its sharding variant —
    # auto picks dp/tp from the mesh + param specs (resolve_fused_ce_mode).
    fused_ce_chunks: int = 0
    fused_ce_mode: str = "auto"
    resume: Optional[str] = None
    # Default under runs/ so checkpoints never land in the repo root
    # (workspace-hygiene; save_checkpoint creates the directory).
    checkpoint_dir: str = "runs"
    ckpt_backend: str = "msgpack"
    # Fault tolerance (ft/): mid-epoch checkpoint cadence (0 = epoch
    # boundaries only — a preemption then loses the partial epoch; N > 0
    # bounds the loss to N steps even under SIGKILL), the in-graph
    # non-finite guard with its rollback policy, and which signals the
    # preemption guard traps.
    save_steps: int = 0
    nan_guard: bool = False
    ft_rollback_k: int = 3
    ft_check_every: int = 10
    ft_lr_backoff: float = 0.5
    preempt_signals: str = "term"
    # Elastic training (ft/elastic.py): re-mesh on rank loss/join and
    # re-shard state from the last-good snapshot.  min_ranks is the shrink
    # floor; rescale_lr picks the LR/global-batch rule across a world
    # change ("none" holds the global batch constant and the LR untouched;
    # "linear"/"sqrt" hold the per-rank batch constant and scale the LR).
    elastic: bool = False
    min_ranks: int = 1
    rescale_lr: str = "none"
    epoch_csv: Optional[str] = None
    profile_dir: Optional[str] = None
    # Profiler capture windows (obs/trace.py ProfileWindow): 'E' or 'A:B'
    # epochs, optionally narrowed to an in-epoch 'I' or 'I:J' step range —
    # steady-state traces instead of the warm-up-only epoch-0 capture.
    profile_epochs: Optional[str] = None
    profile_steps: Optional[str] = None
    telemetry_csv: Optional[str] = None
    # Unified observability (obs/): one structured JSON record per train
    # step, and per-process heartbeats for cross-process straggler
    # detection (scripts/obs_report.py folds all of it into one summary).
    metrics_jsonl: Optional[str] = None
    hb_dir: Optional[str] = None
    hb_interval_s: float = 5.0
    # Efficiency accounting (obs/flops.py, obs/goodput.py, obs/watchdog.py):
    # per-step MFU/HFU from the analytic FLOPs model, the live goodput/
    # badput ledger, and the jax.monitoring recompile watchdog.
    mfu: bool = False
    goodput: bool = False
    watch_recompiles: bool = False
    # Communication ledger (obs/comms.py): AOT-compile the step once at
    # fit() start, itemize every collective (bytes/fan-out/scope), write
    # the ledger JSON next to the run, and stamp model_comm_bytes /
    # comm_wire_bytes / collective_count into each metrics record.
    # Opt-in because the AOT lowering does not share the jit call cache
    # in jax 0.4.x — it costs one extra compile of the step.
    comm_ledger: Optional[str] = None
    # Memory ledger (obs/memory.py): static per-device HBM watermark from
    # the same AOT lowering as the comm ledger (one shared compile for
    # both), with top-buffers-at-peak attribution and class/phase
    # breakdown written as JSON next to the run.
    mem_ledger: Optional[str] = None
    # Lowering-service artifact dir (analysis/lowering.py): the ledger
    # AOT compile additionally persists the step's <name>.hlo/<name>.json
    # pair here so post-hoc tooling re-analyzes text instead of
    # recompiling.
    lowering_cache: Optional[str] = None
    # Flight recorder (obs/flightrec.py): per-rank bounded event ring
    # dumped to flightrec_rank<k>.json in this directory on any death
    # path (signal / rollback / checkpoint corruption / unhandled fit
    # exception / hang watchdog); merge with scripts/postmortem.py.
    flight_rec: Optional[str] = None
    # Collective-hang watchdog floor: a step exceeding
    # max(hang_timeout, 4×p95) triggers a `hang` ft_event + pre-mortem
    # ring dump.  Only active with flight_rec set.
    hang_timeout: float = 30.0
    # Live telemetry plane (obs/export.py): serve the latest drained
    # metrics record as Prometheus text exposition on this port (rank k
    # binds metrics_port + k).  0 = off.  Scrape with scripts/obs_live.py.
    metrics_port: int = 0
    # Declarative alert rules (obs/alerts.py): a JSON rules file, or the
    # literal "default" for the built-in anchor-free set.  Firing alerts
    # are booked as `alert` ft_events in the metrics JSONL.
    alerts: Optional[str] = None
    # Exact per-step wall-time attribution (obs/stepattr.py): stamp
    # attr_* component fields into every metrics record and carry a
    # data_wait EMA in heartbeats.  Costs one explicit block per step
    # (<2% step p50) — the price of the identity closing exactly.
    step_attr: bool = False
    # derived at runtime (reference args.nprocs, distributed.py:114)
    nprocs: int = 1


def build_parser(description: str = "TPU ImageNet Training") -> argparse.ArgumentParser:
    d = Config()
    names = models.model_names()
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--data", metavar="DIR", default=d.data, help="path to dataset")
    p.add_argument("-a", "--arch", metavar="ARCH", default=d.arch, choices=names,
                   help="model architecture: " + " | ".join(names) + f" (default: {d.arch})")
    p.add_argument("-j", "--workers", default=d.workers, type=int, metavar="N",
                   help="number of data loading workers (default: 4)")
    p.add_argument("--worker-type", default=d.worker_type,
                   choices=("thread", "process"), dest="worker_type",
                   help="loader workers: threads (native decode path) or "
                        "spawned processes (GIL-proof Python/PIL decode, "
                        "reference DataLoader worker semantics)")
    p.add_argument("--epochs", default=d.epochs, type=int, metavar="N",
                   help="number of total epochs to run")
    p.add_argument("--start-epoch", default=d.start_epoch, type=int, metavar="N",
                   help="manual epoch number (useful on restarts)")
    p.add_argument("-b", "--batch-size", default=d.batch_size, type=int, metavar="N",
                   help="mini-batch size: total batch size across all chips")
    p.add_argument("--lr", "--learning-rate", default=d.lr, type=float,
                   metavar="LR", help="initial learning rate", dest="lr")
    p.add_argument("--lr-schedule", default=d.lr_schedule,
                   choices=("step", "cosine"), dest="lr_schedule",
                   help="step = reference 0.1x-every-30-epochs decay; "
                   "cosine = warmup+cosine over --epochs")
    p.add_argument("--lr-warmup-epochs", default=d.lr_warmup_epochs, type=int,
                   dest="lr_warmup_epochs",
                   help="linear LR warmup epochs (cosine schedule)")
    p.add_argument("--momentum", default=d.momentum, type=float, metavar="M",
                   help="momentum")
    p.add_argument("--wd", "--weight-decay", default=d.weight_decay, type=float,
                   metavar="W", help="weight decay (default: 1e-4)", dest="weight_decay")
    p.add_argument("-p", "--print-freq", default=d.print_freq, type=int, metavar="N",
                   help="print frequency (default: 10)")
    p.add_argument("-e", "--evaluate", dest="evaluate", action="store_true",
                   help="evaluate model on validation set")
    p.add_argument("--pretrained", dest="pretrained", action="store_true",
                   help="use pre-trained model")
    p.add_argument("--seed", default=d.seed, type=int,
                   help="seed for initializing training.")
    p.add_argument("--dist-file", default=d.dist_file, type=str,
                   help="rendezvous file for multi-host bootstrap (slurm recipe)")
    p.add_argument("--precision", default=d.precision, choices=("fp32", "bf16"),
                   help="compute precision policy (bf16 = apex-AMP slot); "
                   "unset = recipe default")
    p.add_argument("--synthetic", action="store_true",
                   help="use a synthetic dataset instead of --data")
    p.add_argument("--synthetic-length", default=d.synthetic_length, type=int,
                   help="samples per synthetic epoch")
    p.add_argument("--image-size", default=d.image_size, type=int,
                   help="train crop size (default 224)")
    p.add_argument("--num-classes", default=d.num_classes, type=int,
                   help="number of classes (synthetic mode; ImageFolder infers)")
    p.add_argument("--accum-steps", default=d.accum_steps, type=int,
                   help="split each batch into N microbatches, accumulate "
                   "gradients in-graph, apply one update (fits the default "
                   "global batch 3200 on small chip counts)")
    p.add_argument("--local_rank", default=-1, type=int,
                   help="accepted for reference launch-line parity "
                   "(distributed.py:73-76); process identity on TPU comes "
                   "from PTD_TPU_PROCESS_ID / pod metadata instead")
    p.add_argument("--wire", default=d.wire,
                   choices=("f32", "u8host", "u8", "native"),
                   help="input pipeline format: f32 = per-sample normalize "
                   "(reference-shaped); u8host = native C++ batch "
                   "flip+normalize; u8 = uint8 over the wire, normalize on "
                   "device (4x fewer host->device bytes); native = C++ JPEG "
                   "decode+crop+resize AND uint8 wire (full native path)")
    p.add_argument("--grad-compress", default=d.grad_compress,
                   choices=("none", "bf16", "int8", "fp8"),
                   dest="grad_compress",
                   help="gradient wire format for the DP sync: bf16 casts "
                   "the all-reduce operand (Horovod fp16-compression "
                   "analogue); int8/fp8 = per-block quantized all-reduce "
                   "with error feedback (ops/qcomm.py) — true wire "
                   "compression on the explicit-collectives step, numerics "
                   "emulation under GSPMD; unset = recipe default")
    p.add_argument("--zero", default=d.zero, choices=("none", "wus"),
                   help="ZeRO-style weight-update sharding "
                   "(arXiv:2004.13336): wus reduce-scatters gradients, "
                   "keeps optimizer state sharded 1/N over the data axis, "
                   "updates on the shard, and all-gathers the parameter "
                   "delta — ~(N-1)/N of optimizer+gradient bytes reclaimed "
                   "per device; composes with --grad-compress (both wire "
                   "hops quantized); unset = recipe default (none)")
    p.add_argument("--overlap", default=d.overlap,
                   choices=("none", "bucketed"),
                   help="comm-overlap scheduler (parallel/overlap.py): "
                   "bucketed splits the explicit grad sync into "
                   "~--bucket-mb MiB reverse-autodiff buckets issued as "
                   "separate collectives that overlap the remaining "
                   "backward; bit-equal numerics (requires the "
                   "explicit-collectives step — horovod recipe, or "
                   "lm_pretrain pure-DP)")
    p.add_argument("--bucket-mb", default=d.bucket_mb, type=float,
                   dest="bucket_mb", metavar="MIB",
                   help="target gradient bucket size in MiB for --overlap "
                   "bucketed (smaller = more overlap, more collectives)")
    p.add_argument("--resume", default=d.resume, type=str, metavar="PATH",
                   help="path to checkpoint to resume from")
    p.add_argument("--checkpoint-dir", default=d.checkpoint_dir, type=str,
                   help="directory for checkpoint files")
    p.add_argument("--ckpt-backend", default=d.ckpt_backend,
                   choices=("msgpack", "orbax"), dest="ckpt_backend",
                   help="msgpack = single-file portable (default); orbax = "
                   "async sharded per-process writes (multi-host TP/SP scale)")
    p.add_argument("--save-steps", default=d.save_steps, type=int,
                   dest="save_steps", metavar="N",
                   help="also checkpoint every N train steps (step-granular "
                   "resume: preemption/SIGKILL loses at most N steps instead "
                   "of the whole epoch); 0 = epoch boundaries only")
    p.add_argument("--nan-guard", action="store_true", dest="nan_guard",
                   help="divergence guard: detect non-finite loss/grad-norm "
                   "inside the compiled step, skip the bad batch's update, "
                   "and after --ft-rollback-k consecutive bad steps roll "
                   "back to the last-good state with an LR backoff")
    p.add_argument("--ft-rollback-k", default=d.ft_rollback_k, type=int,
                   dest="ft_rollback_k", metavar="K",
                   help="consecutive non-finite steps before the guard "
                   "rolls back (default 3)")
    p.add_argument("--ft-check-every", default=d.ft_check_every, type=int,
                   dest="ft_check_every", metavar="N",
                   help="drain the guard's buffered non-finite flags every "
                   "N steps — one amortized host sync, never per step "
                   "(default 10)")
    p.add_argument("--ft-lr-backoff", default=d.ft_lr_backoff, type=float,
                   dest="ft_lr_backoff", metavar="F",
                   help="multiply the LR by this factor at each rollback "
                   "(default 0.5)")
    p.add_argument("--preempt-signals", default=d.preempt_signals, type=str,
                   dest="preempt_signals", metavar="SIGS",
                   help="comma-separated signals the preemption guard traps "
                   "(default 'term'; add 'int' for interactive Ctrl-C runs, "
                   "e.g. 'term,int')")
    p.add_argument("--elastic", action="store_true", dest="elastic",
                   help="elastic training (ft/elastic.py): on rank loss "
                   "re-mesh to the survivors and continue from the "
                   "last-good snapshot; on rank join re-shard and re-admit "
                   "— every shrink/grow is a 'remesh' ft_event the goodput "
                   "ledger books")
    p.add_argument("--min-ranks", default=d.min_ranks, type=int,
                   dest="min_ranks", metavar="N",
                   help="elastic shrink floor: refuse membership changes "
                   "that would take the data axis below N ranks "
                   "(default 1)")
    p.add_argument("--rescale-lr", default=d.rescale_lr,
                   choices=("none", "linear", "sqrt"), dest="rescale_lr",
                   help="LR/global-batch rule across an elastic world "
                   "change: none = hold the global batch constant, LR "
                   "untouched (parity default); linear/sqrt = hold the "
                   "per-rank batch constant and scale the LR by (new/old) "
                   "or sqrt(new/old)")
    p.add_argument("--epoch-csv", default=d.epoch_csv, type=str,
                   help="append [timestamp, epoch_seconds] rows to this CSV")
    p.add_argument("--profile-dir", default=d.profile_dir, type=str,
                   help="write an XPlane/TensorBoard profiler trace of the "
                   "first trained epoch of this run to this directory "
                   "(narrow the window with --profile-epochs/--profile-steps)")
    p.add_argument("--profile-epochs", default=d.profile_epochs, type=str,
                   dest="profile_epochs", metavar="E[:F]",
                   help="epoch window to trace under --profile-dir "
                   "('2' or '2:4'); default: the first trained epoch")
    p.add_argument("--profile-steps", default=d.profile_steps, type=str,
                   dest="profile_steps", metavar="I[:J]",
                   help="in-epoch step window narrowing the trace to steady "
                   "state ('10' or '10:20'); default: whole epoch")
    p.add_argument("--metrics-jsonl", default=d.metrics_jsonl, type=str,
                   dest="metrics_jsonl", metavar="PATH",
                   help="append one structured JSON record per train step "
                   "(wall time, step-time EMA/p50/p95/max, throughput, "
                   "loss, lr, in-graph grad/param norms) to this file; "
                   "summarize with scripts/obs_report.py")
    p.add_argument("--hb-dir", default=d.hb_dir, type=str, dest="hb_dir",
                   metavar="DIR",
                   help="shared heartbeat directory: each mesh process "
                   "appends {pid, step, t} beats; scripts/obs_report.py "
                   "flags stragglers by step lag / beat age")
    p.add_argument("--hb-interval", default=d.hb_interval_s, type=float,
                   dest="hb_interval_s", metavar="SEC",
                   help="minimum seconds between heartbeats (default 5)")
    p.add_argument("--mfu", action="store_true",
                   help="report per-step MFU/HFU in the metrics JSONL: the "
                   "analytic FLOPs model for the arch (obs/flops.py, "
                   "cross-checked against XLA cost_analysis) over the "
                   "chip's peak; supported for the ResNet and ViT families")
    p.add_argument("--goodput", action="store_true",
                   help="track the goodput/badput ledger live (nan-skips, "
                   "rollback discards, preemption gaps, recompiles, "
                   "stalls) and print the summary at end of fit; the "
                   "post-hoc equivalent is scripts/obs_report.py over "
                   "--metrics-jsonl")
    p.add_argument("--watch-recompiles", action="store_true",
                   dest="watch_recompiles",
                   help="recompile watchdog (obs/watchdog.py): count XLA "
                   "compilations per jitted step-fn via jax.monitoring and "
                   "flag any recompilation after warmup as an anomaly "
                   "event in the metrics JSONL")
    p.add_argument("--comm-ledger", default=d.comm_ledger, type=str,
                   dest="comm_ledger", metavar="PATH",
                   help="write the step's itemized communication ledger "
                   "(per-collective bytes, replica-group fan-out, scope "
                   "attribution; obs/comms.py) to PATH and stamp "
                   "model_comm_bytes/comm_wire_bytes/collective_count "
                   "into each metrics record; costs one extra AOT compile "
                   "of the step")
    p.add_argument("--mem-ledger", default=d.mem_ledger, type=str,
                   dest="mem_ledger", metavar="PATH",
                   help="write the step's static HBM memory ledger "
                   "(per-instruction live-range watermark, top buffers at "
                   "the high-water mark, params/opt-state/activations/"
                   "collective breakdown; obs/memory.py) to PATH and stamp "
                   "mem_peak_bytes into each metrics record; rides the "
                   "--comm-ledger AOT lowering, so together they cost one "
                   "extra compile, not two")
    p.add_argument("--lowering-cache", default=d.lowering_cache, type=str,
                   dest="lowering_cache", metavar="DIR",
                   help="persist the ledger AOT lowering's artifacts "
                   "(<step>.hlo + <step>.json: HLO text, mesh shape, "
                   "measured peak, arg classes; analysis/lowering.py "
                   "layout) under DIR for post-hoc text-only re-analysis")
    p.add_argument("--flight-rec", default=d.flight_rec, type=str,
                   dest="flight_rec", metavar="DIR",
                   help="flight recorder (obs/flightrec.py): keep a "
                   "bounded in-memory ring of step/collective/ft events "
                   "(~zero hot-path cost) and dump it to DIR/"
                   "flightrec_rank<k>.json on any death path — signal, "
                   "rollback, checkpoint corruption, unhandled exception, "
                   "or the collective-hang watchdog; merge dumps with "
                   "scripts/postmortem.py")
    p.add_argument("--hang-timeout", default=d.hang_timeout, type=float,
                   dest="hang_timeout", metavar="SEC",
                   help="hang-watchdog floor: flag a step exceeding "
                   "max(SEC, 4×p95 of completed steps), emit a `hang` "
                   "ft_event with the last-entered collective, and dump "
                   "the flight ring pre-mortem (needs --flight-rec)")
    p.add_argument("--metrics-port", default=d.metrics_port, type=int,
                   dest="metrics_port", metavar="PORT",
                   help="serve live Prometheus metrics on PORT + rank "
                   "(one daemon thread per rank, latest drained record; "
                   "0 disables; watch the fleet with scripts/obs_live.py)")
    p.add_argument("--alerts", default=d.alerts, type=str, dest="alerts",
                   metavar="RULES",
                   help="declarative alert rules: a JSON rules file or "
                   "'default' for the built-in set (obs/alerts.py); "
                   "firing alerts are booked as `alert` ft_events in the "
                   "metrics JSONL and exported to /metrics")
    p.add_argument("--step-attr", action="store_true",
                   default=d.step_attr, dest="step_attr",
                   help="exact per-step wall-time attribution "
                   "(obs/stepattr.py): stamp attr_* fields — compute / "
                   "exposed_comm / host_sync / data_wait / other, summing "
                   "to step_time exactly — into every metrics record; "
                   "analyze with scripts/obs_roofline.py")
    p.add_argument("--telemetry-csv", default=d.telemetry_csv, type=str,
                   help="sample device memory stats to this CSV every 500ms "
                   "during training (statistics.sh-in-process)")
    p.add_argument("--stem", default=d.stem,
                   choices=("conv7", "space_to_depth"),
                   help="ResNet stem: torchvision conv7 or the numerically "
                   "identical space-to-depth packing (TPU MXU-friendly)")
    p.add_argument("--fused-convbn", action="store_true", dest="fused_convbn",
                   help="fuse BN-backward dx into the bottleneck conv "
                   "dgrad/wgrad (Pallas, 1x1 + stride-1 3x3; dy never hits "
                   "HBM); checkpoints stay interchangeable with the "
                   "unfused model")
    p.add_argument("--fused-ce", default=d.fused_ce_chunks, type=int,
                   metavar="CHUNKS", dest="fused_ce_chunks",
                   help="LM family: fused tied-head+CE loss in CHUNKS row "
                   "blocks (ops/fused_ce.py); 0 = unfused logits head")
    p.add_argument("--fused-ce-mode", default=d.fused_ce_mode,
                   choices=("auto", "replicated", "dp", "tp"),
                   dest="fused_ce_mode",
                   help="fused-CE sharding variant: dp keeps the backward's "
                   "dE accumulator vocab-row-sharded over the data axis; tp "
                   "consumes the Megatron vocab-sharded embedding directly; "
                   "auto picks from the mesh + param specs")
    p.add_argument("--sync-bn", action="store_true", dest="sync_bn",
                   help="cross-replica BatchNorm for the explicit-"
                   "collectives step: psum the batch moments over the data "
                   "axis (global-batch statistics, = torch SyncBatchNorm); "
                   "GSPMD runs already have this semantics implicitly")
    return p


def parse_config(argv=None, description: str = "TPU ImageNet Training") -> Config:
    args = build_parser(description).parse_args(argv)
    return Config(**{k: v for k, v in vars(args).items()})
