"""Running-average meters and the progress row printer.

Capability parity with the reference's ``AverageMeter`` / ``ProgressMeter``
(reference distributed.py:333-371): named running val/avg with a format
string, and a ``[ i/N]``-prefixed, tab-joined progress row.

TPU-first delta: ``update()`` accepts jax scalars lazily — values are only
converted to Python floats at display/read time, so per-step device→host
syncs (the reference's three ``.item()`` calls per batch,
distributed.py:262-264) never happen in the hot loop.
"""

from __future__ import annotations

import time
from typing import Iterable, List, Sequence, Tuple


def _to_float(v) -> float:
    # jax.Array / numpy scalar / python number all land here; float() blocks
    # until the value is ready, which is why meters defer it to read time.
    return float(v)


class AverageMeter:
    """Tracks current value, running sum/count, and average."""

    def __init__(self, name: str, fmt: str = ":f"):
        self.name = name
        self.fmt = fmt
        self.reset()

    def reset(self) -> None:
        self._pending: List[tuple] = []  # (value, n) possibly still on device
        self._sum = 0.0
        self._count = 0
        self._val = 0.0

    def update(self, val, n: int = 1) -> None:
        """Record a value; ``val`` may be an unready device scalar."""
        self._pending.append((val, n))

    def _drain(self) -> None:
        for val, n in self._pending:
            v = _to_float(val)
            self._val = v
            self._sum += v * n
            self._count += n
        self._pending.clear()

    @property
    def val(self) -> float:
        self._drain()
        return self._val

    @property
    def avg(self) -> float:
        self._drain()
        return self._sum / self._count if self._count else 0.0

    @property
    def sum(self) -> float:
        self._drain()
        return self._sum

    @property
    def count(self) -> int:
        self._drain()
        return self._count

    def __str__(self) -> str:
        fmtstr = "{name} {val" + self.fmt + "} ({avg" + self.fmt + "})"
        return fmtstr.format(name=self.name, val=self.val, avg=self.avg)


class ProgressMeter:
    """Prints ``<prefix>[ i/N]\\t<meter>\\t<meter>…`` rows (reference :358-366)."""

    def __init__(self, num_batches: int, meters: Iterable[AverageMeter], prefix: str = ""):
        self.batch_fmtstr = self._batch_fmtstr(num_batches)
        self.meters = list(meters)
        self.prefix = prefix

    def display(self, batch: int) -> str:
        entries = [self.prefix + self.batch_fmtstr.format(batch)]
        entries += [str(m) for m in self.meters]
        line = "\t".join(entries)
        print(line, flush=True)
        return line

    @staticmethod
    def _batch_fmtstr(num_batches: int) -> str:
        num_digits = len(str(num_batches // 1))
        fmt = "{:" + str(num_digits) + "d}"
        return "[" + fmt + "/" + fmt.format(num_batches) + "]"


class StepMeters:
    """The step-loop instrumentation bundle: a batch-time meter, named
    metric meters fed from the step's metrics dict, and the reference-format
    progress row — the single copy of the loop previously duplicated between
    ``train/trainer.py`` and ``train/lm.py``.

    ``fields`` is an ordered sequence of ``(metrics_key, display_name,
    fmt)`` triples; ``update`` accepts the (possibly unready device) metrics
    dict and returns the host-measured step seconds so callers can feed the
    same number to ``obs.MetricsLogger``.
    """

    def __init__(self, num_batches: int,
                 fields: Sequence[Tuple[str, str, str]], prefix: str = ""):
        self.batch_time = AverageMeter("Time", ":6.3f")
        self._keys = [k for k, _, _ in fields]
        self.meters = {k: AverageMeter(name, fmt) for k, name, fmt in fields}
        self.progress = ProgressMeter(
            num_batches, [self.batch_time, *self.meters.values()], prefix
        )
        self._end = time.time()

    def __getitem__(self, key: str) -> AverageMeter:
        return self.meters[key]

    def update(self, metrics, n: int = 1) -> float:
        """Record one step; values stay lazy (drained at display/read time)."""
        for k in self._keys:
            self.meters[k].update(metrics[k], n)
        now = time.time()
        dt = now - self._end
        self.batch_time.update(dt)
        self._end = now
        return dt

    def restart_clock(self) -> None:
        """Exclude out-of-band work (eval, checkpoint) from the step timer."""
        self._end = time.time()

    def maybe_display(self, batch: int, print_freq: int) -> None:
        if print_freq > 0 and batch % print_freq == 0:
            self.progress.display(batch)
