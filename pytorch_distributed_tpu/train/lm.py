"""Language-model pretraining harness: next-token objective over dp×tp or
dp×sp meshes — the long-context counterpart of the image harness.

Shares the framework's core pieces (SGD with torch semantics, TrainState,
meters, msgpack checkpoints) and adds:

- a deterministic synthetic token stream with *learnable* structure (affine
  next-token process) so smoke runs have a convergence oracle;
- ``make_lm_train_step``: the jitted step with parameter shardings taken
  from ``parallel/tp.py`` (replicated = DP; Megatron specs = TP) — XLA
  inserts the gradient psum over ``data`` and the two per-block activation
  all-reduces over ``model``;
- an epochless step-driven ``LMTrainer`` (LM convention), with meters and
  rank-0 checkpoints.
"""

from __future__ import annotations

from contextlib import nullcontext
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.ops import cross_entropy, qcomm
from pytorch_distributed_tpu.train.meters import StepMeters
from pytorch_distributed_tpu.train.optim import sgd_init, sgd_update
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.steps import (
    gate_update,
    nonfinite_flag,
    tree_l2_norm,
)


class SyntheticTokenDataset:
    """Affine token process: ``x[t+1] = (a·x[t] + c) mod vocab`` with
    per-sample random (a, c, x0).  A 1-layer transformer can learn it, so
    loss visibly drops — the LM smoke oracle."""

    def __init__(self, length: int, seq_len: int, vocab: int, seed: int = 0):
        self.length = length
        self.seq_len = seq_len
        self.vocab = vocab
        self.seed = seed
        self._cache: Dict[int, np.ndarray] = {}

    def __len__(self) -> int:
        return self.length

    def __getitem__(self, index: int) -> np.ndarray:
        # Cached: sequences are deterministic, and at long seq_len the
        # per-token recurrence is real host work that must not sit in the
        # training hot loop more than once per sample.
        cached = self._cache.get(index)
        if cached is not None:
            return cached
        rng = np.random.default_rng((self.seed, index))
        a = int(rng.integers(1, 8))
        c = int(rng.integers(0, self.vocab))
        x = np.empty(self.seq_len, np.int32)
        x[0] = int(rng.integers(0, self.vocab))
        for t in range(1, self.seq_len):
            x[t] = (a * x[t - 1] + c) % self.vocab
        self._cache[index] = x
        return x

    def batch(self, step: int, batch_size: int) -> np.ndarray:
        return _wraparound_batch(self, step, batch_size)


def _wraparound_batch(ds, step: int, batch_size: int,
                      rows: Optional[Tuple[int, int]] = None) -> np.ndarray:
    """Sequential wrap-around batching shared by the LM datasets.
    ``rows=(lo, hi)``: assemble only that row range of the logical global
    batch (multi-process: each host builds just its own shard)."""
    base = (step * batch_size) % max(1, len(ds))
    lo, hi = rows if rows is not None else (0, batch_size)
    return np.stack([ds[(base + i) % len(ds)] for i in range(lo, hi)])


class TextFileDataset:
    """Byte-level LM dataset over real files — vocab 256, sequences are
    strided windows of the concatenated bytes.  The real-data counterpart
    of ``SyntheticTokenDataset`` (zero tokenizer dependencies: bytes ARE the
    tokens, the GPT-style fallback that works on any corpus)."""

    vocab = 256

    def __init__(self, paths, seq_len: int, stride: Optional[int] = None,
                 span=(0.0, 1.0)):
        """``span``: (start, end) fractions of the corpus — carve held-out
        eval windows from the tail, e.g. train (0, .9) / eval (.9, 1)."""
        import glob as _glob

        if isinstance(paths, (str, bytes)):
            paths = sorted(_glob.glob(paths, recursive=True))
        blobs = []
        for p in paths:
            with open(p, "rb") as f:
                blobs.append(f.read())
        data = np.frombuffer(b"\n".join(blobs), dtype=np.uint8)
        # .copy(): a bare view would keep the whole joined corpus resident
        # just to serve a 10% eval tail.
        self.data = data[int(len(data) * span[0]):int(len(data) * span[1])].copy()
        if len(self.data) < seq_len + 1:
            raise ValueError(
                f"corpus has {len(self.data)} bytes < seq_len+1 "
                f"({seq_len + 1}); add files"
            )
        self.seq_len = seq_len
        self.stride = stride or seq_len
        self.length = 1 + (len(self.data) - seq_len - 1) // self.stride

    def __len__(self) -> int:
        return self.length

    def __getitem__(self, index: int) -> np.ndarray:
        lo = index * self.stride
        return self.data[lo:lo + self.seq_len].astype(np.int32)

    def batch(self, step: int, batch_size: int) -> np.ndarray:
        return _wraparound_batch(self, step, batch_size)


def warmup_cosine_lr(base_lr: float, warmup_steps: int, total_steps: int,
                     min_frac: float = 0.1):
    """Standard LM-pretraining schedule: linear warmup then cosine decay to
    ``min_frac·base_lr``.  Returns ``step -> lr`` for ``LMTrainer``'s
    ``lr_schedule`` (computed host-side; the step takes lr as a live scalar
    operand, so no retrace)."""

    def schedule(step: int) -> float:
        if warmup_steps > 0 and step < warmup_steps:
            return base_lr * (step + 1) / warmup_steps
        span = max(1, total_steps - warmup_steps)
        t = min(1.0, (step - warmup_steps) / span)
        cos = 0.5 * (1.0 + np.cos(np.pi * t))
        return base_lr * (min_frac + (1.0 - min_frac) * cos)

    return schedule


def resolve_fused_ce_mode(
    mode: str,
    param_specs,
    mesh: Mesh,
    vocab_size: Optional[int],
    data_axis: str = "data",
) -> Tuple[str, Optional[str]]:
    """Pick the fused-CE sharding variant (ops/fused_ce.py) for this
    mesh/spec combination → ``(mode, model_axis)``.

    - ``'tp'`` when the tied embedding's PartitionSpec shards the vocab dim
      over a live mesh axis other than ``data_axis`` (the parallel/tp.py
      ``P('model', None)`` layout): the shard_map variant consumes the
      shard directly — no replication of ``e`` or ``dE``.
    - ``'dp'`` when the embedding is effectively replicated but the mesh
      data axis is >1 and divides the vocab: the dE accumulator is kept as
      a vocab-row shard per device.
    - ``'replicated'`` otherwise (single device, or an indivisible vocab) —
      the original GSPMD path.

    Explicit ``mode`` values are validated against the same constraints so
    a mis-paired flag fails loudly at step-build time, not at trace time.
    """
    if mode not in ("auto", "replicated", "dp", "tp"):
        raise ValueError(
            f"fused_ce_mode must be auto|replicated|dp|tp, got {mode!r}")
    try:
        embed_spec = param_specs["embed"]["embedding"]
    except (KeyError, TypeError):
        embed_spec = P()
    mesh_shape = dict(mesh.shape)
    vocab_axis = embed_spec[0] if len(embed_spec) >= 1 else None
    tp_ok = (vocab_axis is not None and vocab_axis != data_axis
             and mesh_shape.get(vocab_axis, 1) > 1
             and vocab_size is not None
             and vocab_size % mesh_shape[vocab_axis] == 0)
    dp = mesh_shape.get(data_axis, 1)
    dp_ok = (dp > 1 and vocab_size is not None and vocab_size % dp == 0
             and (vocab_axis is None or mesh_shape.get(vocab_axis, 1) == 1))
    if mode == "auto":
        mode = "tp" if tp_ok else ("dp" if dp_ok else "replicated")
    elif mode == "tp" and not tp_ok:
        raise ValueError(
            "fused_ce_mode='tp' needs the tied embedding vocab-sharded "
            f"over a non-data mesh axis dividing the vocab; got spec "
            f"{embed_spec} on mesh {mesh_shape} (vocab {vocab_size})")
    elif mode == "dp" and not dp_ok:
        raise ValueError(
            "fused_ce_mode='dp' needs a replicated embedding, a data axis "
            f"> 1, and vocab divisible by it; got spec {embed_spec} on "
            f"mesh {mesh_shape} (vocab {vocab_size})")
    return mode, (vocab_axis if mode == "tp" else None)


def make_lm_train_step(
    model,
    mesh: Mesh,
    param_specs,
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    data_axis: str = "data",
    clip_grad_norm: float = 0.0,
    accum_steps: int = 1,
    fused_ce_chunks: int = 0,
    fused_ce_mode: str = "auto",
    log_norms: bool = False,
    guard_nonfinite: bool = False,
    grad_compress: Optional[str] = None,
    zero: str = "none",
    params=None,
    overlap: str = "none",
    bucket_mb: float = 4.0,
    explicit_collectives: bool = False,
):
    """Jitted LM step; ``param_specs`` is a PartitionSpec pytree from
    parallel/tp.py (``replicated_like`` for pure DP, ``tp_specs`` for TP).
    ``clip_grad_norm > 0`` rescales gradients to that global L2 norm
    (in-graph, before the update — the torch ``clip_grad_norm_`` analogue).
    ``accum_steps > 1`` accumulates gradients over that many strided
    microbatches inside the one compiled step (same semantics as the image
    path, train/steps.py).  For dense models the update equals the
    unaccumulated step up to fp reassociation (tested); for MoE models the
    router's load-balancing aux loss is computed from *microbatch-local*
    routing fractions, so accumulated and unaccumulated runs differ
    slightly — the standard per-microbatch aux-loss semantics, not a bug.

    ``fused_ce_mode`` selects the sharded fused-CE variant (see
    ``resolve_fused_ce_mode``); the default ``'auto'`` picks from the
    mesh + param specs, so ``fused_ce_chunks=N`` alone does the right
    thing on DP, TP, and single-device meshes alike.

    ``log_norms`` adds in-graph global ``grad_norm``/``param_norm`` metrics
    (per-leaf reductions stay sharding-local; the scalars replicate).  Off
    by default — the extra reduce ops lengthen compiles, so the cost is
    only paid when a metrics sink is on (``LMTrainer`` enables it with
    ``metrics_jsonl``).

    ``guard_nonfinite``: gate the whole update on an in-graph
    loss/grad-norm finiteness check and emit the ``nonfinite`` flag as a
    lazy metric — the divergence guard's detection half (train/steps.py
    ``nonfinite_flag``/``gate_update``; policy in ft/divergence.py).

    ``grad_compress``: gradient-sync compression mode (ops/qcomm.py,
    ``none | bf16 | int8 | fp8``).  Under the default GSPMD step XLA owns
    the gradient psum, so quantized modes run as a *numerics emulation*
    (fake-quantize + error feedback applied to the already-synced global
    gradient; wire bytes unchanged).  ``explicit_collectives=True`` (or
    ``overlap='bucketed'``, which implies it) switches pure-DP meshes onto
    the explicit ``shard_map`` step where the hand-written
    ``psum``/``compressed_psum`` carries the *real* int8/bf16 wire —
    the LM counterpart of the image path's wire transformation.

    ``overlap``: ``none | bucketed`` — the comm-overlap scheduler
    (parallel/overlap.py).  ``bucketed`` partitions the grad pytree into
    ~``bucket_mb``-MiB buckets in reverse-autodiff order and issues one
    collective per bucket under nested ``grad_sync``/``b<k>`` scopes, so
    early-bucket sync can run concurrently with the remaining backward;
    per-leaf math is identical, so results are bit-equal to monolithic
    sync.  Requires a pure data-parallel mesh with replicated params
    (no TP / pipeline / fused-CE / accum / wus — those stay on their
    existing paths).

    ``zero='wus'`` (parallel/zero.py): momentum leaves take data-axis
    ``fsdp_specs`` shardings (``zero_momentum_specs``, composed over
    ``param_specs`` so TP layouts keep their model-axis dims) while the
    update math is untouched — XLA derives the weight-update sharding
    from the layout alone.  Per-device optimizer bytes drop to ~1/N;
    ``params`` (the concrete param tree) is required to size the specs."""
    from pytorch_distributed_tpu.parallel import overlap as overlap_lib
    from pytorch_distributed_tpu.parallel import zero as zero_lib

    zero_mode = zero_lib.resolve_zero(zero)
    overlap_mode = overlap_lib.resolve_overlap(overlap)
    if explicit_collectives or overlap_mode == "bucketed":
        manual = getattr(model, "has_manual_grads", lambda: False)()
        unsupported = [
            ("the 1F1B pipeline's manual-gradient schedule", manual),
            (f"accum_steps={accum_steps}", accum_steps > 1),
            (f"fused_ce_chunks={fused_ce_chunks}", bool(fused_ce_chunks)),
            (f"zero={zero_mode!r} (use the image trainer's explicit wus "
             "path)", zero_mode != "none"),
        ]
        bad = [what for what, cond in unsupported if cond]
        if bad:
            raise ValueError(
                "the explicit-collectives LM step (overlap/"
                "explicit_collectives) supports the plain pure-DP step "
                "only; got " + "; ".join(bad))
        gc_mode, gc_cast = qcomm.resolve_mode(grad_compress, None)
        return _make_lm_train_step_explicit(
            model, mesh, param_specs, momentum=momentum,
            weight_decay=weight_decay, data_axis=data_axis,
            clip_grad_norm=clip_grad_norm, log_norms=log_norms,
            guard_nonfinite=guard_nonfinite, gc_mode=gc_mode,
            gc_cast=gc_cast, overlap_mode=overlap_mode,
            bucket_mb=bucket_mb)
    mom_specs = None
    if zero_mode == "wus":
        if params is None:
            raise ValueError(
                "make_lm_train_step(zero='wus') needs the concrete params "
                "tree to size the momentum fsdp_specs")
        mom_specs = zero_lib.zero_momentum_specs(
            params, mesh, data_axis, base_specs=param_specs)
    manual = getattr(model, "has_manual_grads", lambda: False)()
    gc_mode, gc_cast = qcomm.resolve_mode(grad_compress, None)
    if gc_mode != "none":
        import warnings

        warnings.warn(
            f"make_lm_train_step: grad_compress={gc_mode!r} under GSPMD is "
            "a NUMERICS emulation only — the gradient psum stays f32 on the "
            "wire (XLA owns the collective). Use the explicit-collectives "
            "image path for true wire compression.",
            UserWarning, stacklevel=2)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if accum_steps > 1 and manual:
        raise ValueError(
            "accum_steps > 1 with the 1F1B pipeline is redundant — the "
            "schedule already splits the batch into pipeline microbatches; "
            "raise n_microbatches instead"
        )
    if fused_ce_chunks and manual:
        raise ValueError(
            "fused_ce_chunks composes with autodiff loss_fn models only, "
            "not the 1F1B pipeline's manual-gradient schedule")
    ce_mode, ce_model_axis = ("replicated", None)
    if fused_ce_chunks:
        ce_mode, ce_model_axis = resolve_fused_ce_mode(
            fused_ce_mode, param_specs, mesh,
            getattr(model, "vocab_size", None), data_axis)

    def step(state: TrainState, tokens: jnp.ndarray, lr: jnp.ndarray):
        def loss_fn(params, toks):
            # named_scope: forward ops carry the phase name into XPlane
            # traces (autodiff derives the backward names from it) —
            # per-phase self-time instead of anonymous fusions.
            with jax.named_scope("lm_forward"):
                return loss_impl(params, toks)

        def loss_impl(params, toks):
            if fused_ce_chunks:
                # Fused tied-head + CE (ops/fused_ce.py): the [B, L, V]
                # logits tensor never materializes — hidden rows project
                # against the tied embedding per chunk inside a custom VJP.
                # The sharded variants keep the backward's dE accumulator
                # sharded too (vocab rows over data, or the tp.py vocab
                # shard), instead of the replicated [V, D] f32 carry that
                # erased the memory win on data-sharded meshes.
                from pytorch_distributed_tpu.ops.fused_ce import (
                    fused_ce_sums,
                    fused_ce_sums_dp,
                    fused_ce_sums_tp,
                )

                hidden, sown = model.apply(
                    {"params": params}, toks, mutable=["losses"],
                    return_hidden=True,
                )
                d = hidden.shape[-1]
                cdt = getattr(model, "dtype", jnp.float32)
                h = hidden[:, :-1].reshape(-1, d).astype(cdt)
                t = toks[:, 1:].reshape(-1)
                w = jnp.ones(t.shape, jnp.float32)
                e = params["embed"]["embedding"].astype(cdt)
                if ce_mode == "tp":
                    loss_sum, correct = fused_ce_sums_tp(
                        h, e, t, w, fused_ce_chunks, mesh,
                        data_axis=data_axis, model_axis=ce_model_axis)
                elif ce_mode == "dp":
                    loss_sum, correct = fused_ce_sums_dp(
                        h, e, t, w, fused_ce_chunks, mesh,
                        data_axis=data_axis)
                else:
                    loss_sum, correct = fused_ce_sums(
                        h, e, t, w, fused_ce_chunks)
                ntok = h.shape[0]
                loss = loss_sum / ntok
                for leaf in jax.tree_util.tree_leaves(
                        sown.get("losses", {})):
                    loss = loss + leaf
                return loss, correct / ntok
            # mutable=["losses"] collects sown auxiliary objectives (the MoE
            # router's load-balancing loss); {} for dense models.
            logits, sown = model.apply(
                {"params": params}, toks, mutable=["losses"]
            )
            vocab = logits.shape[-1]
            loss = cross_entropy(
                logits[:, :-1].reshape(-1, vocab),
                toks[:, 1:].reshape(-1),
            )
            for leaf in jax.tree_util.tree_leaves(sown.get("losses", {})):
                loss = loss + leaf
            acc = jnp.mean(
                (jnp.argmax(logits[:, :-1], axis=-1) == toks[:, 1:]).astype(
                    jnp.float32
                )
            )
            return loss, acc

        if manual:
            # 1F1B pipeline: gradients come from the schedule's own
            # interleaved scan, not autodiff over the whole step
            # (models/pipeline_lm.py loss_and_grads).
            (loss, acc), grads = model.loss_and_grads(state.params, tokens)
        elif accum_steps == 1:
            (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, tokens
            )
        else:
            B = tokens.shape[0]
            if B % accum_steps:
                raise ValueError(
                    f"batch {B} not divisible by accum_steps {accum_steps}"
                )
            # Strided split keeps every microbatch evenly spread over the
            # data-sharded rows (a contiguous split would concentrate each
            # microbatch on a device subset — train/steps.py note).
            micro = tokens.reshape(
                B // accum_steps, accum_steps, -1).swapaxes(0, 1)

            def body(carry, mb):
                g_acc, loss_acc, acc_acc = carry
                (l, a), g = jax.value_and_grad(loss_fn, has_aux=True)(
                    state.params, mb
                )
                g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                return (g_acc, loss_acc + l, acc_acc + a), None

            init = (
                jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), state.params),
                jnp.float32(0.0),
                jnp.float32(0.0),
            )
            (grads, loss, acc), _ = jax.lax.scan(body, init, micro)
            inv = 1.0 / accum_steps  # means-of-equal-size-microbatch-means
            grads = jax.tree_util.tree_map(
                lambda g, p: (g * inv).astype(p.dtype), grads, state.params)
            loss, acc = loss * inv, acc * inv
        # Pre-clip global grad norm: computed in-graph when clipping needs
        # it, when the obs layer asked for it, or when the divergence guard
        # watches it (an on-device scalar — converted lazily, never a host
        # sync).
        gnorm = (tree_l2_norm(grads)
                 if (log_norms or clip_grad_norm > 0.0 or guard_nonfinite)
                 else None)
        if clip_grad_norm > 0.0:
            with jax.named_scope("grad_clip"):
                scale = jnp.minimum(
                    1.0, clip_grad_norm / jnp.maximum(gnorm, 1e-12))
                grads = jax.tree_util.tree_map(
                    lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype),
                    grads,
                )
        new_residual = state.residual
        if gc_mode in qcomm.QUANTIZED_MODES:
            # GSPMD numerics emulation: fake-quantize the (already synced)
            # global gradient with error feedback — see module warning.
            with jax.named_scope("grad_sync"):
                grads, new_residual = qcomm.compress_emulated(
                    grads, state.residual, gc_mode)
        elif gc_cast is not None:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(gc_cast).astype(jnp.float32), grads)
        with jax.named_scope("optimizer"):
            new_params, new_momentum = sgd_update(
                grads, state.momentum, state.params, lr,
                momentum=momentum, weight_decay=weight_decay,
            )
        metrics = {"loss": loss, "acc": acc * 100.0}
        if guard_nonfinite:
            bad = nonfinite_flag(loss, gnorm)
            new_params = gate_update(bad, state.params, new_params)
            new_momentum = gate_update(bad, state.momentum, new_momentum)
            new_residual = gate_update(bad, state.residual, new_residual)
            metrics["nonfinite"] = bad
        new_state = TrainState(state.step + 1, new_params, state.batch_stats,
                               new_momentum, new_residual)
        if log_norms:
            metrics["grad_norm"] = gnorm
            metrics["param_norm"] = tree_l2_norm(new_params)
        return new_state, metrics

    from pytorch_distributed_tpu.parallel.tp import state_specs

    state_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        state_specs(param_specs, residual=gc_mode in qcomm.QUANTIZED_MODES,
                    momentum_specs=mom_specs),
    )
    token_sharding = NamedSharding(mesh, P(data_axis, None))
    return jax.jit(
        step,
        in_shardings=(state_shardings, token_sharding,
                      NamedSharding(mesh, P())),
        out_shardings=(state_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


def _make_lm_train_step_explicit(
    model,
    mesh: Mesh,
    param_specs,
    *,
    momentum: float,
    weight_decay: float,
    data_axis: str,
    clip_grad_norm: float,
    log_norms: bool,
    guard_nonfinite: bool,
    gc_mode: str,
    gc_cast,
    overlap_mode: str,
    bucket_mb: float,
):
    """Explicit ``shard_map`` DP LM step — the wire-transformation half of
    the overlap scheduler (parallel/overlap.py).

    Pure data parallelism with replicated params: each shard computes its
    local mean loss and grads, the hand-written ``psum`` /
    ``compressed_psum`` syncs them (so ``grad_compress`` compresses the
    *actual* wire, unlike the GSPMD emulation), and
    ``overlap='bucketed'`` splits the sync into reverse-autodiff-ordered
    buckets under ``grad_sync``/``b<k>`` scopes so each bucket's
    collective is free to run concurrently with the remaining backward.
    Per-leaf math is unchanged, so monolithic and bucketed steps are
    bit-equal.  Quantized error-feedback residuals ride in
    ``TrainState.residual`` in the stacked ``(n_data, *shape)`` layout
    sharded over ``data_axis`` (ops/qcomm.py ``init_residual``
    ``explicit=True``)."""
    from jax import shard_map

    from pytorch_distributed_tpu.parallel import overlap as overlap_lib

    mesh_shape = dict(mesh.shape)
    off_axes = {a: s for a, s in mesh_shape.items()
                if a != data_axis and s > 1}
    if off_axes:
        raise ValueError(
            "the explicit-collectives LM step needs a pure data-parallel "
            f"mesh; axes {off_axes} are > 1 besides {data_axis!r}")
    nontrivial = [
        s for s in jax.tree_util.tree_leaves(
            param_specs, is_leaf=lambda x: isinstance(x, P))
        if isinstance(s, P) and any(ax is not None for ax in s)
    ]
    if nontrivial:
        raise ValueError(
            "the explicit-collectives LM step keeps params replicated; "
            f"got sharded param_specs {nontrivial[:3]}...")
    n = mesh_shape.get(data_axis, 1)
    quantized = gc_mode in qcomm.QUANTIZED_MODES

    def local_step(state: TrainState, tokens: jnp.ndarray, lr: jnp.ndarray):
        def loss_fn(p, toks):
            with jax.named_scope("lm_forward"):
                logits, sown = model.apply({"params": p}, toks,
                                           mutable=["losses"])
                vocab = logits.shape[-1]
                loss = cross_entropy(
                    logits[:, :-1].reshape(-1, vocab),
                    toks[:, 1:].reshape(-1),
                )
                for leaf in jax.tree_util.tree_leaves(
                        sown.get("losses", {})):
                    loss = loss + leaf
                acc = jnp.mean(
                    (jnp.argmax(logits[:, :-1], axis=-1)
                     == toks[:, 1:]).astype(jnp.float32))
                return loss, acc

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, tokens)
        new_residual = state.residual
        # Equal-size shards: mean-of-shard-means == global mean, so the
        # synced gradient is psum/n of the local d(mean loss)/dp.
        with jax.named_scope("grad_sync"):
            if overlap_mode == "bucketed":
                grads, new_residual = overlap_lib.bucketed_psum(
                    grads, state.residual, data_axis, mode=gc_mode,
                    cast_dtype=gc_cast, bucket_mb=bucket_mb)
            elif quantized:
                grads, new_residual = qcomm.compressed_psum(
                    grads, state.residual, data_axis, mode=gc_mode)
            else:
                if gc_cast is not None:
                    grads = jax.tree_util.tree_map(
                        lambda g: g.astype(gc_cast), grads)
                grads = jax.lax.psum(grads, data_axis)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) / n, grads)
        loss = jax.lax.psum(loss, data_axis) / n
        acc = jax.lax.psum(acc, data_axis) / n
        # Synced grads are identical on every shard, so the per-shard norm
        # IS the global norm — no extra collective.
        gnorm = (tree_l2_norm(grads)
                 if (log_norms or clip_grad_norm > 0.0 or guard_nonfinite)
                 else None)
        if clip_grad_norm > 0.0:
            with jax.named_scope("grad_clip"):
                scale = jnp.minimum(
                    1.0, clip_grad_norm / jnp.maximum(gnorm, 1e-12))
                grads = jax.tree_util.tree_map(
                    lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype),
                    grads,
                )
        with jax.named_scope("optimizer"):
            new_params, new_momentum = sgd_update(
                grads, state.momentum, state.params, lr,
                momentum=momentum, weight_decay=weight_decay,
            )
        metrics = {"loss": loss, "acc": acc * 100.0}
        if guard_nonfinite:
            bad = nonfinite_flag(loss, gnorm)
            new_params = gate_update(bad, state.params, new_params)
            new_momentum = gate_update(bad, state.momentum, new_momentum)
            new_residual = gate_update(bad, state.residual, new_residual)
            metrics["nonfinite"] = bad
        new_state = TrainState(state.step + 1, new_params, state.batch_stats,
                               new_momentum, new_residual)
        if log_norms:
            metrics["grad_norm"] = gnorm
            metrics["param_norm"] = tree_l2_norm(new_params)
        return new_state, metrics

    replicated = NamedSharding(mesh, P())
    state_spec = TrainState(
        step=P(), params=P(), batch_stats=P(), momentum=P(),
        residual=P(data_axis) if quantized else P())
    state_sharding = TrainState(
        step=replicated, params=replicated, batch_stats=replicated,
        momentum=replicated,
        residual=(NamedSharding(mesh, P(data_axis)) if quantized
                  else replicated))
    stepped = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_spec, P(data_axis, None), P()),
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    return jax.jit(
        stepped,
        in_shardings=(state_sharding, NamedSharding(mesh, P(data_axis, None)),
                      replicated),
        out_shardings=(state_sharding, replicated),
        donate_argnums=(0,),
    )


def make_lm_eval_step(model, mesh: Mesh, param_specs, data_axis: str = "data",
                      has_residual: bool = False, momentum_specs=None,
                      residual_specs=None):
    """Jitted held-out eval step returning exact token-weighted *sums*
    (loss·count, correct, count) — the LM counterpart of the image harness's
    ``make_eval_step`` (reference validate() pattern,
    reference distributed.py:279-324): aggregation is exact on the host,
    reductions live inside the compiled program.  ``has_residual``: the
    caller's TrainState carries error-feedback residuals (quantized
    ``grad_compress``), so in_shardings must cover that subtree too.
    ``momentum_specs``: the ``--zero wus`` momentum layout
    (``zero_momentum_specs``) — in_shardings must match or XLA gathers
    the sharded optimizer state on every eval call.  ``residual_specs``
    overrides the residual layout: the bucketed-overlap explicit step
    stores residuals stacked per rank and sharded ``P(data_axis)``, not
    param-shaped."""

    def step(state: TrainState, tokens: jnp.ndarray):
        # mutable=["losses"]: MoE models sow the router aux loss even in
        # inference; collected and dropped (eval reports data loss only).
        logits, _ = model.apply({"params": state.params}, tokens,
                                mutable=["losses"])
        vocab = logits.shape[-1]
        flat_logits = logits[:, :-1].reshape(-1, vocab)
        flat_targets = tokens[:, 1:].reshape(-1)
        count = jnp.float32(flat_targets.shape[0])
        loss = cross_entropy(flat_logits, flat_targets)
        correct = jnp.sum(
            (jnp.argmax(flat_logits, axis=-1) == flat_targets).astype(jnp.float32)
        )
        return {"loss_sum": loss * count, "correct": correct, "count": count}

    from pytorch_distributed_tpu.parallel.tp import state_specs

    specs = state_specs(param_specs, residual=has_residual,
                        momentum_specs=momentum_specs)
    if residual_specs is not None:
        specs = specs.replace(residual=residual_specs)
    state_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs)
    token_sharding = NamedSharding(mesh, P(data_axis, None))
    return jax.jit(
        step,
        in_shardings=(state_shardings, token_sharding),
        out_shardings=NamedSharding(mesh, P()),
    )


class LMTrainer:
    """Step-driven driver: meters, periodic display, rank-0 checkpoints,
    and a held-out eval loop (loss / perplexity / next-token accuracy) with
    best tracking — mirroring the image harness's validate/best-acc flow
    (reference distributed.py:212-225)."""

    def __init__(
        self,
        model,
        mesh: Mesh,
        dataset: SyntheticTokenDataset,
        batch_size: int,
        lr: float = 1e-2,
        param_specs=None,
        seed: int = 0,
        is_primary: bool = True,
        checkpoint_dir: Optional[str] = None,
        eval_dataset: Optional[SyntheticTokenDataset] = None,
        eval_every: int = 0,
        eval_batches: int = 8,
        lr_schedule=None,
        clip_grad_norm: float = 0.0,
        preempt=None,
        prefetch: int = 2,
        accum_steps: int = 1,
        fused_ce_chunks: int = 0,
        fused_ce_mode: str = "auto",
        metrics_jsonl: Optional[str] = None,
        hb_dir: Optional[str] = None,
        hb_interval_s: float = 5.0,
        mfu: bool = False,
        goodput: bool = False,
        watch_recompiles: bool = False,
        comm_ledger: Optional[str] = None,
        mem_ledger: Optional[str] = None,
        lowering_cache: Optional[str] = None,
        save_steps: int = 0,
        resume: Optional[str] = None,
        nan_guard: bool = False,
        ft_rollback_k: int = 3,
        ft_check_every: int = 10,
        ft_lr_backoff: float = 0.5,
        chaos=None,
        grad_compress: Optional[str] = None,
        zero: Optional[str] = None,
        overlap: str = "none",
        bucket_mb: float = 4.0,
        elastic=None,
        rescale_lr: str = "none",
        flight_rec: Optional[str] = None,
        hang_timeout: float = 30.0,
        metrics_port: int = 0,
        alerts: Optional[str] = None,
        step_attr: bool = False,
    ):
        """``lr_schedule``: optional ``step -> lr`` callable (e.g.
        ``warmup_cosine_lr``) overriding the fixed ``lr``;
        ``clip_grad_norm``: in-graph global-norm gradient clipping;
        ``accum_steps``: gradient accumulation inside the compiled step;
        ``preempt``: optional installed ``utils.preempt.PreemptionGuard`` —
        when it triggers, ``fit`` stops at the next step boundary and the
        end-of-fit checkpoint captures the state.
        ``prefetch``: token batches kept in flight by the background feeder
        (0 = synchronous host assembly + transfer in the step loop — the
        before/after axis measured in experiments/lm_feeder_bench.py);
        ``fused_ce_mode``: sharding variant of the fused loss head
        (auto | replicated | dp | tp — see ``resolve_fused_ce_mode``);
        ``metrics_jsonl``/``hb_dir``: unified observability (obs/) — one
        structured record per step, and per-process heartbeats for the
        cross-process straggler monitor.

        Efficiency accounting (obs/flops.py, goodput.py, watchdog.py):
        ``mfu`` adds per-step MFU/HFU fields from the analytic LM FLOPs
        model (fused-CE / remat / pipeline-aware) over the chips' peak;
        ``goodput`` tracks the live goodput/badput ledger and prints it at
        end of fit; ``watch_recompiles`` installs the jax.monitoring
        recompile watchdog around the step/eval functions.

        Fault tolerance (ft/): ``save_steps`` checkpoints every N steps
        (ft record carries the step, so SIGKILL loses at most N steps);
        ``resume`` restores state AND the exact step from a checkpoint —
        the run continues as if never interrupted (the step-indexed
        wraparound batching regenerates the identical token stream);
        ``nan_guard`` turns on the in-graph non-finite skip plus the
        K-consecutive rollback policy with LR backoff (``ft_rollback_k``,
        ``ft_check_every``, ``ft_lr_backoff`` — see
        ``ft.divergence.DivergenceGuard``); ``chaos``: an optional
        ``ft.chaos`` injector schedule driven once per loop step (tests
        and drills only); ``grad_compress``: gradient-sync compression
        mode (``none | bf16 | int8 | fp8`` — numerics emulation under the
        LM GSPMD step, see ``make_lm_train_step``); ``zero``: ``none|wus``
        weight-update sharding (parallel/zero.py) — momentum leaves take
        ``fsdp_specs`` data-axis shardings over the param specs, 1/N
        optimizer bytes per device, identical numerics and checkpoints;
        ``overlap``/``bucket_mb``: the comm-overlap scheduler
        (parallel/overlap.py) — ``'bucketed'`` switches pure-DP meshes
        onto the explicit shard_map step with ~``bucket_mb``-MiB
        reverse-autodiff grad-sync buckets (real compressed wire under
        ``grad_compress``; bit-equal numerics).

        Elastic training (ft/elastic.py): ``elastic`` is a membership
        controller (``ElasticSim`` in-process, or any object with
        ``poll(step) -> MembershipChange | None``); on a change ``fit``
        tears down and rebuilds the mesh/shardings/feeder/jitted steps
        from the survivor set and re-shards the last-good ``StateKeeper``
        snapshot onto the new topology.  ``rescale_lr`` is the rescale
        rule across a world change: ``none`` holds the *global* batch
        constant (LR untouched — the parity-fence default), ``linear`` /
        ``sqrt`` hold the *per-rank* batch constant and scale the LR by
        (new/old) or sqrt(new/old).

        Crash forensics (obs/flightrec.py): ``flight_rec`` is a directory
        receiving this rank's ``flightrec_rank<k>.json`` ring dump on any
        death path (signal / rollback / checkpoint corruption / unhandled
        exception / hang watchdog); ``hang_timeout`` is the watchdog's
        floor — a step exceeding ``max(hang_timeout, 4×p95)`` emits a
        ``hang`` ft_event and dumps the ring pre-mortem."""
        from pytorch_distributed_tpu.parallel import zero as zero_lib
        from pytorch_distributed_tpu.parallel.tp import (
            replicated_like,
            shard_state,
        )

        self.model = model
        self.mesh = mesh
        self.dataset = dataset
        self.batch_size = batch_size
        self.lr = lr
        self.is_primary = is_primary
        self.checkpoint_dir = checkpoint_dir
        self.preempt = preempt

        # Init batch must divide the data axis (ring attention shard_maps the
        # batch dim during init tracing too).
        init_b = dict(mesh.shape).get("data", 1)
        tokens0 = jnp.zeros((init_b, dataset.seq_len), jnp.int32)
        variables = model.init(jax.random.PRNGKey(seed), tokens0)
        params = variables["params"]
        self.param_specs = (
            param_specs if param_specs is not None else replicated_like(params)
        )
        self.grad_compress, _ = qcomm.resolve_mode(grad_compress, None)
        self.zero = zero_lib.resolve_zero(zero)
        from pytorch_distributed_tpu.parallel import overlap as overlap_lib

        self.overlap = overlap_lib.resolve_overlap(overlap)
        self.bucket_mb = float(bucket_mb)
        if self.overlap == "bucketed" and elastic is not None:
            raise ValueError(
                "overlap='bucketed' carries stacked per-rank residual "
                "state the elastic re-mesh does not re-grid on the LM "
                "path; run elastic with overlap='none'")
        self.lr_schedule = lr_schedule
        self.eval_dataset = eval_dataset
        self.eval_every = eval_every
        self.eval_batches = eval_batches
        self.best_ppl = float("inf")
        self.eval_history: list = []  # (loss, ppl, acc%) per evaluate() call
        self.prefetch = prefetch
        # ---- elastic membership (ft/elastic.py) ----
        from pytorch_distributed_tpu.ft import elastic as elastic_lib

        if rescale_lr not in elastic_lib.RESCALE_RULES:
            raise ValueError(f"rescale_lr must be one of "
                             f"{elastic_lib.RESCALE_RULES}, got {rescale_lr!r}")
        self.elastic = elastic
        self.rescale_lr_rule = rescale_lr
        self._elastic_lr_scale = 1.0
        self._membership_epoch = 0
        # Everything mesh-shape-dependent lives in _build_for_mesh so a
        # membership change can rebuild it against the survivor set.
        self._step_kwargs = dict(
            clip_grad_norm=clip_grad_norm, accum_steps=accum_steps,
            fused_ce_chunks=fused_ce_chunks, fused_ce_mode=fused_ce_mode,
            overlap=self.overlap, bucket_mb=self.bucket_mb,
            # in-graph norms only when a metrics sink will consume them
            log_norms=bool(metrics_jsonl), guard_nonfinite=nan_guard)
        self._build_for_mesh(mesh, params)
        # Bucketed overlap runs the explicit shard_map step: quantized
        # error-feedback residuals take the stacked per-rank layout
        # sharded over the data axis (one slot per rank).
        explicit = self.overlap == "bucketed"
        residual = qcomm.init_residual(
            params, self.grad_compress, explicit=explicit,
            n_data=dict(mesh.shape).get("data", 1))
        state = TrainState.create({"params": params}, sgd_init(params),
                                  residual=residual)
        self.state = shard_state(state, self.param_specs, mesh,
                                 momentum_specs=self._mom_specs)
        if explicit and self.grad_compress in qcomm.QUANTIZED_MODES:
            self.state = self.state.replace(residual=jax.device_put(
                self.state.residual, NamedSharding(mesh, P("data"))))
        from pytorch_distributed_tpu.obs import HeartbeatWriter, MetricsLogger

        self.obs = MetricsLogger(metrics_jsonl,
                                 process_index=jax.process_index())
        self.hb = (HeartbeatWriter(hb_dir, jax.process_index(),
                                   interval_s=hb_interval_s,
                                   world=dict(mesh.shape).get("data", 1),
                                   epoch=self._membership_epoch)
                   if hb_dir else None)

        # ---- efficiency accounting (obs/) ----
        self._mfu = None
        self._mfu_on = mfu
        if mfu:
            self._build_mfu()
        self._goodput = None
        if goodput:
            from pytorch_distributed_tpu.obs.goodput import GoodputTracker

            self._goodput = self.obs.register(GoodputTracker())
        self.watchdog = None
        if watch_recompiles:
            from pytorch_distributed_tpu.obs.watchdog import (
                RecompileWatchdog,
            )

            self.watchdog = RecompileWatchdog(obs=self.obs).install()
        # Exact step attribution (obs/stepattr.py, --step-attr): see the
        # image Trainer's twin block — three wall windows + one explicit
        # block per step, identity closed against the meters' seconds.
        self.stepattr = None
        self._stepattr_phases_booked = False
        if step_attr:
            from pytorch_distributed_tpu.obs.flops import chip_link_bytes
            from pytorch_distributed_tpu.obs.stepattr import StepAttr

            kind = getattr(mesh.devices.flat[0], "device_kind", "")
            self.stepattr = StepAttr(link_bytes_per_s=chip_link_bytes(kind))
        # Communication + memory ledgers (obs/comms.py, obs/memory.py):
        # emitted lazily on the first fit() batch; opt-in — the AOT
        # lowering does not share the jit call cache in jax 0.4.x, so the
        # pair costs one extra step compile, shared between them.
        self._comm_ledger_path = comm_ledger
        self._mem_ledger_path = mem_ledger
        self._lowering_cache = lowering_cache
        self._comm_fields: Optional[dict] = None
        # Dominant ledger collective labelling the flight ring's
        # coll_enter events; None until a ledger lowering runs.
        self._flight_coll: Optional[dict] = None

        # ---- crash forensics (obs/flightrec.py) ----
        self.flight = None
        self._hang_wd = None
        if flight_rec:
            from pytorch_distributed_tpu.obs.flightrec import (
                FlightRecorder,
                HangWatchdog,
                attach_to_metrics,
            )

            self.flight = FlightRecorder(flight_rec,
                                         rank=jax.process_index())
            self._hang_wd = HangWatchdog(self.flight, obs=self.obs,
                                         timeout=float(hang_timeout))
            attach_to_metrics(self.flight, self.obs)
            self.flight.set_membership(dict(mesh.shape).get("data", 1),
                                       self._membership_epoch)

        # ---- live telemetry plane (obs/export.py + obs/alerts.py) ----
        # Both are flush-time sinks on the same logger — zero additions
        # to the hot loop.  Rank k serves metrics_port + k; the exporter
        # is an owned sink (started here, stopped at obs.close()).
        self._exporter = None
        if int(metrics_port or 0) > 0:
            from pytorch_distributed_tpu.obs.export import MetricsExporter

            self._exporter = MetricsExporter(
                int(metrics_port) + jax.process_index(),
                rank=jax.process_index())
            self.obs.register(self._exporter)        # lifecycle
            self.obs.register(self._exporter.update)  # per-record sink
        self.alerts = None
        if alerts:
            from pytorch_distributed_tpu.obs.alerts import (
                AlertEngine,
                default_rules,
                load_rules,
            )

            rules = (default_rules() if alerts == "default"
                     else load_rules(alerts))
            self.alerts = AlertEngine(rules, emit=self._emit_alert,
                                      process_index=jax.process_index())
            self.obs.register(self.alerts)
            if self._exporter is not None:
                self._exporter.engine = self.alerts  # ptd_alert_firing

        # ---- fault tolerance (ft/) ----
        self.save_steps = int(save_steps)
        self.chaos = chaos
        self.ft_guard = None
        self._keeper = None
        if nan_guard:
            from pytorch_distributed_tpu.ft import DivergenceGuard

            self.ft_guard = DivergenceGuard(
                rollback_k=ft_rollback_k, check_every=ft_check_every,
                lr_backoff=ft_lr_backoff, obs=self.obs)
        if nan_guard or self.elastic is not None:
            # Elastic re-meshing re-shards from the same last-good host
            # snapshot the divergence guard rolls back to.
            from pytorch_distributed_tpu.ft import StateKeeper

            self._keeper = StateKeeper()
        self._start_step = 0
        if resume:
            from pytorch_distributed_tpu.train.checkpoint import load_checkpoint

            loaded, meta = load_checkpoint(resume, self.state)
            # Host-numpy leaves → re-shard to this trainer's specs (any
            # mesh shape can resume any mesh shape's checkpoint; the
            # momentum re-shards to the wus layout when zero is on).
            self.state = shard_state(loaded, self.param_specs, mesh,
                                     momentum_specs=self._mom_specs)
            ft = meta["ft"]
            self._start_step = max(int(ft["global_step"]), int(ft["step"]))
            if self.ft_guard is not None:
                self.ft_guard.lr_scale = float(ft["lr_scale"])
            if self._eval_fn is not None and float(meta["best_acc1"]) > 0:
                self.best_ppl = float(meta["best_acc1"])
            print(f"=> resumed {meta['arch']} from '{resume}' at step "
                  f"{self._start_step}", flush=True)

    def _build_for_mesh(self, mesh: Mesh, params) -> None:
        """Build (or rebuild) every mesh-shape-dependent piece against
        ``mesh``: momentum shardings, the jitted train/eval steps, the
        token sharding, and the caches keyed to the old topology (row
        span, preemption agreement, comm-ledger fields).  Called once
        from ``__init__`` and again on every elastic ``remesh`` — this is
        the mesh-shape-agnostic seam the ISSUE's refactor names."""
        from pytorch_distributed_tpu.parallel import zero as zero_lib

        self.mesh = mesh
        self._mom_specs = (
            zero_lib.zero_momentum_specs(params, mesh,
                                         base_specs=self.param_specs)
            if self.zero == "wus" else None)
        self.step_fn = make_lm_train_step(self.model, mesh, self.param_specs,
                                          grad_compress=self.grad_compress,
                                          zero=self.zero, params=params,
                                          **self._step_kwargs)
        self.token_sharding = NamedSharding(mesh, P("data", None))
        quantized = self.grad_compress in qcomm.QUANTIZED_MODES
        self._eval_fn = (
            make_lm_eval_step(
                self.model, mesh, self.param_specs,
                has_residual=quantized,
                momentum_specs=self._mom_specs,
                # bucketed overlap trains the explicit step: residuals are
                # stacked per rank and sharded over data (_build_for_mesh)
                residual_specs=(
                    jax.tree_util.tree_map(lambda _: P("data"),
                                           self.param_specs)
                    if quantized and self.overlap == "bucketed" else None))
            if self.eval_dataset is not None else None)
        self._span = None   # per-process row range: topology-keyed
        self._agree = None  # lazy PreemptionAgreement holds the old mesh
        self._comm_fields = None  # ledger re-emits against the new mesh

    def _emit_alert(self, **fields) -> None:
        """AlertEngine emit hook: book a firing as an ``alert`` ft_event
        in the same JSONL, so goodput/postmortem/obs_report fold it (and
        the flight ring records it via attach_to_metrics)."""
        self.obs.log_event("alert", **fields)

    def _build_mfu(self) -> None:
        from pytorch_distributed_tpu.obs.flops import (
            MFUReporter,
            device_peak_flops,
            lm_step_cost_for,
        )

        cost = lm_step_cost_for(
            self.model, self.batch_size, self.dataset.seq_len,
            fused_ce_chunks=self._step_kwargs["fused_ce_chunks"])
        dev = self.mesh.devices.flat[0]
        self._mfu = MFUReporter(cost, n_devices=self.mesh.devices.size,
                                peak_per_chip=device_peak_flops(dev))

    def remesh(self, new_world: int, completed: int,
               refresh_snapshot: bool = True) -> int:
        """Re-mesh to ``new_world`` data-parallel devices: rebuild mesh /
        shardings / jitted steps from the survivor set and re-shard the
        last-good ``StateKeeper`` snapshot onto the new topology.  Returns
        the resume step (the snapshot's step — a shrink rewinds to the
        last state the dead rank could not have tainted; a grow refreshes
        the snapshot first, so it resumes where it left off).

        LM state re-shards without layout surgery: params, GSPMD momentum
        (param-shaped, ``zero_momentum_specs``-sharded under ``wus``), and
        the quantized-emulation residual are all param-shaped host leaves,
        and ``shard_state`` places them under any mesh — the same "any
        shape resumes any shape" property the checkpoints already prove.
        (The explicit stacked layouts live in the image ``Trainer``, which
        re-grids them via ft/elastic.py.)"""
        axes = tuple(self.mesh.axis_names)
        if axes != ("data",):
            raise ValueError(
                f"elastic re-mesh supports pure data-parallel meshes; "
                f"this trainer's mesh has axes {axes}")
        devs = jax.devices()
        if not 1 <= new_world <= len(devs):
            raise ValueError(
                f"new world {new_world} outside [1, {len(devs)}] devices")
        old_world = dict(self.mesh.shape)["data"]
        if self._keeper is None:
            from pytorch_distributed_tpu.ft import StateKeeper

            self._keeper = StateKeeper()
        if refresh_snapshot or not self._keeper.has_snapshot:
            self._keeper.update(self.state, completed)
        host = self._keeper.restore()
        resume = int(self._keeper.step)
        from pytorch_distributed_tpu.ft import elastic as elastic_lib

        if self.rescale_lr_rule != "none":
            self.batch_size = elastic_lib.rescale_batch(
                self.batch_size, old_world, new_world, self.rescale_lr_rule)
            self._elastic_lr_scale *= elastic_lib.rescale_lr(
                1.0, old_world, new_world, self.rescale_lr_rule)
        if self.batch_size % new_world:
            raise ValueError(
                f"global batch {self.batch_size} does not divide the new "
                f"data axis ({new_world} devices); pick --min-ranks / batch "
                "so every admissible world divides it")
        from pytorch_distributed_tpu.parallel.mesh import MeshSpec, build_mesh
        from pytorch_distributed_tpu.parallel.tp import shard_state

        new_mesh = build_mesh(MeshSpec(("data",), (new_world,)),
                              devices=devs[:new_world])
        self._build_for_mesh(new_mesh, host.params)
        self.state = shard_state(host, self.param_specs, new_mesh,
                                 momentum_specs=self._mom_specs)
        if self._mfu_on:
            self._build_mfu()  # n_devices (and maybe batch) changed
        self._membership_epoch += 1
        if self.hb is not None:
            self.hb.set_membership(new_world, self._membership_epoch)
        if self.flight is not None:
            self.flight.set_membership(new_world, self._membership_epoch)
        return resume

    def _apply_remesh(self, chg, at_step: int) -> int:
        """Act on a committed ``MembershipChange`` inside ``fit``: log the
        ``remesh`` ft_event (goodput books the gap to the first step on
        the new mesh as ``remesh`` badput) and rebuild.  Returns the
        resume step."""
        kind = chg.kind
        old_world = dict(self.mesh.shape)["data"]
        self.obs.log_event("remesh", step=at_step, change=kind,
                           old_world=chg.old.world, new_world=chg.new.world,
                           epoch=chg.new.epoch, reason=chg.reason,
                           rescale=self.rescale_lr_rule)
        resume = self.remesh(chg.new.world, completed=at_step,
                             refresh_snapshot=(kind == "grow"))
        print(f"=> remesh ({kind}) at step {at_step}: world "
              f"{old_world}->{chg.new.world}, epoch {chg.new.epoch}, "
              f"resuming at step {resume} ({chg.reason})", flush=True)
        return resume

    def _row_span(self) -> Tuple[int, int]:
        """This process's row range of the global batch under the token
        sharding — the LM counterpart of DistributedSampler's per-rank
        shard (reference distributed.py:174-175).  Replicated axes (e.g.
        a cross-process TP mesh with data=1) span the full batch; a
        cross-process data axis yields a contiguous slice.  Static for
        fixed shapes, so computed once."""
        if self._span is None:
            B = self.batch_size
            if jax.process_count() == 1:
                self._span = (0, B)
            else:
                gm = self.token_sharding.devices_indices_map(
                    (B, self.dataset.seq_len))
                me = jax.process_index()
                spans = [
                    (s[0].start or 0, B if s[0].stop is None else s[0].stop)
                    for d, s in gm.items() if d.process_index == me
                ]
                lo = min(s[0] for s in spans)
                hi = max(s[1] for s in spans)
                # (min, max) assumes this process's row slices tile a
                # contiguous range; a future hybrid/multi-slice device
                # order could interleave processes, and an over-wide span
                # would surface as a confusing shape error deep inside
                # make_array_from_process_local_data (advisor r3).
                rows = sum(b - a for a, b in set(spans))
                if hi - lo != rows:
                    raise ValueError(
                        f"process {me} holds a non-contiguous row shard "
                        f"{sorted(set(spans))} of the global batch; "
                        "contiguous per-process rows are required for the "
                        "local-assembly feed path"
                    )
                self._span = (lo, hi)
        return self._span

    def _local_rows(self, global_batch: np.ndarray) -> np.ndarray:
        """Slice an already-assembled global batch down to this process's
        rows (prefer ``_local_batch``, which never assembles foreign rows)."""
        lo, hi = self._row_span()
        return global_batch[lo:hi]

    def _local_batch(self, ds, step: int) -> np.ndarray:
        """Assemble ONLY this process's rows of logical global batch
        ``step`` — no cross-host redundant window stacking."""
        return _wraparound_batch(ds, step, self.batch_size,
                                 rows=self._row_span())

    def _put_tokens(self, local_tokens: np.ndarray) -> jax.Array:
        """This process's host rows → sharded global device array (the LM
        counterpart of DeviceFeeder._put)."""
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(
                self.token_sharding, local_tokens
            )
        return jax.device_put(local_tokens, self.token_sharding)

    def _wd_watch(self, label: str, step: Optional[int] = None):
        """Watchdog attribution context for a jitted call (inert when
        ``watch_recompiles`` is off)."""
        if self.watchdog is not None:
            return self.watchdog.watch(label, step=step)
        import contextlib

        return contextlib.nullcontext()

    def _preempt_agreed(self) -> bool:
        """Cross-process 'any rank flagged?' — every rank calls this at the
        same step (it runs a collective on multi-process meshes)."""
        if self._agree is None:
            from pytorch_distributed_tpu.utils.preempt import (
                PreemptionAgreement,
            )

            self._agree = PreemptionAgreement(self.mesh)
        return self._agree(self.preempt.triggered)

    def evaluate(self) -> Tuple[float, float, float]:
        """Held-out ``(loss, perplexity, next-token acc%)`` over
        ``eval_batches`` batches; prints the summary line (the LM analogue of
        the reference's ``* Acc@1 …``, distributed.py:321-322)."""
        if self._eval_fn is None:
            raise ValueError("LMTrainer built without eval_dataset")
        totals = {"loss_sum": 0.0, "correct": 0.0, "count": 0.0}
        for i in range(self.eval_batches):
            tokens = self._put_tokens(self._local_batch(self.eval_dataset, i))
            with self._wd_watch("lm_eval_step"):
                sums = self._eval_fn(self.state, tokens)
            for k in totals:
                totals[k] += float(sums[k])
        count = max(totals["count"], 1.0)
        loss = totals["loss_sum"] / count
        ppl = float(np.exp(min(loss, 30.0)))
        acc = totals["correct"] * 100.0 / count
        print(f" * Eval loss {loss:.4f} ppl {ppl:.2f} Acc@1 {acc:.2f}",
              flush=True)
        self.eval_history.append((loss, ppl, acc))
        return loss, ppl, acc

    def _ft_record(self, completed: int) -> dict:
        """The step-granular resume record for a checkpoint at
        ``completed`` finished steps (LM is epochless: step == global
        step; the wraparound batching is purely step-indexed, so these
        two integers restore the exact token stream)."""
        return {
            "step": int(completed),
            "global_step": int(completed),
            "lr_scale": (self.ft_guard.lr_scale
                         if self.ft_guard is not None else 1.0),
        }

    def _save_checkpoint(self, completed: int, is_best: bool = False) -> None:
        """ALL ranks call: save_checkpoint gathers sharded leaves with a
        cross-process collective before its primary guard — gating the
        call itself on is_primary would deadlock multi-host TP/SP runs.
        best_acc1 slot carries the best perplexity for the LM family."""
        from pytorch_distributed_tpu.train.checkpoint import save_checkpoint

        save_checkpoint(
            self.checkpoint_dir, self.state, 0, "transformer_lm",
            self.best_ppl if self._eval_fn is not None else 0.0,
            is_best=is_best, is_primary=self.is_primary,
            ft=self._ft_record(completed),
        )
        if self.flight is not None:
            self.flight.event("checkpoint", completed)

    def _rollback(self, step: int) -> None:
        """Divergence recovery: restore the last-good snapshot and back
        off the LR scale (ft/divergence.py policy).  The jitted step's
        ``in_shardings`` re-shard the host-numpy snapshot on the next
        call, exactly like a ``--resume`` load."""
        restored_step = None
        if self._keeper is not None and self._keeper.has_snapshot:
            self.state = self._keeper.restore()
            restored_step = self._keeper.step
        scale = self.ft_guard.note_rollback(step, restored_step)
        print(f"=> divergence rollback at step {step}: restored state from "
              f"step {restored_step}, lr scale now {scale:g}", flush=True)
        if self.flight is not None:
            # The rollback itself is forensic: snapshot the ring (the
            # `rollback` ft_event is already in it via attach_to_metrics).
            self.flight.dump("rollback")

    def _emit_ledgers(self, tokens, lr) -> None:
        """AOT-compile the live LM step once against the first batch's
        real shardings and itemize both opt-in receipts off that single
        lowering (``analysis.lowering.aot_ledgers`` — counted against
        the process-wide compile budget and, with ``lowering_cache``
        set, persisted in the service's artifact layout): the collective
        ledger and the static HBM memory ledger.  The cached metrics
        fields ride every subsequent record."""
        from pytorch_distributed_tpu.analysis import lowering
        from pytorch_distributed_tpu.obs import comms

        args = (self.state, tokens, lr)
        ledger, mled = lowering.aot_ledgers(
            self.step_fn, args, step="lm_step",
            mesh_shape=dict(self.mesh.shape),
            want_comm=self._comm_ledger_path is not None,
            want_mem=self._mem_ledger_path is not None,
            cache_dir=self._lowering_cache)
        self._comm_fields = {}
        if ledger is not None:
            self._comm_fields.update(ledger.metrics_fields())
            if ledger.entries:
                top = max(ledger.entries, key=lambda e: e.wire_bytes)
                self._flight_coll = {"kind": top.kind, "bytes": top.bytes,
                                     "name": top.name}
            if self.is_primary:
                comms.write_ledgers(self._comm_ledger_path, [ledger])
                print(f"=> wrote comm ledger ({ledger.count} collectives, "
                      f"{ledger.total_bytes} B/step payload) to "
                      f"{self._comm_ledger_path}", flush=True)
        if mled is not None:
            from pytorch_distributed_tpu.obs import memory

            self._comm_fields.update(mled.metrics_fields())
            if self.is_primary:
                memory.write_ledgers(self._mem_ledger_path, [mled])
                print(f"=> wrote mem ledger (peak {mled.peak_bytes} B at "
                      f"instr {mled.peak_index}/{mled.n_instructions}) to "
                      f"{self._mem_ledger_path}", flush=True)

    def _book_stepattr_phases(self) -> None:
        """Image-Trainer twin: hand the attribution recorder the comm
        ledger's wire bytes (when one ran) and book the static per-phase
        roofline ledger once as a ``stepattr_phases`` ft_event."""
        if self.stepattr is None or self._stepattr_phases_booked:
            return
        self._stepattr_phases_booked = True
        from pytorch_distributed_tpu.obs import flops, stepattr

        wire = float((self._comm_fields or {}).get("comm_wire_bytes", 0.0))
        if wire > 0:
            self.stepattr.set_comm_bytes(wire)
        try:
            cost = flops.lm_step_cost_for(
                self.model, self.batch_size, self.dataset.seq_len,
                fused_ce_chunks=self._step_kwargs["fused_ce_chunks"])
        except (AttributeError, KeyError, ValueError):
            return  # exotic model: attribution still runs, no roofline
        kind = getattr(self.mesh.devices.flat[0], "device_kind", "")
        prof = stepattr.phase_profile(
            cost.breakdown,
            stepattr.split_step_bytes(cost.bytes, cost.params),
            comm_bytes=wire,
            peak_flops=flops.chip_peak_flops(kind),
            hbm_bw=flops.chip_hbm_bw(kind),
            link_bw=flops.chip_link_bytes(kind),
            n_devices=self.mesh.devices.size)
        self.obs.log_event("stepattr_phases",
                           **stepattr.phase_event_fields(prof))

    def _token_iter(self, start: int, steps: int):
        """Token stream for logical steps ``[start, steps)`` — prefetched
        via AsyncFeeder or synchronous.  Factored out so an elastic
        re-mesh can rebuild it mid-fit: the generators bind ``self``
        lazily, so a fresh iterator picks up the new batch size, row span,
        and token sharding."""
        from pytorch_distributed_tpu.data.loader import AsyncFeeder

        host_iter = (
            self._local_batch(self.dataset, i) for i in range(start, steps)
        )
        if self.prefetch > 0:
            return AsyncFeeder(self._put_tokens,
                               prefetch=self.prefetch)(host_iter)
        # synchronous baseline (measured in lm_feeder_bench)
        return (self._put_tokens(b) for b in host_iter)

    def fit(self, steps: int, print_freq: int = 10) -> float:
        from pytorch_distributed_tpu.obs import scope

        if self.watchdog is not None:
            self.watchdog.install()  # idempotent (re-fit after a fit)
        if self._exporter is not None and not self._exporter.running:
            # A prior fit's obs.close() stopped the owned exporter;
            # re-register so this fit serves (and tears down) again.
            self.obs.register(self._exporter)

        meters = StepMeters(
            steps,
            [("loss", "Loss", ":.4e"), ("acc", "Acc@1", ":6.2f")],
            prefix="Step: ",
        )
        start = min(self._start_step, steps)
        # Tokens per optimizer step — the LM throughput unit (tokens/s).
        tokens_per_step = self.batch_size * self.dataset.seq_len
        final_ppl = None  # ppl from an interval eval on the very last step
        preempted = False
        completed = start  # steps finished (preemption/ft checkpoints)
        # Prefetch ≥2: batch assembly (real host work for TextFileDataset
        # windows) + async transfer dispatch run on a producer thread, off
        # the step hot path — the LM counterpart of the image DeviceFeeder
        # (reference apex data_prefetcher, apex_distributed.py:115-169).
        # Each process assembles ONLY its own rows (wraparound batching,
        # the convention both LM datasets implement); a resumed run starts
        # the stream at the checkpointed step — no epoch rerun.
        token_iter = self._token_iter(start, steps)
        if self._keeper is not None and not self._keeper.has_snapshot:
            # Initial last-good snapshot (all ranks — see StateKeeper).
            self._keeper.update(self.state, start)
        lr_val = None  # cached: jnp.float32() only when the value changes
        lr = jnp.float32(self.lr)
        # Flight recorder death paths: signal-dump chain (chains to the
        # caller's PreemptionGuard handler when both hold the same
        # signals) + the collective-hang watchdog daemon.
        flight_sig = None
        if self.flight is not None:
            import signal as _signal
            import threading as _threading

            if _threading.current_thread() is _threading.main_thread():
                from pytorch_distributed_tpu.obs.flightrec import (
                    FlightSignalDump,
                )

                sigs = (getattr(self.preempt, "_signals", None)
                        or (_signal.SIGTERM,))
                flight_sig = FlightSignalDump(self.flight,
                                              signals=sigs).install()
            if self._hang_wd is not None:
                self._hang_wd.start()
        try:
            meters.restart_clock()
            i = start
            while i < steps:
                # print_freq cadence: the cross-process agreement collective
                # (see utils/preempt.py) must run at the same step on every
                # rank, and stays off the per-step hot path.
                if (self.preempt is not None and i % print_freq == 0
                        and self._preempt_agreed()):
                    print(f"=> preemption signal: stopping at step {i}",
                          flush=True)
                    self.obs.log_event("preempt", step=i)
                    preempted = True
                    break
                if self.chaos is not None:
                    self.chaos.on_step(self, i)
                if self.elastic is not None:
                    # Membership epochs are coordinator-committed and read
                    # by every rank at the same step — an agreed value,
                    # not a local liveness probe (synclint would otherwise
                    # flag the re-mesh below as a divergent collective).
                    chg = self.elastic.poll(i)  # synclint: agreement
                    if chg is not None:
                        # Membership changed: rebuild against the survivor
                        # set and restart the token stream at the resume
                        # step (a shrink rewinds to the last-good snapshot;
                        # the step-indexed batching regenerates the same
                        # tokens, so retrained steps replay, not drift).
                        token_iter.close()
                        completed = i = self._apply_remesh(chg, at_step=i)
                        token_iter = self._token_iter(i, steps)
                        tokens_per_step = (self.batch_size
                                           * self.dataset.seq_len)
                        lr_val = None  # re-push the LR to the new mesh
                        meters.restart_clock()
                        continue
                # Attribution windows (--step-attr): data_wait wraps
                # batch acquisition *and* the chaos on_batch hook, so
                # injected loader delay lands in the measured component.
                sa = self.stepattr
                _dw = sa.data_wait if sa is not None else nullcontext
                with _dw():
                    tokens = next(token_iter)
                if self.chaos is not None:
                    with _dw():
                        tokens = self.chaos.on_batch(i, tokens)
                val = (self.lr_schedule(i)
                       if self.lr_schedule is not None else self.lr)
                if self.ft_guard is not None:
                    val = val * self.ft_guard.lr_scale
                val = val * self._elastic_lr_scale
                if val != lr_val:
                    lr_val, lr = val, jnp.float32(val)
                if ((self._comm_ledger_path is not None
                        or self._mem_ledger_path is not None)
                        and self._comm_fields is None):
                    self._emit_ledgers(tokens, lr)
                if self.flight is not None:
                    # Ring: step window + collective region (labelled with
                    # the ledger's dominant entry when the AOT lowering
                    # ran) — two deque appends, no sync/I/O.
                    self.flight.step_begin(i)
                    fc = self._flight_coll or {}
                    self.flight.coll_enter(i, kind=fc.get("kind"),
                                           bytes=fc.get("bytes"),
                                           name=fc.get("name"))
                if self.chaos is not None:
                    self.chaos.on_collective(self, i)
                _dev = sa.device if sa is not None else nullcontext
                _hs = sa.host_sync if sa is not None else nullcontext
                with scope("lm_step"), self._wd_watch("lm_step", i), _dev():
                    self.state, metrics = self.step_fn(self.state, tokens, lr)
                    if sa is not None:
                        # The step's blocking transfer: without it, async
                        # dispatch smears step N's device time into N+1's
                        # windows.  Only when --step-attr opted in;
                        # overhead fenced <2% p50 in RESULTS_stepattr.json.
                        jax.block_until_ready(metrics)  # shardlint: allow-sync
                if self.flight is not None:
                    self.flight.coll_exit(i)
                    self.flight.step_end(i)
                completed = i + 1
                with _hs():
                    dt = meters.update(metrics, self.batch_size)
                extra = (dict(self._mfu.fields(dt))
                         if self._mfu is not None else {})
                if self._comm_fields:
                    extra.update(self._comm_fields)
                if sa is not None:
                    extra.update(sa.fields(dt))
                # log_step's lazy-flush scalar drain accrues to the *next*
                # step's host_sync window (its dt covers this wall time).
                with _hs():
                    self.obs.log_step(
                        i, step_time=dt, n_items=tokens_per_step, lr=lr,
                        scalars=dict(metrics),  # incl. norms when log_norms on
                        extra=extra or None,
                    )
                # booked after the first step's record so the event's
                # timestamp cannot widen the post-hoc goodput wall span
                # back across the step-0 compile
                if sa is not None and not self._stepattr_phases_booked:
                    self._book_stepattr_phases()
                if self.hb is not None:
                    from pytorch_distributed_tpu.obs import (
                        sample_process_memory,
                    )
                    self.hb.beat(i, step_time_ema=self.obs.ema,
                                 last_ft=self.obs.last_event_kind,
                                 mem_bytes=sample_process_memory(),
                                 data_wait_ms=(sa.data_wait_ema_ms
                                               if sa is not None else None))
                    if self.flight is not None:
                        self.flight.heartbeat(
                            {"step": i,
                             "last_ft": self.obs.last_event_kind})
                meters.maybe_display(i, print_freq)
                at_save = (self.save_steps > 0
                           and completed % self.save_steps == 0)
                if self.ft_guard is not None:
                    # Lazy-sync policy: flags buffer unconverted and drain
                    # every check_every steps — forced at a save boundary so
                    # a snapshot never races an undetected divergence.
                    rollback = self.ft_guard.observe(
                        i, metrics.get("nonfinite"))
                    if at_save:
                        # Agreed: the drained flag is the in-step
                        # all-reduced nonfinite count — every rank reads
                        # the identical verdict at the same boundary.
                        rollback = self.ft_guard.drain() or rollback  # synclint: agreement
                    if rollback:
                        self._rollback(i)
                    # A flagged streak means the current state is suspect —
                    # don't refresh the last-good snapshot from it.
                    at_save = at_save and self.ft_guard.consecutive == 0
                if at_save:
                    if self._keeper is not None:
                        self._keeper.update(self.state, completed)
                    if self.checkpoint_dir:
                        self._save_checkpoint(completed)
                        meters.restart_clock()  # exclude ckpt I/O from meter
                if (
                    self._eval_fn is not None
                    and self.eval_every > 0
                    and (i + 1) % self.eval_every == 0
                ):
                    _, final_ppl, _ = self.evaluate()
                    self.best_ppl = min(self.best_ppl, final_ppl)
                    meters.restart_clock()  # eval must not pollute the meter
                else:
                    final_ppl = None
                i += 1
            if self.ft_guard is not None and self.ft_guard.drain():  # synclint: agreement
                # Trailing flags buffered past the last cadence point must
                # resolve before the end-of-fit checkpoint can capture a
                # diverged state.  Agreed: the flag drains an in-step
                # all-reduced scalar.
                self._rollback(completed)
        except BaseException as e:
            if self.flight is not None:
                from pytorch_distributed_tpu.ft.integrity import (
                    CheckpointCorruptError,
                )

                self.flight.record("exception", completed,
                                   error=type(e).__name__)
                self.flight.dump("checkpoint_corrupt"
                                 if isinstance(e, CheckpointCorruptError)
                                 else f"exception:{type(e).__name__}")
            raise
        finally:
            token_iter.close()  # unblocks the producer on early exit
            if self._hang_wd is not None:
                self._hang_wd.stop()
            if flight_sig is not None:
                flight_sig.uninstall()
            if self.watchdog is not None:
                self.watchdog.uninstall()
            if self.hb is not None:
                from pytorch_distributed_tpu.obs import sample_process_memory
                self.hb.close(int(self.state.step) - 1,
                              step_time_ema=self.obs.ema,
                              last_ft=self.obs.last_event_kind,
                              mem_bytes=sample_process_memory(),
                              data_wait_ms=(self.stepattr.data_wait_ema_ms
                                            if self.stepattr is not None
                                            else None))
            self.obs.flush()
            if self._goodput is not None:
                print(f"=> {self._goodput.format_summary()}", flush=True)
            self.obs.close()
        is_best = False
        if self._eval_fn is not None and not preempted:
            # Preempted runs skip the final eval: the SIGTERM grace window
            # belongs to the checkpoint, and a partial-state eval must not
            # contend for the best-checkpoint slot.
            if final_ppl is None:  # last step didn't land on an eval boundary
                _, final_ppl, _ = self.evaluate()
            # <= so the final state is marked best when it ties the best seen
            # (the common case: the just-run interval eval set best_ppl).
            is_best = final_ppl <= self.best_ppl
            self.best_ppl = min(self.best_ppl, final_ppl)
        last_loss = meters["loss"].val  # end-of-training loss, not run avg
        if self.checkpoint_dir:
            # End-of-fit checkpoint; its ft record carries the exact
            # completed-step count, so a preempted run resumes mid-stream.
            self._save_checkpoint(completed, is_best=is_best)
        return last_loss
