"""Jitted SPMD train/eval steps — the heart of the framework.

Replaces the reference's hot loop (reference distributed.py:242-276), which
performs 4 synchronous collectives + 3 ``.item()`` host syncs per batch
*before* backward even starts (SURVEY.md §3.1a note), with one compiled XLA
program per step:

- forward, loss, backward, gradient sync, SGD update, and the global metric
  means are all **inside** the jitted function;
- gradient all-reduce is not a backward hook (DDP, distributed.py:147) but a
  collective XLA fuses into the step — under GSPMD it is inserted
  automatically from the shardings; in the explicit variant we write the
  ``psum`` ourselves inside ``shard_map`` (Horovod-recipe analogue, with
  bf16 wire compression ≙ horovod_distributed.py:159-164);
- the reference's ``barrier()`` has no equivalent: XLA programs are
  bulk-synchronous by construction (SURVEY.md §5.8).

Metrics are returned as unready device scalars; meters read them lazily, so
the host never blocks inside the loop.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from pytorch_distributed_tpu.ops import cross_entropy, qcomm, topk_correct
from pytorch_distributed_tpu.parallel import overlap as overlap_lib
from pytorch_distributed_tpu.parallel import zero as zero_lib
from pytorch_distributed_tpu.train.optim import sgd_update
from pytorch_distributed_tpu.train.state import TrainState

Batch = Dict[str, jnp.ndarray]
Metrics = Dict[str, jnp.ndarray]


def tree_l2_norm(tree) -> jnp.ndarray:
    """Global L2 norm of a pytree, f32 accumulation — computed in-graph so
    the host never syncs for it (meters / MetricsLogger convert lazily)."""
    return jnp.sqrt(sum(
        jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        for leaf in jax.tree_util.tree_leaves(tree)
    ))


def nonfinite_flag(loss: jnp.ndarray, grad_norm: jnp.ndarray) -> jnp.ndarray:
    """1.0 when loss or the global grad norm is NaN/inf, else 0.0 — the
    divergence-guard observable (ft/divergence.py).  The grad norm covers
    gradient overflow the loss alone misses (f32 loss can stay finite while
    a bf16 backward has already produced infs)."""
    ok = jnp.logical_and(jnp.isfinite(loss), jnp.isfinite(grad_norm))
    return jnp.logical_not(ok).astype(jnp.float32)


def gate_update(bad: jnp.ndarray, old_tree, new_tree):
    """Select ``old_tree`` leaf-wise when ``bad`` (a 0/1 scalar) is set —
    the in-graph skip that keeps a non-finite batch's update out of the
    weights entirely, with no host round-trip.  ``jnp.where`` on a
    replicated scalar predicate compiles to a select XLA fuses into the
    optimizer; sharded leaves keep their layout."""
    pred = bad > 0
    return jax.tree_util.tree_map(
        lambda old, new: jnp.where(pred, old, new), old_tree, new_tree
    )


def _forward_and_sums(model, params, batch_stats, batch: Batch, train: bool,
                      dropout_rng=None):
    """Weighted-sum loss/metric numerators + weight count (exact over padding)."""
    variables = {"params": params, "batch_stats": batch_stats}
    # named_scope: forward ops carry this name into XPlane traces (autodiff
    # derives the backward op names from it), so profiler self-time
    # attributes to phases instead of anonymous fusions.
    with jax.named_scope("forward"):
        if train:
            rngs = {"dropout": dropout_rng} if dropout_rng is not None else None
            logits, mutated = model.apply(
                variables, batch["images"], train=True,
                mutable=["batch_stats"], rngs=rngs,
            )
            new_stats = mutated.get("batch_stats", batch_stats)
        else:
            logits = model.apply(variables, batch["images"], train=False)
            new_stats = batch_stats
    with jax.named_scope("loss_and_metrics"):
        w = batch["weights"].astype(jnp.float32)
        count = jnp.sum(w)
        loss_sum = cross_entropy(logits, batch["labels"], weights=w) * count
        c1 = jnp.sum(topk_correct(logits, batch["labels"], 1) * w)
        c5 = jnp.sum(topk_correct(logits, batch["labels"], 5) * w)
    return loss_sum, (logits, new_stats, c1, c5, count)


def make_train_step(
    model,
    mesh: Mesh,
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    data_axis: str = "data",
    wire_dtype: Optional[jnp.dtype] = None,
    grad_compress: Optional[str] = None,
    explicit_collectives: bool = False,
    seed: int = 0,
    tx=None,
    accum_steps: int = 1,
    log_norms: bool = False,
    guard_nonfinite: bool = False,
    zero: str = "none",
    params: Optional[Any] = None,
    overlap: str = "none",
    bucket_mb: float = overlap_lib.DEFAULT_BUCKET_MB,
    wus_gather: str = "eager",
) -> Callable[[TrainState, Batch, jnp.ndarray], Tuple[TrainState, Metrics]]:
    """Build the jitted train step for ``mesh``.

    Two interchangeable gradient-sync expressions (the recipe difference
    matrix, SURVEY.md §2.3):

    - GSPMD (default): shardings in, XLA inserts the gradient all-reduce.
      ≙ DDP's fused bucketed allreduce (reference distributed.py:147-148).
    - ``explicit_collectives=True``: ``shard_map`` over the data axis with a
      hand-written ``psum`` — the Horovod-analogue; ``grad_compress="bf16"``
      reproduces fp16 gradient wire compression
      (horovod_distributed.py:159-164) as bf16-compressed collectives, and
      ``grad_compress="int8"``/``"fp8"`` goes further: a per-block
      quantized all-reduce (ops/qcomm.py, the EQuARX decomposition) with
      DynamiQ-style error feedback — the residual rides in
      ``TrainState.residual``, stacked over the data axis.

    ``grad_compress``: ``none | bf16 | int8 | fp8`` — the gradient wire
    format for the DP sync.  Under GSPMD every non-``none`` mode is a
    NUMERICS emulation only (XLA owns the collective; see the warning);
    real wire compression requires ``explicit_collectives=True``.  The
    legacy ``wire_dtype`` argument is a deprecated alias for the ``bf16``
    mode.

    ``accum_steps``: gradient accumulation — the batch is split into that
    many microbatches (strided, so each microbatch stays evenly spread over
    the data-sharded devices with no resharding), gradients/metrics are
    summed across a ``lax.scan`` inside the compiled step, and one optimizer
    update is applied.  Lets the reference's global-batch-3200 default
    (distributed.py:43-48) run on any chip count within HBM limits.  For
    BN-free, dropout-free models the numerics exactly equal the
    unaccumulated step (sum-form loss normalized once); with BatchNorm the
    batch statistics are per-microbatch (like training at the smaller batch)
    and dropout draws per-microbatch keys — standard accumulation semantics,
    same as torch.

    ``tx``: an optional optax ``GradientTransformation``.  Default (None) is
    the torch-parity SGD (train/optim.py), with ``lr`` as a live scalar
    operand; with optax the schedule lives inside ``tx`` and the ``lr``
    argument is ignored (state.momentum carries the optax opt_state).

    ``log_norms``: add in-graph global ``grad_norm``/``param_norm`` scalars
    to the metrics dict (the obs-layer observables, converted lazily by the
    MetricsLogger).  Off by default: the per-leaf reductions measurably
    lengthen XLA compiles, so the cost is only paid when a metrics sink is
    actually attached (Trainer enables it with ``--metrics-jsonl``).

    ``zero``: ``none | wus`` — ZeRO-style weight-update sharding
    (parallel/zero.py, arXiv:2004.13336).  Under ``wus`` the explicit
    path replaces the gradient all-reduce with a reduce-scatter, keeps
    the momentum buffer sharded ``P(data_axis)`` in stacked-chunk layout,
    applies the torch-parity SGD update on the 1/N shard, and all-gathers
    the parameter delta once per step; ``grad_compress`` composes — both
    wire hops ride the quantized qcomm path with error feedback
    (``compressed_reduce_scatter`` / ``compressed_all_gather``).  Under
    GSPMD the same semantics are a sharding-spec change: momentum takes
    ``fsdp_specs`` shardings (pass ``params`` so the layout can be
    derived) and XLA inserts the reduce-scatter/all-gather pair.  The
    momentum pytree under explicit wus is ``{"buf": chunks[, "agerr":
    chunks]}`` — build it with ``zero_lib.init_wus_momentum``; checkpoints
    still store the param-shaped layout (train/checkpoint.py gathers on
    save and re-chunks on restore).  Requires the default torch-parity
    SGD (``tx`` must be None: the chunked update re-implements
    ``optim._upd`` on flat shards).

    ``guard_nonfinite``: compute a ``nonfinite`` flag from loss + global
    grad norm and gate the whole update (params, momentum, BN stats) on it
    inside the compiled step — a NaN/inf batch is structurally skipped
    (state passes through unchanged except the step counter) and the flag
    lands in the metrics as a lazily-converted device scalar for the host
    ``DivergenceGuard`` policy (ft/divergence.py).  ``--nan-guard``.

    ``overlap``: ``none | bucketed`` — the comm-overlap scheduler
    (parallel/overlap.py).  ``bucketed`` partitions the gradient pytree
    into ~``bucket_mb``-MiB buckets in reverse-autodiff order and issues
    each bucket's sync (``psum`` / ``compressed_psum`` / reduce-scatter)
    as its own collective under a nested ``grad_sync``/``b<k>`` scope, so
    the sync of early-produced gradients can run concurrently with the
    remaining backward instead of as one tail-end collective; the per-leaf
    math is identical, so results are bit-equal to ``overlap="none"``.
    Requires ``explicit_collectives=True`` (under GSPMD, XLA owns the
    collective placement).  The ``--zero wus`` delta all-gather buckets
    too (``ag_b<k>`` scopes, forward order).

    ``wus_gather``: ``eager | deferred`` — with ``zero='wus'`` +
    ``overlap='bucketed'``, ``deferred`` double-buffers the param state:
    the step *stages* its delta chunks in ``momentum["pending"]`` and
    drains the previous step's at its head under a ``param_gather`` scope
    (parallel/overlap.py), so the gather overlaps the next forward.
    ``state.params`` then lag one staged delta; drain with
    ``overlap_lib.materialize_params`` before eval/checkpoint.  Build the
    momentum with an extra ``pending`` slot (``init_pending``).  Only the
    f32/bf16 delta wire supports deferral (quantized error feedback is
    step-order-dependent).

    BatchNorm semantics differ deliberately, matching each formulation's GPU
    ancestor: GSPMD BN normalizes over the *global* batch (SyncBN — XLA
    inserts the cross-replica mean), while the shard_map variant normalizes
    per shard, exactly like torch DDP's unsynced BN (the reference's
    behavior).  Running stats are pmean'd in both so replicas stay consistent.
    """

    mode, cast_dtype = qcomm.resolve_mode(grad_compress, wire_dtype)
    zero_mode = zero_lib.resolve_zero(zero)
    overlap_mode = overlap_lib.resolve_overlap(overlap)
    if overlap_mode == "bucketed" and not explicit_collectives:
        raise ValueError(
            "overlap='bucketed' schedules hand-written collectives and "
            "requires explicit_collectives=True (under GSPMD, XLA owns "
            "collective placement — there is nothing to bucket)")
    if wus_gather not in ("eager", "deferred"):
        raise ValueError(
            f"wus_gather must be 'eager' or 'deferred', got {wus_gather!r}")
    if wus_gather == "deferred":
        if zero_mode != "wus" or overlap_mode != "bucketed":
            raise ValueError(
                "wus_gather='deferred' is the double-buffered ZeRO-WUS "
                "delta gather — it requires zero='wus' and "
                "overlap='bucketed'")
        if mode in qcomm.QUANTIZED_MODES:
            raise ValueError(
                "wus_gather='deferred' supports the f32/bf16 delta wire "
                "only: the quantized gather's error feedback is step-order"
                "-dependent and cannot be staged across steps")
    if zero_mode == "wus":
        if tx is not None:
            raise ValueError(
                "zero='wus' implements the torch-parity SGD update on 1/N "
                "shards; an optax tx cannot be chunked — drop one of them")
        if not explicit_collectives and params is None:
            raise ValueError(
                "zero='wus' under GSPMD derives the momentum shardings "
                "from the params tree — pass params=state.params")

    def sync_grads(grads, count, residual):
        # grads arrive as *local weighted sums*; sync then normalize.
        with jax.named_scope("grad_sync"):
            if overlap_mode == "bucketed":
                grads, residual = overlap_lib.bucketed_psum(
                    grads, residual, data_axis, mode=mode,
                    cast_dtype=cast_dtype, bucket_mb=bucket_mb)
            elif mode in qcomm.QUANTIZED_MODES:
                grads, residual = qcomm.compressed_psum(
                    grads, residual, data_axis, mode=mode)
            else:
                if cast_dtype is not None:
                    grads = jax.tree_util.tree_map(
                        lambda g: g.astype(cast_dtype), grads)
                grads = jax.lax.psum(grads, data_axis)
            gcount = jax.lax.psum(count, data_axis)
            return jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) / gcount, grads
            ), gcount, residual

    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    base_key = jax.random.PRNGKey(seed)
    if tx is not None:
        import warnings

        warnings.warn(
            "make_train_step: tx provided — the lr argument (and the "
            "harness's step-decay schedule) plus the momentum/weight_decay "
            "settings are INACTIVE; configure schedule and regularization "
            "inside the optax transformation.",
            stacklevel=2,
        )
    if mode != "none" and not explicit_collectives:
        import warnings

        warnings.warn(
            f"make_train_step: grad_compress={mode!r} under GSPMD is a "
            "NUMERICS emulation only — XLA places the gradient all-reduce "
            "from the shardings, so the quantize/cast rounds already-synced "
            "values and does not compress the collective wire format. Use "
            "explicit_collectives=True for true compressed-wire gradient "
            "sync (the Horovod-compression analogue).",
            stacklevel=2,
        )

    def apply_updates(state: TrainState, grads, lr):
        with jax.named_scope("optimizer"):
            if tx is None:
                return sgd_update(
                    grads, state.momentum, state.params, lr,
                    momentum=momentum, weight_decay=weight_decay,
                )
            import optax

            updates, new_opt = tx.update(grads, state.momentum, state.params)
            return optax.apply_updates(state.params, updates), new_opt

    def micro_grads(params, stats, mbatch, mrng):
        """Unnormalized (sum-form) grads + metric sums for one microbatch."""

        def loss_fn(params):
            loss_sum, aux = _forward_and_sums(
                model, params, stats, mbatch, train=True, dropout_rng=mrng
            )
            return loss_sum, aux

        (loss_sum, (_, new_stats, c1, c5, count)), grads = (
            jax.value_and_grad(loss_fn, has_aux=True)(params)
        )
        return grads, new_stats, (loss_sum, c1, c5, count)

    def accumulated_grads(params, stats, batch: Batch, rng):
        """Sum-form grads/metric-sums over ``accum_steps`` strided microbatches.

        Shared by both formulations: under GSPMD the batch is the global
        batch; under shard_map it is the per-shard slice (the strided split
        is then shard-local, and the single psum still happens *after* the
        scan — one collective per optimizer step, not per microbatch, which
        is the whole point of accumulating)."""
        if accum_steps == 1:
            return micro_grads(params, stats, batch, rng)
        b = batch["images"].shape[0]
        if b % accum_steps:
            raise ValueError(
                f"batch dimension {b} (per-shard under explicit collectives, "
                f"global under GSPMD) is not divisible by accum_steps "
                f"{accum_steps}"
            )
        # Strided split: microbatch i = samples [i::accum_steps].  A
        # contiguous split would concentrate each microbatch on a subset
        # of the data-sharded devices and force an all-to-all of the
        # whole input every step; the strided layout keeps every
        # microbatch evenly distributed shard-locally.
        micro = jax.tree_util.tree_map(
            lambda v: v.reshape(
                (v.shape[0] // accum_steps, accum_steps) + v.shape[1:]
            ).swapaxes(0, 1),
            batch,
        )

        def body(carry, xs):
            g_acc, stats, sums = carry
            mb, i = xs
            g, stats, s = micro_grads(params, stats, mb, jax.random.fold_in(rng, i))
            g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
            sums = tuple(a + b for a, b in zip(sums, s))
            return (g_acc, stats, sums), None

        init = (
            jax.tree_util.tree_map(jnp.zeros_like, params),
            stats,
            (jnp.float32(0), jnp.float32(0), jnp.float32(0), jnp.float32(0)),
        )
        (grads, new_stats, sums), _ = jax.lax.scan(
            body, init, (micro, jnp.arange(accum_steps))
        )
        return grads, new_stats, sums

    def local_step(state: TrainState, batch: Batch, lr: jnp.ndarray):
        """Runs per-shard under shard_map; all reductions explicit."""
        # Per-step, per-shard dropout stream (shards see different data).
        rng = jax.random.fold_in(
            jax.random.fold_in(base_key, state.step),
            jax.lax.axis_index(data_axis),
        )
        params = state.params
        if wus_gather == "deferred":
            # Double-buffered WUS: drain the PREVIOUS step's staged delta
            # chunks at the head of this step — in dataflow terms layer
            # k's gather only blocks layer k's forward, so the gather
            # overlaps this step's earlier-layer compute.
            params = overlap_lib.drain_pending(
                params, state.momentum["pending"], data_axis,
                cast_dtype=cast_dtype)
        grads, new_stats, (loss_sum, c1, c5, count) = accumulated_grads(
            params, state.batch_stats, batch, rng
        )
        if zero_mode == "wus":
            # Weight-update sharding: reduce-scatter the gradient sums so
            # this rank owns the exact f32 sum of its 1/N chunk, update on
            # the shard (momentum stays chunked), all-gather the delta.
            n = jax.lax.axis_size(data_axis)
            idx = jax.lax.axis_index(data_axis)
            with jax.named_scope("grad_sync"):
                if overlap_mode == "bucketed":
                    gchunks, new_residual = overlap_lib.bucketed_reduce_scatter(
                        grads, state.residual, data_axis, n, mode=mode,
                        cast_dtype=cast_dtype, bucket_mb=bucket_mb)
                elif mode in qcomm.QUANTIZED_MODES:
                    gchunks, new_residual = qcomm.compressed_reduce_scatter(
                        grads, state.residual, data_axis, mode=mode)
                else:
                    gchunks = zero_lib.reduce_scatter_grads(
                        grads, data_axis, n, cast_dtype=cast_dtype)
                    new_residual = state.residual
                gcount = jax.lax.psum(count, data_axis)
                gchunks = jax.tree_util.tree_map(
                    lambda g: g / gcount, gchunks)
            with jax.named_scope("optimizer"):
                if wus_gather == "deferred":
                    # Stage this step's deltas; the next step drains them.
                    deltas, new_buf = zero_lib.wus_update_chunks(
                        params, state.momentum, gchunks, lr, idx, n,
                        momentum_coef=momentum, weight_decay=weight_decay)
                    new_params = params
                    new_momentum = {
                        "buf": new_buf,
                        "pending": jax.tree_util.tree_map(
                            lambda d: d.reshape((1,) + d.shape), deltas),
                    }
                else:
                    new_params, new_momentum = zero_lib.wus_apply_updates(
                        params, state.momentum, gchunks, lr, idx, n,
                        data_axis, momentum_coef=momentum,
                        weight_decay=weight_decay, mode=mode,
                        cast_dtype=cast_dtype,
                        bucket_mb=(bucket_mb if overlap_mode == "bucketed"
                                   else None))
        else:
            grads, gcount, new_residual = sync_grads(
                grads, count, state.residual)
            new_params, new_momentum = apply_updates(state, grads, lr)
        # BN running stats: average local EMAs across shards so replicas agree.
        new_stats = jax.lax.pmean(new_stats, data_axis)
        metrics = {
            "loss": jax.lax.psum(loss_sum, data_axis) / gcount,
            "acc1": jax.lax.psum(c1, data_axis) * 100.0 / gcount,
            "acc5": jax.lax.psum(c5, data_axis) * 100.0 / gcount,
        }
        gnorm = None
        if log_norms or guard_nonfinite:
            if zero_mode == "wus":
                # Reduce-scattered chunks are disjoint across ranks, so the
                # replicated-path shortcut (per-shard norm == global norm)
                # does not hold — one extra scalar psum of per-chunk square
                # sums recovers the exact global norm (padding is zeros).
                gnorm = jnp.sqrt(jax.lax.psum(
                    zero_lib.chunk_sq_sum(gchunks), data_axis))
            else:
                # Synced grads are identical on every shard, so the
                # per-shard norm IS the global norm — no extra collective.
                gnorm = tree_l2_norm(grads)
        if guard_nonfinite:
            bad = nonfinite_flag(metrics["loss"], gnorm)
            new_params = gate_update(bad, state.params, new_params)
            new_momentum = gate_update(bad, state.momentum, new_momentum)
            new_stats = gate_update(bad, state.batch_stats, new_stats)
            new_residual = gate_update(bad, state.residual, new_residual)
            metrics["nonfinite"] = bad
        if log_norms:
            metrics["grad_norm"] = gnorm
            metrics["param_norm"] = tree_l2_norm(new_params)
        return (
            TrainState(state.step + 1, new_params, new_stats, new_momentum,
                       new_residual),
            metrics,
        )

    def global_step(state: TrainState, batch: Batch, lr: jnp.ndarray):
        """GSPMD formulation: global-semantics math, XLA infers collectives."""
        rng = jax.random.fold_in(base_key, state.step)
        grads, new_stats, (loss_sum, c1, c5, count) = accumulated_grads(
            state.params, state.batch_stats, batch, rng
        )
        count = jnp.maximum(count, 1.0)
        grads = jax.tree_util.tree_map(lambda g: g / count, grads)
        new_residual = state.residual
        if mode in qcomm.QUANTIZED_MODES:
            with jax.named_scope("grad_sync"):
                grads, new_residual = qcomm.compress_emulated(
                    grads, state.residual, mode)
        elif cast_dtype is not None:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(cast_dtype).astype(jnp.float32), grads
            )
        new_params, new_momentum = apply_updates(state, grads, lr)
        metrics = {
            "loss": loss_sum / count,
            "acc1": c1 * 100.0 / count,
            "acc5": c5 * 100.0 / count,
        }
        gnorm = (tree_l2_norm(grads)
                 if (log_norms or guard_nonfinite) else None)
        if guard_nonfinite:
            bad = nonfinite_flag(metrics["loss"], gnorm)
            new_params = gate_update(bad, state.params, new_params)
            new_momentum = gate_update(bad, state.momentum, new_momentum)
            new_stats = gate_update(bad, state.batch_stats, new_stats)
            new_residual = gate_update(bad, state.residual, new_residual)
            metrics["nonfinite"] = bad
        if log_norms:
            metrics["grad_norm"] = gnorm
            metrics["param_norm"] = tree_l2_norm(new_params)
        return (
            TrainState(state.step + 1, new_params, new_stats, new_momentum,
                       new_residual),
            metrics,
        )

    replicated = NamedSharding(mesh, P())
    sharded = NamedSharding(mesh, P(data_axis))
    batch_shardings = {"images": sharded, "labels": sharded, "weights": sharded}
    # The error-feedback residual of the explicit quantized path is per-rank
    # state: stacked (n_data, *shape) leaves sharded over the data axis so
    # each rank owns exactly its slot (a TrainState-shaped prefix tree; the
    # other fields stay replicated).
    state_sharding = replicated
    state_spec = P()
    quantized = mode in qcomm.QUANTIZED_MODES
    if explicit_collectives and (quantized or zero_mode == "wus"):
        # Weight-update sharding adds a second sharded-state subtree: the
        # stacked-chunk momentum {"buf"[, "agerr"]} rides P(data_axis) with
        # the same one-slot-per-rank discipline as the residual.
        res_sh = (NamedSharding(mesh, P(data_axis)) if quantized
                  else replicated)
        mom_sh = (NamedSharding(mesh, P(data_axis)) if zero_mode == "wus"
                  else replicated)
        state_sharding = TrainState(
            step=replicated, params=replicated, batch_stats=replicated,
            momentum=mom_sh, residual=res_sh)
        state_spec = TrainState(
            step=P(), params=P(), batch_stats=P(),
            momentum=P(data_axis) if zero_mode == "wus" else P(),
            residual=P(data_axis) if quantized else P())
    elif zero_mode == "wus":
        # GSPMD WUS is a layout statement: momentum leaves take their
        # fsdp_specs sharding while params stay replicated; XLA's SPMD
        # partitioner inserts the gradient reduce-scatter (into the
        # sharded buffer) and the parameter-delta all-gather on its own.
        mom_sharding = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            zero_lib.zero_momentum_specs(params, mesh, data_axis=data_axis))
        state_sharding = TrainState(
            step=replicated, params=replicated, batch_stats=replicated,
            momentum=mom_sharding, residual=replicated)

    if explicit_collectives:
        batch_specs = {k: P(data_axis) for k in ("images", "labels", "weights")}
        stepped = shard_map(
            local_step,
            mesh=mesh,
            in_specs=(state_spec, batch_specs, P()),
            out_specs=(state_spec, P()),
            check_vma=False,
        )
    else:
        stepped = global_step

    return jax.jit(
        stepped,
        in_shardings=(state_sharding, batch_shardings, replicated),
        out_shardings=(state_sharding, replicated),
        donate_argnums=(0,),
    )


def make_eval_step(
    model,
    mesh: Mesh,
    data_axis: str = "data",
    residual_sharded: bool = False,
    momentum_sharding=None,
) -> Callable[[TrainState, Batch], Metrics]:
    """Distributed evaluation step (reference validate(),
    distributed.py:279-324 + the README's distributed-eval chapter).

    Returns weighted *sums* (loss·w, correct@1, correct@5, count) so the host
    can aggregate exactly over an epoch — the all-reduce lives inside the
    compiled program; no ``barrier()`` + 3 ``all_reduce`` calls per batch.

    ``residual_sharded``: the explicit quantized grad-sync path
    (``grad_compress=int8|fp8``) carries stacked error-feedback residuals
    sharded over ``data_axis`` in ``TrainState.residual``; eval never reads
    them, but the in_shardings must still describe them or pjit rejects the
    state.

    ``momentum_sharding``: same story for ``--zero wus`` optimizer state —
    pass the momentum sharding (a NamedSharding prefix or a momentum-shaped
    tree of them) the train step uses; ``None`` keeps the replicated-DP
    default.
    """

    def step(state: TrainState, batch: Batch) -> Metrics:
        loss_sum, (_, _, c1, c5, count) = _forward_and_sums(
            model, state.params, state.batch_stats, batch, train=False
        )
        return {"loss_sum": loss_sum, "correct1": c1, "correct5": c5, "count": count}

    replicated = NamedSharding(mesh, P())
    sharded = NamedSharding(mesh, P(data_axis))
    state_shardings = TrainState(
        step=replicated,
        params=replicated,
        batch_stats=replicated,
        momentum=(replicated if momentum_sharding is None
                  else momentum_sharding),
        residual=sharded if residual_sharded else replicated,
    )
    batch_shardings = {"images": sharded, "labels": sharded, "weights": sharded}
    return jax.jit(
        step,
        in_shardings=(state_shardings, batch_shardings),
        out_shardings=replicated,
    )
