"""Checkpoint save / resume.

Capability parity with the reference's ``save_checkpoint``
(reference distributed.py:327-330, payload at :219-225): a single-file
checkpoint of ``{epoch, arch, state, best_acc1}`` written by rank 0, copied
to ``model_best`` on a new best — plus the **resume load path the reference
lacks** (no ``torch.load`` exists anywhere in the reference; SURVEY.md §5.3).

Like the reference's ``model.module.state_dict()`` unwrap (:223), the saved
tree is plain host numpy — recipe-interchangeable: any recipe can load any
recipe's checkpoint regardless of mesh shape, because state is replicated
(DP) and re-sharding happens at restore time via ``device_put``.

Format: flax msgpack (``flax.serialization``), written atomically
(tmp + rename).

Fault tolerance (ft/): every msgpack write carries an atomic sha256
sidecar, the previous checkpoint is retained as ``checkpoint.prev.msgpack``
(retain N=2, matching the orbax manager's ``max_to_keep=2``), loads verify
the sidecar *before* deserializing and fall back to the retained previous
file when the latest is corrupt/truncated, and all file I/O runs under
bounded exponential-backoff retries for flaky shared filesystems.  The
payload additionally carries an ``ft`` record (step-in-epoch, global step,
sampler RNG state, LR backoff scale) so ``--resume`` restores the exact
step, not just the epoch.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from pytorch_distributed_tpu.ft.integrity import (
    CheckpointCorruptError,
    check_integrity,
    replace_with_sidecar,
    retrying,
    verify_sidecar,
    write_sidecar,
)
from pytorch_distributed_tpu.parallel import zero as zero_lib
from pytorch_distributed_tpu.train.state import TrainState

CHECKPOINT_NAME = "checkpoint.msgpack"
PREV_NAME = "checkpoint.prev.msgpack"
BEST_NAME = "model_best.msgpack"

# Data-iterator / FT state stored alongside the model state: enough to
# restore the exact step.  ``step`` is the step-in-epoch offset (0 = "this
# epoch is complete; resume starts the next one" — the legacy epoch
# semantics); the sampler's (seed, epoch) pair regenerates the identical
# permutation with no communication, so no index lists are stored.
FT_DEFAULTS: Dict[str, Any] = {
    "step": 0,
    "global_step": 0,
    "sampler_seed": 0,
    "sampler_epoch": 0,
    "lr_scale": 1.0,
}


def _ft_record(ft: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Normalize a (possibly partial/absent) ft dict to the canonical
    schema with plain-python values msgpack/json can carry."""
    out = dict(FT_DEFAULTS)
    for k, v in (ft or {}).items():
        if k not in FT_DEFAULTS:
            raise ValueError(f"unknown ft checkpoint field {k!r}; expected "
                             f"one of {sorted(FT_DEFAULTS)}")
        out[k] = float(v) if k == "lr_scale" else int(v)
    return out


def _to_host(tree: Any, want_value: bool = True) -> Any:
    """Fetch to host numpy, gathering sharded leaves first.

    DP state is replicated (plain fetch); TP/SP-sharded state on multi-host
    meshes spans non-addressable devices, where ``np.asarray`` would raise —
    those leaves are all-gathered across processes so the written checkpoint
    is always the full, replicated tree (the recipe-interchange invariant).

    ``want_value=False`` (non-primary ranks): still participate in the
    cross-process all-gather for non-addressable leaves — a collective every
    rank must enter — but skip the device→host copy of addressable leaves,
    whose bytes only the writing rank needs."""

    def fetch(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(x, tiled=True)
            return np.asarray(gathered) if want_value else None
        return np.asarray(x) if want_value else None

    return jax.tree_util.tree_map(fetch, tree)


_ORBAX_DIRNAME = "orbax"
_orbax_managers: Dict[str, Any] = {}


def _orbax_manager(directory: str):
    """One async CheckpointManager per directory (kept alive so in-flight
    async writes finish; per-epoch saves wait on the previous write)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.path.join(directory, _ORBAX_DIRNAME))
    mgr = _orbax_managers.get(path)
    if mgr is None:
        # Retention is latest-N (NOT best_fn): resume-from-latest must always
        # work, and a best_fn policy would garbage-collect the just-written
        # newest step whenever it isn't top-N.  The best epoch's score lives
        # in each step's meta/metrics for offline selection.
        mgr = ocp.CheckpointManager(
            path,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=2,
                enable_async_checkpointing=True,
            ),
        )
        _orbax_managers[path] = mgr
    return mgr


def wait_for_async_saves() -> None:
    """Drain in-flight orbax async writes.  Call before process exit (the
    epoch drivers do, end of fit) — Python shuts down executor threads
    before atexit handlers run, so deferring this to atexit loses the final
    epoch's checkpoint."""
    for mgr in _orbax_managers.values():
        mgr.wait_until_finished()


def _save_orbax(
    directory: str, state: TrainState, epoch: int, arch: str,
    best_acc1: float, is_best: bool, metric: Optional[float] = None,
    ft: Optional[Dict[str, Any]] = None,
) -> str:
    """Async sharded save: every process writes its own shards (OCDBT) — no
    host gather, no full-tree allgather; the at-scale story the msgpack
    backend's replicated single file cannot give (multi-host TP/SP state
    stays distributed on disk).  All processes must call (orbax coordinates
    across hosts internally)."""
    import orbax.checkpoint as ocp

    mgr = _orbax_manager(directory)
    momentum = state.momentum
    if zero_lib.is_wus_momentum(momentum):
        # Gather-on-save (weight-update sharding): checkpoints always store
        # the param-shaped replicated momentum layout so any recipe/mode can
        # restore any checkpoint.  All ranks gather (collective); the
        # error-feedback agerr is resettable state and is dropped.
        host = _to_host({"m": momentum, "p": state.params})
        momentum = zero_lib.gather_momentum(host["m"], host["p"])
    tree = {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "momentum": momentum,
    }
    has_residual = bool(jax.tree_util.tree_leaves(state.residual))
    if has_residual:
        # Error-feedback residuals (quantized grad sync, ops/qcomm.py) —
        # only written when carried, so uncompressed runs keep the legacy
        # payload layout.
        tree["residual"] = state.residual
    mgr.save(
        int(epoch),
        args=ocp.args.Composite(
            state=ocp.args.StandardSave(tree),
            meta=ocp.args.JsonSave(
                {"epoch": int(epoch), "arch": arch,
                 "best_acc1": float(best_acc1), "is_best": bool(is_best),
                 "has_residual": has_residual,
                 "ft": _ft_record(ft)}
            ),
        ),
        # The retention metric must be THIS epoch's own score: the running
        # max would tie every later epoch with the true best and let the
        # manager garbage-collect the actual best weights.
        metrics={"best_acc1": float(metric if metric is not None else best_acc1)},
    )
    return os.path.join(directory, _ORBAX_DIRNAME, str(int(epoch)))


def _load_orbax(path: str, state_template: TrainState):
    import orbax.checkpoint as ocp

    # `path` may be the checkpoint dir, the orbax subdir, or a specific step
    # (`.../orbax/<N>`).  A numeric basename counts as a step only when its
    # parent is the orbax subdir — a sweep layout like `runs/3` is a
    # checkpoint dir that happens to be named with digits.
    root = os.path.abspath(path)
    parent = os.path.dirname(root)
    if (os.path.basename(root).isdigit()
            and os.path.basename(parent) == _ORBAX_DIRNAME):
        step, root = int(os.path.basename(root)), parent
    else:
        if os.path.isdir(os.path.join(root, _ORBAX_DIRNAME)):
            root = os.path.join(root, _ORBAX_DIRNAME)
        step = None
    live = _orbax_managers.get(root)
    if live is not None:
        live.wait_until_finished()  # drain an in-flight async save
    mgr = live or ocp.CheckpointManager(root)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no orbax checkpoints under '{root}'")
    wus = zero_lib.is_wus_momentum(state_template.momentum)
    template = {
        "step": state_template.step,
        "params": state_template.params,
        "batch_stats": state_template.batch_stats,
        # Disk always holds the param-shaped momentum (gather-on-save
        # invariant), so a --zero wus template restores against a
        # param-shaped stand-in and re-chunks below.
        "momentum": (jax.tree_util.tree_map(
            lambda p: np.zeros(np.shape(p), np.float32),
            state_template.params) if wus else state_template.momentum),
    }
    # The residual is only restorable when both sides carry it (same
    # compression mode); otherwise the template's (possibly zero) residuals
    # stand — a mode switch across resume resets error feedback, it does
    # not fail the load.  The saved meta's has_residual flag (absent on
    # legacy checkpoints) says which payload layout is on disk.
    want_residual = bool(jax.tree_util.tree_leaves(state_template.residual))
    pre_meta = mgr.restore(
        step, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))["meta"]
    if want_residual and pre_meta.get("has_residual"):
        template["residual"] = state_template.residual
    restored = mgr.restore(
        step,
        args=ocp.args.Composite(
            state=ocp.args.StandardRestore(template),
            meta=ocp.args.JsonRestore(),
        ),
    )
    st = restored["state"]
    momentum = st["momentum"]
    if wus:
        buf = zero_lib.shard_momentum(momentum,
                                      state_template.momentum["buf"])
        momentum = {"buf": buf}
        if "agerr" in state_template.momentum:
            momentum["agerr"] = jax.tree_util.tree_map(np.zeros_like, buf)
    state = TrainState(
        step=st["step"],
        params=st["params"],
        batch_stats=st["batch_stats"],
        momentum=momentum,
        residual=st.get("residual", state_template.residual),
    )
    meta = {k: restored["meta"][k] for k in ("epoch", "arch", "best_acc1")}
    meta["ft"] = _ft_record(restored["meta"].get("ft"))
    return state, meta


def save_checkpoint(
    directory: str,
    state: TrainState,
    epoch: int,
    arch: str,
    best_acc1: float,
    is_best: bool,
    is_primary: bool = True,
    backend: str = "msgpack",
    metric: Optional[float] = None,
    ft: Optional[Dict[str, Any]] = None,
) -> Optional[str]:
    """Rank-0-guarded atomic save (reference distributed.py:218-225).

    The host gather runs on EVERY process before the primary guard:
    ``_to_host`` performs a cross-process all-gather for non-fully-addressable
    (multi-host-sharded) leaves, and a collective entered by rank 0 alone
    would deadlock the job at the first checkpoint. All ranks gather; only
    the primary writes.

    ``ft``: optional step-granular resume record (see ``FT_DEFAULTS``);
    omitted fields default to the epoch-boundary semantics.

    Write discipline (msgpack): payload to tmp + rename, the previous
    checkpoint rotated to ``checkpoint.prev.msgpack`` (with its sidecar)
    first, then the new sha256 sidecar — so at every instant the directory
    holds at least one complete, verifiable checkpoint.  The whole sequence
    is retried with bounded backoff on OSError (flaky shared filesystems);
    it is safe to re-run from the top because the rotation step is skipped
    once the target no longer exists.

    ``backend="orbax"``: async sharded per-process writes instead (see
    ``_save_orbax``); all ranks call, orbax coordinates."""
    if backend == "orbax":
        return _save_orbax(directory, state, epoch, arch, best_acc1, is_best,
                           metric=metric, ft=ft)
    if backend != "msgpack":
        raise ValueError(f"unknown checkpoint backend '{backend}'")
    host_tree = {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "momentum": state.momentum,
    }
    if jax.tree_util.tree_leaves(state.residual):
        # Error-feedback residuals (quantized grad sync, ops/qcomm.py);
        # omitted when empty so uncompressed runs keep the legacy layout.
        host_tree["residual"] = state.residual
    host_state = _to_host(host_tree, want_value=is_primary)
    if not is_primary:
        return None
    if zero_lib.is_wus_momentum(state.momentum):
        # Gather-on-save (weight-update sharding): the stacked-chunk
        # optimizer shards flatten back to the param-shaped layout every
        # checkpoint stores — zero and replicated runs stay
        # restore-compatible in both directions.  The error-feedback agerr
        # twin is resettable state and is dropped (like qcomm residuals on
        # a mode switch).
        host_state["momentum"] = zero_lib.gather_momentum(
            host_state["momentum"], host_state["params"])
    payload = {
        "epoch": epoch,
        "arch": arch,
        "best_acc1": float(best_acc1),
        "ft": _ft_record(ft),
        "state": host_state,
    }
    blob = serialization.to_bytes(payload)
    path = os.path.join(directory, CHECKPOINT_NAME)

    def write() -> str:
        os.makedirs(directory, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        if os.path.exists(path):
            # Retain N=2: the outgoing latest becomes the fallback the
            # loader reaches for when the new file turns out corrupt.
            replace_with_sidecar(path, os.path.join(directory, PREV_NAME))
        os.replace(tmp, path)
        write_sidecar(path)
        return path

    retrying(write)
    if is_best:
        # Crash-safe best copy: tmp + os.replace like the main file (a bare
        # copyfile interrupted mid-write left a torn model_best).
        best = os.path.join(directory, BEST_NAME)

        def write_best() -> None:
            tmp = best + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, best)
            write_sidecar(best)

        retrying(write_best)
    return path


def _load_msgpack(
    path: str, state_template: TrainState
) -> Tuple[TrainState, Dict[str, Any]]:
    """Verify-then-deserialize one msgpack checkpoint file.

    Sidecar verification runs BEFORE flax touches the bytes, so corruption
    surfaces as ``CheckpointCorruptError`` instead of a cryptic msgpack
    unpack failure.  Legacy files without a sidecar still load; their parse
    errors are converted to ``CheckpointCorruptError`` (a verified file
    that fails to parse indicates a template/arch mismatch and propagates
    as-is)."""
    check_integrity(path)
    verified = verify_sidecar(path) is True
    raw = retrying(lambda: open(path, "rb").read())
    try:
        tree = serialization.msgpack_restore(raw)
        # from_state_dict (not from_bytes-with-template): tolerates the
        # pre-FT payload layout — a missing 'ft' key defaults instead of
        # failing the whole-template key match.
        template = {
            "step": state_template.step,
            "params": state_template.params,
            "batch_stats": state_template.batch_stats,
            "momentum": state_template.momentum,
        }
        saved = dict(tree["state"])
        if zero_lib.is_wus_momentum(state_template.momentum):
            # Shard-on-restore (weight-update sharding): disk always holds
            # the param-shaped momentum (gather-on-save invariant — also
            # what any legacy replicated-DP checkpoint holds), so a --zero
            # wus template re-chunks it into its stacked (n, chunk) layout.
            # Works across mesh sizes: the template's chunking wins.  The
            # agerr error-feedback twin (quantized all-gather) restarts at
            # the template's zeros.
            t_mom = serialization.to_state_dict(state_template.momentum)
            saved_mom = saved.get("momentum")
            chunked = dict(t_mom)
            if saved_mom is not None and not (
                    isinstance(saved_mom, dict) and "buf" in saved_mom):
                chunked["buf"] = zero_lib.shard_momentum(
                    saved_mom, t_mom["buf"])
            saved["momentum"] = chunked
        saved_res = saved.pop("residual", None)
        t_res = serialization.to_state_dict(state_template.residual)
        if t_res:
            # This run carries error-feedback residuals: restore the saved
            # ones when they exist with matching shapes (same compression
            # mode and mesh), else start from the template's zeros — a mode
            # or mesh switch resets error feedback, it never fails resume.
            template["residual"] = state_template.residual
            same_shape = saved_res is not None and [
                np.shape(x) for x in jax.tree_util.tree_leaves(saved_res)
            ] == [np.shape(x) for x in jax.tree_util.tree_leaves(t_res)]
            saved["residual"] = saved_res if same_shape else t_res
        st = serialization.from_state_dict(template, saved)
        meta = {
            "epoch": int(tree["epoch"]),
            "arch": str(tree["arch"]),
            "best_acc1": float(tree["best_acc1"]),
            "ft": _ft_record(tree.get("ft")),
        }
    except CheckpointCorruptError:
        raise
    except Exception as e:
        if verified:
            raise
        raise CheckpointCorruptError(
            f"checkpoint '{path}' failed to deserialize and carries no "
            f"sha256 sidecar to pinpoint corruption: {e}"
        ) from e
    state = TrainState(
        step=st["step"],
        params=st["params"],
        batch_stats=st["batch_stats"],
        momentum=st["momentum"],
        residual=st.get("residual", {}),
    )
    return state, meta


def load_checkpoint(
    path: str, state_template: TrainState, fallback: bool = True
) -> Tuple[TrainState, Dict[str, Any]]:
    """Restore ``(state, meta)`` from a checkpoint file.

    ``state_template`` supplies the pytree structure/shapes (a freshly
    initialized state for the same arch); meta carries epoch/arch/best_acc1
    plus the ``ft`` step-granular resume record.

    Backend is auto-detected: a directory (or ``.../orbax[/<step>]`` path)
    restores via orbax; a file is the msgpack format.

    ``fallback``: when the latest ``checkpoint.msgpack`` fails sidecar
    verification (or a legacy file fails to parse), resume continues from
    the retained ``checkpoint.prev.msgpack`` instead of crashing — losing
    one save interval, not the run.  Only when both are bad does
    ``CheckpointCorruptError`` propagate.
    """
    if os.path.isdir(path):
        return _load_orbax(path, state_template)
    try:
        return _load_msgpack(path, state_template)
    except CheckpointCorruptError as e:
        prev = None
        if os.path.basename(path) == CHECKPOINT_NAME:
            prev = os.path.join(os.path.dirname(path), PREV_NAME)
        if fallback and prev and os.path.exists(prev):
            warnings.warn(
                f"latest checkpoint is corrupt; falling back to '{prev}' "
                f"({e})",
                stacklevel=2,
            )
            return _load_msgpack(prev, state_template)
        raise
