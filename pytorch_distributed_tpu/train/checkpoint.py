"""Checkpoint save / resume.

Capability parity with the reference's ``save_checkpoint``
(reference distributed.py:327-330, payload at :219-225): a single-file
checkpoint of ``{epoch, arch, state, best_acc1}`` written by rank 0, copied
to ``model_best`` on a new best — plus the **resume load path the reference
lacks** (no ``torch.load`` exists anywhere in the reference; SURVEY.md §5.3).

Like the reference's ``model.module.state_dict()`` unwrap (:223), the saved
tree is plain host numpy — recipe-interchangeable: any recipe can load any
recipe's checkpoint regardless of mesh shape, because state is replicated
(DP) and re-sharding happens at restore time via ``device_put``.

Format: flax msgpack (``flax.serialization``), written atomically
(tmp + rename).
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from pytorch_distributed_tpu.train.state import TrainState

CHECKPOINT_NAME = "checkpoint.msgpack"
BEST_NAME = "model_best.msgpack"


def _to_host(tree: Any, want_value: bool = True) -> Any:
    """Fetch to host numpy, gathering sharded leaves first.

    DP state is replicated (plain fetch); TP/SP-sharded state on multi-host
    meshes spans non-addressable devices, where ``np.asarray`` would raise —
    those leaves are all-gathered across processes so the written checkpoint
    is always the full, replicated tree (the recipe-interchange invariant).

    ``want_value=False`` (non-primary ranks): still participate in the
    cross-process all-gather for non-addressable leaves — a collective every
    rank must enter — but skip the device→host copy of addressable leaves,
    whose bytes only the writing rank needs."""

    def fetch(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(x, tiled=True)
            return np.asarray(gathered) if want_value else None
        return np.asarray(x) if want_value else None

    return jax.tree_util.tree_map(fetch, tree)


_ORBAX_DIRNAME = "orbax"
_orbax_managers: Dict[str, Any] = {}


def _orbax_manager(directory: str):
    """One async CheckpointManager per directory (kept alive so in-flight
    async writes finish; per-epoch saves wait on the previous write)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.path.join(directory, _ORBAX_DIRNAME))
    mgr = _orbax_managers.get(path)
    if mgr is None:
        # Retention is latest-N (NOT best_fn): resume-from-latest must always
        # work, and a best_fn policy would garbage-collect the just-written
        # newest step whenever it isn't top-N.  The best epoch's score lives
        # in each step's meta/metrics for offline selection.
        mgr = ocp.CheckpointManager(
            path,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=2,
                enable_async_checkpointing=True,
            ),
        )
        _orbax_managers[path] = mgr
    return mgr


def wait_for_async_saves() -> None:
    """Drain in-flight orbax async writes.  Call before process exit (the
    epoch drivers do, end of fit) — Python shuts down executor threads
    before atexit handlers run, so deferring this to atexit loses the final
    epoch's checkpoint."""
    for mgr in _orbax_managers.values():
        mgr.wait_until_finished()


def _save_orbax(
    directory: str, state: TrainState, epoch: int, arch: str,
    best_acc1: float, is_best: bool, metric: Optional[float] = None,
) -> str:
    """Async sharded save: every process writes its own shards (OCDBT) — no
    host gather, no full-tree allgather; the at-scale story the msgpack
    backend's replicated single file cannot give (multi-host TP/SP state
    stays distributed on disk).  All processes must call (orbax coordinates
    across hosts internally)."""
    import orbax.checkpoint as ocp

    mgr = _orbax_manager(directory)
    tree = {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "momentum": state.momentum,
    }
    mgr.save(
        int(epoch),
        args=ocp.args.Composite(
            state=ocp.args.StandardSave(tree),
            meta=ocp.args.JsonSave(
                {"epoch": int(epoch), "arch": arch,
                 "best_acc1": float(best_acc1), "is_best": bool(is_best)}
            ),
        ),
        # The retention metric must be THIS epoch's own score: the running
        # max would tie every later epoch with the true best and let the
        # manager garbage-collect the actual best weights.
        metrics={"best_acc1": float(metric if metric is not None else best_acc1)},
    )
    return os.path.join(directory, _ORBAX_DIRNAME, str(int(epoch)))


def _load_orbax(path: str, state_template: TrainState):
    import orbax.checkpoint as ocp

    # `path` may be the checkpoint dir, the orbax subdir, or a specific step
    # (`.../orbax/<N>`).  A numeric basename counts as a step only when its
    # parent is the orbax subdir — a sweep layout like `runs/3` is a
    # checkpoint dir that happens to be named with digits.
    root = os.path.abspath(path)
    parent = os.path.dirname(root)
    if (os.path.basename(root).isdigit()
            and os.path.basename(parent) == _ORBAX_DIRNAME):
        step, root = int(os.path.basename(root)), parent
    else:
        if os.path.isdir(os.path.join(root, _ORBAX_DIRNAME)):
            root = os.path.join(root, _ORBAX_DIRNAME)
        step = None
    live = _orbax_managers.get(root)
    if live is not None:
        live.wait_until_finished()  # drain an in-flight async save
    mgr = live or ocp.CheckpointManager(root)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no orbax checkpoints under '{root}'")
    template = {
        "step": state_template.step,
        "params": state_template.params,
        "batch_stats": state_template.batch_stats,
        "momentum": state_template.momentum,
    }
    restored = mgr.restore(
        step,
        args=ocp.args.Composite(
            state=ocp.args.StandardRestore(template),
            meta=ocp.args.JsonRestore(),
        ),
    )
    st = restored["state"]
    state = TrainState(
        step=st["step"],
        params=st["params"],
        batch_stats=st["batch_stats"],
        momentum=st["momentum"],
    )
    meta = {k: restored["meta"][k] for k in ("epoch", "arch", "best_acc1")}
    return state, meta


def save_checkpoint(
    directory: str,
    state: TrainState,
    epoch: int,
    arch: str,
    best_acc1: float,
    is_best: bool,
    is_primary: bool = True,
    backend: str = "msgpack",
    metric: Optional[float] = None,
) -> Optional[str]:
    """Rank-0-guarded atomic save (reference distributed.py:218-225).

    The host gather runs on EVERY process before the primary guard:
    ``_to_host`` performs a cross-process all-gather for non-fully-addressable
    (multi-host-sharded) leaves, and a collective entered by rank 0 alone
    would deadlock the job at the first checkpoint. All ranks gather; only
    the primary writes.

    ``backend="orbax"``: async sharded per-process writes instead (see
    ``_save_orbax``); all ranks call, orbax coordinates."""
    if backend == "orbax":
        return _save_orbax(directory, state, epoch, arch, best_acc1, is_best,
                           metric=metric)
    if backend != "msgpack":
        raise ValueError(f"unknown checkpoint backend '{backend}'")
    host_state = _to_host(
        {
            "step": state.step,
            "params": state.params,
            "batch_stats": state.batch_stats,
            "momentum": state.momentum,
        },
        want_value=is_primary,
    )
    if not is_primary:
        return None
    os.makedirs(directory, exist_ok=True)
    payload = {
        "epoch": epoch,
        "arch": arch,
        "best_acc1": float(best_acc1),
        "state": host_state,
    }
    path = os.path.join(directory, CHECKPOINT_NAME)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.to_bytes(payload))
    os.replace(tmp, path)
    if is_best:
        shutil.copyfile(path, os.path.join(directory, BEST_NAME))
    return path


def load_checkpoint(
    path: str, state_template: TrainState
) -> Tuple[TrainState, Dict[str, Any]]:
    """Restore ``(state, meta)`` from a checkpoint file.

    ``state_template`` supplies the pytree structure/shapes (a freshly
    initialized state for the same arch); meta carries epoch/arch/best_acc1
    for the ``--start-epoch``/resume flow.

    Backend is auto-detected: a directory (or ``.../orbax[/<step>]`` path)
    restores via orbax; a file is the msgpack format.
    """
    if os.path.isdir(path):
        return _load_orbax(path, state_template)
    with open(path, "rb") as f:
        raw = f.read()
    template = {
        "epoch": 0,
        "arch": "",
        "best_acc1": 0.0,
        "state": {
            "step": state_template.step,
            "params": state_template.params,
            "batch_stats": state_template.batch_stats,
            "momentum": state_template.momentum,
        },
    }
    payload = serialization.from_bytes(template, raw)
    st = payload["state"]
    state = TrainState(
        step=st["step"],
        params=st["params"],
        batch_stats=st["batch_stats"],
        momentum=st["momentum"],
    )
    meta = {k: payload[k] for k in ("epoch", "arch", "best_acc1")}
    return state, meta
