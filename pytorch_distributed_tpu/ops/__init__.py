"""Loss and metric ops (pure, jit-friendly) plus Pallas TPU kernels."""

from pytorch_distributed_tpu.ops.loss import cross_entropy
from pytorch_distributed_tpu.ops.metrics import accuracy, topk_correct

__all__ = ["cross_entropy", "accuracy", "topk_correct"]
