"""Fused conv1x1+BN(+ReLU) backward — the BN-dx fold (ROADMAP item 1).

**Measured outcome (v5e, 2026-07-31): the fold LOSES — keep it off.**  The
full-model fused variant runs the b256 ResNet-50 step at 1,208-1,395 img/s
vs 2,536 unfused (scripts/fused_triage.py); per-shape, the kernels never beat
the XLA backward at any of the 13 distinct conv->BN backward shapes in the
model (0.54-0.96x, scripts/profile_fused_conv_bn.py).  The premise was
traffic: autodiff writes dy to HBM and the dgrad/wgrad convs read it back.
The optimized HLO (scripts/hlo_dy_check.py) shows XLA instead *clones* the
cheap elementwise dy computation into each consumer's input fusion and its
conv emitters stream near HBM peak — so the fold saves less traffic than
theorized and pays for it with hand-scheduled Mosaic matmuls that reach a
fraction of the conv emitters' effective bandwidth, plus custom-call
boundaries that break XLA's surrounding fusions.  The module stays as an
opt-in (``--fused-convbn``), fully parity-tested, as the measured record of
why the obvious kernel-fusion route past the step's memory roofline does
not work on this chip.

The round-2 roofline (scripts/profile_trace.py) showed the ResNet-50 step is
HBM-bound with a ~3,080 img/s ceiling at b256; the only route past it is
removing whole memory passes.  The largest remaining pass *appeared* to be
the BN-backward dx: autodiff materializes ``dy`` (the gradient at the conv
output / BN input) to HBM, then the dgrad and wgrad convolutions each read
it back — for every conv→BN pair, (y, do) are read for the reductions, read
again to form dy, dy is written, then read twice more:

    XLA (theorized): reduce(y,do) + write dy(y,do) + dgrad(dy) + wgrad(dy,a)
                     ≈ 9 tensor-passes per pair
    this kernel:     reduce(y,do) + fused[dy in VMEM → dgrad+wgrad]
                     ≈ 6 tensor-passes — dy never exists in HBM

For the 1×1 stride-1 convolutions the conv is exactly a matmul over
channels, so the fold is a single Pallas kernel: per M-tile (M = N·H·W
rows), recompute the ReLU mask and dy in VMEM from (y, do) and per-channel
vectors, then

    da(tile)  = dy @ Wᵀ                       (MXU)
    dW       += aᵀ @ dy     (f32 accumulator, written at the last grid step)

reading y, do, a from HBM exactly once each.  The 3×3 stride-1 SAME conv
(the bottleneck's middle conv) folds the same way with per-IMAGE tiling —
every ResNet-50 3×3 plane fits VMEM whole, so dgrad/wgrad become 9
shifted matmuls each off the in-VMEM dy with no halo exchange
(``_bwd3_kernel``).  Together that folds every conv of a stride-1
bottleneck whose plane passes the VMEM guard below (under the 96 MiB
``CompilerParams`` cap all four ResNet-50 bf16 stages engage, full-model
compile validated on v5e) plus the 1×1s of strided blocks; strided /
grouped / genuinely oversized slots keep the plain XLA backward
(``models/resnet.py`` selects).

Forward is unchanged XLA (conv + the one-pass BN+ReLU of ops/fused_bn.py) —
forward fusion is something XLA already does well; the backward pass is where
the traffic lives.

Semantics match ``nn.Conv(use_bias=False)`` → ``FusedBatchNormAct`` exactly
(global-batch SyncBN statistics under GSPMD, per-shard statistics under
shard_map — identical to the unfused pair; tests/test_fused_conv_bn.py).

Reference anchor: the conv+BN stacks of every torchvision model the
reference instantiates (reference distributed.py:134-139); the perf target
is the reference's recorded-wall-clock methodology (reference README.md:15-17).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_tpu.ops.fused_bn import _bn_act, _bn_act_fwd


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


# Mosaic's default scoped-VMEM cap is 16 MiB; the whole-plane 3x3 kernel's
# stack (f32 dy/dof temporaries + padded copies, every channel dim lane-
# padded to 128) measures 21.7 MiB at ResNet-50's 56x56x64 slot on a real
# v5e.  The chip has 128 MiB of VMEM — raise the cap for these kernels and
# let conv3x3_plane_fits_vmem keep genuinely oversized slots on the XLA
# backward.
_VMEM_LIMIT_BYTES = 96 << 20
# jax 0.4.x ships the class as TPUCompilerParams, newer as CompilerParams —
# resolve whichever this container's pallas exposes (import-time, so a miss
# would take the whole package down with it).
_COMPILER_PARAMS = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)(vmem_limit_bytes=_VMEM_LIMIT_BYTES)


def _pick_mtile(M: int, Ci: int, Co: int, itemsize: int) -> int:
    """M-tile for ``_bwd_kernel``: as many rows as fit a ~24 MiB stack.

    A v5e measurement (runs of 2026-07-31) showed the original fixed
    128/256-row tiles cost the full-model step 45%: stage 1 becomes a
    3,136-step grid moving 32 KB blocks — far too little work per step to
    amortize DMA issue + grid overhead.  Per-row footprint counts the
    lane-padded (128) channel dims: the y/do/a/da blocks (double-buffered
    by Mosaic's pipeline), the f32 y/do temporaries, and the f32 dgrad
    accumulator before the output cast."""
    ci_p = ((Ci + 127) // 128) * 128
    co_p = ((Co + 127) // 128) * 128
    # Per-row: y/do/a/da blocks (double-buffered), f32 y/do temps, the
    # cast dy tile, and the f32 dgrad accumulator pre-cast.
    row = (2 * (ci_p + co_p) * itemsize * 2 + 2 * co_p * 4
           + co_p * itemsize + ci_p * 4)
    # Grid-constant: the weights tile and the f32 dW accumulator.
    fixed = ci_p * co_p * (itemsize + 4)
    mt = max(0, (24 << 20) - fixed) // row
    mt = max(256, min(8192, (mt // 256) * 256))
    # Never tile far past M itself (small call sites pad to one tile).
    return min(mt, ((M + 255) // 256) * 256)


def _bwd_kernel(y_ref, do_ref, a_ref, w_ref, vec_ref, da_ref, dw_ref,
                *, relu: bool, cdt):
    """One M-tile: dy in VMEM, then dgrad + wgrad off the same registers.

    vec rows: 0=s (γ·inv), 1=t, 2=u  (dy = s∘dof + t∘y + u), 3=v
    (mask pre-activation = s∘y + v); see the wrapper for the algebra.
    """
    i = pl.program_id(0)
    yf = y_ref[:].astype(jnp.float32)                    # [MT, Co]
    dof = do_ref[:].astype(jnp.float32)                  # [MT, Co]
    s = vec_ref[0:1, :]                                  # [1, Co]
    t = vec_ref[1:2, :]
    u = vec_ref[2:3, :]
    if relu:
        v = vec_ref[3:4, :]
        dof = jnp.where(yf * s + v > 0, dof, 0.0)
    dy = (dof * s + yf * t + u).astype(cdt)              # [MT, Co]
    # dgrad: da = dy @ Wᵀ (contract Co)
    da_ref[:] = jax.lax.dot_general(
        dy, w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(da_ref.dtype)
    # wgrad: dW += aᵀ @ dy (contract M), f32 accumulation across the grid —
    # the output block is grid-constant, so it lives in VMEM for the whole
    # kernel and is written back once.
    contrib = jax.lax.dot_general(
        a_ref[:].astype(cdt), dy, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == 0)
    def _():
        dw_ref[:] = contrib

    @pl.when(i > 0)
    def _():
        dw_ref[:] = dw_ref[:] + contrib


def _fused_dgrad_wgrad(y, do, a, w, s, t, u, v, relu: bool, interpret: bool
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """da, dW for the 1×1 conv whose output fed BN — one pass over (y,do,a).

    Shapes: y/do [..., Co], a [..., Ci] with identical leading dims; w
    [Ci, Co].  Leading dims are flattened to M rows and zero-padded to the
    tile size (padded ``do``/``a`` rows are zero, so they contribute nothing
    to dW and their da rows are dropped; bench shapes divide evenly).
    """
    Ci, Co = w.shape
    M = 1
    for d in y.shape[:-1]:
        M *= d
    y2 = y.reshape(M, Co)
    do2 = do.reshape(M, Co)
    a2 = a.reshape(M, Ci)
    cdt = a.dtype
    mt = _pick_mtile(M, Ci, Co, jnp.dtype(cdt).itemsize)
    mp = ((M + mt - 1) // mt) * mt
    if mp != M:
        pad = ((0, mp - M), (0, 0))
        y2 = jnp.pad(y2, pad)
        do2 = jnp.pad(do2, pad)
        a2 = jnp.pad(a2, pad)
    vec = jnp.stack([s, t, u, v]).astype(jnp.float32)    # [4, Co]
    da2, dw = pl.pallas_call(
        functools.partial(_bwd_kernel, relu=relu, cdt=cdt),
        grid=(mp // mt,),
        in_specs=[
            pl.BlockSpec((mt, Co), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((mt, Co), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((mt, Ci), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Ci, Co), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((4, Co), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((mt, Ci), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Ci, Co), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, Ci), cdt),
            jax.ShapeDtypeStruct((Ci, Co), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(y2, do2, a2, w.astype(cdt), vec)
    return da2[:M].reshape(a.shape), dw


def _bwd3_kernel(y_ref, do_ref, a_ref, w_ref, vec_ref, da_ref, dw_ref,
                 *, relu: bool, cdt, H: int, Wd: int):
    """One image (grid over N): dy for the full [H, W, Co] plane in VMEM,
    then the 3x3 dgrad and wgrad as 9 shifted matmuls each — the same
    one-read-per-tensor economics as the 1x1 kernel, with the halo problem
    dissolved by whole-plane tiling (every ResNet-50 3x3 plane fits VMEM;
    56x56x64 bf16 is ~400 KB, 7x7x512 is ~50 KB).
    """
    n = pl.program_id(0)
    Co = y_ref.shape[-1]
    Ci = a_ref.shape[-1]
    yf = y_ref[0].astype(jnp.float32)                    # [H, W, Co]
    dof = do_ref[0].astype(jnp.float32)
    s = vec_ref[0:1, :].reshape(1, 1, Co)
    t = vec_ref[1:2, :].reshape(1, 1, Co)
    u = vec_ref[2:3, :].reshape(1, 1, Co)
    if relu:
        v = vec_ref[3:4, :].reshape(1, 1, Co)
        dof = jnp.where(yf * s + v > 0, dof, 0.0)
    dy = (dof * s + yf * t + u).astype(cdt)              # [H, W, Co]
    af = a_ref[0].astype(cdt)                            # [H, W, Ci]
    # Zero-pad once; every (kh, kw) tap is then a static slice.
    dyp = jnp.pad(dy, ((1, 1), (1, 1), (0, 0)))
    ap = jnp.pad(af, ((1, 1), (1, 1), (0, 0)))
    dx = jnp.zeros((H * Wd, Ci), jnp.float32)
    for kh in range(3):
        for kw in range(3):
            # dgrad: dx[p,q] += dy[p-kh+1, q-kw+1] @ W[kh,kw]^T
            sh = dyp[2 - kh:2 - kh + H, 2 - kw:2 - kw + Wd, :]
            dx = dx + jax.lax.dot_general(
                sh.reshape(H * Wd, Co), w_ref[kh, kw],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            # wgrad: dW[kh,kw] += a[h+kh-1, w+kw-1]^T @ dy[h, w]
            sa = ap[kh:kh + H, kw:kw + Wd, :]
            contrib = jax.lax.dot_general(
                sa.reshape(H * Wd, Ci), dy.reshape(H * Wd, Co),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

            @pl.when(n == 0)
            def _():
                dw_ref[kh, kw] = contrib

            @pl.when(n > 0)
            def _():
                dw_ref[kh, kw] = dw_ref[kh, kw] + contrib
    da_ref[0] = dx.reshape(H, Wd, Ci).astype(da_ref.dtype)


def _fused_dgrad_wgrad_3x3(y, do, a, w, s, t, u, v, relu: bool,
                           interpret: bool):
    """da, dW for the 3x3 stride-1 SAME conv whose output fed BN.

    Shapes: y/do [N, H, W, Co], a [N, H, W, Ci], w [3, 3, Ci, Co]."""
    N, H, Wd, Co = y.shape
    Ci = a.shape[-1]
    cdt = a.dtype
    vec = jnp.stack([s, t, u, v]).astype(jnp.float32)
    da, dw = pl.pallas_call(
        functools.partial(_bwd3_kernel, relu=relu, cdt=cdt, H=H, Wd=Wd),
        grid=(N,),
        in_specs=[
            pl.BlockSpec((1, H, Wd, Co), lambda n: (n, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, H, Wd, Co), lambda n: (n, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, H, Wd, Ci), lambda n: (n, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, 3, Ci, Co), lambda n: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4, Co), lambda n: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, H, Wd, Ci), lambda n: (n, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, 3, Ci, Co), lambda n: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, H, Wd, Ci), cdt),
            jax.ShapeDtypeStruct((3, 3, Ci, Co), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(y, do, a, w.astype(cdt), vec)
    return da, dw


def _conv3x3(a, w):
    return jax.lax.conv_general_dilated(
        a, w.astype(a.dtype), (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _conv1x1(a, w):
    return jax.lax.conv_general_dilated(
        a, w.astype(a.dtype), (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _make_conv_bn_op(conv_fwd, dgrad_wgrad, doc: str):
    """Build a ``(o, mu, var) = BN+ReLU(conv(a, w))`` custom-VJP op from a
    forward conv primitive and a fused dgrad+wgrad backward — one
    residual-packing / cotangent-unpacking implementation for both kernel
    shapes."""

    @functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
    def op(a, w, gamma, beta, eps: float, relu: bool,
           interpret: Optional[bool] = None):
        (o, mu, var), _ = fwd(a, w, gamma, beta, eps, relu, interpret)
        return o, mu, var

    def fwd(a, w, gamma, beta, eps, relu, interpret):
        y = conv_fwd(a, w)
        (o, mu, var), (y_res, mu_res, inv, g_res, b_res) = _bn_act_fwd(
            y, gamma, beta, eps, relu
        )
        return (o, mu, var), (a, w, y_res, mu_res, inv, g_res, b_res)

    def bwd(eps, relu, interpret, res, cts):
        a, w, y, mu, inv, gamma, beta = res
        do = cts[0]  # mu/var cotangents are zero (EMA is stop-grad)
        s, t, u, v, dgamma, dbeta = _bn_bwd_vectors(y, do, mu, inv, gamma,
                                                    beta, relu)
        da, dw = dgrad_wgrad(y, do, a, w, s, t, u, v, relu,
                             _resolve_interpret(interpret))
        return (da.astype(a.dtype), dw.reshape(w.shape).astype(w.dtype),
                dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype))

    op.defvjp(fwd, bwd)
    op.__doc__ = doc
    return op


conv1x1_bn_act = _make_conv_bn_op(
    _conv1x1,
    lambda y, do, a, w, *r: _fused_dgrad_wgrad(
        y, do, a, w.reshape(w.shape[-2], w.shape[-1]), *r),
    doc="""``(o, mu, var) = BN+ReLU(conv1x1(a, w))`` with the fused backward.

    ``a``: NHWC activations; ``w``: [1, 1, Ci, Co] (HWIO) f32 params cast to
    ``a.dtype`` for compute, like ``nn.Conv(dtype=...)``.  mu/var are exposed
    for the EMA update (stop-gradiented by the caller, like ops/fused_bn).
    """,
)

conv3x3_bn_act = _make_conv_bn_op(
    _conv3x3,
    _fused_dgrad_wgrad_3x3,
    doc="""``(o, mu, var) = BN+ReLU(conv3x3_s1_SAME(a, w))`` with the fused
    backward — the 3x3 counterpart of ``conv1x1_bn_act`` (the bottleneck's
    middle conv when stride 1 and ungrouped).""",
)


def conv3x3_plane_fits_vmem(h: int, w_: int, ci: int, co: int,
                            itemsize: int, budget: int = 48 << 20) -> bool:
    """Per-grid-step working-set estimate for ``_bwd3_kernel`` (blocks +
    padded copies + f32 accumulators + weights and the f32 dW): whole-plane
    tiling only engages when it fits comfortably under the raised
    ``_VMEM_LIMIT_BYTES``; otherwise the caller keeps the unfused XLA
    backward for that slot.  Under the 96 MiB cap every ResNet-50 bf16
    plane engages (and the wide-resnet f32 stage-1 plane, ~30 MiB
    estimated, now fits too); genuinely oversized working sets — e.g.
    112x112 planes at 256+ f32 channels — still decline.

    Mosaic lays every [..., C] VMEM buffer out in (8, 128) tiles, so channel
    dims are lane-padded to 128 — at ResNet-50's 64-channel stage that
    doubles every plane buffer.  With padded channels this formula estimates
    14.7 MiB for the 56x56x64 slot; a real v5e measures a 21.7 MiB scoped
    allocation (extra Mosaic temporaries for the 9 shifted-slice matmuls),
    so the estimate carries a 1.5x headroom factor."""
    ci_p = ((ci + 127) // 128) * 128
    co_p = ((co + 127) // 128) * 128
    hw = (h + 2) * (w_ + 2)
    # planes (y/do/a/da blocks + f32 dy intermediates + padded copies) +
    # the grid-constant weights and f32 dW accumulator (not
    # double-buffered).
    est = (hw * (12 * co_p + 8 * ci_p + 3 * itemsize * (ci_p + co_p))
           + 9 * ci_p * co_p * (itemsize + 4))
    return (est * 3) // 2 <= budget


def _bn_bwd_vectors(y, do, mu, inv, gamma, beta, relu: bool):
    """Pass 1 (XLA, fused reductions): dβ, dγ and the per-channel vectors
    the fused kernels consume.  Under GSPMD with a sharded batch the
    reductions are global (SyncBN backward); under shard_map per-shard —
    identical to the unfused _bn_act_bwd.

    dy = s·(dof − dβ/n − x̂·dγ/n) rearranged to two per-channel FMAs:
    dy = s∘dof + t∘y + u with t = −s·inv·dγ/n, u = −s·dβ/n − t·μ; the
    ReLU mask pre-activation is s∘y + v with v = β − s·μ."""
    f32 = jnp.float32
    axes = tuple(range(y.ndim - 1))
    n = 1
    for ax in axes:
        n *= y.shape[ax]
    yf = y.astype(f32)
    dof = do.astype(f32)
    s = gamma * inv
    v = beta - s * mu
    if relu:
        dof = jnp.where(yf * s + v > 0, dof, 0.0)
    dbeta = dof.sum(axes)
    xhat = (yf - mu) * inv
    dgamma = (dof * xhat).sum(axes)
    t = -(s * inv) * (dgamma / n)
    u = -s * (dbeta / n) - t * mu
    return s, t, u, v, dgamma, dbeta


def conv1x1_bn(mdl, conv_name: str, bn_name: str, x, features: int, *,
               relu: bool, use_running_average: bool, dtype,
               momentum: float = 0.9, eps: float = 1e-5,
               scale_init=None, fused: bool = True,
               interpret: Optional[bool] = None,
               kernel_size: Tuple[int, int] = (1, 1)):
    """Flax-level combinator: a ``Conv_k``→``FusedBatchNormAct_k`` pair whose
    params live at EXACTLY the unfused pair's paths (declared through child
    scopes), so toggling the fused backward never invalidates a checkpoint —
    asserted by tests/test_fused_conv_bn.py.

    ``mdl`` is the calling (compact) module; names are the explicit child
    names the unfused branch would auto-assign.  ``kernel_size`` selects
    the fused op: (1, 1) or (3, 3) stride-1 SAME (the two bottleneck
    shapes with fused backwards).
    """
    from flax import linen as nn

    if kernel_size not in ((1, 1), (3, 3)):
        raise ValueError(f"no fused backward for kernel {kernel_size}")
    is3 = kernel_size == (3, 3)
    conv_fwd = _conv3x3 if is3 else _conv1x1
    fused_op = conv3x3_bn_act if is3 else conv1x1_bn_act
    if is3 and fused and not conv3x3_plane_fits_vmem(
            x.shape[1], x.shape[2], x.shape[-1], features,
            jnp.dtype(dtype).itemsize):
        fused = False  # unfused XLA backward for this oversized slot
    if scale_init is None:
        scale_init = nn.initializers.ones
    ci = x.shape[-1]
    csc = mdl.scope.push(conv_name)
    kernel = csc.param("kernel", nn.initializers.lecun_normal(),
                       kernel_size + (ci, features), jnp.float32)
    bsc = mdl.scope.push(bn_name)
    gamma = bsc.param("scale", scale_init, (features,), jnp.float32)
    beta = bsc.param("bias", nn.initializers.zeros, (features,), jnp.float32)
    ra_mean = bsc.variable("batch_stats", "mean",
                           lambda: jnp.zeros((features,), jnp.float32))
    ra_var = bsc.variable("batch_stats", "var",
                          lambda: jnp.ones((features,), jnp.float32))

    xd = x.astype(dtype)
    if use_running_average:
        y = conv_fwd(xd, kernel)
        invr = jax.lax.rsqrt(ra_var.value + eps)
        scale = gamma * invr
        shift = beta - ra_mean.value * scale
        o = (y.astype(jnp.float32) * scale + shift).astype(y.dtype)
        return jax.nn.relu(o) if relu else o

    if mdl.is_initializing() or not fused:
        y = conv_fwd(xd, kernel)
        o, mu, var = _bn_act(y, gamma, beta, eps, relu)
    else:
        o, mu, var = fused_op(xd, kernel, gamma, beta, eps, relu,
                              interpret)
    if not mdl.is_initializing():
        m = momentum
        ra_mean.value = m * ra_mean.value + (1 - m) * jax.lax.stop_gradient(mu)
        ra_var.value = m * ra_var.value + (1 - m) * jax.lax.stop_gradient(var)
    return o
