"""Quantized gradient collectives: int8/fp8 wire compression for DP sync.

The reference's Horovod recipe compresses gradients to fp16 on the wire
(horovod_distributed.py:159-164); our explicit-collectives step matched it
with a bf16 cast.  This module goes further, following two results that map
directly onto the ``shard_map`` grad_sync scope:

- **EQuARX** (arXiv:2506.17615): a quantized all-reduce decomposed as
  quantize -> reduce-scatter -> dequantize/accumulate in f32 -> all-gather
  of re-quantized shards, so the wire carries ~1 byte/element on both hops
  while every accumulation stays full precision.  One deliberate deviation:
  a raw int8 ``psum_scatter`` would overflow (127 + 127 doesn't fit) and
  cannot carry per-block scales through XLA's reduction, so the
  reduce-scatter stage is realized as an ``all_to_all`` of the int8 payload
  (+ f32 block scales) with shard-local f32 accumulation — byte-identical
  on the wire ((n-1)/n of the payload), overflow-free by construction.
- **DynamiQ** (arXiv:2602.08923): error feedback preserves convergence
  under aggressive compression — each rank keeps the part of its gradient
  the quantizer dropped and adds it back into the next step's gradient
  before compressing again, so the error telescopes instead of
  accumulating.

Quantization is per-block symmetric: blocks of ``DEFAULT_BLOCK`` elements
share one f32 absmax-derived scale (overhead 4/256 ~ 1.6%), int8 payload
(or fp8-e4m3 where the jax build supports the dtype).  All helpers are
pure jax and trace inside ``shard_map``/``jit``; nothing here talks to
hardware directly — the collectives lower to whatever the backend provides.

Error-feedback state layout (the subtle part):

- the **explicit** (shard_map) path has genuinely per-rank residuals —
  rank j's quantizer drops different bits than rank k's.  The residual is
  therefore carried *stacked*: leaf shape ``(n_data, *param_shape)``,
  sharded over the data axis, so each rank reads and writes only its own
  slot and the error-feedback state costs zero extra collectives.
- the **GSPMD / emulation** paths quantize the already-synced global
  gradient, so the error is replicated by construction and the residual is
  plain param-shaped.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

Pytree = Any

MODES = ("none", "bf16", "int8", "fp8")
QUANTIZED_MODES = ("int8", "fp8")
DEFAULT_BLOCK = 256

# Largest finite magnitudes of the wire formats (int8 symmetric: -127..127,
# keeping -128 unused so the range is sign-symmetric; e4m3fn: 448).
_QMAX = {"int8": 127.0, "fp8": 448.0}

_FP8 = getattr(jnp, "float8_e4m3fn", None)


def fp8_supported() -> bool:
    """True when this jax build ships the float8_e4m3fn dtype."""
    return _FP8 is not None


def resolve_mode(
    grad_compress: Optional[str],
    wire_dtype=None,
) -> Tuple[str, Optional[Any]]:
    """Canonical ``(mode, cast_dtype)`` from the new flag + the legacy knob.

    ``--grad-compress`` subsumes the old ``wire_dtype`` argument:
    ``wire_dtype=jnp.bfloat16`` (the only dtype the recipes ever passed)
    maps to mode ``"bf16"``.  ``cast_dtype`` is only meaningful for the
    cast modes — it preserves the legacy behavior of casting to an
    arbitrary caller-supplied dtype.  Conflicting settings raise.
    """
    mode = grad_compress if grad_compress is not None else "none"
    if mode not in MODES:
        raise ValueError(
            f"grad_compress must be one of {MODES}, got {mode!r}")
    cast_dtype = None
    if wire_dtype is not None:
        if mode == "none":
            import warnings

            warnings.warn(
                "wire_dtype is deprecated; use grad_compress='bf16' "
                "(the wire_dtype=jnp.bfloat16 equivalent)",
                DeprecationWarning, stacklevel=3,
            )
            mode = "bf16"
            cast_dtype = wire_dtype
        elif mode == "bf16":
            cast_dtype = wire_dtype
        else:
            raise ValueError(
                f"wire_dtype={wire_dtype} conflicts with "
                f"grad_compress={mode!r}; drop the deprecated wire_dtype")
    if mode == "bf16" and cast_dtype is None:
        cast_dtype = jnp.bfloat16
    if mode == "fp8" and not fp8_supported():
        raise ValueError(
            "grad_compress='fp8' requires a jax build with "
            "jnp.float8_e4m3fn; use 'int8' on this install")
    return mode, cast_dtype


# ----------------------------------------------------------- quantize core

def _quantize(xb: jnp.ndarray, mode: str):
    """Per-block symmetric quantization along the last axis.

    ``xb``: f32 ``(..., block)``.  Returns ``(q, scale)`` with ``q`` int8
    or fp8-e4m3 of ``xb.shape`` and ``scale`` f32 of ``xb.shape[:-1]``.
    All-zero blocks get scale 0 (dequantizes to exact zeros).
    """
    qmax = _QMAX[mode]
    absmax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = absmax / qmax
    inv = jnp.where(absmax > 0, qmax / absmax, 0.0)
    y = jnp.clip(xb * inv, -qmax, qmax)
    if mode == "int8":
        q = jnp.round(y).astype(jnp.int8)
    else:
        if _FP8 is None:  # pragma: no cover - guarded by resolve_mode
            raise ValueError("fp8 dtype unsupported by this jax build")
        q = y.astype(_FP8)
    return q, scale.squeeze(-1)


def _dequantize(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    return q.astype(jnp.float32) * scale[..., None]


def quantize_blockwise(x: jnp.ndarray, mode: str = "int8",
                       block: int = DEFAULT_BLOCK):
    """Quantize an arbitrary-shaped array: flatten, zero-pad to a block
    multiple, quantize per block.  Returns ``(q, scale)`` with ``q`` of
    shape ``(n_blocks, block)`` and ``scale`` of ``(n_blocks,)``."""
    flat = x.astype(jnp.float32).ravel()
    pad = (-flat.size) % block
    xb = jnp.pad(flat, (0, pad)).reshape(-1, block)
    return _quantize(xb, mode)


def dequantize_blockwise(q: jnp.ndarray, scale: jnp.ndarray,
                         shape: Tuple[int, ...]) -> jnp.ndarray:
    """Inverse of :func:`quantize_blockwise` (drops the zero padding)."""
    flat = _dequantize(q, scale).ravel()
    return flat[: math.prod(shape)].reshape(shape)


def fake_quantize(x: jnp.ndarray, mode: str = "int8",
                  block: int = DEFAULT_BLOCK) -> jnp.ndarray:
    """Quantize-dequantize round trip — the numerics of the wire format
    without any collective (the GSPMD emulation primitive)."""
    q, s = quantize_blockwise(x, mode, block)
    return dequantize_blockwise(q, s, x.shape)


# -------------------------------------------------------- error feedback

def _has_leaves(tree) -> bool:
    return len(jax.tree_util.tree_leaves(tree)) > 0


def init_residual(params: Pytree, mode: str, explicit: bool = False,
                  n_data: int = 1) -> Pytree:
    """Zero error-feedback residuals for ``mode`` (empty tree when the mode
    carries no quantization error).  Explicit-collectives residuals are
    stacked ``(n_data, *shape)`` — one slot per data-axis rank, sharded
    over that axis (see the module docstring)."""
    if mode not in QUANTIZED_MODES:
        return {}
    if explicit:
        return jax.tree_util.tree_map(
            lambda p: jnp.zeros((n_data,) + p.shape, jnp.float32), params)
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)


def compress_emulated(grads: Pytree, residual: Pytree, mode: str,
                      block: int = DEFAULT_BLOCK) -> Tuple[Pytree, Pytree]:
    """Quantization *numerics* + error feedback on an already-synced
    (replicated-semantics) gradient — the GSPMD-path analogue of the old
    wire_dtype cast.  Does not move fewer bytes; see make_train_step's
    NUMERICS-emulation warning."""
    if _has_leaves(residual):
        comp = jax.tree_util.tree_map(
            lambda g, r: g.astype(jnp.float32) + r, grads, residual)
        out = jax.tree_util.tree_map(
            lambda g: fake_quantize(g, mode, block), comp)
        new_res = jax.tree_util.tree_map(jnp.subtract, comp, out)
        return out, new_res
    out = jax.tree_util.tree_map(
        lambda g: fake_quantize(g.astype(jnp.float32), mode, block), grads)
    return out, residual


# ---------------------------------------------- compressed all-reduce (EQuARX)

def chunk_layout(size: int, n: int,
                 block: int = DEFAULT_BLOCK) -> Tuple[int, int]:
    """``(padded_total, blocks_per_chunk)`` of a ``size``-element leaf split
    into ``n`` per-rank chunks of whole blocks.  Small leaves shrink the
    block instead of ballooning the padding (a 10-element bias on a 4-way
    mesh pads to 12 elements, not 1024).  Shared with the analytic
    wire-byte model in ``obs/flops.py``."""
    chunk = -(-size // n)
    blk = min(block, chunk)
    chunk = -(-chunk // blk) * blk
    return n * chunk, chunk // blk


def _compressed_leaf(g, r, axis_name, n, idx, mode, block):
    """One leaf of the compressed all-reduce; runs per-rank in shard_map.

    ``g``: this rank's local f32 gradient (sum-form).  ``r``: this rank's
    residual slot ``(1, *g.shape)`` or None.  Returns the replicated f32
    sum over ranks and the new residual slot.
    """
    shape, size = g.shape, g.size
    p = g.astype(jnp.float32)
    if r is not None:
        p = p + r.reshape(shape)
    total, nb = chunk_layout(size, n, block)
    blk = (total // n) // nb
    xb = jnp.pad(p.ravel(), (0, total - size)).reshape(n, nb, blk)

    # Stage 1: quantize the whole local gradient; exchange chunks so rank i
    # ends up with every rank's chunk i (the reduce-scatter stage, realized
    # as an all_to_all of int8 payload + f32 scales — overflow-safe).
    q1, s1 = _quantize(xb, mode)
    q_t = jax.lax.all_to_all(q1, axis_name, split_axis=0, concat_axis=0)
    s_t = jax.lax.all_to_all(s1, axis_name, split_axis=0, concat_axis=0)

    # Stage 2: accumulate the owned chunk in f32, re-quantize, all-gather.
    owned = jnp.sum(_dequantize(q_t, s_t), axis=0)          # (nb, blk) f32
    q2, s2 = _quantize(owned, mode)
    qg = jax.lax.all_gather(q2, axis_name)                   # (n, nb, blk)
    sg = jax.lax.all_gather(s2, axis_name)                   # (n, nb)
    summed = _dequantize(qg, sg).reshape(total)[:size].reshape(shape)

    r_new = None
    if r is not None:
        # Stage-1 error is local; the owner also folds in its chunk's
        # stage-2 (re-quantization) error, so the residuals summed over
        # ranks equal exactly (true sum - wire sum): perfect telescoping.
        e1 = xb - _dequantize(q1, s1)
        e2 = owned - _dequantize(q2, s2)
        own = jax.lax.dynamic_slice(e1, (idx, 0, 0), (1, nb, blk))
        e1 = jax.lax.dynamic_update_slice(e1, own + e2[None], (idx, 0, 0))
        r_new = e1.reshape(total)[:size].reshape((1,) + shape)
    return summed, r_new


def compressed_psum(grads: Pytree, residual: Pytree, axis_name: str,
                    mode: str = "int8",
                    block: int = DEFAULT_BLOCK) -> Tuple[Pytree, Pytree]:
    """Quantized all-reduce of a gradient pytree inside ``shard_map``.

    Wire cost per leaf vs an f32 psum (ring conventions, n ranks,
    L elements): f32 moves ``2(n-1)/n * 4L`` bytes; this moves
    ``2(n-1)/n * (L + 4L/block)`` — a ~3.9x reduction at block=256.
    Accumulation is f32 throughout; only the wire is narrow.
    """
    if mode not in QUANTIZED_MODES:
        raise ValueError(f"compressed_psum: mode must be one of "
                         f"{QUANTIZED_MODES}, got {mode!r}")
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    use_ef = _has_leaves(residual)
    g_leaves, treedef = jax.tree_util.tree_flatten(grads)
    r_leaves = (jax.tree_util.tree_leaves(residual) if use_ef
                else [None] * len(g_leaves))
    if use_ef and len(r_leaves) != len(g_leaves):
        raise ValueError("residual tree does not match the gradient tree")
    out_g, out_r = [], []
    for g, r in zip(g_leaves, r_leaves):
        summed, r_new = _compressed_leaf(g, r, axis_name, n, idx, mode, block)
        out_g.append(summed)
        out_r.append(r_new)
    synced = jax.tree_util.tree_unflatten(treedef, out_g)
    new_res = (jax.tree_util.tree_unflatten(treedef, out_r) if use_ef
               else residual)
    return synced, new_res


# ------------------------------------- compressed ZeRO collectives (WUS path)

def _rs_leaf(g, r, axis_name, n, idx, mode, block):
    """Stage 1 of :func:`_compressed_leaf` alone: quantized reduce-scatter.

    Stops at the f32 ``owned`` accumulation — the caller (the weight-update
    -sharding optimizer, parallel/zero.py) consumes the exact chunk sum
    directly, so there is no stage-2 re-quantization and no all-gather of
    gradients at all; the second wire hop of WUS carries the *parameter
    delta* instead (:func:`compressed_all_gather`, with its own error
    feedback).  Residual update is therefore stage-1-only: summed over
    ranks, the residuals equal (true sum - what reached the owners).
    """
    shape, size = g.shape, g.size
    p = g.astype(jnp.float32)
    if r is not None:
        p = p + r.reshape(shape)
    total, nb = chunk_layout(size, n, block)
    blk = (total // n) // nb
    xb = jnp.pad(p.ravel(), (0, total - size)).reshape(n, nb, blk)
    q1, s1 = _quantize(xb, mode)
    q_t = jax.lax.all_to_all(q1, axis_name, split_axis=0, concat_axis=0)
    s_t = jax.lax.all_to_all(s1, axis_name, split_axis=0, concat_axis=0)
    owned = jnp.sum(_dequantize(q_t, s_t), axis=0)          # (nb, blk) f32
    r_new = None
    if r is not None:
        e1 = xb - _dequantize(q1, s1)
        r_new = e1.reshape(total)[:size].reshape((1,) + shape)
    return owned.reshape(-1), r_new                          # flat (chunk,)


def compressed_reduce_scatter(grads: Pytree, residual: Pytree, axis_name: str,
                              mode: str = "int8",
                              block: int = DEFAULT_BLOCK,
                              ) -> Tuple[Pytree, Pytree]:
    """Quantized reduce-scatter of a gradient pytree inside ``shard_map``.

    Each rank receives the f32 *sum* of its flat ``chunk_layout`` chunk of
    every leaf (shape ``(chunk,)``), accumulated from the other ranks'
    dequantized contributions — half of :func:`compressed_psum`'s wire
    (the all_to_all hop only), with the same DynamiQ error feedback riding
    in the stacked residual.
    """
    if mode not in QUANTIZED_MODES:
        raise ValueError(f"compressed_reduce_scatter: mode must be one of "
                         f"{QUANTIZED_MODES}, got {mode!r}")
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    use_ef = _has_leaves(residual)
    g_leaves, treedef = jax.tree_util.tree_flatten(grads)
    r_leaves = (jax.tree_util.tree_leaves(residual) if use_ef
                else [None] * len(g_leaves))
    if use_ef and len(r_leaves) != len(g_leaves):
        raise ValueError("residual tree does not match the gradient tree")
    out_g, out_r = [], []
    for g, r in zip(g_leaves, r_leaves):
        owned, r_new = _rs_leaf(g, r, axis_name, n, idx, mode, block)
        out_g.append(owned)
        out_r.append(r_new)
    chunks = jax.tree_util.tree_unflatten(treedef, out_g)
    new_res = (jax.tree_util.tree_unflatten(treedef, out_r) if use_ef
               else residual)
    return chunks, new_res


def compressed_all_gather(chunks: Pytree, err: Pytree, axis_name: str,
                          shaped: Pytree, mode: str = "int8",
                          block: int = DEFAULT_BLOCK) -> Tuple[Pytree, Pytree]:
    """Quantized all-gather of per-rank flat chunks back to full leaves.

    ``chunks``: this rank's flat ``(chunk,)`` f32 values per leaf (the
    WUS parameter-delta).  ``err``: per-rank error-feedback slots of shape
    ``(1, chunk)`` per leaf (or an empty tree to disable EF) — the wire
    carries ``q(chunk + err)`` and the new error is what the quantizer
    dropped, so sub-quantum deltas accumulate across steps instead of
    vanishing.  ``shaped``: a pytree giving each leaf's target shape (the
    params).  Every rank dequantizes the same wire payload, so the
    gathered result — and anything updated from it — stays bit-identical
    across replicas.
    """
    if mode not in QUANTIZED_MODES:
        raise ValueError(f"compressed_all_gather: mode must be one of "
                         f"{QUANTIZED_MODES}, got {mode!r}")
    use_ef = _has_leaves(err)
    c_leaves, treedef = jax.tree_util.tree_flatten(chunks)
    p_leaves = jax.tree_util.tree_leaves(shaped)
    e_leaves = (jax.tree_util.tree_leaves(err) if use_ef
                else [None] * len(c_leaves))
    if len(p_leaves) != len(c_leaves) or (use_ef and
                                          len(e_leaves) != len(c_leaves)):
        raise ValueError("compressed_all_gather: chunk / shape / error "
                         "trees do not match")
    out_f, out_e = [], []
    for c, e, p in zip(c_leaves, e_leaves, p_leaves):
        x = c.astype(jnp.float32)
        if e is not None:
            x = x + e.reshape(x.shape)
        xb = x.reshape(-1, min(block, x.size))
        q, s = _quantize(xb, mode)
        qg = jax.lax.all_gather(q, axis_name)                # (n, nb, blk)
        sg = jax.lax.all_gather(s, axis_name)                # (n, nb)
        full = _dequantize(qg, sg).reshape(-1)[: p.size].reshape(p.shape)
        out_f.append(full)
        out_e.append(None if e is None else
                     (x - _dequantize(q, s).reshape(x.shape)
                      ).reshape((1,) + x.shape))
    gathered = jax.tree_util.tree_unflatten(treedef, out_f)
    new_err = (jax.tree_util.tree_unflatten(treedef, out_e) if use_ef
               else err)
    return gathered, new_err
