"""Fused tied-head + cross-entropy: the LM loss without the [N, V] logits
tensor.

The LM step's last matmul projects hidden states onto the 32k-vocab tied
embedding and feeds softmax cross-entropy (models/transformer.py:251-253 →
ops/loss.py).  Materializing those logits costs N·V f32 in HBM *twice over*
(forward write + backward read) plus the softmax intermediates — at
b8·L1024·V32k that is >2 GB of pure loss-head traffic per step, charged
against an HBM-bound budget (ROADMAP roofline).  This op computes the SAME
loss in row chunks with a custom VJP:

- **forward**: ``lax.scan`` over N/num_chunks row blocks — each block's
  logits ([chunk, V], f32-accumulated MXU matmul) live only in VMEM-scale
  scratch; only the scalar loss/correct sums survive.
- **backward**: recomputes each block's logits (one extra matmul pass —
  FLOPs are free here, bytes are not), forms ``softmax − onehot`` locally,
  and accumulates ``dh`` and ``dE`` per block.  Residuals are just the
  inputs; nothing O(N·V) is ever saved.

Numerics: logits accumulate in f32 (``preferred_element_type``) from
bf16/f32 operands — at least as accurate as the unfused head (which casts
the f32 hidden back through the embed dtype).  Equality to the unfused
``cross_entropy(model(tokens))`` path is pinned in tests/test_fused_ce.py.

Reference anchor: the loss of every reference recipe is
``nn.CrossEntropyLoss`` on the model head (reference distributed.py:151);
this is that capability, restructured for the TPU memory hierarchy.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _block_sums(h_blk, e, t_blk, w_blk):
    """One row block: (loss_sum, correct_sum) in f32."""
    logits = jax.lax.dot_general(
        h_blk, e, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [chunk, V] f32
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, t_blk[:, None], axis=-1)[:, 0]
    loss = jnp.sum((logz - true_logit) * w_blk)
    correct = jnp.sum(
        (jnp.argmax(logits, axis=-1) == t_blk).astype(jnp.float32) * w_blk)
    return loss, correct


def fused_ce_sums(h, e, targets, weights, num_chunks: int):
    """``h [N, D]`` hidden rows, ``e [V, D]`` tied embedding, ``targets
    [N]`` int32, ``weights [N]`` f32 → ``(loss_sum, correct_sum)`` f32
    scalars (weighted sums; divide by ``weights.sum()`` for means).

    N is padded up to a multiple of ``num_chunks`` with weight-0 rows
    (zero loss and zero gradient contribution — the same masking the
    image eval path uses for partial batches).  ``correct_sum`` is
    non-differentiable (its cotangent is ignored)."""
    n = h.shape[0]
    pad = (-n) % num_chunks
    if pad:
        h = jnp.concatenate(
            [h, jnp.zeros((pad, h.shape[1]), h.dtype)], axis=0)
        targets = jnp.concatenate(
            [targets, jnp.zeros((pad,), targets.dtype)], axis=0)
        weights = jnp.concatenate(
            [weights, jnp.zeros((pad,), weights.dtype)], axis=0)
    out = _fused_ce_sums(h, e, targets, weights, num_chunks)
    return out


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused_ce_sums(h, e, targets, weights, num_chunks: int):
    (out, _) = _fwd(h, e, targets, weights, num_chunks)
    return out


def _split(x, c):
    return x.reshape((c, x.shape[0] // c) + x.shape[1:])


def _fwd(h, e, targets, weights, num_chunks: int):
    def body(carry, blk):
        loss, correct = carry
        hb, tb, wb = blk
        dl, dc = _block_sums(hb, e, tb, wb)
        return (loss + dl, correct + dc), None

    (sums, _) = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)),
        (_split(h, num_chunks), _split(targets, num_chunks),
         _split(weights, num_chunks)),
    )
    return sums, (h, e, targets, weights)


def _bwd(num_chunks: int, res, cts):
    h, e, targets, weights = res
    g_loss = cts[0]  # cotangent for correct_sum (cts[1]) is ignored

    def body(de_acc, blk):
        hb, tb, wb = blk
        logits = jax.lax.dot_general(
            hb, e, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        p = jax.nn.softmax(logits, axis=-1)
        onehot = jax.nn.one_hot(tb, e.shape[0], dtype=jnp.float32)
        dlogit = (p - onehot) * (wb * g_loss)[:, None]  # [chunk, V] f32
        dh_b = jax.lax.dot_general(
            dlogit, e, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(h.dtype)
        de_acc = de_acc + jax.lax.dot_general(
            dlogit, hb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return de_acc, dh_b

    de, dh = jax.lax.scan(
        body, jnp.zeros(e.shape, jnp.float32),
        (_split(h, num_chunks), _split(targets, num_chunks),
         _split(weights, num_chunks)),
    )
    return (dh.reshape(h.shape), de.astype(e.dtype), None, None)


_fused_ce_sums.defvjp(_fwd, _bwd)
