"""Fused tied-head + cross-entropy: the LM loss without the [N, V] logits
tensor.

The LM step's last matmul projects hidden states onto the 32k-vocab tied
embedding and feeds softmax cross-entropy (models/transformer.py:251-253 →
ops/loss.py).  Materializing those logits costs N·V f32 in HBM *twice over*
(forward write + backward read) plus the softmax intermediates — at
b8·L1024·V32k that is >2 GB of pure loss-head traffic per step, charged
against an HBM-bound budget (ROADMAP roofline).  This op computes the SAME
loss in row chunks with a custom VJP:

- **forward**: ``lax.scan`` over N/num_chunks row blocks — each block's
  logits ([chunk, V], f32-accumulated MXU matmul) live only in VMEM-scale
  scratch; only the scalar loss/correct sums survive.
- **backward**: recomputes each block's logits (one extra matmul pass —
  FLOPs are free here, bytes are not), forms ``softmax − onehot`` locally,
  and accumulates ``dh``, ``dE``, and the per-row ``weights`` cotangent
  (``(logz − true_logit)·ḡ`` — the loss path only; ``correct_sum`` stays
  non-differentiable) per block.  Residuals are just the inputs; nothing
  O(N·V) is ever saved.

**Sharded composition** — three variants, selected by the sharding context
(train/lm.py ``fused_ce_mode``):

- ``fused_ce_sums`` (replicated): the GSPMD baseline.  Under pure data
  sharding its backward carries a fully *replicated* ``[V, D]`` f32 ``dE``
  accumulator (125 MiB/device at V32k·D1024) while the logits it eliminates
  were already batch-sharded — measured net-neutral at 8-way
  (RESULTS_fused_ce_memory.json round 5).
- ``fused_ce_sums_dp`` (DP mode): explicit ``shard_map`` over the data
  axis.  The scan's ``dE`` carry is a *vocab-row shard* ``[V/k, D]`` f32
  per device; each block's ``dlogit`` is exchanged with one
  ``all_to_all`` (batch-sharded → vocab-sharded — the cross-replica
  partial-sum reduction of arXiv 2004.13336, the traffic EQuARX/2506.17615
  compresses) and the cotangent is returned still vocab-sharded, so the
  one gather back to the replicated parameter rides the existing GSPMD
  gradient reduction outside the scan.  Restores the full fused-head
  memory win on data-sharded meshes.
- ``fused_ce_sums_tp`` (TP mode): accepts the *vocab-sharded* tied
  embedding from parallel/tp.py (``P('model', None)``) directly inside
  ``shard_map`` — block-local logsumexp / true-logit partials are combined
  with ``psum``/``pmax`` over the model axis, ``dE`` accumulates as the
  local ``[V/tp, D]`` shard (one deferred psum over data at scan end), and
  the cotangent comes back ``P(model, None)``: neither ``e`` nor ``dE`` is
  ever replicated.

Numerics: logits accumulate in f32 (``preferred_element_type``) from
bf16/f32 operands — at least as accurate as the unfused head (which casts
the f32 hidden back through the embed dtype).  Equality to the unfused
``cross_entropy(model(tokens))`` path is pinned in tests/test_fused_ce.py
for all three variants.

Reference anchor: the loss of every reference recipe is
``nn.CrossEntropyLoss`` on the model head (reference distributed.py:151);
this is that capability, restructured for the TPU memory hierarchy.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp


def _block_sums(h_blk, e, t_blk, w_blk):
    """One row block: (loss_sum, correct_sum) in f32."""
    logits = jax.lax.dot_general(
        h_blk, e, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [chunk, V] f32
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, t_blk[:, None], axis=-1)[:, 0]
    loss = jnp.sum((logz - true_logit) * w_blk)
    correct = jnp.sum(
        (jnp.argmax(logits, axis=-1) == t_blk).astype(jnp.float32) * w_blk)
    return loss, correct


def _pad_rows(h, targets, weights, multiple: int):
    """Pad N up to a multiple with weight-0 rows (zero loss and zero
    gradient contribution — the same masking the image eval path uses for
    partial batches)."""
    pad = (-h.shape[0]) % multiple
    if pad:
        h = jnp.concatenate(
            [h, jnp.zeros((pad, h.shape[1]), h.dtype)], axis=0)
        targets = jnp.concatenate(
            [targets, jnp.zeros((pad,), targets.dtype)], axis=0)
        weights = jnp.concatenate(
            [weights, jnp.zeros((pad,), weights.dtype)], axis=0)
    return h, targets, weights


def fused_ce_sums(h, e, targets, weights, num_chunks: int):
    """``h [N, D]`` hidden rows, ``e [V, D]`` tied embedding, ``targets
    [N]`` int32, ``weights [N]`` f32 → ``(loss_sum, correct_sum)`` f32
    scalars (weighted sums; divide by ``weights.sum()`` for means).

    N is padded up to a multiple of ``num_chunks`` (see ``_pad_rows``).
    ``correct_sum`` is non-differentiable (its cotangent is ignored);
    ``weights`` carries the true loss-path cotangent
    ``(logz − true_logit)·ḡ`` per row."""
    h, targets, weights = _pad_rows(h, targets, weights, num_chunks)
    return _fused_ce_sums(h, e, targets, weights, num_chunks)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused_ce_sums(h, e, targets, weights, num_chunks: int):
    (out, _) = _fwd(h, e, targets, weights, num_chunks)
    return out


def _split(x, c):
    return x.reshape((c, x.shape[0] // c) + x.shape[1:])


def _fwd(h, e, targets, weights, num_chunks: int):
    def body(carry, blk):
        loss, correct = carry
        hb, tb, wb = blk
        dl, dc = _block_sums(hb, e, tb, wb)
        return (loss + dl, correct + dc), None

    (sums, _) = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)),
        (_split(h, num_chunks), _split(targets, num_chunks),
         _split(weights, num_chunks)),
    )
    return sums, (h, e, targets, weights)


def _bwd(num_chunks: int, res, cts):
    h, e, targets, weights = res
    g_loss = cts[0]  # cotangent for correct_sum (cts[1]) is ignored

    def body(de_acc, blk):
        hb, tb, wb = blk
        logits = jax.lax.dot_general(
            hb, e, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        logz = jax.nn.logsumexp(logits, axis=-1)
        true_logit = jnp.take_along_axis(logits, tb[:, None], axis=-1)[:, 0]
        p = jnp.exp(logits - logz[:, None])
        onehot = jax.nn.one_hot(tb, e.shape[0], dtype=jnp.float32)
        dlogit = (p - onehot) * (wb * g_loss)[:, None]  # [chunk, V] f32
        dh_b = jax.lax.dot_general(
            dlogit, e, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(h.dtype)
        de_acc = de_acc + jax.lax.dot_general(
            dlogit, hb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # d loss_sum / d w_i = (logz_i - true_logit_i): the per-row CE
        # itself (loss path only; the correct_sum path is non-diff).
        dw_b = (logz - true_logit) * g_loss
        return de_acc, (dh_b, dw_b)

    de, (dh, dw) = jax.lax.scan(
        body, jnp.zeros(e.shape, jnp.float32),
        (_split(h, num_chunks), _split(targets, num_chunks),
         _split(weights, num_chunks)),
    )
    return (dh.reshape(h.shape), de.astype(e.dtype), None,
            dw.reshape(weights.shape).astype(weights.dtype))


_fused_ce_sums.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------------------
# DP mode: vocab-row-sharded dE accumulator over the data axis.
# ---------------------------------------------------------------------------


def fused_ce_sums_dp(h, e, targets, weights, num_chunks: int, mesh,
                     data_axis: str = "data"):
    """Data-sharded fused CE: same contract as ``fused_ce_sums`` but the
    backward's ``dE`` scan carry is a vocab-row shard ``[V/k, D]`` f32 per
    device instead of the replicated ``[V, D]``.

    Rows (``h``/``targets``/``weights``) enter batch-sharded over
    ``data_axis``; ``e`` is the replicated tied embedding.  Each backward
    block exchanges its ``[chunk/k, V]`` dlogit with one ``all_to_all``
    (batch-sharded → vocab-sharded) so every device accumulates only its
    vocab slice; the cotangent is returned still ``P(data, None)``-sharded
    and the single gather back to the replicated parameter is left to the
    existing GSPMD gradient reduction, outside the scan.

    Requires ``V % k == 0`` for the vocab all_to_all split (k = data-axis
    size).  ``train/lm.py`` ``fused_ce_mode='auto'`` falls back to the
    replicated variant otherwise."""
    k = dict(mesh.shape).get(data_axis, 1)
    if k <= 1:
        return fused_ce_sums(h, e, targets, weights, num_chunks)
    if e.shape[0] % k:
        raise ValueError(
            f"fused_ce_sums_dp: vocab {e.shape[0]} not divisible by the "
            f"'{data_axis}' axis size {k} (needed for the vocab-sharded "
            f"dE accumulator); use the replicated variant")
    h, targets, weights = _pad_rows(h, targets, weights, num_chunks * k)
    fn = _make_dp_fn(num_chunks, mesh, data_axis)
    return fn(h, e, targets, weights)


@functools.lru_cache(maxsize=None)
def _make_dp_fn(num_chunks: int, mesh, data_axis: str):
    from jax.sharding import PartitionSpec as P

    row = P(data_axis)
    rows2d = P(data_axis, None)
    rep = P()

    def fwd_local(h, e, t, w):
        def body(carry, blk):
            loss, correct = carry
            hb, tb, wb = blk
            dl, dc = _block_sums(hb, e, tb, wb)
            return (loss + dl, correct + dc), None

        sums, _ = jax.lax.scan(
            body, (jnp.float32(0.0), jnp.float32(0.0)),
            (_split(h, num_chunks), _split(t, num_chunks),
             _split(w, num_chunks)),
        )
        return jax.lax.psum(sums[0], data_axis), jax.lax.psum(
            sums[1], data_axis)

    k_dp = dict(mesh.shape)[data_axis]

    def bwd_local(h, e, t, w, g_loss):
        vshard = e.shape[0] // k_dp

        def body(de_acc, blk):
            hb, tb, wb = blk  # this shard's rows of the block
            logits = jax.lax.dot_general(
                hb, e, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [chunk/k, V] f32
            logz = jax.nn.logsumexp(logits, axis=-1)
            true_logit = jnp.take_along_axis(
                logits, tb[:, None], axis=-1)[:, 0]
            p = jnp.exp(logits - logz[:, None])
            onehot = jax.nn.one_hot(tb, e.shape[0], dtype=jnp.float32)
            dlogit = (p - onehot) * (wb * g_loss)[:, None]
            dh_b = jax.lax.dot_general(
                dlogit, e, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(h.dtype)
            dw_b = (logz - true_logit) * g_loss
            # Batch-sharded → vocab-sharded: this device receives ALL the
            # block's rows restricted to its vocab slice — the per-block
            # cross-replica partial-sum exchange (arXiv 2004.13336).
            dl_v = jax.lax.all_to_all(
                dlogit, data_axis, split_axis=1, concat_axis=0, tiled=True
            )  # [chunk, V/k]
            h_full = jax.lax.all_gather(
                hb, data_axis, axis=0, tiled=True)  # [chunk, D]
            de_acc = de_acc + jax.lax.dot_general(
                dl_v, h_full, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [V/k, D] — complete sum for this vocab slice
            return de_acc, (dh_b, dw_b)

        de, (dh, dw) = jax.lax.scan(
            body, jnp.zeros((vshard, e.shape[1]), jnp.float32),
            (_split(h, num_chunks), _split(t, num_chunks),
             _split(w, num_chunks)),
        )
        return (dh.reshape((-1,) + h.shape[1:]), de.astype(e.dtype),
                dw.reshape(-1).astype(w.dtype))

    fwd_sm = jax.shard_map(
        fwd_local, mesh=mesh, in_specs=(rows2d, rep, row, row),
        out_specs=(rep, rep), check_vma=False,
    )
    bwd_sm = jax.shard_map(
        bwd_local, mesh=mesh, in_specs=(rows2d, rep, row, row, rep),
        out_specs=(rows2d, rows2d, row), check_vma=False,
    )

    @jax.custom_vjp
    def f(h, e, t, w):
        return fwd_sm(h, e, t, w)

    def f_fwd(h, e, t, w):
        return fwd_sm(h, e, t, w), (h, e, t, w)

    def f_bwd(res, cts):
        h, e, t, w = res
        dh, de, dw = bwd_sm(h, e, t, w, cts[0])  # correct_sum ct ignored
        return dh, de, None, dw

    f.defvjp(f_fwd, f_bwd)
    return f


# ---------------------------------------------------------------------------
# TP mode: vocab-sharded tied embedding (parallel/tp.py P('model', None)).
# ---------------------------------------------------------------------------


def fused_ce_sums_tp(h, e, targets, weights, num_chunks: int, mesh,
                     data_axis: str = "data", model_axis: str = "model"):
    """Tensor-parallel fused CE: ``e`` enters *vocab-sharded* over
    ``model_axis`` (the parallel/tp.py ``P('model', None)`` layout) and is
    never replicated — each device's scan sees only its ``[V/tp, D]``
    shard.

    Per block, each model shard computes its local ``[chunk, V/tp]``
    logits and the global softmax statistics are combined with one
    ``pmax`` + two ``psum`` over the model axis (logsumexp / true logit;
    argmax for ``correct_sum`` keeps jnp.argmax's first-occurrence
    tie-break via a pmin over candidate indices).  The backward ``dE``
    accumulates as the local ``[V/tp, D]`` shard with the cross-replica
    (data-axis) sum deferred to one psum at scan end, and the cotangent
    returns ``P(model, None)``-sharded.  Per-row ``logz``/``true_logit``
    are saved as O(N) residuals so the backward re-runs no model-axis
    collectives for the softmax.

    Requires ``V % tp == 0`` (the tp.py layout already does) and
    ``model_axis != data_axis``."""
    tp = dict(mesh.shape).get(model_axis, 1)
    if tp <= 1:
        return fused_ce_sums(h, e, targets, weights, num_chunks)
    if model_axis == data_axis:
        raise ValueError(
            "fused_ce_sums_tp: model_axis must differ from data_axis "
            f"(both {model_axis!r}); a same-axis vocab shard would mix "
            "row shards into the softmax reductions")
    if e.shape[0] % tp:
        raise ValueError(
            f"fused_ce_sums_tp: vocab {e.shape[0]} not divisible by the "
            f"'{model_axis}' axis size {tp}")
    dp = dict(mesh.shape).get(data_axis, 1)
    h, targets, weights = _pad_rows(h, targets, weights, num_chunks * dp)
    fn = _make_tp_fn(num_chunks, mesh, data_axis, model_axis)
    return fn(h, e, targets, weights)


@functools.lru_cache(maxsize=None)
def _make_tp_fn(num_chunks: int, mesh, data_axis: str, model_axis: str):
    from jax.sharding import PartitionSpec as P

    has_dp = dict(mesh.shape).get(data_axis, 1) > 1
    row_axis = data_axis if has_dp else None
    row = P(row_axis)
    rows2d = P(row_axis, None)
    vocab2d = P(model_axis, None)
    rep = P()

    def _psum_dp(x):
        return jax.lax.psum(x, data_axis) if has_dp else x

    tp_size = dict(mesh.shape)[model_axis]

    def fwd_local(h, e, t, w):
        vloc = e.shape[0]
        lo = jax.lax.axis_index(model_axis) * vloc
        v_total = vloc * tp_size

        def body(carry, blk):
            loss, correct = carry
            hb, tb, wb = blk
            logits = jax.lax.dot_general(
                hb, e, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [chunk, V/tp] f32 — this shard's vocab columns only
            lmax_loc = jnp.max(logits, axis=-1)
            lmax = jax.lax.pmax(lmax_loc, model_axis)
            ssum = jax.lax.psum(
                jnp.sum(jnp.exp(logits - lmax[:, None]), axis=-1),
                model_axis)
            logz = lmax + jnp.log(ssum)
            tloc = tb - lo
            in_shard = (tloc >= 0) & (tloc < vloc)
            tl_part = jnp.where(
                in_shard,
                jnp.take_along_axis(
                    logits, jnp.clip(tloc, 0, vloc - 1)[:, None],
                    axis=-1)[:, 0],
                0.0)
            true_logit = jax.lax.psum(tl_part, model_axis)
            # global argmax with jnp.argmax's first-occurrence tie-break:
            # among shards achieving the global max, take the lowest
            # global index.
            amax_loc = lo + jnp.argmax(logits, axis=-1)
            cand = jnp.where(lmax_loc >= lmax, amax_loc, v_total)
            gidx = jax.lax.pmin(cand, model_axis)
            loss = loss + jnp.sum((logz - true_logit) * wb)
            correct = correct + jnp.sum(
                (gidx == tb).astype(jnp.float32) * wb)
            return (loss, correct), (logz, true_logit)

        (loss, correct), (logz, tl) = jax.lax.scan(
            body, (jnp.float32(0.0), jnp.float32(0.0)),
            (_split(h, num_chunks), _split(t, num_chunks),
             _split(w, num_chunks)),
        )
        return (_psum_dp(loss), _psum_dp(correct),
                logz.reshape(-1), tl.reshape(-1))

    def bwd_local(h, e, t, w, logz, tl, g_loss):
        vloc = e.shape[0]
        lo = jax.lax.axis_index(model_axis) * vloc

        def body(de_acc, blk):
            hb, tb, wb, lzb, tlb = blk
            logits = jax.lax.dot_general(
                hb, e, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [chunk, V/tp]
            p = jnp.exp(logits - lzb[:, None])
            # one_hot of an out-of-shard (negative / >= vloc) index is the
            # zero row — exactly the wanted restriction to local columns.
            onehot = jax.nn.one_hot(tb - lo, vloc, dtype=jnp.float32)
            dlogit = (p - onehot) * (wb * g_loss)[:, None]
            dh_b = jax.lax.psum(
                jax.lax.dot_general(
                    dlogit, e, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ), model_axis).astype(h.dtype)
            de_acc = de_acc + jax.lax.dot_general(
                dlogit, hb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [V/tp, D] — this data shard's rows only
            dw_b = (lzb - tlb) * g_loss
            return de_acc, (dh_b, dw_b)

        de, (dh, dw) = jax.lax.scan(
            body, jnp.zeros((vloc, e.shape[1]), jnp.float32),
            (_split(h, num_chunks), _split(t, num_chunks),
             _split(w, num_chunks), _split(logz, num_chunks),
             _split(tl, num_chunks)),
        )
        de = _psum_dp(de)  # deferred cross-replica sum: ONE collective
        return (dh.reshape((-1,) + h.shape[1:]), de.astype(e.dtype),
                dw.reshape(-1).astype(w.dtype))

    fwd_sm = jax.shard_map(
        fwd_local, mesh=mesh, in_specs=(rows2d, vocab2d, row, row),
        out_specs=(rep, rep, row, row), check_vma=False,
    )
    bwd_sm = jax.shard_map(
        bwd_local, mesh=mesh,
        in_specs=(rows2d, vocab2d, row, row, row, row, rep),
        out_specs=(rows2d, vocab2d, row), check_vma=False,
    )

    @jax.custom_vjp
    def f(h, e, t, w):
        loss, correct, _, _ = fwd_sm(h, e, t, w)
        return loss, correct

    def f_fwd(h, e, t, w):
        loss, correct, logz, tl = fwd_sm(h, e, t, w)
        return (loss, correct), (h, e, t, w, logz, tl)

    def f_bwd(res, cts):
        h, e, t, w, logz, tl = res
        dh, de, dw = bwd_sm(h, e, t, w, logz, tl, cts[0])
        return dh, de, None, dw

    f.defvjp(f_fwd, f_bwd)
    return f
