"""Fused BatchNorm(+ReLU) with a hand-written VJP — the HBM-traffic fix.

Training ResNet-50 on TPU is HBM-bound, not MXU-bound: profiling the round-1
step (scripts/profile_trace.py) showed backward conv fusions re-reading the
full pre-BN activation through flax BatchNorm's f32-promoted autodiff
residuals, putting the step at ~2× the memory-roofline time.  This module
replaces ``flax.linen.BatchNorm`` (+ the following ReLU) in the conv stacks:

- **forward** computes batch statistics in one pass (mean + mean-of-squares,
  f32 accumulation over bf16 reads) and normalizes; XLA fuses the stats
  reduce into the producing conv's epilogue and the normalize into the
  consuming conv's input.
- **backward** is a custom VJP whose residuals are the *bf16* pre-BN tensor
  plus per-channel vectors — flax's autodiff saves an f32-promoted copy
  (2× the bytes) and reads both the pre-BN and post-ReLU tensors; ours
  reads exactly one saved tensor (the ReLU mask is recomputed from it:
  ``relu'(γ·x̂+β) = [γ·x̂+β > 0]``).

Semantics match ``nn.BatchNorm(momentum=0.9, epsilon=1e-5)`` + ``nn.relu``
exactly (tested to f32 tolerance in tests/test_fused_bn.py), including
SyncBN-under-GSPMD: the statistics reductions are global-semantics means, so
XLA inserts the cross-replica psum when the batch is sharded — same as the
flax path (reference capability: torch DDP's unsynced BN, see
train/steps.py docstring for the per-recipe BN semantics note).

Reference anchor: the BN layers of every torchvision model the reference
instantiates (reference distributed.py:134-139).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


def _stats(y: jnp.ndarray,
           axis_name: Optional[str] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One-pass batch mean/variance over all-but-channel axes, f32 accum.

    ``axis_name``: SyncBN — additionally reduce the moments over that mesh
    axis (equal-size shards under shard_map), so the statistics cover the
    *global* batch.  The torch capability analogue is ``nn.SyncBatchNorm``
    wrapping DDP at small per-device batch."""
    axes = tuple(range(y.ndim - 1))
    yf = y.astype(jnp.float32)
    mu = yf.mean(axes)
    ms = (yf * yf).mean(axes)
    if axis_name is not None:
        mu = jax.lax.pmean(mu, axis_name)
        ms = jax.lax.pmean(ms, axis_name)
    # One-pass E[y²]−μ² can go (numerically) negative under cancellation for
    # large-mean/small-spread channels; clamp like flax's _compute_stats or
    # rsqrt(var+eps) NaNs mid-training.
    var = jnp.maximum(ms - mu * mu, 0.0)
    return mu, var


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _bn_act(y, gamma, beta, eps: float, relu: bool,
            axis_name: Optional[str] = None):
    """Returns ``(o, mean, var)`` — stats are exposed for the EMA update
    (stop-gradiented by the caller, so their cotangents are zero)."""
    (o, mu, var), _ = _bn_act_fwd(y, gamma, beta, eps, relu, axis_name)
    return o, mu, var


def _bn_act_fwd(y, gamma, beta, eps: float, relu: bool,
                axis_name: Optional[str] = None):
    mu, var = _stats(y, axis_name)
    inv = jax.lax.rsqrt(var + eps)
    scale = gamma * inv
    shift = beta - mu * scale
    o = (y.astype(jnp.float32) * scale + shift).astype(y.dtype)
    if relu:
        o = jax.nn.relu(o)
    # Residuals: the bf16 pre-BN tensor + per-channel vectors.  Neither the
    # normalized nor the post-ReLU tensor is saved — backward reconstructs
    # x̂ and the ReLU mask from y.
    return (o, mu, var), (y, mu, inv, gamma, beta)


def _bn_act_bwd(eps: float, relu: bool, axis_name: Optional[str], res, cts):
    y, mu, inv, gamma, beta = res
    do = cts[0]  # cotangents for (mu, var) outputs are zero (EMA is stop-grad)
    axes = tuple(range(y.ndim - 1))
    n = 1
    for a in axes:
        n *= y.shape[a]
    yf = y.astype(jnp.float32)
    xhat = (yf - mu) * inv
    dof = do.astype(jnp.float32)
    if relu:
        dof = jnp.where(gamma * xhat + beta > 0, dof, 0.0)
    dbeta = dof.sum(axes)
    dgamma = (dof * xhat).sum(axes)
    # Standard BN backward through the batch statistics.  SyncBN: the
    # statistics covered the global batch, so the through-stats terms use
    # the axis-summed reductions over the global element count — while the
    # RETURNED dgamma/dbeta stay local (sum-form), because the outer
    # explicit-collectives step psums parameter gradients itself
    # (train/steps.py sync_grads); same split as torch SyncBatchNorm
    # (all-reduced sum_dy inside, DDP-reduced grad_weight outside).
    dbeta_g, dgamma_g, n_g = dbeta, dgamma, n
    if axis_name is not None:
        dbeta_g = jax.lax.psum(dbeta, axis_name)
        dgamma_g = jax.lax.psum(dgamma, axis_name)
        n_g = n * jax.lax.psum(1, axis_name)
    dx = (gamma * inv) * (dof - dbeta_g / n_g - xhat * (dgamma_g / n_g))
    return dx.astype(y.dtype), dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype)


_bn_act.defvjp(_bn_act_fwd, _bn_act_bwd)


class FusedBatchNormAct(nn.Module):
    """Drop-in for ``nn.BatchNorm(...)`` (+ optional fused ReLU).

    Variable names/collections match flax BatchNorm (params ``scale``/
    ``bias``; batch_stats ``mean``/``var``) so checkpoints remain
    recipe-interchangeable with the round-1 models.
    """

    use_running_average: Optional[bool] = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    # No dtype knob: storage follows the input dtype, normalization math is
    # always f32-in-register (reads/writes stay bf16 under the bf16 policy).
    relu: bool = False
    scale_init: Any = nn.initializers.ones
    bias_init: Any = nn.initializers.zeros
    # SyncBN: reduce batch moments over this mesh axis (only meaningful
    # under shard_map/explicit collectives — GSPMD's global-semantics BN
    # is already synced by construction).  ≙ torch nn.SyncBatchNorm.
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        # Call-time flag overrides the constructor (unlike flax's merge_param,
        # which forbids setting both — recipes set it at construction, tests
        # at call time).
        use_ra = (
            use_running_average
            if use_running_average is not None
            else bool(self.use_running_average)
        )
        features = x.shape[-1]
        gamma = self.param("scale", self.scale_init, (features,), jnp.float32)
        beta = self.param("bias", self.bias_init, (features,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((features,), jnp.float32)
        )

        if use_ra:
            inv = jax.lax.rsqrt(ra_var.value + self.epsilon)
            scale = gamma * inv
            shift = beta - ra_mean.value * scale
            o = (x.astype(jnp.float32) * scale + shift).astype(x.dtype)
            return jax.nn.relu(o) if self.relu else o

        o, mu, var = _bn_act(x, gamma, beta, self.epsilon, self.relu,
                             self.axis_name)
        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1 - m) * jax.lax.stop_gradient(mu)
            ra_var.value = m * ra_var.value + (1 - m) * jax.lax.stop_gradient(var)
        return o
