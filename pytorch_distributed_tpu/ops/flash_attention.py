"""Fused flash attention — Pallas TPU kernel for the framework's hot op.

Replaces the reference's native-kernel layer for attention-bearing models:
where the GPU stack reaches cuDNN/apex fused kernels through torch bindings
(SURVEY.md §2.2), the TPU stack reaches the MXU through this Pallas kernel.
Dense XLA attention materializes the [L, L] score matrix in HBM; this kernel
keeps score blocks in VMEM with online softmax, so HBM traffic stays
O(L·D) and memory O(L·BK) — the single-chip complement of the cross-chip
ring attention in parallel/ring.py (which this kernel's math mirrors).

Forward: Pallas kernel, grid (batch·heads, q-blocks, kv-blocks), f32
accumulators in VMEM scratch, causal blocks skipped via predication.
Backward: fused Pallas kernels in the flash-attention-2 decomposition —
a dq pass (grid bh × q-blocks × kv-blocks) and a dk/dv pass (grid
bh × kv-blocks × q-blocks), both recomputing P online from the saved
logsumexp with VMEM accumulators; O(L·BK) memory, every matmul on the MXU.
``bwd_impl="xla"`` selects the plain-XLA blockwise recompute (the oracle
the kernels are tested against).

Layout: [B, L, H, D] like parallel/ring.py; block sizes default to the
128-lane MXU tile.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale: float, causal: bool,
                block_q: int, block_k: int):
    """One (bh, qi, kj) grid step: accumulate q-block × kv-block online."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: whole block masked out when the kv block starts after the
    # q block ends; cheap predication, no wasted MXU work.
    run = True
    if causal:
        run = kj * block_k <= qi * block_q + (block_q - 1)

    @pl.when(run)
    def _block():
        q = q_ref[0].astype(jnp.float32)            # [BQ, D]
        k = k_ref[0].astype(jnp.float32)            # [BK, D]
        v = v_ref[0].astype(jnp.float32)            # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                    # [BQ, BK]
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        m_prev = m_scr[:, :1]                        # [BQ, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                       # [BQ, BK]
        corr = jnp.exp(m_prev - m_new)               # [BQ, 1]
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kj == nk - 1)
    def _final():
        l = l_scr[:, :1]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # lse is lane-broadcast to 128 (TPU block alignment; caller reads
        # lane 0) — same layout as jax's reference TPU kernel.
        lse_ref[0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(safe_l), lse_ref.shape[1:]
        )


def _flash_fwd(q, k, v, causal: bool, block_q: int, block_k: int,
               interpret: bool):
    B, L, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    bq = min(block_q, L)
    bk = min(block_k, L)
    assert L % bq == 0 and L % bk == 0, (
        f"sequence length {L} must divide block sizes ({bq}, {bk})"
    )
    # [B, L, H, D] -> [B*H, L, D]
    qr = q.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    kr = k.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    vr = v.transpose(0, 2, 1, 3).reshape(B * H, L, D)

    grid = (B * H, L // bq, L // bk)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, L, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max
            pltpu.VMEM((bq, 128), jnp.float32),   # running denominator
            pltpu.VMEM((bq, D), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(qr, kr, vr)
    # Residual lse is [B*H, L] (lane 0 of the kernel's lane-broadcast
    # output) — saving the full 128-lane layout would hold 128x the bytes
    # across the fwd->bwd interval; the backward re-broadcasts cheaply.
    return out.reshape(B, H, L, D).transpose(0, 2, 1, 3), lse[:, :, 0]


def _causal_run(qi, kj, block_q, block_k):
    """Whole-block predicate: any (q, k) pair in the block is unmasked."""
    return kj * block_k <= qi * block_q + (block_q - 1)


def _mask_scores(s, qi, kj, block_q, block_k):
    qpos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(kpos <= qpos, s, NEG_INF)


def _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, qi, kj,
                    scale, causal, block_q, block_k):
    """Shared backward block math: online-recomputed (p, ds) plus the f32
    block views — the single source for both the dq and dk/dv kernels (and
    the same masking the forward kernel applies)."""
    q = q_ref[0].astype(jnp.float32)             # [BQ, D]
    k = k_ref[0].astype(jnp.float32)             # [BK, D]
    v = v_ref[0].astype(jnp.float32)             # [BK, D]
    do = do_ref[0].astype(jnp.float32)           # [BQ, D]
    lse = lse_ref[0][:, :1]                      # [BQ, 1]
    dlt = dlt_ref[0][:, :1]                      # [BQ, 1]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                    # [BQ, BK]
    if causal:
        s = _mask_scores(s, qi, kj, block_q, block_k)
    p = jnp.exp(s - lse)                         # [BQ, BK]
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                            # [BQ, BK]
    ds = p * (dp - dlt) * scale
    return p, ds, q, k, do


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref,
                   dq_scr, *, scale: float, causal: bool,
                   block_q: int, block_k: int):
    """dq pass: grid (bh, qi, kj), accumulate dq_i over kv blocks."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = _causal_run(qi, kj, block_q, block_k) if causal else True

    @pl.when(run)
    def _block():
        _, ds, _, k, _ = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, qi, kj,
            scale, causal, block_q, block_k)
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kj == nk - 1)
    def _final():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                    causal: bool, block_q: int, block_k: int):
    """dk/dv pass: grid (bh, kj, qi), accumulate over q blocks."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # q block entirely before the kv block contributes nothing.
    run = _causal_run(qi, kj, block_q, block_k) if causal else True

    @pl.when(run)
    def _block():
        p, ds, q, _, do = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, qi, kj,
            scale, causal, block_q, block_k)
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                            # [BK, D]
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                            # [BK, D]

    @pl.when(qi == nq - 1)
    def _final():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_pallas(res, g, causal: bool, block_q: int, block_k: int,
                interpret: bool):
    """Fused Pallas backward: dq pass + dk/dv pass, both with online
    recompute from the saved lse — no [L, L] materialization, all matmuls
    on the MXU (flash-attention-2 decomposition)."""
    q, k, v, out, lse = res               # lse: [B*H, L] f32
    B, L, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    bq = min(block_q, L)
    bk = min(block_k, L)
    f32 = jnp.float32
    qr = q.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    kr = k.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    vr = v.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    gr = g.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    of = out.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    delta = jnp.sum(of.astype(f32) * gr.astype(f32), axis=-1)     # [BH, L]
    # Lane-broadcast for block slicing (transient, not a saved residual).
    lse128 = jnp.broadcast_to(lse[:, :, None], (B * H, L, 128))
    dlt128 = jnp.broadcast_to(delta[:, :, None], (B * H, L, 128))

    def spec_q(pos_q):
        return pl.BlockSpec((1, bq, D), lambda b, x, y: (b, (x, y)[pos_q], 0),
                            memory_space=pltpu.VMEM)

    def spec_k(pos_k):
        return pl.BlockSpec((1, bk, D), lambda b, x, y: (b, (x, y)[pos_k], 0),
                            memory_space=pltpu.VMEM)

    def spec_l(pos_q):
        return pl.BlockSpec((1, bq, 128), lambda b, x, y: (b, (x, y)[pos_q], 0),
                            memory_space=pltpu.VMEM)

    # dq: grid (bh, qi, kj) — q-side blocks keyed by grid pos 0, kv by 1.
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(B * H, L // bq, L // bk),
        in_specs=[spec_q(0), spec_k(1), spec_k(1), spec_q(0),
                  spec_l(0), spec_l(0)],
        out_specs=[spec_q(0)],
        out_shape=[jax.ShapeDtypeStruct((B * H, L, D), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, D), f32)],
        interpret=interpret,
    )(qr, kr, vr, gr, lse128, dlt128)[0]

    # dk/dv: grid (bh, kj, qi) — kv blocks keyed by grid pos 0, q by 1.
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(B * H, L // bk, L // bq),
        in_specs=[spec_q(1), spec_k(0), spec_k(0), spec_q(1),
                  spec_l(1), spec_l(1)],
        out_specs=[spec_k(0), spec_k(0)],
        out_shape=[jax.ShapeDtypeStruct((B * H, L, D), k.dtype),
                   jax.ShapeDtypeStruct((B * H, L, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, D), f32),
                        pltpu.VMEM((bk, D), f32)],
        interpret=interpret,
    )(qr, kr, vr, gr, lse128, dlt128)

    def back(x):
        return x.reshape(B, H, L, D).transpose(0, 2, 1, 3)

    return back(dq), back(dk), back(dv)


def _bwd_blockwise(res, g, causal: bool, block_k: int):
    """Memory-efficient backward: recompute P blockwise from saved lse.
    (Plain-XLA reference path, selected via ``bwd_impl="xla"`` — the
    semantics oracle the Pallas backward kernels are tested against.)"""
    q, k, v, out, lse = res  # q,k,v,out: [B,L,H,D]; lse: [B*H, L]
    B, L, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    f32 = jnp.float32
    qf = q.astype(f32).transpose(0, 2, 1, 3).reshape(B * H, L, D)
    kf = k.astype(f32).transpose(0, 2, 1, 3).reshape(B * H, L, D)
    vf = v.astype(f32).transpose(0, 2, 1, 3).reshape(B * H, L, D)
    of = out.astype(f32).transpose(0, 2, 1, 3).reshape(B * H, L, D)
    gf = g.astype(f32).transpose(0, 2, 1, 3).reshape(B * H, L, D)

    delta = jnp.sum(of * gf, axis=-1)  # [BH, L] = rowsum(dO ∘ O)
    bk = min(block_k, L)
    nk = L // bk
    pos = jnp.arange(L)

    def kv_block(carry, j):
        dq = carry
        ks = jax.lax.dynamic_slice_in_dim(kf, j * bk, bk, axis=1)  # [BH,bk,D]
        vs = jax.lax.dynamic_slice_in_dim(vf, j * bk, bk, axis=1)
        s = jnp.einsum("zqd,zkd->zqk", qf, ks) * scale             # [BH,L,bk]
        if causal:
            kpos = j * bk + jnp.arange(bk)
            s = jnp.where(kpos[None, None, :] <= pos[None, :, None], s, NEG_INF)
        p = jnp.exp(s - lse[:, :, None])                           # [BH,L,bk]
        dv = jnp.einsum("zqk,zqd->zkd", p, gf)
        dp = jnp.einsum("zqd,zkd->zqk", gf, vs)
        ds = p * (dp - delta[:, :, None]) * scale
        dq = dq + jnp.einsum("zqk,zkd->zqd", ds, ks)
        dk = jnp.einsum("zqk,zqd->zkd", ds, qf)
        return dq, (dk, dv)

    dq0 = jnp.zeros_like(qf)
    dq, (dks, dvs) = jax.lax.scan(kv_block, dq0, jnp.arange(nk))
    dk = jnp.moveaxis(dks, 0, 1).reshape(B * H, L, D)
    dv = jnp.moveaxis(dvs, 0, 1).reshape(B * H, L, D)

    def back(x):
        return x.reshape(B, H, L, D).transpose(0, 2, 1, 3)

    return (back(dq).astype(q.dtype), back(dk).astype(k.dtype),
            back(dv).astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    bwd_impl: str = "pallas",
) -> jnp.ndarray:
    """Fused attention over [B, L, H, D].  ``interpret=None`` auto-selects
    the Pallas interpreter off-TPU (slow, exact) and compiled mode on TPU.
    ``bwd_impl``: "pallas" = fused dq/dk/dv kernels (default); "xla" = the
    blockwise-recompute reference path."""
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_k,
                        _resolve_interpret(interpret))
    return out


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def pick_attention_impl(L: int, attn_impl: str = "auto") -> str:
    """The shared 'auto' policy: the Pallas flash kernel on TPU at long,
    1024-aligned L (where it beats XLA dense ~1.4-2.4×, RESULTS_flash.json);
    dense otherwise.  Used by models/transformer.SelfAttention and the
    Ulysses a2a inner attention (parallel/ulysses.py)."""
    if attn_impl in ("flash", "dense"):
        return attn_impl
    if jax.default_backend() == "tpu" and L >= 4096 and L % 1024 == 0:
        return "flash"
    return "dense"


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret, bwd_impl):
    out, lse = _flash_fwd(q, k, v, causal, block_q, block_k,
                          _resolve_interpret(interpret))
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, block_q, block_k, interpret, bwd_impl, res, g):
    if bwd_impl == "pallas":
        # The dq pass reuses the forward's q-block size; the dk/dv pass
        # accumulates over q blocks with the same tiling.
        return _bwd_pallas(res, g, causal, block_q, block_k,
                           _resolve_interpret(interpret))
    if bwd_impl != "xla":
        raise ValueError(
            f"unknown bwd_impl {bwd_impl!r}: expected 'pallas' or 'xla'"
        )
    return _bwd_blockwise(res, g, causal, block_k)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
