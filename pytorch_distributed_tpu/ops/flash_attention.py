"""Fused flash attention — Pallas TPU kernel for the framework's hot op.

Replaces the reference's native-kernel layer for attention-bearing models:
where the GPU stack reaches cuDNN/apex fused kernels through torch bindings
(SURVEY.md §2.2), the TPU stack reaches the MXU through this Pallas kernel.
Dense XLA attention materializes the [L, L] score matrix in HBM; this kernel
keeps score blocks in VMEM with online softmax, so HBM traffic stays
O(L·D) and memory O(L·BK) — the single-chip complement of the cross-chip
ring attention in parallel/ring.py (which this kernel's math mirrors).

Forward: Pallas kernel, grid (batch·heads, q-blocks, kv-blocks), f32
accumulators in VMEM scratch, causal blocks skipped via predication.
Backward: custom VJP that recomputes attention blockwise from the saved
logsumexp (flash-attention-2 style) in plain XLA — O(L·BK) memory, no
[L, L] materialization; a Pallas backward kernel is the planned upgrade.

Layout: [B, L, H, D] like parallel/ring.py; block sizes default to the
128-lane MXU tile.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale: float, causal: bool,
                block_q: int, block_k: int):
    """One (bh, qi, kj) grid step: accumulate q-block × kv-block online."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: whole block masked out when the kv block starts after the
    # q block ends; cheap predication, no wasted MXU work.
    run = True
    if causal:
        run = kj * block_k <= qi * block_q + (block_q - 1)

    @pl.when(run)
    def _block():
        q = q_ref[0].astype(jnp.float32)            # [BQ, D]
        k = k_ref[0].astype(jnp.float32)            # [BK, D]
        v = v_ref[0].astype(jnp.float32)            # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                    # [BQ, BK]
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        m_prev = m_scr[:, :1]                        # [BQ, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                       # [BQ, BK]
        corr = jnp.exp(m_prev - m_new)               # [BQ, 1]
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kj == nk - 1)
    def _final():
        l = l_scr[:, :1]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # lse is lane-broadcast to 128 (TPU block alignment; caller reads
        # lane 0) — same layout as jax's reference TPU kernel.
        lse_ref[0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(safe_l), lse_ref.shape[1:]
        )


def _flash_fwd(q, k, v, causal: bool, block_q: int, block_k: int,
               interpret: bool):
    B, L, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    bq = min(block_q, L)
    bk = min(block_k, L)
    assert L % bq == 0 and L % bk == 0, (
        f"sequence length {L} must divide block sizes ({bq}, {bk})"
    )
    # [B, L, H, D] -> [B*H, L, D]
    qr = q.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    kr = k.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    vr = v.transpose(0, 2, 1, 3).reshape(B * H, L, D)

    grid = (B * H, L // bq, L // bk)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, L, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max
            pltpu.VMEM((bq, 128), jnp.float32),   # running denominator
            pltpu.VMEM((bq, D), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(B, H, L, D).transpose(0, 2, 1, 3), lse[:, :, 0]


def _bwd_blockwise(res, g, causal: bool, block_k: int):
    """Memory-efficient backward: recompute P blockwise from saved lse."""
    q, k, v, out, lse = res  # q,k,v,out: [B,L,H,D]; lse: [B*H, L]
    B, L, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    f32 = jnp.float32
    qf = q.astype(f32).transpose(0, 2, 1, 3).reshape(B * H, L, D)
    kf = k.astype(f32).transpose(0, 2, 1, 3).reshape(B * H, L, D)
    vf = v.astype(f32).transpose(0, 2, 1, 3).reshape(B * H, L, D)
    of = out.astype(f32).transpose(0, 2, 1, 3).reshape(B * H, L, D)
    gf = g.astype(f32).transpose(0, 2, 1, 3).reshape(B * H, L, D)

    delta = jnp.sum(of * gf, axis=-1)  # [BH, L] = rowsum(dO ∘ O)
    bk = min(block_k, L)
    nk = L // bk
    pos = jnp.arange(L)

    def kv_block(carry, j):
        dq = carry
        ks = jax.lax.dynamic_slice_in_dim(kf, j * bk, bk, axis=1)  # [BH,bk,D]
        vs = jax.lax.dynamic_slice_in_dim(vf, j * bk, bk, axis=1)
        s = jnp.einsum("zqd,zkd->zqk", qf, ks) * scale             # [BH,L,bk]
        if causal:
            kpos = j * bk + jnp.arange(bk)
            s = jnp.where(kpos[None, None, :] <= pos[None, :, None], s, NEG_INF)
        p = jnp.exp(s - lse[:, :, None])                           # [BH,L,bk]
        dv = jnp.einsum("zqk,zqd->zkd", p, gf)
        dp = jnp.einsum("zqd,zkd->zqk", gf, vs)
        ds = p * (dp - delta[:, :, None]) * scale
        dq = dq + jnp.einsum("zqk,zkd->zqd", ds, ks)
        dk = jnp.einsum("zqk,zqd->zkd", ds, qf)
        return dq, (dk, dv)

    dq0 = jnp.zeros_like(qf)
    dq, (dks, dvs) = jax.lax.scan(kv_block, dq0, jnp.arange(nk))
    dk = jnp.moveaxis(dks, 0, 1).reshape(B * H, L, D)
    dv = jnp.moveaxis(dvs, 0, 1).reshape(B * H, L, D)

    def back(x):
        return x.reshape(B, H, L, D).transpose(0, 2, 1, 3)

    return (back(dq).astype(q.dtype), back(dk).astype(k.dtype),
            back(dv).astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused attention over [B, L, H, D].  ``interpret=None`` auto-selects
    the Pallas interpreter off-TPU (slow, exact) and compiled mode on TPU."""
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_k,
                        _resolve_interpret(interpret))
    return out


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, causal, block_q, block_k,
                          _resolve_interpret(interpret))
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, block_q, block_k, interpret, res, g):
    return _bwd_blockwise(res, g, causal, block_k)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
