"""Classification metrics, computed *inside* the compiled step function.

Capability parity with the reference's ``accuracy()`` (reference
distributed.py:381-395): top-k percentage over a batch for k in (1, 5).
The reference computes this on device then immediately ``.item()``s the
result, forcing a host sync per step; here the op is pure and jit-traced so
metric reduction stays in-graph (SURVEY.md §7.4 item 1).

Design delta (TPU-first): supports an optional per-example ``weights`` mask so
padded batches (static-shape XLA requirement) contribute zero — this makes
sharded evaluation *exact* where the reference's DistributedSampler padding
slightly skews val metrics (SURVEY.md §7.4 item 3).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp


def topk_correct(logits: jnp.ndarray, labels: jnp.ndarray, k: int) -> jnp.ndarray:
    """Per-example 0/1 indicator that the true label is within the top-k logits.

    Implemented rank-style (count of strictly-greater logits < k) rather than
    via ``top_k`` + equality sweep: one vectorized comparison, no gather, maps
    cleanly onto the VPU, and ties resolve conservatively (a tie on the k-th
    boundary counts as correct only if strictly fewer than k logits beat the
    true class — identical to torch.topk semantics for distinct values).
    """
    true_logit = jnp.take_along_axis(logits, labels[:, None], axis=-1)  # [B, 1]
    rank = jnp.sum(logits > true_logit, axis=-1)  # [B] number of classes beating truth
    return (rank < k).astype(jnp.float32)


def accuracy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    topk: Sequence[int] = (1,),
    weights: jnp.ndarray | None = None,
) -> Tuple[jnp.ndarray, ...]:
    """Top-k accuracy in percent over the (possibly weighted) batch.

    Matches reference ``accuracy(output, target, topk=(1, 5))``
    (distributed.py:381-395): returns one scalar per k, scaled by 100.

    ``weights`` (0/1 per example) masks padding; the denominator is the
    weight sum, so padded shards still produce exact dataset-level metrics.
    """
    if weights is None:
        denom = jnp.float32(labels.shape[0])
        results = tuple(
            jnp.sum(topk_correct(logits, labels, k)) * 100.0 / denom for k in topk
        )
    else:
        weights = weights.astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(weights), 1.0)
        results = tuple(
            jnp.sum(topk_correct(logits, labels, k) * weights) * 100.0 / denom
            for k in topk
        )
    return results
