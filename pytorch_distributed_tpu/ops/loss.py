"""Loss functions (pure, jit-friendly).

Capability parity with the reference's ``nn.CrossEntropyLoss()``
(reference distributed.py:151): softmax cross-entropy from integer labels,
mean-reduced over the batch.  Weighted variant supports padded static-shape
batches (see ops/metrics.py docstring).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    weights: jnp.ndarray | None = None,
    label_smoothing: float = 0.0,
) -> jnp.ndarray:
    """Mean softmax cross-entropy.  Always accumulates in float32.

    ``logits`` may be bf16 (mixed-precision recipes); the log-softmax and
    reduction are promoted to f32 so the loss scale matches the fp32 recipes
    within noise (SURVEY.md §7.4 item 6 — bf16 parity).
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    per_example = logz - true_logit
    if label_smoothing > 0.0:
        # Smoothed target = (1-eps)*onehot + eps*uniform; CE against it
        # decomposes into the hard-label term plus the uniform term below.
        smooth = logz - jnp.mean(logits, axis=-1)
        per_example = (1.0 - label_smoothing) * per_example + label_smoothing * smooth
    if weights is None:
        return jnp.mean(per_example)
    weights = weights.astype(jnp.float32)
    return jnp.sum(per_example * weights) / jnp.maximum(jnp.sum(weights), 1.0)
