"""Preemption-aware training — the failure-detection subsystem.

TPU pods get reclaimed (maintenance events, spot preemption) with a SIGTERM
grace window.  The reference's entire fault-tolerance story is a manual
``--start-epoch`` restart flag (SURVEY.md §5.3; reference distributed.py:
48-52): no detection, no reaction.  Here a signal flips a flag, the epoch/
step drivers poll it at safe boundaries (between compiled steps — never
mid-collective, so every rank exits at the same step), checkpoint, and
leave; ``--resume`` then continues from the last completed epoch.

Signal handlers are process-global state, so installation is explicit and
reversible (``install()``/``uninstall()``); the previous handler is chained,
not clobbered.
"""

from __future__ import annotations

import signal
import threading
from typing import Dict, Tuple


def parse_signals(spec: str) -> Tuple[int, ...]:
    """``'term,int'`` / ``'SIGTERM, SIGINT'`` / ``'15'`` → signal numbers.

    The ``--preempt-signals`` parser: SIGTERM is every platform's reclaim
    grace signal; SIGINT is the opt-in for interactive runs where Ctrl-C
    should checkpoint-and-exit instead of stack-tracing (SIGKILL is
    rejected — it cannot be trapped; that case is what ``--save-steps``
    cadence checkpoints are for)."""
    out = []
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok.isdigit():
            num = int(tok)
        else:
            name = tok.upper()
            if not name.startswith("SIG"):
                name = "SIG" + name
            try:
                num = int(getattr(signal, name))
            except AttributeError:
                raise ValueError(
                    f"unknown signal {tok!r} in --preempt-signals "
                    f"{spec!r}") from None
        if num == int(signal.SIGKILL):
            raise ValueError(
                "--preempt-signals: SIGKILL cannot be trapped; rely on "
                "--save-steps cadence checkpoints for kill-without-grace")
        out.append(num)
    if not out:
        raise ValueError(f"--preempt-signals {spec!r} names no signals")
    return tuple(dict.fromkeys(out))  # dedup, keep order


class PreemptionGuard:
    """Flag-on-signal with handler chaining.

    >>> guard = PreemptionGuard().install()
    >>> ...  # training loop polls guard.triggered between steps
    >>> guard.uninstall()

    Polling is a local ``Event`` check — no collective, no device sync.  All
    processes of a job receive the platform's preemption signal, so each
    rank observes the flag independently and breaks at the same loop
    boundary (the next step's collective never starts anywhere).
    """

    def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM,)):
        self._signals = signals
        self._flag = threading.Event()
        self._prev: Dict[int, object] = {}

    def _handler(self, signum, frame):
        self._flag.set()
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    def install(self) -> "PreemptionGuard":
        """Install handlers (main thread only — a Python restriction)."""
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._handler)
        return self

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()

    @property
    def triggered(self) -> bool:
        return self._flag.is_set()

    def trigger(self) -> None:
        """Set the flag directly (tests; cooperative shutdown)."""
        self._flag.set()


class PreemptionAgreement:
    """Cross-process agreement on the preemption flag.

    Signal delivery skews across hosts: rank 0's flag may set just before a
    loop-boundary check while rank 1's sets just after, so per-rank local
    polling would break the ranks at *different* boundaries and deadlock the
    next collective.  This wraps the decision in a tiny compiled all-reduce
    (any-rank-flagged → everyone stops) that every process executes at the
    same cadence, making the stop decision itself bulk-synchronous — the
    same reasoning that lets the framework drop the reference's explicit
    ``barrier()`` (SURVEY.md §5.8).

    Single-process meshes skip the device round-trip entirely.
    """

    def __init__(self, mesh, data_axis: str = "data"):
        import jax

        self._single = jax.process_count() == 1
        if self._single:
            return
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        self._mesh = mesh
        self._sharding = NamedSharding(mesh, P(data_axis))
        n_local = len(mesh.local_devices)
        self._ones = {
            flag: jnp.full((n_local,), 1.0 if flag else 0.0, jnp.float32)
            for flag in (False, True)
        }
        self._any = jax.jit(
            lambda x: jnp.sum(x) > 0,
            out_shardings=NamedSharding(mesh, P()),
        )

    def __call__(self, flag: bool) -> bool:
        if self._single:
            return flag
        import jax

        arr = jax.make_array_from_process_local_data(
            self._sharding, self._ones[bool(flag)]
        )
        return bool(self._any(arr))
