"""Shared measurement harness for train-step throughput benchmarks.

One implementation of the tunneled-platform timing discipline used by
``bench.py`` (the driver headline) and ``experiments/arch_bench.py`` (the
zoo table), so the two can never drift apart on the subtle part: on the
tunneled axon backend ``block_until_ready`` can return before the device
queue drains, so a scalar VALUE FETCH is the only reliable barrier — the
warmup ends with ``float(metrics["loss"])`` and the timed loop closes with
an isfinite assert on the same fetch (see ``scripts/benchlib.py``).
"""

from __future__ import annotations

import time
from typing import Tuple


def measure_train_step(step, state, device_batch, lr,
                       iters: int = 20, warmup: int = 3) -> Tuple[float, object]:
    """Seconds per compiled train-step call, value-fetch synchronized.

    ``step(state, device_batch, lr) -> (state, metrics)`` with a scalar
    ``metrics["loss"]``.  Returns ``(sec_per_step, final_state)``; raises
    AssertionError if the final loss is not finite.
    """
    import numpy as np

    for _ in range(warmup):
        state, metrics = step(state, device_batch, lr)
    if warmup:
        float(metrics["loss"])  # barrier: drain the queue before t0
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, device_batch, lr)
    assert np.isfinite(float(metrics["loss"]))  # value fetch = flush
    dt = (time.perf_counter() - t0) / iters
    return dt, state


def looks_like_oom(err: BaseException) -> bool:
    """Heuristic: is this a memory/VMEM-capacity failure a smaller batch
    could fix (vs a deterministic error retrying cannot)?"""
    text = f"{type(err).__name__}: {err}"
    needles = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
               "OOM", "Attempting to allocate", "vmem", "VMEM",
               "exceeds the limit", "Ran out of memory")
    return any(n in text for n in needles)
