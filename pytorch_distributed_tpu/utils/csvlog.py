"""Per-epoch wall-clock CSV logging.

Capability parity with the reference's in-loop CSV timer
(reference dataparallel.py:188,205-213; distributed_slurm_main.py:209,227-235):
appends ``[timestamp, epoch_seconds]`` rows to ``<recipe>.csv``, the repo's
de-facto performance oracle (SURVEY.md §4 item 3).  A header row is written
on first append so the files are self-describing; the file is only opened
when ``path`` is set, and per write so concurrent runs can share a file via
O_APPEND.

Registers as an epoch sink of ``obs.MetricsLogger`` (it exposes the
``epoch_start``/``epoch_end`` pair), so the trainer drives it through the
one observability entry point.
"""

from __future__ import annotations

import csv
import os
import time
from typing import Optional

HEADER = ("timestamp", "epoch_seconds")


class EpochCSVLogger:
    def __init__(self, path: Optional[str]):
        self.path = path
        self._t0: Optional[float] = None

    def epoch_start(self) -> None:
        self._t0 = time.time()

    def epoch_end(self) -> float:
        if self._t0 is None:
            raise RuntimeError(
                "EpochCSVLogger.epoch_end() called without a matching "
                "epoch_start()")
        elapsed = time.time() - self._t0
        if self.path:
            write_header = (
                not os.path.exists(self.path) or os.path.getsize(self.path) == 0
            )
            with open(self.path, "a+", newline="") as f:
                w = csv.writer(f)
                if write_header:
                    w.writerow(HEADER)
                w.writerow([time.time(), elapsed])
        self._t0 = None
        return elapsed
