"""Per-epoch wall-clock CSV logging.

Capability parity with the reference's in-loop CSV timer
(reference dataparallel.py:188,205-213; distributed_slurm_main.py:209,227-235):
appends ``[timestamp, epoch_seconds]`` rows to ``<recipe>.csv``, the repo's
de-facto performance oracle (SURVEY.md §4 item 3).
"""

from __future__ import annotations

import csv
import time
from typing import Optional


class EpochCSVLogger:
    def __init__(self, path: Optional[str]):
        self.path = path
        self._t0: Optional[float] = None

    def epoch_start(self) -> None:
        self._t0 = time.time()

    def epoch_end(self) -> float:
        assert self._t0 is not None, "epoch_end without epoch_start"
        elapsed = time.time() - self._t0
        if self.path:
            with open(self.path, "a+", newline="") as f:
                csv.writer(f).writerow([time.time(), elapsed])
        self._t0 = None
        return elapsed
