"""Device telemetry sampler — the reference's ``statistics.sh`` equivalent.

The reference samples ``nvidia-smi --query-gpu=timestamp,index,memory.total,
memory.used,utilization.gpu`` every 500 ms into a per-recipe CSV
(reference statistics.sh:1-4).  Here the same file contract is fed from the
TPU runtime's per-device memory statistics (``Device.memory_stats()``), plus
wall-clock; columns: ``timestamp,index,bytes_limit,bytes_in_use,peak_bytes``.

Run standalone (``python tpu_statistics.py``) or in-process via ``TelemetrySampler``.

Where the runtime exposes no ``memory_stats`` (the CPU simulator, and
tunneled single-chip platforms), ``bytes_in_use``/``peak_bytes`` fall back
to a client-side accounting over ``jax.live_arrays()`` — real buffer bytes
per device as seen from this process, not zeros (``bytes_limit`` stays 0:
the runtime doesn't report capacity there).
"""

from __future__ import annotations

import csv
import threading
import time
from typing import Dict, Optional


def _client_side_bytes() -> Dict[int, int]:
    """Live device-buffer bytes per device id, from the client's array
    registry (works on every backend).  Uses per-shard sizes, which are
    exact for replicated layouts too — every replica holds the full bytes."""
    import jax

    per_dev: Dict[int, int] = {}
    try:
        for arr in jax.live_arrays():
            for shard in arr.addressable_shards:
                d = shard.device
                per_dev[d.id] = per_dev.get(d.id, 0) + shard.data.nbytes
    except Exception:
        return {}
    return per_dev


def sample_devices(peaks: Optional[Dict[int, int]] = None):
    """One CSV row per local device.  ``peaks``: caller-owned running-peak
    state for the client-side fallback (each sampler passes its own dict so
    concurrent samplers don't corrupt one another's peak column); None
    reports peak = current in-use."""
    import jax

    rows = []
    now = time.time()
    client = None  # computed lazily, once per sample
    for i, d in enumerate(jax.local_devices()):
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:  # backends without memory_stats (CPU sim)
            pass
        in_use = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use")
        if in_use is None:
            if client is None:
                client = _client_side_bytes()
            in_use = client.get(d.id, 0)
            if peaks is not None:
                peaks[d.id] = max(peaks.get(d.id, 0), in_use)
                peak = peaks[d.id]
            else:
                peak = in_use
        rows.append(
            [now, i, stats.get("bytes_limit", 0), in_use, peak or 0]
        )
    return rows


class TelemetrySampler:
    """Background 500 ms sampler appending CSV rows (statistics.sh contract)."""

    def __init__(self, path: str, interval_s: float = 0.5):
        self.path = path
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "TelemetrySampler":
        # Per-instance peak tracking: concurrent samplers stay independent.
        peaks: Dict[int, int] = {}

        def loop():
            while not self._stop.is_set():
                rows = sample_devices(peaks)
                with open(self.path, "a+", newline="") as f:
                    csv.writer(f).writerows(rows)
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join()
