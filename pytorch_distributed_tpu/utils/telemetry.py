"""Device telemetry sampler — the reference's ``statistics.sh`` equivalent.

The reference samples ``nvidia-smi --query-gpu=timestamp,index,memory.total,
memory.used,utilization.gpu`` every 500 ms into a per-recipe CSV
(reference statistics.sh:1-4).  Here the same file contract is fed from the
TPU runtime's per-device memory statistics (``Device.memory_stats()``), plus
wall-clock; columns: ``timestamp,index,bytes_limit,bytes_in_use,peak_bytes``.

Run standalone (``python tpu_statistics.py``) or in-process via ``TelemetrySampler``.

Degrades gracefully where the runtime exposes no memory statistics (the CPU
simulator, and tunneled single-chip platforms): rows are still written on
schedule with zeroed byte columns, keeping the file contract intact.
"""

from __future__ import annotations

import csv
import threading
import time
from typing import Optional


def sample_devices():
    import jax

    rows = []
    now = time.time()
    for i, d in enumerate(jax.local_devices()):
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:  # backends without memory_stats (CPU sim)
            pass
        rows.append(
            [
                now,
                i,
                stats.get("bytes_limit", 0),
                stats.get("bytes_in_use", 0),
                stats.get("peak_bytes_in_use", 0),
            ]
        )
    return rows


class TelemetrySampler:
    """Background 500 ms sampler appending CSV rows (statistics.sh contract)."""

    def __init__(self, path: str, interval_s: float = 0.5):
        self.path = path
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "TelemetrySampler":
        def loop():
            while not self._stop.is_set():
                rows = sample_devices()
                with open(self.path, "a+", newline="") as f:
                    csv.writer(f).writerows(rows)
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join()
