"""Import torch/torchvision ResNet checkpoints into this framework.

Migration path for users of the reference: its recipes save
``checkpoint.pth.tar`` holding ``{'epoch', 'arch', 'state_dict', 'best_acc1'}``
(reference distributed.py:219-225, 327-330) where ``state_dict`` is a
torchvision ResNet in torch naming/layout.  This module converts that tree —
or a bare torchvision ``model.state_dict()`` / downloaded zoo weights file —
into this framework's flax variables, so ``--pretrained`` and ``--resume``
work on checkpoints produced by the reference (reference ``--pretrained``
pulls the same torchvision weights, distributed.py:95-98,134-136).

Scope: the ResNet family (resnet18/34/50/101/152, wide_*, resnext_*) — the
arch surface of BASELINE.json and every reference launch line.  The block
structure is derived from the state_dict itself (``conv3`` presence ⇒
Bottleneck; block count by key scan), so any torchvision-shaped ResNet
variant imports without an arch table.

Layout conversions (torch → flax/TPU):
- conv ``weight`` OIHW → HWIO ``kernel`` (grouped convs keep the same
  transpose: torch [O, I/g, kh, kw] → flax [kh, kw, I/g, O]);
- linear ``weight`` [out, in] → ``kernel`` [in, out];
- BN ``weight/bias/running_mean/running_var`` →
  ``scale/bias`` (params) + ``mean/var`` (batch_stats);
  ``num_batches_tracked`` is dropped (torch bookkeeping with no flax
  equivalent — EMA momentum is a constant here).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Mapping, Tuple

import numpy as np


def _np(x: Any) -> np.ndarray:
    """Accept torch tensors or arrays without importing torch."""
    if hasattr(x, "detach"):
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def unwrap_reference_checkpoint(payload: Mapping) -> Tuple[Mapping, Dict]:
    """Split a loaded reference checkpoint into (state_dict, meta).

    Accepts the reference's payload dict (distributed.py:219-225), a bare
    state_dict, and DataParallel/DDP ``module.``-prefixed keys.
    """
    meta: Dict[str, Any] = {}
    sd = payload
    if "state_dict" in payload and not hasattr(payload["state_dict"], "shape"):
        sd = payload["state_dict"]
        for k in ("epoch", "arch", "best_acc1"):
            if k in payload:
                v = payload[k]
                # best_acc1 may be a 0-d (or shape-(1,)) tensor in reference
                # checkpoints (distributed.py:214 keeps it as a tensor).
                meta[k] = float(_np(v).reshape(())) if k == "best_acc1" else v
    sd = {re.sub(r"^module\.", "", k): v for k, v in sd.items()}
    return sd, meta


def _conv(sd: Mapping, key: str) -> np.ndarray:
    # f32 cast: a half-precision checkpoint (model.half()) must not smuggle
    # fp16 master weights into the f32 param tree.
    return _np(sd[key]).transpose(2, 3, 1, 0).astype(np.float32)  # OIHW->HWIO


def _bn(sd: Mapping, prefix: str):
    params = {
        "scale": _np(sd[f"{prefix}.weight"]).astype(np.float32),
        "bias": _np(sd[f"{prefix}.bias"]).astype(np.float32),
    }
    stats = {
        "mean": _np(sd[f"{prefix}.running_mean"]).astype(np.float32),
        "var": _np(sd[f"{prefix}.running_var"]).astype(np.float32),
    }
    return params, stats


def import_resnet_state_dict(state_dict: Mapping) -> Dict[str, Dict]:
    """torchvision-ResNet state_dict → ``{"params", "batch_stats"}``.

    Raises ``KeyError``/``ValueError`` with the offending key on anything
    that is not torchvision-ResNet-shaped.
    """
    sd = {re.sub(r"^module\.", "", k): v for k, v in state_dict.items()}
    if "conv1.weight" not in sd:
        raise ValueError(
            "not a torchvision ResNet state_dict: missing 'conv1.weight' "
            f"(got keys like {sorted(sd)[:3]}...)"
        )
    params: Dict[str, Any] = {"conv_init": {"kernel": _conv(sd, "conv1.weight")}}
    stats: Dict[str, Any] = {}
    params["bn_init"], stats["bn_init"] = _bn(sd, "bn1")

    # Discover stage/block structure from the keys.
    block_re = re.compile(r"^layer(\d+)\.(\d+)\.conv1\.weight$")
    stages: Dict[int, int] = {}
    for k in sd:
        m = block_re.match(k)
        if m:
            s, i = int(m.group(1)), int(m.group(2))
            stages[s] = max(stages.get(s, 0), i + 1)
    if sorted(stages) != list(range(1, len(stages) + 1)):
        raise ValueError(f"non-contiguous layer indices: {sorted(stages)}")
    bottleneck = "layer1.0.conv3.weight" in sd
    block_cls = "Bottleneck" if bottleneck else "BasicBlock"
    n_convs = 3 if bottleneck else 2

    k_global = 0
    for s in sorted(stages):
        for i in range(stages[s]):
            t = f"layer{s}.{i}"
            name = f"{block_cls}_{k_global}"
            bp: Dict[str, Any] = {}
            bs: Dict[str, Any] = {}
            for c in range(n_convs):
                bp[f"Conv_{c}"] = {"kernel": _conv(sd, f"{t}.conv{c + 1}.weight")}
                (bp[f"FusedBatchNormAct_{c}"],
                 bs[f"FusedBatchNormAct_{c}"]) = _bn(sd, f"{t}.bn{c + 1}")
            if f"{t}.downsample.0.weight" in sd:
                bp[f"Conv_{n_convs}"] = {
                    "kernel": _conv(sd, f"{t}.downsample.0.weight")
                }
                (bp[f"FusedBatchNormAct_{n_convs}"],
                 bs[f"FusedBatchNormAct_{n_convs}"]) = _bn(
                    sd, f"{t}.downsample.1")
            params[name] = bp
            stats[name] = bs
            k_global += 1

    params["fc"] = {
        "kernel": _np(sd["fc.weight"]).transpose(1, 0).astype(np.float32),
        "bias": _np(sd["fc.bias"]).astype(np.float32),
    }
    return {"params": params, "batch_stats": stats}


def import_lm_state_dict(state_dict: Mapping) -> Dict[str, Dict]:
    """torch GPT-style LM state_dict → ``{"params": ...}`` matching
    ``models/transformer.py TransformerLM`` (and the serving engine's
    ``PagedTransformerLM`` — same tree).

    Expected torch naming (the decoder-only shape of minGPT/nanoGPT-style
    references, one linear per projection):

    - ``embed.weight``                       [V, D]   (head is tied)
    - ``blocks.{i}.ln1|ln2.weight/bias``     LayerNorm
    - ``blocks.{i}.attn.qkv.weight``         [3D, D]  (no bias)
    - ``blocks.{i}.attn.proj.weight``        [D, D]   (no bias)
    - ``blocks.{i}.fc1.weight/bias``         [4D, D]
    - ``blocks.{i}.fc2.weight/bias``         [D, 4D]
    - ``ln_f.weight/bias``                   final LayerNorm

    An explicit ``head.weight`` is accepted only when it equals
    ``embed.weight`` (this framework ties the output head); anything else
    raises with the offending key.
    """
    sd = {re.sub(r"^module\.", "", k): v for k, v in state_dict.items()}
    if "embed.weight" not in sd:
        raise ValueError(
            "not an LM state_dict: missing 'embed.weight' "
            f"(got keys like {sorted(sd)[:3]}...)")
    if "head.weight" in sd and not np.array_equal(
            _np(sd["head.weight"]), _np(sd["embed.weight"])):
        raise ValueError(
            "untied 'head.weight' is not supported: this framework ties "
            "the output head to embed.weight")

    def _ln(prefix):
        return {"scale": _np(sd[f"{prefix}.weight"]).astype(np.float32),
                "bias": _np(sd[f"{prefix}.bias"]).astype(np.float32)}

    def _linear(key, bias=True):
        out = {"kernel": _np(sd[f"{key}.weight"]).transpose(1, 0)
               .astype(np.float32)}  # [out,in] -> [in,out]
        if bias:
            out["bias"] = _np(sd[f"{key}.bias"]).astype(np.float32)
        return out

    idx_re = re.compile(r"^blocks\.(\d+)\.")
    layers = {int(m.group(1)) for k in sd for m in [idx_re.match(k)] if m}
    if layers and sorted(layers) != list(range(len(layers))):
        raise ValueError(f"non-contiguous block indices: {sorted(layers)}")
    n_layers = len(layers)
    if n_layers == 0:
        raise ValueError("LM state_dict has no 'blocks.{i}.*' keys")

    params: Dict[str, Any] = {
        "embed": {"embedding": _np(sd["embed.weight"]).astype(np.float32)},
    }
    for i in range(n_layers):
        t = f"blocks.{i}"
        params[f"block_{i}"] = {
            "ln1": _ln(f"{t}.ln1"),
            "ln2": _ln(f"{t}.ln2"),
            "attn": {"qkv": _linear(f"{t}.attn.qkv", bias=False),
                     "proj": _linear(f"{t}.attn.proj", bias=False)},
            "fc1": _linear(f"{t}.fc1"),
            "fc2": _linear(f"{t}.fc2"),
        }
    params["ln_f"] = _ln("ln_f")
    return {"params": params}


def import_torch_checkpoint(payload: Mapping) -> Tuple[Dict[str, Dict], Dict]:
    """Reference ``checkpoint.pth.tar`` payload (already ``torch.load``-ed)
    → ``(variables, meta)``.  Dispatches on the state_dict's family:
    ``conv1.weight`` ⇒ torchvision ResNet, ``embed.weight`` ⇒ LM."""
    sd, meta = unwrap_reference_checkpoint(payload)
    if "embed.weight" in {re.sub(r"^module\.", "", k) for k in sd}:
        return import_lm_state_dict(sd), meta
    return import_resnet_state_dict(sd), meta


def save_as_pretrained(
    directory: str, arch: str, variables: Dict[str, Dict], meta: Dict
) -> str:
    """Write imported variables as ``<dir>/<arch>.msgpack`` in the trainer's
    checkpoint format, so ``--pretrained`` finds it
    (train/trainer.py _load_pretrained)."""
    import os

    from flax import serialization

    params = variables["params"]
    payload = {
        "epoch": int(meta.get("epoch", 0)),
        "arch": arch,
        "best_acc1": float(meta.get("best_acc1", 0.0)),
        "state": {
            "step": np.int32(0),
            "params": params,
            # LMs carry no BN stats -> empty dict keeps the payload shape
            "batch_stats": variables.get("batch_stats", {}),
            # torch-parity SGD momentum buffers start at zero
            # (train/optim.py sgd_init).
            "momentum": _tree_zeros(params),
        },
    }
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{arch}.msgpack")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.to_bytes(payload))
    os.replace(tmp, path)
    return path


def _tree_zeros(tree: Any) -> Any:
    if isinstance(tree, Mapping):
        return {k: _tree_zeros(v) for k, v in tree.items()}
    a = _np(tree)
    return np.zeros_like(a, dtype=np.float32)


# --------------------------------------------------------------------- export
# The reverse direction: this framework's variables → a torchvision-shaped
# state_dict / the reference's checkpoint payload, so models trained here
# load back into the reference (torch.load + model.load_state_dict) — the
# migration story runs both ways (docs/MIGRATION.md).


def _inv_conv(kernel: Any) -> np.ndarray:
    return _np(kernel).transpose(3, 2, 0, 1).astype(np.float32)  # HWIO->OIHW


def _inv_bn(sd: Dict[str, np.ndarray], prefix: str, params: Mapping,
            stats: Mapping) -> None:
    sd[f"{prefix}.weight"] = _np(params["scale"]).astype(np.float32)
    sd[f"{prefix}.bias"] = _np(params["bias"]).astype(np.float32)
    sd[f"{prefix}.running_mean"] = _np(stats["mean"]).astype(np.float32)
    sd[f"{prefix}.running_var"] = _np(stats["var"]).astype(np.float32)
    # torch bookkeeping tensor; load_state_dict(strict=True) expects it.
    sd[f"{prefix}.num_batches_tracked"] = np.asarray(0, dtype=np.int64)


def export_resnet_state_dict(
    variables: Mapping, stage_sizes
) -> Dict[str, np.ndarray]:
    """``{"params", "batch_stats"}`` → torchvision-ResNet ``state_dict``
    (numpy values, torch naming/layout; exact inverse of
    ``import_resnet_state_dict``).

    ``stage_sizes`` supplies the flat-block → ``layer{s}.{i}`` naming split
    (the flax tree is flat; e.g. ``[3, 4, 6, 3]`` for resnet50 — read it
    from ``models._REGISTRY[arch].keywords["stage_sizes"]``).
    """
    params, stats = variables["params"], variables["batch_stats"]
    blocks = sorted(
        (k for k in params if re.match(r"^(BasicBlock|Bottleneck)_\d+$", k)),
        key=lambda k: int(k.rsplit("_", 1)[1]),
    )
    if sum(stage_sizes) != len(blocks):
        raise ValueError(
            f"stage_sizes {list(stage_sizes)} sum to {sum(stage_sizes)} but "
            f"the tree has {len(blocks)} blocks"
        )
    sd: Dict[str, np.ndarray] = {"conv1.weight": _inv_conv(
        params["conv_init"]["kernel"])}
    _inv_bn(sd, "bn1", params["bn_init"], stats["bn_init"])

    it = iter(blocks)
    for s, n in enumerate(stage_sizes, start=1):
        for i in range(n):
            name = next(it)
            bp, bs = params[name], stats[name]
            n_convs = 3 if name.startswith("Bottleneck") else 2
            t = f"layer{s}.{i}"
            for c in range(n_convs):
                sd[f"{t}.conv{c + 1}.weight"] = _inv_conv(
                    bp[f"Conv_{c}"]["kernel"])
                _inv_bn(sd, f"{t}.bn{c + 1}",
                        bp[f"FusedBatchNormAct_{c}"],
                        bs[f"FusedBatchNormAct_{c}"])
            if f"Conv_{n_convs}" in bp:  # projection shortcut
                sd[f"{t}.downsample.0.weight"] = _inv_conv(
                    bp[f"Conv_{n_convs}"]["kernel"])
                _inv_bn(sd, f"{t}.downsample.1",
                        bp[f"FusedBatchNormAct_{n_convs}"],
                        bs[f"FusedBatchNormAct_{n_convs}"])
    sd["fc.weight"] = _np(params["fc"]["kernel"]).transpose(1, 0).astype(
        np.float32)
    sd["fc.bias"] = _np(params["fc"]["bias"]).astype(np.float32)
    return sd
