"""Out-of-band instrumentation: CSV timers and device telemetry
(reference statistics.sh / per-epoch CSV parity, SURVEY.md §5.1)."""

from pytorch_distributed_tpu.utils.csvlog import EpochCSVLogger

__all__ = ["EpochCSVLogger"]
