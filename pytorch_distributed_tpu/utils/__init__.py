"""Out-of-band instrumentation: CSV timers and device telemetry
(reference statistics.sh / per-epoch CSV parity, SURVEY.md §5.1).

Both register as sinks of ``obs.MetricsLogger`` — ``EpochCSVLogger`` via
its ``epoch_start``/``epoch_end`` pair, ``TelemetrySampler`` via
``start``/``stop`` — so the unified observability layer (``obs/``) is the
single entry point; these modules stay importable standalone."""

from pytorch_distributed_tpu.utils.csvlog import EpochCSVLogger

__all__ = ["EpochCSVLogger"]
