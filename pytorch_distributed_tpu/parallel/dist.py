"""Multi-process / multi-host bootstrap.

One ``initialize()`` replaces the reference's four rendezvous mechanisms
(SURVEY.md §2.3 "Rendezvous" row):

- env-var launcher (``--local_rank`` from ``torch.distributed.launch``,
  reference distributed.py:73-76,132)
- explicit TCP (``tcp://127.0.0.1:23456``, multiprocessing_distributed.py:132-135)
- SLURM env + shared-file store (distributed_slurm_main.py:124-131,137-140)
- Horovod/MPI (horovod_distributed.py:125-127)

On TPU pods ``jax.distributed.initialize()`` auto-discovers coordinator,
process count and index from the TPU metadata; for CPU/GPU clusters (and the
SLURM-equivalent recipe) we derive them from the environment the same way the
reference's slurm script does, minus its world-size/rank inconsistency
(SURVEY.md §3.5 "latent inconsistency" — we always count *processes*).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax


@dataclasses.dataclass(frozen=True)
class DistContext:
    """Who am I in the job?  (reference args.nprocs / local_rank analogue)."""

    process_index: int
    process_count: int
    coordinator: Optional[str]

    @property
    def is_primary(self) -> bool:
        """Rank-0 guard for checkpointing/logging (reference
        distributed.py:218 ``if args.local_rank == 0``)."""
        return self.process_index == 0


def _first_slurm_host(nodelist: str) -> str:
    """First hostname of a SLURM nodelist, dashed-hostname safe.

    ``scontrol show hostnames`` is authoritative (handles every compressed
    form); the fallback only expands the bracket range — it never splits on
    ``-`` outside brackets, so ``tpu-host[01-04]`` → ``tpu-host01`` and
    ``gpu-node-01`` stays intact (round-1 advisor finding)."""
    if not nodelist:
        return "127.0.0.1"
    try:
        import subprocess

        out = subprocess.run(
            ["scontrol", "show", "hostnames", nodelist],
            capture_output=True, text=True, timeout=10,
        )
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.split()[0]
    except (OSError, subprocess.SubprocessError):
        pass
    head = nodelist.split(",")[0]
    if "[" in head:
        prefix, rest = head.split("[", 1)
        first_token = rest.rstrip("]").split(",")[0].split("-")[0]
        return prefix + first_token
    return head


def _slurm_env() -> Optional[dict]:
    """Derive multi-host topology from SLURM (reference
    distributed_slurm_main.py:124-128), fixed to count processes not nodes."""
    if "SLURM_PROCID" not in os.environ:
        return None
    nodelist = os.environ.get("SLURM_STEP_NODELIST", os.environ.get("SLURM_NODELIST", ""))
    first = _first_slurm_host(nodelist)
    return {
        "process_id": int(os.environ["SLURM_PROCID"]),
        "num_processes": int(os.environ.get("SLURM_NTASKS", os.environ.get("SLURM_NPROCS", "1"))),
        "coordinator_address": f"{first}:{os.environ.get('PTD_TPU_PORT', '12355')}",
    }


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> DistContext:
    """Initialize multi-process JAX if the job is multi-process; no-op for the
    single-process recipes (dataparallel-equivalent).

    Resolution order: explicit args → ``PTD_TPU_*`` env vars (our launcher
    contract, the ``torch.distributed.launch`` env:// analogue) → SLURM env →
    TPU-pod auto-detect (bare ``jax.distributed.initialize()`` when
    ``JAX_COORDINATOR_ADDRESS`` or TPU metadata provides one) → single process.
    """
    env = os.environ
    if coordinator_address is None and "PTD_TPU_COORDINATOR" in env:
        coordinator_address = env["PTD_TPU_COORDINATOR"]
        num_processes = int(env.get("PTD_TPU_NUM_PROCESSES", "1"))
        process_id = int(env.get("PTD_TPU_PROCESS_ID", "0"))
    if coordinator_address is None:
        slurm = _slurm_env()
        if slurm is not None and slurm["num_processes"] > 1:
            coordinator_address = slurm["coordinator_address"]
            num_processes = slurm["num_processes"]
            process_id = slurm["process_id"]

    if coordinator_address is not None and (num_processes or 1) > 1:
        platforms = jax.config.jax_platforms or ""
        if "cpu" in platforms.split(","):
            # Multi-process CPU meshes (the test/e2e simulation path) need
            # the gloo collectives implementation — the default XLA CPU
            # client refuses cross-process computations outright.  No-op
            # on TPU pods, and tolerated where the option is gone (newer
            # jax enables CPU collectives by default).
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception:  # noqa: BLE001
                pass
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif env.get("JAX_COORDINATOR_ADDRESS"):
        # TPU pod: runtime metadata fills in everything.
        jax.distributed.initialize()

    return DistContext(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        coordinator=coordinator_address,
    )


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()
