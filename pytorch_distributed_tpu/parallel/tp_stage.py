"""Tensor parallelism INSIDE pipeline stages (shard_map-level Megatron).

The GSPMD TP of ``parallel/tp.py`` cannot reach inside ``pipeline_apply``'s
``shard_map`` (no sharding constraints under manual collectives), so the
pipelined LM gets its model parallelism from this module instead: a
pure-function transformer stage whose parameters are column/row-sliced over
a ``model`` mesh axis and whose two per-block all-reduces are written as
explicit ``lax.psum`` — exactly Megatron's decomposition, composed with the
``pipe`` axis (and ``data``) in one mesh.

Layout choices:
- q/k/v are separate ``[C, C]`` kernels (NOT one fused ``[C, 3C]``): the
  head dimension is then a contiguous column slice, so ``P(None, 'model')``
  hands each rank its own head group with no shuffling.
- proj ``[C, C]`` and fc2 ``[4C, C]`` are row-parallel (``P('model', None)``)
  with the psum after; fc1 ``[C, 4C]`` and its bias are column-parallel;
  LayerNorm params and fc2 bias are replicated (bias added after the psum).

``tp_stage_apply(params, x, n_heads, model_axis=None)`` runs the same math
replicated (no psum) when ``model_axis`` is None — the tp=1 oracle the
sharded path is tested against.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.models.transformer import rope

Params = Dict[str, Any]


def init_stage_params(rng, d_model: int, n_blocks: int,
                      dtype=jnp.float32) -> Params:
    """Global (unsharded) parameters for one pipeline stage of ``n_blocks``
    pre-LN transformer blocks (separate wq/wk/wv; fan-in-scaled normals,
    flax-Dense-style lecun init)."""
    C = d_model
    blocks = []
    for i in range(n_blocks):
        keys = jax.random.split(jax.random.fold_in(rng, i), 6)

        def dense(k, fan_in, shape):
            return (jax.random.normal(k, shape, jnp.float32)
                    / jnp.sqrt(fan_in)).astype(dtype)

        blocks.append({
            "ln1": {"scale": jnp.ones((C,), jnp.float32),
                    "bias": jnp.zeros((C,), jnp.float32)},
            "wq": dense(keys[0], C, (C, C)),
            "wk": dense(keys[1], C, (C, C)),
            "wv": dense(keys[2], C, (C, C)),
            "proj": dense(keys[3], C, (C, C)),
            "ln2": {"scale": jnp.ones((C,), jnp.float32),
                    "bias": jnp.zeros((C,), jnp.float32)},
            "fc1": {"kernel": dense(keys[4], C, (C, 4 * C)),
                    "bias": jnp.zeros((4 * C,), jnp.float32)},
            "fc2": {"kernel": dense(keys[5], 4 * C, (4 * C, C)),
                    "bias": jnp.zeros((C,), jnp.float32)},
        })
    return {"blocks": blocks}


def stage_param_specs(n_blocks: int, pipe_axis: str = "pipe",
                      model_axis: Optional[str] = "model") -> Params:
    """PartitionSpecs for STACKED stage params (leading ``pipe`` axis),
    Megatron column/row layout over ``model_axis`` (None = replicated)."""
    m = model_axis
    col2 = P(pipe_axis, None, m)     # [S, C, C/4C] column-parallel
    row2 = P(pipe_axis, m, None)     # [S, C/4C, C] row-parallel
    rep1 = P(pipe_axis, None)
    blocks = []
    for _ in range(n_blocks):
        blocks.append({
            "ln1": {"scale": rep1, "bias": rep1},
            "wq": col2, "wk": col2, "wv": col2,
            "proj": row2,
            "ln2": {"scale": rep1, "bias": rep1},
            "fc1": {"kernel": col2, "bias": P(pipe_axis, m)},
            "fc2": {"kernel": row2, "bias": rep1},
        })
    return {"blocks": blocks}


def _layernorm(x, p):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + 1e-6) * p["scale"]
            + p["bias"]).astype(x.dtype)


def _attention(q, k, v):
    """Causal dense attention over local heads [B, L, Hl, D]."""
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (D ** 0.5)
    L = q.shape[1]
    pos = jnp.arange(L)
    s = jnp.where(pos[None, None, None, :] <= pos[None, None, :, None],
                  s, -1e30)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v.astype(jnp.float32)).astype(q.dtype)


def tp_stage_apply(params: Params, x: jnp.ndarray, n_heads: int,
                   model_axis: Optional[str] = None,
                   seq_axis: Optional[str] = None) -> jnp.ndarray:
    """Apply one stage.  Under ``shard_map`` with ``model_axis`` set, params
    arrive as this rank's Megatron slices and the two per-block all-reduces
    run as ``lax.psum``; with ``model_axis=None`` (replicated oracle) the
    same math runs without collectives.

    ``seq_axis``: ring sequence parallelism INSIDE the stage — activations
    arrive sequence-sharded, RoPE uses global positions via the ring index,
    and attention runs ``parallel/ring.py``'s ppermute ring over the local
    heads.  Composes with ``model_axis`` (heads split over model, sequence
    over seq — the shard_map mirror of the GSPMD dp×sp×tp composition)."""
    tp = jax.lax.axis_size(model_axis) if model_axis else 1
    if seq_axis:
        from pytorch_distributed_tpu.parallel.ring import ring_attention

    def maybe_psum(t):
        return jax.lax.psum(t, model_axis) if model_axis else t

    B, L, C = x.shape
    offset = (jax.lax.axis_index(seq_axis) * L) if seq_axis else 0
    heads_local = n_heads // tp
    for blk in params["blocks"]:
        h = _layernorm(x, blk["ln1"])
        q = (h @ blk["wq"]).reshape(B, L, heads_local, -1)
        k = (h @ blk["wk"]).reshape(B, L, heads_local, -1)
        v = (h @ blk["wv"]).reshape(B, L, heads_local, -1)
        q, k = rope(q, offset=offset), rope(k, offset=offset)
        if seq_axis:
            att = ring_attention(q, k, v, axis_name=seq_axis, causal=True)
        else:
            att = _attention(q, k, v)
        att = att.reshape(B, L, -1)                      # [B, L, C/tp]
        x = x + maybe_psum(att @ blk["proj"])            # row-parallel + psum
        h = _layernorm(x, blk["ln2"])
        h = jax.nn.gelu(h @ blk["fc1"]["kernel"] + blk["fc1"]["bias"])
        x = x + maybe_psum(h @ blk["fc2"]["kernel"]) + blk["fc2"]["bias"]
    return x
