"""Interleaved (virtual-stage) 1F1B: V model chunks per device.

Megatron-LM's interleaved schedule (Narayanan et al. 2021) cuts the
pipeline bubble from (P-1)/M to (P-1)/(M·V) by giving each device V
non-adjacent model chunks: C = P·V chunks, chunk c on device c mod P, so
every chunk boundary is the SAME +1 ring hop (the wrap P-1→0 included) and
the comm pattern stays the two ppermutes of ``parallel/pp_1f1b.py``.

The round-3/4 blocker was the "high-risk tick mapping" — closed-form
index arithmetic for which (chunk, microbatch) each device runs at each
tick.  This module removes that risk by **simulating the schedule on the
host at trace time** (`simulate_interleaved_schedule`): per-device
Megatron op order + data/backpressure readiness produces [T, P] tick
tables (chunk, microbatch, stash slot, inbox routing) that the
``shard_map``-ed ``lax.scan`` merely *gathers* — the hazardous arithmetic
becomes a pure Python function with standalone invariant tests
(tests/test_pp_interleaved.py):

- every (c, m) forwarded exactly once and backwarded exactly once;
- a value is consumed only after its 1-tick ppermute hop arrives;
- one F and one B max per device per tick (one hop channel each way);
- single-entry inboxes per chunk (senders back-pressured);
- stash high-water mark reported (the interleave's V× memory trade).

Runtime structure mirrors pp_1f1b: manual gradients inside one scan,
``jax.vjp`` re-runs each chunk forward from its stashed input (in-chunk
remat), the loss head runs on the last chunk's device in the tick its
forward retires and seeds that chunk's backward through a local inbox.

Wired end to end: ``models/pipeline_lm.py`` dispatches here under
``schedule="interleaved"`` (device-major chunk layout) and the
lm_pretrain recipe exposes ``--schedule interleaved --pp-virtual V``;
``--fsdp`` composes through the same boundary gather as 1F1B.
Beyond-reference capability (SURVEY.md §2.3: pipeline parallelism is
"explicitly absent" from the reference).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

Pytree = Any


class InterleavedSchedule(NamedTuple):
    """Host-side tick tables, all int32 [T, P] unless noted."""

    T: int                 # ticks
    S: int                 # stash slots per device (high-water mark)
    f_active: np.ndarray   # bool: device runs a forward this tick
    f_k: np.ndarray        # chunk-local index (0..V-1) of that forward
    f_m: np.ndarray        # microbatch of that forward
    f_slot: np.ndarray     # stash slot the forward's INPUT is written to
    b_active: np.ndarray   # bool: device runs a backward this tick
    b_k: np.ndarray
    b_m: np.ndarray
    b_slot: np.ndarray     # stash slot the backward reads (then frees)
    rf_active: np.ndarray  # bool: incoming fwd hop value lands this tick
    rf_k: np.ndarray       # inbox_f slot (consumer chunk-local k) it fills
    rb_active: np.ndarray  # bool: incoming bwd hop value lands this tick
    rb_k: np.ndarray       # inbox_b slot it fills


def _megatron_order(P_: int, V: int, M: int, d: int):
    """Device d's op list in Megatron's interleaved order:
    [('F'|'B', chunk_local_k, microbatch), ...].

    Forward step s runs chunk-local k = (s // P) % V on microbatch
    m = P·(s // (P·V)) + s % P (microbatches advance in groups of P per
    chunk); backward mirrors it with chunks reversed.  Warmup depth
    (P - d - 1)·2 + (V - 1)·P staggers devices so the 1F1B phase
    alternates one forward with one backward.
    """
    n = M * V  # total forward ops on every device

    def fwd_km(s):
        return (s // P_) % V, P_ * (s // (P_ * V)) + s % P_

    def bwd_km(s):
        return V - 1 - (s // P_) % V, P_ * (s // (P_ * V)) + s % P_

    warmup = min(n, (P_ - d - 1) * 2 + (V - 1) * P_)
    ops = [("F",) + fwd_km(s) for s in range(warmup)]
    nf, nb = warmup, 0
    while nf < n or nb < n:
        if nf < n:
            ops.append(("F",) + fwd_km(nf))
            nf += 1
        if nb < n:
            ops.append(("B",) + bwd_km(nb))
            nb += 1
    return ops


def simulate_interleaved_schedule(P_: int, V: int, M: int
                                  ) -> InterleavedSchedule:
    """Event-driven lockstep simulation → tick tables.

    Each tick every device tries the earliest not-done op in its Megatron
    list (strictly in order — a stalled op stalls the device), and may
    additionally run the NEXT op in the same tick when it is of the other
    type (the F+B-per-tick structure pp_1f1b uses).  Readiness:

    - F(k, m): input available (chunk 0: always; else the hop value
      arrived in a prior tick and still sits in inbox_f[k]) AND the
      consumer's inbox slot for our output is free (backpressure; the
      last chunk's output goes to the local head instead), AND a stash
      slot is free;
    - B(k, m): cotangent available in inbox_b[k] (last chunk: seeded the
      tick its own forward ran, by the head).

    The sim asserts single-entry inboxes, exactly-once execution, and
    termination; the resulting tables make those invariants STATIC for
    the compiled scan.
    """
    if M % P_:
        # Megatron's group-of-P microbatch order requires it; the caller
        # validates, this keeps the sim honest.
        raise ValueError(f"microbatches {M} must divide by pipeline {P_}")
    C = P_ * V
    orders = [_megatron_order(P_, V, M, d) for d in range(P_)]
    pos = [0] * P_
    # inbox occupancy: None or (tag, k, m); fwd value for chunk k / bwd
    # cotangent for chunk k.  Hop values land at the START of tick t+1.
    inbox_f = [[None] * V for _ in range(P_)]
    inbox_b = [[None] * V for _ in range(P_)]
    in_flight_f: list = [None] * P_   # (k_consumer, m) arriving next tick
    in_flight_b: list = [None] * P_
    fwd_done: Dict[Tuple[int, int], int] = {}
    bwd_done: Dict[Tuple[int, int], int] = {}
    free_slots = [list(range(2 * C + M)) for _ in range(P_)]  # generous cap
    slot_of: Dict[Tuple[int, int, int], int] = {}
    rows: Dict[str, list] = {k: [] for k in (
        "f_active", "f_k", "f_m", "f_slot", "b_active", "b_k", "b_m",
        "b_slot", "rf_active", "rf_k", "rb_active", "rb_k")}
    max_slot_used = 0
    t = 0
    limit = 8 * (M * V + 2 * C) + 64
    while any(pos[d] < len(orders[d]) for d in range(P_)):
        assert t < limit, f"schedule deadlocked at tick {t}"
        row = {k: [0] * P_ for k in rows}
        # 1. land in-flight hop values (sent at t-1).
        for d in range(P_):
            if in_flight_f[d] is not None:
                k, m = in_flight_f[d]
                assert inbox_f[d][k] is None, (
                    f"t={t} d={d}: fwd inbox[{k}] collision")
                inbox_f[d][k] = m
                row["rf_active"][d] = 1
                row["rf_k"][d] = k
                in_flight_f[d] = None
            if in_flight_b[d] is not None:
                k, m = in_flight_b[d]
                assert inbox_b[d][k] is None, (
                    f"t={t} d={d}: bwd inbox[{k}] collision")
                inbox_b[d][k] = m
                row["rb_active"][d] = 1
                row["rb_k"][d] = k
                in_flight_b[d] = None
        sends_f: list = [None] * P_
        sends_b: list = [None] * P_
        # The compiled tick body runs F before B, so the last-chunk F's
        # head seed is WRITTEN before any same-tick B reads — a B-then-F
        # sim order that consumes the old seed and then overwrites it
        # would be mis-replayed (the fresh seed would clobber the pending
        # one).  Gate the last-chunk F on the seed slot's occupancy AT
        # TICK START, so that pattern stalls the F one tick instead.
        seed_busy_at_start = [inbox_b[d][V - 1] is not None
                              for d in range(P_)]
        # Slots freed by a B this tick become available only NEXT tick:
        # the compiled tick body writes the forward's stash entry before
        # the backward reads (the same-tick head-seed → backward path
        # needs that order), so a same-tick freed-slot reuse would let
        # the F overwrite the B's input.
        freed_this_tick: list = [[] for _ in range(P_)]

        def try_run(d: int, op) -> bool:
            nonlocal max_slot_used
            kind, k, m = op
            c = k * P_ + d
            if kind == "F":
                if c > 0 and inbox_f[d][k] != m:
                    return False
                if not free_slots[d]:
                    return False
                if c < C - 1:
                    # backpressure: consumer inbox slot must be free and
                    # no same-direction send already queued this tick.
                    nd, nk = (d + 1) % P_, (k if d + 1 < P_ else k + 1)
                    if inbox_f[nd][nk] is not None or in_flight_f[nd]:
                        return False
                    if sends_f[d] is not None:
                        return False
                elif inbox_b[d][k] is not None or seed_busy_at_start[d]:
                    # last chunk: the head seeds inbox_b[V-1] this tick —
                    # the slot must have been free at tick start (the
                    # runtime writes the seed in its F phase, before any
                    # same-tick B consumes).
                    return False
                # run
                if c > 0:
                    inbox_f[d][k] = None
                slot = free_slots[d].pop(0)
                max_slot_used = max(max_slot_used, slot + 1)
                slot_of[(d, k, m)] = slot
                fwd_done[(c, m)] = t
                row["f_active"][d], row["f_k"][d] = 1, k
                row["f_m"][d], row["f_slot"][d] = m, slot
                if c < C - 1:
                    sends_f[d] = ((d + 1) % P_,
                                  k if d + 1 < P_ else k + 1, m)
                else:
                    # head seeds this chunk's own backward locally.
                    assert inbox_b[d][k] is None
                    inbox_b[d][k] = m
                return True
            # B
            if inbox_b[d][k] != m:
                return False
            if c > 0:
                nd, nk = (d - 1) % P_, (k if d > 0 else k - 1)
                if inbox_b[nd][nk] is not None or in_flight_b[nd]:
                    return False
                if sends_b[d] is not None:
                    return False
            assert (c, m) in fwd_done and fwd_done[(c, m)] <= t
            inbox_b[d][k] = None
            slot = slot_of.pop((d, k, m))
            freed_this_tick[d].append(slot)
            bwd_done[(c, m)] = t
            row["b_active"][d], row["b_k"][d] = 1, k
            row["b_m"][d], row["b_slot"][d] = m, slot
            if c > 0:
                sends_b[d] = ((d - 1) % P_, k if d > 0 else k - 1, m)
            return True

        for d in range(P_):
            lst = orders[d]
            if pos[d] >= len(lst):
                continue
            if try_run(d, lst[pos[d]]):
                pos[d] += 1
                if (pos[d] < len(lst)
                        and lst[pos[d]][0] != lst[pos[d] - 1][0]
                        and try_run(d, lst[pos[d]])):
                    pos[d] += 1
        for d in range(P_):
            if sends_f[d] is not None:
                nd, nk, m = sends_f[d]
                in_flight_f[nd] = (nk, m)
            if sends_b[d] is not None:
                nd, nk, m = sends_b[d]
                in_flight_b[nd] = (nk, m)
            free_slots[d] = freed_this_tick[d] + free_slots[d]
        for k in rows:
            rows[k].append(row[k])
        t += 1
    # drain any value still in flight (nothing left to consume it => bug)
    assert all(v is None for v in in_flight_f + in_flight_b)
    assert len(fwd_done) == C * M and len(bwd_done) == C * M, (
        len(fwd_done), len(bwd_done), C * M)
    arrs = {k: np.asarray(v, np.int32) for k, v in rows.items()}
    return InterleavedSchedule(T=t, S=max_slot_used, **arrs)


def interleaved_pipeline_loss_and_grads(
    stage_fn: Callable[[Pytree, jnp.ndarray], jnp.ndarray],
    head_fn: Callable[[Pytree, jnp.ndarray, jnp.ndarray],
                      Tuple[jnp.ndarray, jnp.ndarray]],
    chunk_params: Pytree,
    head_params: Pytree,
    x: jnp.ndarray,
    tokens: jnp.ndarray,
    n_microbatches: int,
    n_virtual: int,
    mesh: Mesh,
    pipe_axis: str = "pipe",
    data_axis: str = "data",
):
    """Interleaved-1F1B counterpart of ``pipeline_1f1b_loss_and_grads``.

    ``chunk_params``: leaves with leading axis C = P·V in **device-major
    order** — position p·V + k holds chunk c = k·P + p (device p's k-th
    chunk), so sharding axis 0 over ``pipe_axis`` lands each device's V
    chunks locally (use ``interleave_order``/``deinterleave_order`` to
    convert from natural chunk order).  Returns ``(loss, correct, count,
    g_chunks, g_head, dx)`` with ``g_chunks`` in the same layout.
    """
    n_stages = mesh.shape[pipe_axis]
    V = n_virtual
    B = x.shape[0]
    M = n_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    for leaf in jax.tree_util.tree_leaves(chunk_params):
        if leaf.shape[0] != n_stages * V:
            raise ValueError(
                f"chunk_params leading axis {leaf.shape[0]} != P*V = "
                f"{n_stages * V}")
    sched = simulate_interleaved_schedule(n_stages, V, M)
    T, S = sched.T, sched.S
    mb = B // M
    micro = x.reshape((M, mb) + x.shape[1:])
    micro_tok = tokens.reshape((M, mb) + tokens.shape[1:])
    ring_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    ring_bwd = [((i + 1) % n_stages, i) for i in range(n_stages)]
    data_size = mesh.shape.get(data_axis, 1)
    has_data = data_axis in mesh.axis_names and data_size > 1
    tables = jnp.stack([
        jnp.asarray(a) for a in (
            sched.f_active, sched.f_k, sched.f_m, sched.f_slot,
            sched.b_active, sched.b_k, sched.b_m, sched.b_slot,
            sched.rf_active, sched.rf_k, sched.rb_active, sched.rb_k)
    ], axis=1)  # [T, 12, P]

    from pytorch_distributed_tpu.parallel.pp_1f1b import _head_vjp

    def per_stage(params_st, head_p, micro_local, tok_local):
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_st)
        # params_local leaves: [V, ...] — this device's chunks.
        idx = jax.lax.axis_index(pipe_axis)
        last_dev = n_stages - 1

        def masked_add(acc, upd, active):
            return jax.tree_util.tree_map(
                lambda a, u: a + jnp.where(active, u, 0).astype(a.dtype),
                acc, upd)

        def chunk_of(tree, k):
            return jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, k, axis=0, keepdims=False), tree)

        def tick(carry, tbl):
            (vin_f, vin_b, inbox_f, inbox_b, stash, g_chunks, g_head,
             d_micro, loss_sum, correct_sum) = carry
            (fa, fk, fm, fsl, ba, bk, bm, bsl,
             rfa, rfk, rba, rbk) = [tbl[i][idx] for i in range(12)]
            # land incoming hop values (sent by neighbors last tick)
            inbox_f = jnp.where(rfa == 1,
                                inbox_f.at[rfk].set(vin_f), inbox_f)
            inbox_b = jnp.where(rba == 1,
                                inbox_b.at[rbk].set(vin_b), inbox_b)
            # ---- forward ------------------------------------------------
            feed = micro_local[jnp.clip(fm, 0, M - 1)]
            is_feed = jnp.logical_and(idx == 0, fk == 0)  # chunk 0
            x_in = jnp.where(is_feed, feed, inbox_f[fk])
            # named_scope per schedule phase: XPlane traces attribute
            # per-tick self-time to fwd/head/bwd/hop (obs/trace.py).
            with jax.named_scope("ppint_fwd"):
                y = stage_fn(chunk_of(params_local, fk), x_in)
            # Double-buffered forward hop (parallel/overlap.py design):
            # `y` is final here — issuing its ring transfer before the
            # head/backward phases lets the ppermute overlap a full tick
            # of compute instead of serializing at the tick boundary.
            # Pure reorder: bit-exact.
            with jax.named_scope("pp_hop"):
                vin_f_next = jax.lax.ppermute(y, pipe_axis, ring_fwd)
            stash = jnp.where(fa == 1, stash.at[fsl].set(x_in), stash)
            # head: producing global chunk C-1 = (V-1)*P + (P-1)
            is_last = jnp.logical_and(idx == last_dev, fk == V - 1)
            tok_m = tok_local[jnp.clip(fm, 0, M - 1)]

            def run_head(hp, yy, tm):
                return _head_vjp(head_fn, hp, yy, tm)

            def skip_head(hp, yy, tm):
                zh = jax.tree_util.tree_map(jnp.zeros_like, hp)
                return ((jnp.float32(0.0), jnp.float32(0.0)),
                        (zh, jnp.zeros_like(yy)))

            with jax.named_scope("ppint_head"):
                (loss_m, correct_m), (dhead_m, dy_head) = jax.lax.cond(
                    jnp.logical_and(is_last, fa == 1), run_head, skip_head,
                    head_p, y, tok_m)
            active_h = jnp.logical_and(fa == 1, is_last)
            g_head = masked_add(g_head, dhead_m, active_h)
            loss_sum = loss_sum + jnp.where(active_h, loss_m, 0.0)
            correct_sum = correct_sum + jnp.where(active_h, correct_m, 0.0)
            # the head's cotangent seeds chunk C-1's backward locally
            inbox_b = jnp.where(
                active_h,
                inbox_b.at[V - 1].set(dy_head.astype(inbox_b.dtype)),
                inbox_b)
            # ---- backward -----------------------------------------------
            x_bwd = stash[bsl]
            dy_in = inbox_b[bk].astype(x_bwd.dtype)
            with jax.named_scope("ppint_bwd"):
                _, svjp = jax.vjp(
                    stage_fn, chunk_of(params_local, bk), x_bwd)
                dp_m, dx_m = svjp(dy_in)
            g_chunks = jax.tree_util.tree_map(
                lambda acc, u: acc.at[bk].add(
                    jnp.where(ba == 1, u, 0).astype(acc.dtype)),
                g_chunks, dp_m)
            write0 = jnp.logical_and(
                ba == 1, jnp.logical_and(idx == 0, bk == 0))  # chunk 0
            d_micro = jnp.where(
                write0,
                d_micro.at[jnp.clip(bm, 0, M - 1)].set(
                    dx_m.astype(d_micro.dtype)),
                d_micro,
            )
            with jax.named_scope("pp_hop"):
                vin_b_next = jax.lax.ppermute(dx_m, pipe_axis, ring_bwd)
            return (vin_f_next, vin_b_next, inbox_f, inbox_b, stash,
                    g_chunks, g_head, d_micro, loss_sum, correct_sum), None

        zeros_act = jnp.zeros_like(micro_local[0])
        act_shape = micro_local.shape[1:]
        carry0 = (
            zeros_act,
            zeros_act,
            jnp.zeros((V,) + act_shape, micro_local.dtype),
            jnp.zeros((V,) + act_shape, micro_local.dtype),
            jnp.zeros((S,) + act_shape, micro_local.dtype),
            jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape[1:], jnp.float32), params_st),
            jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, jnp.float32), head_p),
            jnp.zeros(micro_local.shape, jnp.float32),
            jnp.float32(0.0),
            jnp.float32(0.0),
        )
        (_, _, _, _, _, g_chunks, g_head, d_micro, loss_sum,
         correct_sum), _ = jax.lax.scan(tick, carry0, tables)

        inv_m = 1.0 / M
        g_chunks = jax.tree_util.tree_map(lambda g: g * inv_m, g_chunks)
        g_head = jax.tree_util.tree_map(lambda g: g * inv_m, g_head)
        d_micro = d_micro * inv_m
        loss = jax.lax.psum(loss_sum * inv_m, pipe_axis)
        correct = jax.lax.psum(correct_sum, pipe_axis)
        g_head = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, pipe_axis), g_head)
        d_micro = jax.lax.psum(d_micro, pipe_axis)
        if has_data:
            loss = jax.lax.pmean(loss, data_axis)
            correct = jax.lax.psum(correct, data_axis)
            g_chunks = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, data_axis), g_chunks)
            g_head = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, data_axis), g_head)
            d_micro = d_micro / data_size
        g_chunks = jax.tree_util.tree_map(lambda g: g[None], g_chunks)
        return loss, correct, g_chunks, g_head, d_micro

    micro_spec = P(None, data_axis if has_data else None)
    act_spec = P(*(micro_spec + (None,) * (micro.ndim - 2)))
    tok_spec = P(*(micro_spec + (None,) * (micro_tok.ndim - 2)))
    # device-major [P*V, ...] → shard leading axis over pipe: device p owns
    # rows p·V..p·V+V-1 = its V chunks; inside the body the leading [1]
    # block is dropped and re-added, so leaves are [V, ...] per device.
    pv_spec = jax.tree_util.tree_map(lambda _: P(pipe_axis), chunk_params)
    rep = jax.tree_util.tree_map(lambda _: P(), head_params)
    # reshape [P*V, ...] → [P, V, ...] so shard_map's leading-axis split
    # hands each device exactly its [1, V, ...] block.
    stacked = jax.tree_util.tree_map(
        lambda a: a.reshape((n_stages, V) + a.shape[1:]), chunk_params)
    loss, correct, g_chunks, g_head, d_micro = jax.shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(pv_spec, rep, act_spec, tok_spec),
        out_specs=(P(), P(), pv_spec, rep, act_spec),
        check_vma=False,
    )(stacked, head_params, micro, micro_tok)
    g_chunks = jax.tree_util.tree_map(
        lambda a: a.reshape((n_stages * V,) + a.shape[2:]), g_chunks)
    count = jnp.float32(tokens.shape[0] * (tokens.shape[1] - 1))
    dx = d_micro.reshape(x.shape)
    return loss, correct, count, g_chunks, g_head, dx


def interleave_order(n_stages: int, n_virtual: int) -> np.ndarray:
    """Permutation taking natural chunk order c = 0..C-1 to the
    device-major layout this module consumes: position p·V + k ← chunk
    k·P + p.  ``chunk_params_dm = tree_map(lambda a: a[perm], natural)``."""
    P_, V = n_stages, n_virtual
    return np.asarray([k * P_ + p for p in range(P_) for k in range(V)],
                      np.int32)


def deinterleave_order(n_stages: int, n_virtual: int) -> np.ndarray:
    """Inverse permutation: natural[c] = device_major[inv[c]]."""
    perm = interleave_order(n_stages, n_virtual)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.size, dtype=np.int32)
    return inv
