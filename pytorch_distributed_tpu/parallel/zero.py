"""ZeRO-style weight-update sharding (WUS) over the data axis.

Automatic cross-replica weight-update sharding (arXiv:2004.13336) removes
the replicated-optimizer-state ceiling of pure data parallelism without
touching the forward/backward math: instead of all-reducing gradients and
applying the identical SGD update on every replica,

- **reduce-scatter** the gradients so rank ``i`` owns the exact f32 sum of
  chunk ``i`` (1/N of every leaf);
- keep the momentum buffer **sharded**: each rank stores only its chunk,
  so optimizer state per device drops by ~the data-axis size;
- apply the torch-parity SGD update on the 1/N chunk;
- **all-gather** the resulting parameter *delta* once per step and apply
  it to the (still replicated) parameters on every rank.

Wire cost per step and leaf of L f32 elements on n ranks (ring
conventions, obs/comms.py): the replicated path's all-reduce moves
``2(n-1)/n * 4L`` bytes; reduce-scatter ``(n-1)/n * 4L`` plus all-gather
``(n-1)/n * 4L`` — identical wire, ~(n-1)/n of optimizer+synced-gradient
bytes reclaimed.  Composes with ``--grad-compress int8|fp8``: both hops
ride the quantized qcomm path (``compressed_reduce_scatter`` /
``compressed_all_gather``) with error feedback on each.

Two expressions of the same semantics (mirroring train/steps.py):

- **explicit** (shard_map): this module's chunked helpers — momentum is
  carried *stacked*, leaf shape ``(n_data, chunk)`` sharded ``P("data")``
  (the PR-7 residual discipline: each rank reads/writes only its slot);
- **GSPMD**: a sharding-spec change only — momentum keeps its parameter
  shape but takes ``fsdp_specs`` shardings while the params stay on their
  own specs; XLA inserts the reduce-scatter/all-gather pair.

Checkpoint interchange: ``gather_momentum``/``shard_momentum`` convert
between the stacked-chunk layout and the param-shaped layout every
checkpoint stores (gather-on-save keeps zero and replicated runs
restore-compatible in both directions — train/checkpoint.py).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.ops import qcomm

Pytree = Any

MODES = ("none", "wus")


def resolve_zero(zero: Optional[str]) -> str:
    """Canonical zero mode from the CLI/config value (None -> ``"none"``)."""
    mode = zero if zero is not None else "none"
    if mode not in MODES:
        raise ValueError(f"zero must be one of {MODES}, got {mode!r}")
    return mode


def chunk_size(size: int, n: int, block: int = qcomm.DEFAULT_BLOCK) -> int:
    """Per-rank flat chunk length of a ``size``-element leaf (whole blocks,
    qcomm.chunk_layout padding rules — shared with the wire-byte model)."""
    total, _ = qcomm.chunk_layout(size, n, block)
    return total // n


def init_wus_momentum(params: Pytree, n_data: int, quantized: bool = False,
                      block: int = qcomm.DEFAULT_BLOCK) -> Pytree:
    """Zero-initialized stacked-chunk optimizer state for the explicit path.

    ``{"buf": <tree of (n_data, chunk) f32>}`` — plus an ``"agerr"`` twin
    when the param-delta all-gather is quantized (error feedback on the
    second wire hop, so sub-quantum updates accumulate instead of
    vanishing).  Shard every leaf ``P(data_axis)`` so each rank owns one
    slot; ``gather_momentum`` restores the param-shaped view.
    """
    def chunks(p):
        return jnp.zeros((n_data, chunk_size(int(np.prod(np.shape(p))),
                                             n_data, block)), jnp.float32)

    buf = jax.tree_util.tree_map(chunks, params)
    if quantized:
        return {"buf": buf, "agerr": jax.tree_util.tree_map(chunks, params)}
    return {"buf": buf}


def is_wus_momentum(momentum: Pytree) -> bool:
    """True when ``momentum`` carries the stacked-chunk WUS layout (the
    checkpoint layer keys gather-on-save / shard-on-restore off this).
    ``pending`` is the deferred-gather double buffer (parallel/overlap.py)
    — like ``agerr`` it is transient wire state, dropped on gather; a
    deferred state must be materialized before checkpointing."""
    return (isinstance(momentum, dict) and "buf" in momentum
            and set(momentum) <= {"buf", "agerr", "pending"})


def gather_momentum(momentum: Pytree, params: Pytree) -> Pytree:
    """Stacked-chunk ``momentum["buf"]`` -> param-shaped host tree.

    Host-side (numpy): runs at checkpoint save so every checkpoint stores
    the replicated-DP momentum layout regardless of the writer's zero mode
    (the recipe-interchange invariant).  ``agerr`` is error-feedback state
    and is deliberately dropped — it restarts at zero on restore, exactly
    like the qcomm residuals."""
    def g(b, p):
        shape = np.shape(p)
        size = int(np.prod(shape, dtype=np.int64))
        return np.asarray(b, np.float32).reshape(-1)[:size].reshape(shape)

    return jax.tree_util.tree_map(g, momentum["buf"], params)


def shard_momentum(host_momentum: Pytree, template_buf: Pytree) -> Pytree:
    """Param-shaped momentum -> stacked chunks matching ``template_buf``
    (the restore-side inverse of :func:`gather_momentum`; padding re-zeros)."""
    def s(m, t):
        n, chunk = np.shape(t)
        flat = np.zeros(n * chunk, np.float32)
        arr = np.asarray(m, np.float32).reshape(-1)
        flat[: arr.size] = arr
        return flat.reshape(n, chunk)

    return jax.tree_util.tree_map(s, host_momentum, template_buf)


# ------------------------------------------------------- in-graph (shard_map)

def _own_chunk(p, idx, n, block):
    """This rank's flat f32 chunk of a replicated param leaf."""
    total, nb = qcomm.chunk_layout(p.size, n, block)
    chunk = total // n
    flat = jnp.pad(p.astype(jnp.float32).ravel(), (0, total - p.size))
    return jax.lax.dynamic_slice(flat, (idx * chunk,), (chunk,))


def reduce_scatter_grads(grads: Pytree, axis_name: str, n: int,
                         cast_dtype=None,
                         block: int = qcomm.DEFAULT_BLOCK) -> Pytree:
    """Per-leaf f32 (or bf16-wire) reduce-scatter: each rank receives the
    exact sum of its flat chunk.  Padding rides as zeros so the layout
    matches ``init_wus_momentum`` chunk-for-chunk."""
    def rs(g):
        total, _ = qcomm.chunk_layout(g.size, n, block)
        flat = jnp.pad(g.astype(jnp.float32).ravel(), (0, total - g.size))
        if cast_dtype is not None:
            flat = flat.astype(cast_dtype)
        out = jax.lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                                   tiled=True)
        return out.astype(jnp.float32)

    return jax.tree_util.tree_map(rs, grads)


def wus_update_chunks(
    params: Pytree,
    momentum: Pytree,
    grad_chunks: Pytree,
    lr,
    idx,
    n: int,
    momentum_coef: float = 0.9,
    weight_decay: float = 1e-4,
    block: int = qcomm.DEFAULT_BLOCK,
) -> Tuple[Pytree, Pytree]:
    """The compute half of the WUS step: torch-parity SGD (train/optim.py
    ``_upd``) on this rank's flat 1/N chunk — ``g += wd*p; buf = mu*buf
    + g; delta = lr*buf`` — with no collective.  Returns ``(delta_tree,
    new_buf_tree)`` of flat per-rank chunks; the wire half is
    :func:`wus_gather_deltas` (eager) or the overlap scheduler's deferred
    gather (parallel/overlap.py)."""
    buf = momentum["buf"]
    p_leaves, treedef = jax.tree_util.tree_flatten(params)
    b_leaves = jax.tree_util.tree_leaves(buf)
    g_leaves = jax.tree_util.tree_leaves(grad_chunks)
    if not (len(p_leaves) == len(b_leaves) == len(g_leaves)):
        raise ValueError("wus_update_chunks: params / momentum['buf'] / "
                         "grad chunk trees do not match")

    deltas, new_buf = [], []
    for p, b, g in zip(p_leaves, b_leaves, g_leaves):
        pc = _own_chunk(p, idx, n, block)
        b0 = b.reshape(pc.shape)
        g = g.reshape(pc.shape) + weight_decay * pc
        b1 = momentum_coef * b0 + g
        deltas.append(lr * b1)
        new_buf.append(b1.reshape(b.shape))
    return (jax.tree_util.tree_unflatten(treedef, deltas),
            jax.tree_util.tree_unflatten(treedef, new_buf))


def wus_gather_deltas(
    delta_tree: Pytree,
    agerr: Optional[Pytree],
    params: Pytree,
    axis_name: str,
    mode: str = "none",
    cast_dtype=None,
    block: int = qcomm.DEFAULT_BLOCK,
    bucket_mb: Optional[float] = None,
) -> Tuple[Pytree, Optional[Pytree]]:
    """The wire half of the WUS step: all-gather the per-rank delta chunks
    back to full leaves (f32, bf16 wire, or the quantized qcomm path with
    error feedback in ``agerr``).

    ``bucket_mb``: when set, leaves are gathered in ~MiB-sized groups
    under nested ``ag_b<k>`` scopes (forward flatten order — layer k's
    params unblock layer k's next forward first), so XLA may interleave
    each group's gather with the update compute of later groups.  Per
    leaf the collective is identical either way, so bucketing never
    changes the gathered values.  Returns ``(full_delta_tree,
    new_agerr_or_None)``."""
    if mode in qcomm.QUANTIZED_MODES:
        def gather(ds, es, ps):
            full, new_e = qcomm.compressed_all_gather(
                ds, es if es is not None else {}, axis_name, ps,
                mode=mode, block=block)
            return full, (new_e if es is not None else
                          [None] * len(jax.tree_util.tree_leaves(ds)))
    else:
        def gather(ds, es, ps):
            def ag(d, p):
                wire = d if cast_dtype is None else d.astype(cast_dtype)
                flat = jax.lax.all_gather(wire, axis_name).astype(
                    jnp.float32).reshape(-1)
                return flat[: p.size].reshape(p.shape)

            return ([ag(d, p) for d, p in zip(ds, ps)],
                    es if es is not None else
                    [None] * len(jax.tree_util.tree_leaves(ds)))

    d_leaves, treedef = jax.tree_util.tree_flatten(delta_tree)
    p_leaves = jax.tree_util.tree_leaves(params)
    use_ef = agerr is not None and len(jax.tree_util.tree_leaves(agerr)) > 0
    e_leaves = (jax.tree_util.tree_leaves(agerr) if use_ef
                else [None] * len(d_leaves))

    if bucket_mb is None:
        full, new_e = gather(d_leaves, e_leaves if use_ef else None, p_leaves)
        full_leaves, e_out = list(full), list(new_e)
    else:
        from pytorch_distributed_tpu.parallel import overlap as overlap_lib

        buckets = overlap_lib.plan_buckets(params, bucket_mb)
        # gather buckets in forward order: the reverse-autodiff bucket
        # order of the sync is wrong here — the *next* forward consumes
        # layer 0's params first.
        buckets = list(reversed(buckets))
        full_leaves = [None] * len(d_leaves)
        e_out = [None] * len(d_leaves)
        for k, bucket in enumerate(buckets):
            with jax.named_scope(f"ag_b{k}"):
                full, new_e = gather(
                    [d_leaves[i] for i in bucket],
                    [e_leaves[i] for i in bucket] if use_ef else None,
                    [p_leaves[i] for i in bucket])
            for i, f, e in zip(bucket, full, new_e):
                full_leaves[i] = f
                e_out[i] = e

    full_tree = jax.tree_util.tree_unflatten(treedef, full_leaves)
    new_agerr = (jax.tree_util.tree_unflatten(treedef, e_out) if use_ef
                 else agerr)
    return full_tree, new_agerr


def wus_apply_updates(
    params: Pytree,
    momentum: Pytree,
    grad_chunks: Pytree,
    lr,
    idx,
    n: int,
    axis_name: str,
    momentum_coef: float = 0.9,
    weight_decay: float = 1e-4,
    mode: str = "none",
    cast_dtype=None,
    block: int = qcomm.DEFAULT_BLOCK,
    bucket_mb: Optional[float] = None,
) -> Tuple[Pytree, Pytree]:
    """The 1/N-shard weight update + param all-gather (runs per-rank).

    Composition of :func:`wus_update_chunks` (chunked SGD) and
    :func:`wus_gather_deltas` (delta all-gather — f32, bf16 wire, or the
    quantized qcomm path with error feedback in ``momentum["agerr"]``);
    the gathered delta is applied to the replicated params on every rank,
    so replicas stay bit-identical.  ``bucket_mb`` opts the gather into
    the overlap scheduler's ~MiB bucketing (values unchanged; see
    :func:`wus_gather_deltas`).

    Returns ``(new_params, new_momentum)`` with momentum in the stacked
    layout (``(1, chunk)`` per-rank slots inside shard_map).
    """
    agerr = momentum.get("agerr")
    delta_tree, new_buf = wus_update_chunks(
        params, momentum, grad_chunks, lr, idx, n,
        momentum_coef=momentum_coef, weight_decay=weight_decay, block=block)

    new_momentum = {"buf": new_buf}
    full, new_agerr = wus_gather_deltas(
        delta_tree, agerr, params, axis_name, mode=mode,
        cast_dtype=cast_dtype, block=block, bucket_mb=bucket_mb)
    if new_agerr is not None:
        new_momentum["agerr"] = new_agerr

    new_params = jax.tree_util.tree_map(
        lambda p, d: (p.astype(jnp.float32) - d).astype(p.dtype),
        params, full)
    return new_params, new_momentum


def chunk_sq_sum(tree: Pytree) -> jnp.ndarray:
    """Sum of squares over a chunk tree — one rank's contribution to the
    global grad norm (chunks are disjoint, so a psum of these IS the
    global sum of squares; the replicated-path shortcut of reading the
    norm off the synced gradient does not exist under reduce-scatter)."""
    return sum(jnp.sum(jnp.square(leaf.astype(jnp.float32)))
               for leaf in jax.tree_util.tree_leaves(tree))


# -------------------------------------------------------------- GSPMD layout

def zero_momentum_specs(params: Pytree, mesh, data_axis: str = "data",
                        base_specs: Pytree = None) -> Pytree:
    """Momentum PartitionSpecs for the GSPMD expression of WUS: every
    optimizer leaf takes its ``fsdp_specs`` sharding while the params keep
    ``base_specs`` (or stay replicated) — the update math is unchanged and
    XLA inserts the reduce-scatter (grads -> sharded buf) and all-gather
    (buf -> replicated param delta) from the layout alone."""
    from pytorch_distributed_tpu.parallel.fsdp import fsdp_specs

    return fsdp_specs(params, mesh, data_axis=data_axis,
                      base_specs=base_specs)
