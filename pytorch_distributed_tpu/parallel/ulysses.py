"""All-to-all (Ulysses-style) sequence parallelism over a ``seq`` mesh axis.

Beyond-reference capability (the reference has no sequence parallelism at
all — SURVEY.md §5.7); this is the second of the framework's two SP
formulations, complementing ``parallel/ring.py``:

- **ring**: KV blocks rotate around the ring (P-1 ``ppermute`` neighbor
  hops); attention math is blockwise-online; per-device memory is
  O(L·L/P) score-free and the sequence axis can grow with the ring.
- **a2a (this module)**: two ``all_to_all`` exchanges re-slice the sharded
  activations from sequence-sharded to *head*-sharded and back
  (DeepSpeed-Ulysses pattern, Jacobs et al. 2023).  In between, every
  device holds the FULL sequence for H/P heads, so the inner attention is
  an ordinary single-device kernel — including the Pallas flash kernel
  (``ops/flash_attention.py``), which the blockwise ring formulation
  cannot reuse.  Comms per attention: 4 all-to-alls (q, k, v in; out
  back), each moving B·L·C/P elements over ICI — a constant number of
  hops independent of P, vs the ring's P-1 rounds.

Trade-off (documented, both shipped): a2a needs ``local_heads % P == 0``
and materializes full-L scores per head group under the dense inner
(O(L²·H/P) — use ``inner='flash'`` at long L); ring has no head-count
constraint and never materializes L² anything.

Layout contract matches ring.py: global ``[batch, seq, heads, head_dim]``,
sequence sharded over ``seq_axis``, batch over ``data_axis``, and —
composing with Megatron TP — heads over ``model_axis``; the all-to-all
then subdivides the model-local heads across the seq axis.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_tpu.parallel.ring import dense_attention


def a2a_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "seq",
    causal: bool = True,
    inner: str = "auto",
) -> jnp.ndarray:
    """Ulysses attention on device-local blocks; call *inside* ``shard_map``.

    ``q/k/v``: local ``[B, L/P, H_local, D]``.  ``inner`` selects the
    full-sequence attention run on each head group: ``'dense'``,
    ``'flash'`` (Pallas kernel), or ``'auto'`` (flash on TPU at long,
    1024-aligned L — same policy as models/transformer._pick_attention).
    """
    P_ = jax.lax.axis_size(axis_name)
    B, Lb, H, D = q.shape
    if H % P_:
        raise ValueError(
            f"a2a sequence parallelism needs local heads ({H}) divisible by "
            f"the '{axis_name}' axis size ({P_}); use ring SP otherwise"
        )
    L = Lb * P_
    from pytorch_distributed_tpu.ops.flash_attention import pick_attention_impl

    inner = pick_attention_impl(L, inner)

    # seq-sharded -> head-sharded: [B, L/P, H, D] -> [B, L, H/P, D].
    # Concatenation order along seq follows device order on the axis, so
    # gathered positions are global positions (rope was applied upstream).
    def to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qg, kg, vg = to_heads(q), to_heads(k), to_heads(v)
    if inner == "flash":
        from pytorch_distributed_tpu.ops.flash_attention import flash_attention

        out = flash_attention(qg, kg, vg, causal)
    else:
        out = dense_attention(qg, kg, vg, causal=causal)
    # head-sharded -> seq-sharded: [B, L, H/P, D] -> [B, L/P, H, D]
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def a2a_self_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    causal: bool = True,
    seq_axis: str = "seq",
    data_axis: Optional[str] = "data",
    model_axis: Optional[str] = "model",
    inner: str = "auto",
) -> jnp.ndarray:
    """``shard_map`` wrapper mirroring ``ring_self_attention``: global
    ``[B, L, H, D]`` in/out with L sharded over ``seq_axis`` (B over
    ``data_axis``; composing with Megatron TP, heads over ``model_axis`` —
    the all-to-all splits the model-local head group across ``seq_axis``,
    so H must be divisible by seq·model)."""
    batch_spec = data_axis if data_axis in mesh.axis_names else None
    head_spec = (
        model_axis if model_axis and model_axis in mesh.axis_names else None
    )
    spec = P(batch_spec, seq_axis, head_spec, None)
    fn = functools.partial(a2a_attention, axis_name=seq_axis, causal=causal,
                           inner=inner)
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
