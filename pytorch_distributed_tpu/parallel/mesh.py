"""Device-mesh construction over ICI / DCN.

The mesh is the TPU-native replacement for the reference's process-group +
device-id bookkeeping (``torch.cuda.set_device`` / ``device_ids=[local_rank]``,
reference distributed.py:141,147-148).  Axis conventions used throughout the
framework:

- ``data``  — data parallelism (gradient psum rides ICI; across slices, DCN)
- ``model`` — tensor parallelism (activations/weights sharded)
- ``seq``   — sequence/context parallelism (ring attention, parallel/ring.py)
- ``pipe``  — pipeline stages
- ``expert`` — expert parallelism (MoE)

Single-axis DP is the reference-parity configuration; the extra axes are
first-class so long-context / model-parallel training shares one code path.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape.  ``-1`` for at most one axis means "all remaining
    devices" (like a reshape wildcard)."""

    axes: Tuple[str, ...] = ("data",)
    shape: Tuple[int, ...] = (-1,)

    def resolve(self, n_devices: int) -> Tuple[int, ...]:
        shape = list(self.shape)
        wild = [i for i, s in enumerate(shape) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {self.shape}")
        fixed = int(np.prod([s for s in shape if s != -1])) if shape else 1
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            shape[wild[0]] = n_devices // fixed
        if int(np.prod(shape)) != n_devices:
            raise ValueError(
                f"mesh shape {tuple(shape)} != device count {n_devices}"
            )
        return tuple(shape)


def local_device_count() -> int:
    """Addressable accelerator count — the reference's
    ``torch.cuda.device_count()`` (distributed.py:114)."""
    return jax.local_device_count()


def build_mesh(
    spec: MeshSpec = MeshSpec(),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` over all (global) devices.

    Device order follows ``jax.devices()``, which on TPU pods is already
    ICI-topology-aware; the *last* mesh axes are therefore the
    fastest-varying / most-local, so put the heaviest-communication axis
    (``model`` or ``seq``) last and ``data`` first — gradient allreduce
    tolerates DCN, tensor-parallel collectives should ride ICI
    (scaling-book recipe; SURVEY.md §5.8).
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    shape = spec.resolve(len(devs))
    dev_array = np.asarray(devs).reshape(shape)
    return Mesh(dev_array, spec.axes)


def data_parallel_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """The reference-parity 1-D mesh: every device on one ``data`` axis.

    Multi-slice deployments get the hybrid (slice-major) device order so
    the gradient psum decomposes hierarchically over ICI then DCN."""
    return build_hybrid_mesh(MeshSpec(("data",), (-1,)), devices=devices)


def build_hybrid_mesh(
    spec: MeshSpec,
    dcn_axis: str = "data",
    devices: Optional[Sequence[jax.Device]] = None,
    granule: str = "auto",
) -> Mesh:
    """Multi-slice mesh: ``dcn_axis`` spans slices over DCN, every other
    axis stays inside a slice on ICI.

    The TPU-native equivalent of the reference's multi-node SLURM recipe
    (distributed_slurm_main.py:124-140): there, NCCL ranks spanned nodes
    and every collective crossed the interconnect indiscriminately; here
    the slice topology is explicit — only ``dcn_axis`` collectives (in the
    recipes: the gradient psum) cross the slower inter-slice network, and
    XLA decomposes them hierarchically (in-slice reduce, cross-slice
    exchange, in-slice broadcast).

    ``granule`` — the unit of the outer (DCN) network:

    - ``"slice"``    — TPU slices via ``device.slice_index``;
    - ``"process"``  — host processes (``device.process_index``), for
                       platforms that don't set ``slice_index`` (GPU-style
                       deployments; the multi-process CPU sim — this is
                       what lets the DCN code path run LIVE in
                       tests/test_multiprocess.py);
    - ``"auto"``     — slices when >1 are visible, else processes when >1,
                       else the plain flat mesh.

    On a single granule this degrades to plain ``build_mesh``; the
    ``dcn_axis`` size must then be 1 or divide the flat device order,
    which is what ``jax.devices()`` already gives.
    """
    if dcn_axis not in spec.axes:
        raise ValueError(f"dcn_axis {dcn_axis!r} not in mesh axes {spec.axes}")
    if granule not in ("auto", "slice", "process"):
        raise ValueError(f"unknown granule {granule!r}")
    devs = list(devices) if devices is not None else list(jax.devices())
    n_slices = len({getattr(d, "slice_index", 0) for d in devs})
    n_procs = len({d.process_index for d in devs})
    auto = granule == "auto"
    if auto:
        granule = "slice" if n_slices > 1 else "process"
    n_granules = n_slices if granule == "slice" else n_procs
    if n_granules <= 1:
        return build_mesh(spec, devs)
    shape = spec.resolve(len(devs))
    dcn_pos = spec.axes.index(dcn_axis)
    if shape[dcn_pos] % n_granules:
        if auto and granule == "process":
            # Auto must never turn a previously-valid spec into an error:
            # process granules are a round-4 addition, so an indivisible
            # dcn axis keeps the flat mesh those callers used to get.
            # (Indivisible SLICES still raise, as they always did — real
            # multi-slice topology with a bad axis is a config bug.)
            return build_mesh(spec, devs)
        raise ValueError(
            f"dcn axis {dcn_axis!r} size {shape[dcn_pos]} not divisible by "
            f"the {n_granules} {granule} granules"
        )
    from jax.experimental import mesh_utils

    ici_shape = list(shape)
    ici_shape[dcn_pos] = shape[dcn_pos] // n_granules
    dcn_shape = [1] * len(shape)
    dcn_shape[dcn_pos] = n_granules
    dev_array = mesh_utils.create_hybrid_device_mesh(
        tuple(ici_shape), tuple(dcn_shape), devs,
        process_is_granule=granule == "process",
        allow_split_physical_axes=True,
    )
    return Mesh(dev_array, spec.axes)
