"""FSDP / ZeRO-3-style parameter sharding over the ``data`` axis.

SURVEY.md §2.3 lists FSDP/ZeRO as explicitly absent from the reference;
under GSPMD it is a *layout*, not a wrapper: shard every large parameter
(and its momentum/optimizer state, via ``tp.state_specs`` reusing the same
specs) across the data axis and let XLA insert the all-gathers before use
and reduce-scatters for the gradients.  Per-device parameter + optimizer
memory drops by ~the data-axis size; compute is unchanged.

Composes with the ``model`` axis: leaves already sharded by a Megatron spec
keep it — FSDP takes the largest still-unsharded dim.

The lighter ZeRO-1 point on the same spectrum is ``--zero wus``
(parallel/zero.py): only the *optimizer* leaves take these fsdp_specs
shardings (``zero_momentum_specs`` reuses this module), params stay in
their declared layout — weight-update sharding without the per-use
parameter all-gathers.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

Pytree = Any


def fsdp_specs(
    params: Pytree,
    mesh,
    data_axis: str = "data",
    min_size: int = 1024,
    base_specs: Pytree = None,
) -> Pytree:
    """PartitionSpec tree sharding each parameter's largest free dim over
    ``data_axis`` of ``mesh``.

    - Leaves smaller than ``min_size`` elements stay replicated (scalars,
      norm vectors — sharding them buys nothing and costs collectives).
    - ``base_specs``: optional existing spec tree (e.g. ``tp_specs``) to
      compose with — FSDP picks the largest dim the base spec leaves free.
    Only dims divisible by the data-axis size are eligible; if none, the
    leaf keeps its base spec.
    """
    n_shards = int(dict(mesh.shape)[data_axis])

    def spec_for(leaf, base: P) -> P:
        shape = np.shape(leaf)
        if int(np.prod(shape, dtype=np.int64)) < min_size:
            return base
        entries = list(base) + [None] * (len(shape) - len(base))
        candidates = [
            (shape[i], i) for i in range(len(shape))
            if entries[i] is None and shape[i] % n_shards == 0
        ]
        if not candidates:
            return base
        _, dim = max(candidates)
        entries[dim] = data_axis
        return P(*entries)

    if base_specs is None:
        return jax.tree_util.tree_map(lambda leaf: spec_for(leaf, P()), params)
    return jax.tree_util.tree_map(spec_for, params, base_specs)
