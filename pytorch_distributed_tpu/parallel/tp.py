"""Tensor parallelism: Megatron-style parameter sharding over a ``model``
mesh axis, expressed as GSPMD sharding specs (XLA inserts the all-reduces).

Beyond-reference capability (the reference is DP-only, SURVEY.md §2.3
"Explicitly absent"), first-class per the framework brief.  The design
follows the scaling-book recipe: pick a mesh, annotate parameter shardings,
let XLA place collectives — no hand-written all-reduce in the model code.

For the TransformerLM the classic layout is:

- attention ``qkv`` kernel: column-parallel  → ``P(None, 'model')``
- attention ``proj`` kernel: row-parallel    → ``P('model', None)``
- MLP ``fc1``: column-parallel               → ``P(None, 'model')``
- MLP ``fc2``: row-parallel                  → ``P('model', None)``
- embedding: vocab-sharded                   → ``P('model', None)``
- everything else (norms, biases): replicated

With these specs, XLA emits exactly Megatron's two all-reduces per block
(after ``proj`` and after ``fc2``) on the ``model`` axis — which should be
the innermost/fastest mesh axis so they ride ICI (parallel/mesh.py note).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any

_COLUMN_PARALLEL = ("qkv", "fc1")
_ROW_PARALLEL = ("proj", "fc2")


def transformer_tp_spec(path: tuple, leaf, model_axis: str = "model") -> P:
    """PartitionSpec for one TransformerLM parameter, by its tree path.

    Covers both the fp tree (``kernel``) and the int8 weight-only serving
    tree (``w_q`` + per-output-channel ``scale``, models/quant.py): ``w_q``
    shards exactly like ``kernel``; ``scale`` follows the OUTPUT dim, so it
    shards with column-parallel modules and replicates with row-parallel
    ones."""
    names = [getattr(k, "key", str(k)) for k in path]
    is_kernel = names[-1] in ("kernel", "w_q")
    module = names[-2] if len(names) >= 2 else ""
    if names[-1] == "embedding":
        return P(model_axis, None)  # vocab-sharded (tied head stays sharded)
    if is_kernel and module in _COLUMN_PARALLEL:
        return P(None, model_axis)
    if is_kernel and module in _ROW_PARALLEL:
        return P(model_axis, None)
    if names[-1] == "scale" and module in _COLUMN_PARALLEL:
        return P(model_axis)
    return P()  # norms, biases, row-parallel scales: replicated


def tp_specs(params: Pytree, model_axis: str = "model") -> Pytree:
    """Pytree of PartitionSpecs shaped like ``params``."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: transformer_tp_spec(path, leaf, model_axis), params
    )


def shard_pytree(tree: Pytree, specs: Pytree, mesh: Mesh) -> Pytree:
    """Place a (host or replicated) pytree onto the mesh per ``specs``."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs
    )


def replicated_like(tree: Pytree) -> Pytree:
    """All-replicated specs shaped like ``tree`` (DP-only layout)."""
    return jax.tree_util.tree_map(lambda _: P(), tree)


def state_specs(param_specs: Pytree, residual: bool = False,
                momentum_specs: Optional[Pytree] = None):
    """TrainState-shaped PartitionSpec tree: params and momentum share
    ``param_specs``; step and (empty) batch_stats are replicated.  The single
    source for jit in_shardings and device placement — keep them identical
    or XLA silently reshards every step.

    ``residual=True``: the state carries error-feedback residuals for
    quantized gradient sync (ops/qcomm.py) — param-shaped under the GSPMD
    emulation, so they shard exactly like the params.

    ``momentum_specs``: override the momentum layout — the ``--zero wus``
    hook (parallel/zero.py ``zero_momentum_specs``): optimizer leaves take
    data-axis ``fsdp_specs`` shardings while the params keep
    ``param_specs``, and XLA derives the reduce-scatter/all-gather
    weight-update pair from the layout mismatch."""
    from pytorch_distributed_tpu.train.state import TrainState

    return TrainState(step=P(), params=param_specs, batch_stats={},
                      momentum=(param_specs if momentum_specs is None
                                else momentum_specs),
                      residual=param_specs if residual else {})


def shard_state(state, param_specs: Pytree, mesh: Mesh,
                momentum_specs: Optional[Pytree] = None):
    """Place a TrainState on ``mesh`` per ``state_specs(param_specs)``."""
    specs = state_specs(
        param_specs,
        residual=bool(jax.tree_util.tree_leaves(state.residual)),
        momentum_specs=momentum_specs)
    return shard_pytree(state, specs, mesh)
