"""1F1B pipeline schedule: memory-bounded alternative to GPipe.

``parallel/pp.py``'s GPipe schedule runs all ``M`` microbatch forwards, then
lets autodiff reverse the scan — so every stage stashes activations for all
``M`` microbatches (with ``remat`` the stash is one stage-*input* per tick,
but still O(M)).  The 1F1B (one-forward-one-backward) schedule interleaves:
once the pipeline is full, each stage retires one backward for every forward
it admits, so at most ``2·(P-1)`` microbatch stage-inputs are ever live per
stage — **independent of M**.  That is the schedule that makes deep
pipelines train at large microbatch counts without activation OOM
(Narayanan et al., PipeDream-Flush / Megatron-LM's non-interleaved 1F1B).

TPU-native formulation: gradients are computed *manually* inside one
``lax.scan`` over ``T = M + 2(P-1)`` ticks under ``shard_map`` — each tick
every stage runs (masked) one forward and one backward.  The backward
re-runs the stage forward from the stashed stage-input via ``jax.vjp``
(= full in-stage rematerialization; residuals never cross ticks), the
activation cotangent hops stage→stage-1 over the reversed ``ppermute`` ring,
and the loss head (final LN → tied-embedding logits → CE) runs on the last
stage in the same tick its forward retires, producing both the microbatch
loss and the cotangent that seeds its backward.  Autodiff is never applied
over the schedule — the scan carry holds only the two hop buffers, the
bounded stash, and the gradient accumulators, so compiled peak memory is the
1F1B bound by construction.

Bubble note: this synchronous formulation pays a ``2(P-1)``-tick bubble
(vs GPipe's ``P-1``) because forward and backward share a tick clock; for
``M ≫ P`` the difference vanishes, and each tick does F+B work so the
steady state is fully utilized.

Memory-claim scope: the **M-independent bound covers the activation
stash** (the term that explodes under GPipe).  Each stage still holds the
full ``[B, ...]`` microbatch input stack (``micro``/``tokens``, replicated
over ``pipe`` by the shard_map specs) plus the equally-shaped fp32
``d_micro`` cotangent accumulator — two O(B·L·D) buffers that scale with
the *batch*, not with M.  Measured at d512/seq512/8 stages they are a few
hundred MiB against GPipe's multi-GiB O(M) stash
(RESULTS_pp_memory.json); slicing the feed to stage 0 / the head to the
last stage would need per-stage data placement that uniform shard_map
specs cannot express, so the replication is documented rather than
removed.

Beyond-reference capability (SURVEY.md §2.3: pipeline parallelism is
"explicitly absent" from the reference)."""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

Pytree = Any


def pipeline_1f1b_loss_and_grads(
    stage_fn: Callable[[Pytree, jnp.ndarray], jnp.ndarray],
    head_fn: Callable[[Pytree, jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
    stage_params: Pytree,
    head_params: Pytree,
    x: jnp.ndarray,
    tokens: jnp.ndarray,
    n_microbatches: int,
    mesh: Mesh,
    pipe_axis: str = "pipe",
    data_axis: str = "data",
    stage_param_specs: Pytree = None,
):
    """Run the 1F1B schedule; returns ``(loss, correct, count, g_stage,
    g_head, dx)``.

    - ``stage_fn(params_slice, x) -> y``: one pipeline stage (pure).
    - ``head_fn(head_params, y, tok) -> (mean_loss, correct_count)``: the
      per-microbatch loss head, differentiable in its first two args.
    - ``x``: [B, ...] activations entering stage 0 (already embedded).
    - ``tokens``: [B, L] targets, microbatched alongside ``x``.
    Gradients: ``g_stage`` stays sharded over ``pipe_axis`` (stage-stacked,
    like the inputs); ``g_head`` and the scalar outputs are replicated;
    ``dx`` ([B, ...]) is the cotangent for ``x`` — feed it to the embed vjp.
    All gradients correspond to the mean loss over all M microbatches.
    """
    n_stages = mesh.shape[pipe_axis]
    B = x.shape[0]
    M = n_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leading axis {leaf.shape[0]} != '{pipe_axis}' "
                f"mesh size {n_stages}"
            )
    mb = B // M
    micro = x.reshape((M, mb) + x.shape[1:])
    micro_tok = tokens.reshape((M, mb) + tokens.shape[1:])
    # Stash slots: at stage 0, tick t both admits microbatch t (write) and
    # retires microbatch t-2(P-1) (read) — 2(P-1)+1 simultaneously live.
    S = 2 * (n_stages - 1) + 1              # the 1F1B bound, M-independent
    T = M + 2 * (n_stages - 1)              # schedule length in ticks
    perm_fwd = [(i, i + 1) for i in range(n_stages - 1)]
    perm_bwd = [(i + 1, i) for i in range(n_stages - 1)]

    data_size = mesh.shape.get(data_axis, 1)
    has_data = data_axis in mesh.axis_names and data_size > 1

    def per_stage(params_st, head_p, micro_local, tok_local):
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_st)
        idx = jax.lax.axis_index(pipe_axis)
        last = n_stages - 1

        def masked_add(acc, upd, active):
            return jax.tree_util.tree_map(
                lambda a, u: a + jnp.where(active, u, 0).astype(a.dtype),
                acc, upd)

        def tick(carry, t):
            (fbuf, bbuf, stash, g_stage, g_head, d_micro,
             loss_sum, correct_sum) = carry

            # ---- forward: stage `idx` admits microbatch t - idx ----------
            fwd_m = t - idx
            active_f = jnp.logical_and(fwd_m >= 0, fwd_m < M)
            feed = micro_local[jnp.clip(fwd_m, 0, M - 1)]
            cur = jnp.where(idx == 0, feed, fbuf)
            slot_f = jnp.mod(fwd_m, S)
            stash = jnp.where(active_f, stash.at[slot_f].set(cur), stash)
            # named_scope on each schedule phase: XPlane traces attribute
            # per-tick self-time to fwd/head/bwd/hop (obs/trace.py).
            with jax.named_scope("pp1f1b_fwd"):
                y = stage_fn(params_local, cur)
            # Double-buffered forward hop (parallel/overlap.py design):
            # issue the ring transfer the moment `y` exists — the head and
            # backward phases below don't read `fbuf_next`, so the
            # ppermute overlaps a full tick of compute instead of
            # serializing at the tick boundary.  Pure reorder: bit-exact.
            with jax.named_scope("pp_hop"):
                fbuf_next = jax.lax.ppermute(y, pipe_axis, perm_fwd)

            # ---- loss head: last stage, same tick its forward retires ----
            # lax.cond so only the last stage pays the head (vocab-matmul
            # fwd+bwd) each tick — the branch is runtime-resolved per
            # device from axis_index, and contains no collectives.
            tok_m = tok_local[jnp.clip(fwd_m, 0, M - 1)]

            def run_head(hp, yy, tm):
                return _head_vjp(head_fn, hp, yy, tm)

            def skip_head(hp, yy, tm):
                zh = jax.tree_util.tree_map(jnp.zeros_like, hp)
                return ((jnp.float32(0.0), jnp.float32(0.0)),
                        (zh, jnp.zeros_like(yy)))

            with jax.named_scope("pp1f1b_head"):
                (loss_m, correct_m), (dhead_m, dy_head) = jax.lax.cond(
                    idx == last, run_head, skip_head, head_p, y, tok_m)
            active_h = jnp.logical_and(active_f, idx == last)
            g_head = masked_add(g_head, dhead_m, active_h)
            loss_sum = loss_sum + jnp.where(active_h, loss_m, 0.0)
            correct_sum = correct_sum + jnp.where(active_h, correct_m, 0.0)

            # ---- backward: stage `idx` retires microbatch t-2(P-1)+idx ---
            bwd_m = t - 2 * (n_stages - 1) + idx
            active_b = jnp.logical_and(bwd_m >= 0, bwd_m < M)
            dy_in = jnp.where(idx == last, dy_head, bbuf).astype(y.dtype)
            x_in = stash[jnp.mod(bwd_m, S)]
            # vjp re-runs the stage forward from the stashed input: in-stage
            # remat by construction; residuals live only within this tick.
            with jax.named_scope("pp1f1b_bwd"):
                _, svjp = jax.vjp(stage_fn, params_local, x_in)
                dp_m, dx_m = svjp(dy_in)
            g_stage = masked_add(g_stage, dp_m, active_b)
            write0 = jnp.logical_and(active_b, idx == 0)
            d_micro = jnp.where(
                write0,
                d_micro.at[jnp.clip(bwd_m, 0, M - 1)].set(
                    dx_m.astype(d_micro.dtype)),
                d_micro,
            )

            with jax.named_scope("pp_hop"):
                bbuf_next = jax.lax.ppermute(dx_m, pipe_axis, perm_bwd)
            return (fbuf_next, bbuf_next, stash, g_stage, g_head, d_micro,
                    loss_sum, correct_sum), None

        zeros_act = jnp.zeros_like(micro_local[0])
        carry0 = (
            zeros_act,
            zeros_act,
            jnp.zeros((S,) + micro_local.shape[1:], micro_local.dtype),
            jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape[1:], jnp.float32), params_st),
            jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, jnp.float32), head_p),
            jnp.zeros(micro_local.shape, jnp.float32),
            jnp.float32(0.0),
            jnp.float32(0.0),
        )
        (_, _, _, g_stage, g_head, d_micro, loss_sum, correct_sum), _ = (
            jax.lax.scan(tick, carry0, jnp.arange(T))
        )

        # Mean-of-microbatch-means: grads and loss scale by 1/M.
        inv_m = 1.0 / M
        g_stage = jax.tree_util.tree_map(lambda g: g * inv_m, g_stage)
        g_head = jax.tree_util.tree_map(lambda g: g * inv_m, g_head)
        d_micro = d_micro * inv_m
        loss = loss_sum * inv_m

        # Only the last stage holds loss/head grads; only stage 0 holds
        # d_micro — psum over `pipe` broadcasts each to every stage.
        loss = jax.lax.psum(loss, pipe_axis)
        correct = jax.lax.psum(correct_sum, pipe_axis)
        g_head = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, pipe_axis), g_head)
        d_micro = jax.lax.psum(d_micro, pipe_axis)
        if has_data:
            # The loss is the mean over GLOBAL tokens = mean over data shards
            # of the per-shard means — so parameter grads are the pmean of
            # the per-shard grads, and the per-shard input cotangent carries
            # a 1/data_size factor.  correct is a plain count: psum.
            loss = jax.lax.pmean(loss, data_axis)
            correct = jax.lax.psum(correct, data_axis)
            g_stage = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, data_axis), g_stage)
            g_head = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, data_axis), g_head)
            d_micro = d_micro / data_size
        # Re-stack the stage axis so out_specs P(pipe, ...) slots each
        # stage's gradient into the stacked layout.
        g_stage = jax.tree_util.tree_map(lambda g: g[None], g_stage)
        return loss, correct, g_stage, g_head, d_micro

    micro_spec = P(None, data_axis if has_data else None)
    act_spec = P(*(micro_spec + (None,) * (micro.ndim - 2)))
    tok_spec = P(*(micro_spec + (None,) * (micro_tok.ndim - 2)))
    param_specs = (
        stage_param_specs
        if stage_param_specs is not None
        else jax.tree_util.tree_map(lambda _: P(pipe_axis), stage_params)
    )
    rep = jax.tree_util.tree_map(lambda _: P(), head_params)
    loss, correct, g_stage, g_head, d_micro = jax.shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(param_specs, rep, act_spec, tok_spec),
        out_specs=(P(), P(), param_specs, rep, act_spec),
        check_vma=False,
    )(stage_params, head_params, micro, micro_tok)
    count = jnp.float32(tokens.shape[0] * (tokens.shape[1] - 1))
    dx = d_micro.reshape(x.shape)
    return loss, correct, count, g_stage, g_head, dx


def _head_vjp(head_fn, head_p, y, tok_m):
    """``jax.vjp`` of the loss head with the correct-count as aux: returns
    ``(loss, correct), (dhead, dy)`` with the loss cotangent seeded at 1."""
    loss_m, vjp, correct_m = jax.vjp(
        lambda hp, yy: head_fn(hp, yy, tok_m), head_p, y, has_aux=True
    )
    dhead, dy = vjp(jnp.float32(1.0))
    return (loss_m, correct_m), (dhead, dy)
