"""Comm-overlap scheduler: bucketed backward-overlapped gradient sync.

The reference's Horovod recipe hides the gradient all-reduce behind the
backward pass via bucketed ring-allreduce (fusion buffers + hooks firing
as soon as a bucket's gradients are ready).  Our explicit ``shard_map``
steps so far issued *one* tail-end collective per leaf group under a
single ``grad_sync`` scope — correct, but fully exposed: the timeline
analyzer (obs/timeline.py) reports the whole sync as ``exposed_comm_ms``
because no backward compute remains to hide it under.

This module is the jax expression of the bucketed schedule
(arXiv:1810.11112 characterizes the overlap-driven design space):

- the gradient pytree is partitioned into ~``bucket_mb``-MiB buckets in
  **reverse flatten order** — flax param dicts flatten in layer order,
  so reversed ≈ reverse-autodiff order: the bucket whose cotangents are
  produced *first* during backward is issued first;
- each bucket is synced by its own ``psum`` / ``compressed_psum`` under
  a nested ``grad_sync``/``b<k>`` scope, so XLA's scheduler is free to
  run bucket k's collective concurrently with the backward compute that
  produces bucket k+1's cotangents (on hardware with async collectives;
  the CPU test backend serializes, which is why the A/B fence derives
  its timelines from the schedule + the real compiled ledger);
- the math per leaf is **identical** to the monolithic sync — the same
  per-leaf ``psum`` / EQuARX decomposition, just grouped differently —
  so bucketed ≡ monolithic is bit-exact, not approximately equal
  (tests/test_overlap.py pins this for f32/bf16/int8-EF).

Scope labels: collectives land under ``.../grad_sync/b<k>/...`` op
names.  ``obs.comms.phase_of_op_name`` matches path *components*, so the
phase stays ``grad_sync`` (per-phase attribution still sums) and the new
``bucket`` ledger field recovers the index (``obs.comms.bucket_of_op_name``).

The ZeRO-WUS analogue (parallel/zero.py) splits the same way: bucketed
reduce-scatter here, bucketed delta all-gather in
``zero.wus_apply_updates(..., bucket_mb=...)``, and the *deferred* form
(``wus_gather="deferred"`` in train/steps.py) double-buffers the param
state through ``TrainState.momentum["pending"]`` so step t's delta
gather overlaps step t+1's forward.
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops import qcomm

Pytree = Any

MODES = ("none", "bucketed")
DEFAULT_BUCKET_MB = 4.0
_MIB = float(1 << 20)


def resolve_overlap(overlap: Optional[str]) -> str:
    """Canonical overlap mode from the CLI/config value (None -> "none")."""
    mode = overlap if overlap is not None else "none"
    if mode not in MODES:
        raise ValueError(f"overlap must be one of {MODES}, got {mode!r}")
    return mode


def _leaf_bytes(leaf) -> int:
    size = int(math.prod(jnp.shape(leaf))) if jnp.shape(leaf) else 1
    try:
        item = jnp.dtype(leaf.dtype).itemsize
    except Exception:
        item = 4
    return size * item


def plan_buckets(tree: Pytree, bucket_mb: float = DEFAULT_BUCKET_MB,
                 ) -> List[List[int]]:
    """Partition a pytree's flat leaves into reverse-order byte buckets.

    Returns a list of leaf-index lists covering every leaf exactly once.
    Bucket 0 holds the *last* leaves of the flatten order (the first
    gradients autodiff produces); a bucket closes once it has accumulated
    ``bucket_mb`` MiB, except that a single oversized leaf still gets its
    own bucket (leaves are never split — the per-leaf collective math
    must stay identical to the monolithic path).  Deterministic: a pure
    function of the leaf shapes/dtypes and ``bucket_mb``.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return []
    if bucket_mb <= 0:
        raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
    budget = bucket_mb * _MIB
    buckets: List[List[int]] = []
    cur: List[int] = []
    acc = 0.0
    for i in reversed(range(len(leaves))):
        cur.append(i)
        acc += _leaf_bytes(leaves[i])
        if acc >= budget:
            buckets.append(cur)
            cur, acc = [], 0.0
    if cur:
        buckets.append(cur)
    return buckets


def n_buckets(tree: Pytree, bucket_mb: float = DEFAULT_BUCKET_MB) -> int:
    return len(plan_buckets(tree, bucket_mb))


def _split_by_buckets(leaves: Sequence[Any],
                      buckets: Sequence[Sequence[int]]) -> List[List[Any]]:
    return [[leaves[i] for i in bucket] for bucket in buckets]


def _scatter_back(n: int, buckets: Sequence[Sequence[int]],
                  per_bucket: Sequence[Sequence[Any]]) -> List[Any]:
    out: List[Any] = [None] * n
    for bucket, vals in zip(buckets, per_bucket):
        for i, v in zip(bucket, vals):
            out[i] = v
    return out


def bucketed_psum(
    grads: Pytree,
    residual: Pytree,
    axis_name: str,
    *,
    mode: str = "none",
    cast_dtype=None,
    bucket_mb: float = DEFAULT_BUCKET_MB,
    block: int = qcomm.DEFAULT_BLOCK,
) -> Tuple[Pytree, Pytree]:
    """Bucketed gradient all-reduce inside ``shard_map``.

    Drop-in replacement for the monolithic body of train/steps.py's
    ``sync_grads`` (minus the count psum / normalization, which the
    caller keeps): per bucket, ``mode in QUANTIZED_MODES`` rides
    ``qcomm.compressed_psum`` (error-feedback residual threaded through),
    otherwise an optional ``cast_dtype`` wire cast + ``jax.lax.psum``.
    Per-leaf results are bit-identical to the single-call path — psum
    batches leaves into one HLO op per call, so bucketing only changes
    the op *grouping*, never the per-leaf reduction.
    """
    g_leaves, treedef = jax.tree_util.tree_flatten(grads)
    buckets = plan_buckets(grads, bucket_mb)
    use_ef = (mode in qcomm.QUANTIZED_MODES
              and len(jax.tree_util.tree_leaves(residual)) > 0)
    r_leaves = (jax.tree_util.tree_leaves(residual) if use_ef
                else [None] * len(g_leaves))
    if use_ef and len(r_leaves) != len(g_leaves):
        raise ValueError("residual tree does not match the gradient tree")

    out_g: List[List[Any]] = []
    out_r: List[List[Any]] = []
    for k, bucket in enumerate(buckets):
        gs = [g_leaves[i] for i in bucket]
        with jax.named_scope(f"b{k}"):
            if mode in qcomm.QUANTIZED_MODES:
                rs = [r_leaves[i] for i in bucket] if use_ef else {}
                synced, new_rs = qcomm.compressed_psum(
                    gs, rs, axis_name, mode=mode, block=block)
                out_g.append(synced)
                out_r.append(new_rs if use_ef else [None] * len(bucket))
            else:
                if cast_dtype is not None:
                    gs = [g.astype(cast_dtype) for g in gs]
                out_g.append(jax.lax.psum(gs, axis_name))
                out_r.append([None] * len(bucket))

    synced_leaves = _scatter_back(len(g_leaves), buckets, out_g)
    synced = jax.tree_util.tree_unflatten(treedef, synced_leaves)
    if use_ef:
        new_res = jax.tree_util.tree_unflatten(
            treedef, _scatter_back(len(g_leaves), buckets, out_r))
    else:
        new_res = residual
    return synced, new_res


def bucketed_reduce_scatter(
    grads: Pytree,
    residual: Pytree,
    axis_name: str,
    n: int,
    *,
    mode: str = "none",
    cast_dtype=None,
    bucket_mb: float = DEFAULT_BUCKET_MB,
    block: int = qcomm.DEFAULT_BLOCK,
) -> Tuple[Pytree, Pytree]:
    """Bucketed gradient reduce-scatter for the ZeRO-WUS path.

    Same bucketing/scoping as :func:`bucketed_psum`, over
    ``zero.reduce_scatter_grads`` (f32/bf16 wire) or
    ``qcomm.compressed_reduce_scatter`` (int8/fp8 + EF) per bucket.
    Returns flat ``(chunk,)`` sum leaves exactly like the monolithic
    helpers — chunk layout is per-leaf, so bucketing cannot move it.
    """
    from pytorch_distributed_tpu.parallel import zero as zero_lib

    g_leaves, treedef = jax.tree_util.tree_flatten(grads)
    buckets = plan_buckets(grads, bucket_mb)
    use_ef = (mode in qcomm.QUANTIZED_MODES
              and len(jax.tree_util.tree_leaves(residual)) > 0)
    r_leaves = (jax.tree_util.tree_leaves(residual) if use_ef
                else [None] * len(g_leaves))

    out_g: List[List[Any]] = []
    out_r: List[List[Any]] = []
    for k, bucket in enumerate(buckets):
        gs = [g_leaves[i] for i in bucket]
        with jax.named_scope(f"b{k}"):
            if mode in qcomm.QUANTIZED_MODES:
                rs = [r_leaves[i] for i in bucket] if use_ef else {}
                chunks, new_rs = qcomm.compressed_reduce_scatter(
                    gs, rs, axis_name, mode=mode, block=block)
                out_g.append(chunks)
                out_r.append(new_rs if use_ef else [None] * len(bucket))
            else:
                out_g.append(zero_lib.reduce_scatter_grads(
                    gs, axis_name, n, cast_dtype=cast_dtype, block=block))
                out_r.append([None] * len(bucket))

    chunk_leaves = _scatter_back(len(g_leaves), buckets, out_g)
    chunks = jax.tree_util.tree_unflatten(treedef, chunk_leaves)
    if use_ef:
        new_res = jax.tree_util.tree_unflatten(
            treedef, _scatter_back(len(g_leaves), buckets, out_r))
    else:
        new_res = residual
    return chunks, new_res


# ------------------------------------------- deferred WUS gather (2-buffer)

def init_pending(params: Pytree, n_data: int,
                 block: int = qcomm.DEFAULT_BLOCK) -> Pytree:
    """Zero pending-delta chunks for the deferred WUS gather: stacked
    ``(n_data, chunk)`` leaves (the ``init_wus_momentum`` layout), carried
    in ``momentum["pending"]`` and sharded ``P(data_axis)``.  Zeros make
    the first step's head-of-step gather a mathematical no-op."""
    from pytorch_distributed_tpu.parallel import zero as zero_lib

    return zero_lib.init_wus_momentum(params, n_data, block=block)["buf"]


def drain_pending(params: Pytree, pending: Pytree, axis_name: str, *,
                  cast_dtype=None) -> Pytree:
    """Gather + apply the previous step's staged delta chunks (in-graph,
    per-rank).  Runs at the *head* of the step under a ``param_gather``
    scope, so in dataflow terms layer k's gather only blocks layer k's
    forward — the double-buffered overlap window.  Returns the live
    params; the staged chunks it consumed should be replaced by the new
    step's deltas (``train/steps.py`` wires this)."""
    def apply_one(p, d):
        wire = d if cast_dtype is None else d.astype(cast_dtype)
        flat = jax.lax.all_gather(wire, axis_name, tiled=True).astype(
            jnp.float32).reshape(-1)
        delta = flat[: p.size].reshape(p.shape)
        return (p.astype(jnp.float32) - delta).astype(p.dtype)

    with jax.named_scope("param_gather"):
        return jax.tree_util.tree_map(
            apply_one, params,
            jax.tree_util.tree_map(lambda d: d.reshape(-1), pending))


def materialize_params(params: Pytree, pending: Pytree, *,
                       cast_dtype=None) -> Pytree:
    """Host-side (numpy) drain of staged deltas: the checkpoint/eval view
    of a deferred-gather state.  ``pending`` leaves are stacked
    ``(n_data, chunk)`` — the full delta is just the chunks concatenated,
    so no collective is needed; ``cast_dtype`` replays the wire cast the
    in-graph gather would have applied, keeping the two drains bit-equal.
    """
    import numpy as np

    def m(p, d):
        flat = np.asarray(d, np.float32).reshape(-1)
        if cast_dtype is not None:
            flat = flat.astype(jnp.dtype(cast_dtype)).astype(np.float32)
        shape = np.shape(p)
        size = int(np.prod(shape, dtype=np.int64))
        delta = flat[:size].reshape(shape)
        base = np.asarray(p, np.float32)
        return (base - delta).astype(np.asarray(p).dtype)

    return jax.tree_util.tree_map(m, params, pending)
