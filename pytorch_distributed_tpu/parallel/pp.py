"""Pipeline parallelism: GPipe-style microbatch pipelining over a ``pipe``
mesh axis, completing the framework's parallelism menu (dp/tp/sp/ep/**pp**).

Beyond-reference capability.  Each device owns one pipeline *stage* (a stack
of identical transformer blocks); microbatches stream through the ring:
device ``p`` processes microbatch ``m`` at tick ``t = p + m``, activations
hop to the next stage via ``ppermute`` (ICI neighbor exchange).  The whole
schedule is a ``lax.scan`` over ``M + P - 1`` ticks inside ``shard_map`` —
compiled once, bulk-synchronous, differentiable (the backward pipeline falls
out of autodiff through scan+ppermute; synchronous GPipe semantics, no
weight staleness).

Stage parameters are created stacked on a leading ``P`` axis (``nn.vmap``
over stages, like models/moe.py's experts) and sharded ``P('pipe', …)``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

Pytree = Any


def pipeline_apply(
    stage_fn: Callable[[Pytree, jnp.ndarray], jnp.ndarray],
    stage_params: Pytree,
    x: jnp.ndarray,
    n_microbatches: int,
    mesh: Mesh,
    pipe_axis: str = "pipe",
    data_axis: str = "data",
    stage_param_specs: Pytree = None,
    seq_axis: Optional[str] = None,
    remat: bool = False,
) -> jnp.ndarray:
    """Run ``x`` through ``n_stages`` of ``stage_fn`` as a GPipe pipeline.

    - ``stage_params``: pytree with a leading stage axis (size = pipe axis).
    - ``x``: [B, ...] activations entering stage 0; ``n_microbatches`` must
      divide ``B``.
    Returns the activations after the final stage, same shape as ``x``.

    Composes with data parallelism: when the mesh has ``data_axis``, the
    microbatch batch dim stays sharded over it (each data-parallel replica
    runs its own pipeline; activations hop only along ``pipe_axis``).

    ``stage_param_specs``: optional PartitionSpec tree matching
    ``stage_params`` for additional within-stage sharding (e.g. Megatron TP
    over a ``model`` axis — ``parallel/tp_stage.py``); each spec must still
    lead with ``pipe_axis``.  Default: every leaf ``P(pipe_axis)``.

    ``remat=True`` checkpoints the stage function: autodiff through the
    schedule then stashes only each tick's stage *input* (recomputing the
    in-stage activations during backward) — the O(M·layers) GPipe
    activation stash drops to O(M) stage-inputs.  For an M-independent
    bound use the 1F1B schedule (``parallel/pp_1f1b.py``).
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    n_stages = mesh.shape[pipe_axis]
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(f"batch {B} not divisible by {n_microbatches} microbatches")
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leading axis {leaf.shape[0]} != '{pipe_axis}' "
                f"mesh size {n_stages} — stages would be silently dropped"
            )
    mb = B // n_microbatches
    micro = x.reshape((n_microbatches, mb) + x.shape[1:])

    def per_stage(params_local, micro_local):
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_local)
        idx = jax.lax.axis_index(pipe_axis)
        n_ticks = n_microbatches + n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            buf = carry  # activations arriving at this stage this tick
            feed = micro_local[jnp.minimum(t, n_microbatches - 1)]
            cur = jnp.where(idx == 0, feed, buf)
            # named_scope: XPlane self-time attributes to the stage compute
            # vs the ring hop instead of anonymous fusions (obs/trace.py).
            with jax.named_scope("pp_stage_fwd"):
                y = stage_fn(params_local, cur)
            # Double-buffered hop (parallel/overlap.py design): issue the
            # ring transfer the moment `y` exists — the output-collection
            # ops below don't read `buf_next`, so the ppermute overlaps
            # them instead of serializing at the tick boundary.  Pure
            # reorder: bit-exact.
            with jax.named_scope("pp_hop"):
                buf_next = jax.lax.ppermute(y, pipe_axis, perm)
            # Last stage's finished microbatch index at tick t is t-(P-1).
            out_idx = t - (n_stages - 1)
            is_out = jnp.logical_and(idx == n_stages - 1, out_idx >= 0)
            out_contrib = jnp.where(is_out, y, jnp.zeros_like(y))
            return buf_next, (out_contrib, out_idx)

        buf0 = jnp.zeros_like(micro_local[0])
        _, (outs, out_idxs) = jax.lax.scan(
            tick, buf0, jnp.arange(n_ticks)
        )
        # Scatter finished microbatches into order; rows with out_idx < 0 are
        # already zeroed by the is_out gate, and only the last stage
        # contributes nonzero rows — the psum broadcasts them to all stages.
        result = jnp.zeros_like(micro_local)
        result = result.at[jnp.clip(out_idxs, 0, n_microbatches - 1)].add(outs)
        return jax.lax.psum(result, pipe_axis)

    # micro is [M, mb, L, ...]: shard the per-microbatch batch dim over
    # data and (for in-stage ring SP) the sequence dim over seq.
    micro_spec = P(
        None,
        data_axis if data_axis in mesh.axis_names else None,
        seq_axis if seq_axis and seq_axis in mesh.axis_names else None,
    )
    param_specs = (
        stage_param_specs
        if stage_param_specs is not None
        else jax.tree_util.tree_map(lambda _: P(pipe_axis), stage_params)
    )
    sharded = jax.shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(param_specs, micro_spec),  # params sharded by stage (+TP)
        out_specs=micro_spec,
        check_vma=False,
    )(stage_params, micro)
    return sharded.reshape(x.shape)
