"""Ring attention: sequence/context parallelism over a ``seq`` mesh axis.

Beyond-reference capability (the reference has none — SURVEY.md §5.7), built
first-class per the framework brief: long sequences are sharded across
devices on the ``seq`` axis; each device computes attention for its local
query block while key/value blocks rotate around the ring via
``jax.lax.ppermute`` (ICI neighbor exchanges), overlapping compute with
transfer.  Softmax is computed **online** (flash-attention style running
max/denominator), so no device ever materializes the full [L, L] score
matrix — memory is O(L·L/P) per device and sequence length scales linearly
with ring size.

Layout contract: ``[batch, seq, heads, head_dim]``, sequence sharded on
``seq``, batch optionally sharded on ``data``.  The inner function runs
under ``shard_map``; ``ring_self_attention`` applies the wrapper for you.

Reference pattern: Ring Attention (Liu et al. 2023) blockwise formulation;
see also the ring-collective pattern in the Pallas TPU guide (§Patterns:
Ring Collectives) — a Pallas RDMA kernel is the planned upgrade path; this
XLA-collective version is the semantics anchor it will be tested against.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30  # large-negative instead of -inf: keeps fully-masked rows NaN-free


def _block_attention(q, k, v, m, l, acc, qpos, kpos, scale, causal):
    """One online-softmax accumulation of q against a (k, v) block.

    q: [B, Lq, H, D]; k/v: [B, Lk, H, D]; m,l: [B, H, Lq]; acc like q.
    qpos: [Lq] global query positions; kpos: [Lk] global key positions.
    """
    # scores: [B, H, Lq, Lk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = kpos[None, None, None, :] <= qpos[None, None, :, None]
        scores = jnp.where(mask, scores, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])          # [B, H, Lq, Lk]
    l_new = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    acc_new = acc * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "seq",
    causal: bool = True,
) -> jnp.ndarray:
    """Blockwise ring attention; call *inside* ``shard_map``.

    Arguments are the device-local blocks ``[B, L/P, H, D]``.  Requires the
    global sequence to be evenly sharded (same L/P on every device).
    """
    P_ = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Lb, H, D = q.shape
    scale = 1.0 / (D ** 0.5)

    local_pos = jnp.arange(Lb)
    qpos = idx * Lb + local_pos

    m0 = jnp.full((B, H, Lb), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Lb), jnp.float32)
    acc0 = jnp.zeros((B, Lb, H, D), jnp.float32)

    perm = [(i, (i + 1) % P_) for i in range(P_)]

    def body(carry, _):
        kv_k, kv_v, kv_idx, m, l, acc = carry
        kpos = kv_idx * Lb + local_pos
        m, l, acc = _block_attention(
            q.astype(jnp.float32),
            kv_k.astype(jnp.float32),
            kv_v.astype(jnp.float32),
            m, l, acc, qpos, kpos, scale, causal,
        )
        # Rotate kv blocks one step around the ring (ICI neighbor exchange).
        kv_k = jax.lax.ppermute(kv_k, axis_name, perm)
        kv_v = jax.lax.ppermute(kv_v, axis_name, perm)
        kv_idx = jax.lax.ppermute(kv_idx, axis_name, perm)
        return (kv_k, kv_v, kv_idx, m, l, acc), None

    init = (k, v, idx, m0, l0, acc0)
    (kv_k, kv_v, kv_idx, m, l, acc), _ = jax.lax.scan(
        body, init, None, length=P_
    )
    # Normalize; fully-masked rows (l==0) can only occur non-causally with
    # empty inputs — guard anyway.
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (acc / denom).astype(q.dtype)


def ring_self_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    causal: bool = True,
    seq_axis: str = "seq",
    data_axis: Optional[str] = "data",
    model_axis: Optional[str] = "model",
) -> jnp.ndarray:
    """``shard_map`` wrapper: global ``[B, L, H, D]`` in, same out, with L
    sharded over ``seq_axis`` (B over ``data_axis``, and — composing with
    Megatron TP — heads over ``model_axis`` when the mesh has one; attention
    is independent per head, so the ring math is untouched and the
    TP-sharded qkv activations enter without an all-gather)."""
    batch_spec = data_axis if data_axis in mesh.axis_names else None
    head_spec = (
        model_axis if model_axis and model_axis in mesh.axis_names else None
    )
    spec = P(batch_spec, seq_axis, head_spec, None)
    fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal)
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def dense_attention(q, k, v, causal: bool = True) -> jnp.ndarray:
    """Single-device reference semantics (the oracle ring_attention is
    tested against)."""
    B, L, H, D = q.shape
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / (D ** 0.5)
    if causal:
        pos = jnp.arange(L)
        scores = jnp.where(pos[None, None, None, :] <= pos[None, None, :, None],
                           scores, NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v.astype(jnp.float32)).astype(q.dtype)
