"""Device meshes, distributed bootstrap, and collective helpers.

This is the framework's single communication layer (SURVEY.md §5.8): it
replaces all four of the reference's backend stacks — NCCL via
``torch.distributed`` (reference distributed.py:132), apex DDP flat-buffer
allreduce (apex_distributed.py:217), Horovod's MPI ring-allreduce core
(horovod_distributed.py:125), and the SLURM file-rendezvous
(distributed_slurm_main.py:137-140) — with ``jax.distributed.initialize``
plus a ``jax.sharding.Mesh`` over ICI (and DCN for multi-slice), inside
which XLA emits the collectives.
"""

from pytorch_distributed_tpu.parallel.mesh import (
    MeshSpec,
    build_hybrid_mesh,
    build_mesh,
    data_parallel_mesh,
    local_device_count,
)
from pytorch_distributed_tpu.parallel.dist import (
    DistContext,
    initialize,
    process_count,
    process_index,
)

__all__ = [
    "MeshSpec",
    "build_hybrid_mesh",
    "build_mesh",
    "data_parallel_mesh",
    "local_device_count",
    "DistContext",
    "initialize",
    "process_count",
    "process_index",
]
