"""Batching loader + double-buffered device feeder.

Replaces the reference's ``DataLoader(num_workers, pin_memory=True)``
(reference distributed.py:176-180) and the apex CUDA-stream
``data_prefetcher`` (apex_distributed.py:115-169).  On TPU the prefetcher's
job — overlap host→device copies with device compute — is done by enqueueing
the *next* batch's async transfer while the current step runs, from a
background thread (XLA transfers are async; dispatch is cheap).

Batches have **static shapes** (XLA requirement): the final partial batch is
zero-padded and carries a 0/1 ``weights`` mask, which the step functions use
so padding contributes nothing to loss/metrics — this makes evaluation exact
rather than DistributedSampler-approximate (SURVEY.md §7.4 item 3).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.data.sampler import DistributedShardSampler

Batch = Dict[str, np.ndarray]


class DataLoader:
    """Iterates this rank's shard as padded, masked numpy batches.

    ``batch_size`` here is the *per-process* batch (the harness divides the
    global batch by process count, mirroring reference distributed.py:146).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        sampler: Optional[DistributedShardSampler] = None,
        num_workers: int = 2,
        drop_last: bool = False,
        seed: int = 0,
        batch_mode: str = "f32",
        random_flip: bool = False,
        worker_type: str = "thread",
    ):
        """``batch_mode``:

        - ``"f32"``     — per-sample transforms yield normalized float32
                          (reference-shaped pipeline; default);
        - ``"u8_host"`` — transforms yield uint8; flip+normalize run at batch
                          level in the native C++ library (data/native/);
        - ``"u8_wire"`` — transforms yield uint8; flip runs host-side, the
                          batch crosses PCIe/ICI as uint8 (4× fewer bytes)
                          and normalization happens on device (DeviceFeeder).
        ``random_flip`` applies the train-stack horizontal flip in the u8
        modes (in f32 mode the flip lives in the per-sample transform).

        ``worker_type``: ``"thread"`` (default; right for the native-decode
        path, whose C++ batch decode releases the GIL) or ``"process"`` —
        spawn-based worker processes for the Python/PIL per-sample path,
        where threads serialize on the GIL (reference ``DataLoader``
        worker processes, reference distributed.py:176-180).  Spawn, not
        fork, so the dataset+transform must be picklable (the built-in
        ones are); see ``_iter_process`` for why fork is unsafe here.
        """
        if batch_mode not in ("f32", "u8_host", "u8_wire"):
            raise ValueError(f"unknown batch_mode {batch_mode!r}")
        if worker_type not in ("thread", "process"):
            raise ValueError(f"unknown worker_type {worker_type!r}")
        self.worker_type = worker_type
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler or DistributedShardSampler(
            len(dataset), shuffle=False, seed=seed
        )
        self.num_workers = max(1, num_workers)
        self.drop_last = drop_last
        self.seed = seed
        self.batch_mode = batch_mode
        self.random_flip = random_flip
        self._pool = None      # persistent spawn pool (process worker_type)
        self._pool_key = None

    def set_epoch(self, epoch: int) -> None:
        self.sampler.set_epoch(epoch)

    def __len__(self) -> int:
        n = self.sampler.num_samples
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def _fetch(self, index: int, valid: int):
        if valid:
            rng = np.random.default_rng((self.seed, self.sampler.epoch, int(index)))
            if hasattr(self.dataset, "get"):
                return self.dataset.get(int(index), rng)
            return self.dataset[int(index)]
        return None  # padding slot

    def _assemble_native(self, samples):
        """Batch the ("jpeg", blob, params, label) / ("u8", arr, None, label)
        samples of a native_decode dataset: one C++ call decodes, crops and
        resizes every JPEG in the batch (libjpeg, multithreaded, GIL-free).

        Returns ``(images, labels, dead)`` — ``dead`` lists batch slots whose
        JPEG failed to decode; the caller zeroes their weights so corrupt
        files drop out of loss/metrics instead of training as black images."""
        from pytorch_distributed_tpu.data.native import decode_crop_resize_batch

        size = self.dataset.image_size
        images = np.zeros((self.batch_size, size, size, 3), np.uint8)
        labels = np.zeros(self.batch_size, dtype=np.int32)
        blobs, params, slots = [], [], []
        dead: list = []
        for i, s in enumerate(samples):
            if s is None:
                continue
            kind, payload, p, label = s
            labels[i] = label
            if kind == "jpeg":
                slots.append(i)
                blobs.append(payload)
                params.append(p)
            else:
                images[i] = payload
        if blobs:
            params_arr = (
                np.stack(params) if params[0] is not None else None
            )
            decoded, failed = decode_crop_resize_batch(
                blobs, size, params=params_arr, return_failed=True
            )
            images[slots] = decoded
            if failed.any():
                dead = [slots[j] for j in np.nonzero(failed)[0]]
                import warnings

                warnings.warn(
                    f"{len(dead)} corrupt JPEG(s) in batch — samples masked "
                    f"out of loss/metrics",
                    stacklevel=2,
                )
        return images, labels, dead

    def _batch_indices(self, indices, valid, b: int):
        lo, hi = b * self.batch_size, (b + 1) * self.batch_size
        idx = indices[lo:hi]
        val = valid[lo:hi]
        # Pad the trailing batch to the static batch size.
        pad = self.batch_size - len(idx)
        if pad:
            idx = np.concatenate([idx, np.zeros(pad, dtype=idx.dtype)])
            val = np.concatenate([val, np.zeros(pad, dtype=val.dtype)])
        return idx, val

    def _assemble(self, b: int, val, samples) -> Batch:
        """Samples → one padded/masked batch (shared by both worker modes)."""
        if getattr(self.dataset, "native_decode", False):
            if self.batch_mode == "f32":
                raise TypeError(
                    "native_decode datasets produce uint8 batches; "
                    "use batch_mode 'u8_host' or 'u8_wire'"
                )
            images, labels, dead = self._assemble_native(samples)
            if dead:
                val = val.copy()
                val[dead] = 0
        else:
            proto = next(s for s in samples if s is not None)
            img_dtype = (
                np.uint8 if self.batch_mode != "f32" else np.float32
            )
            if self.batch_mode != "f32" and proto[0].dtype != np.uint8:
                raise TypeError(
                    f"batch_mode {self.batch_mode!r} needs uint8 "
                    f"samples (use the *_transform_u8 stacks), got "
                    f"{proto[0].dtype}"
                )
            images = np.zeros(
                (self.batch_size,) + proto[0].shape, dtype=img_dtype
            )
            labels = np.zeros(self.batch_size, dtype=np.int32)
            for i, s in enumerate(samples):
                if s is not None:
                    images[i] = s[0]
                    labels[i] = s[1]
        if self.batch_mode != "f32":
            flip_rng = np.random.default_rng(
                (self.seed, self.sampler.epoch, b, 1)
            )
            flip = (
                (flip_rng.random(self.batch_size) < 0.5).astype(np.uint8)
                if self.random_flip
                else None
            )
            if self.batch_mode == "u8_host":
                from pytorch_distributed_tpu.data.native import (
                    normalize_batch,
                )
                from pytorch_distributed_tpu.data.transforms import (
                    IMAGENET_MEAN,
                    IMAGENET_STD,
                )

                images = normalize_batch(
                    images, IMAGENET_MEAN, IMAGENET_STD, flip=flip
                )
            elif flip is not None:  # u8_wire: flip on host, u8 out
                fidx = np.nonzero(flip)[0]
                images[fidx] = images[fidx, :, ::-1, :]
        return {
            "images": images,
            "labels": labels,
            "weights": val.astype(np.float32),
        }

    def __iter__(self) -> Iterator[Batch]:
        return self.iter_batches(0)

    def iter_batches(self, start: int = 0) -> Iterator[Batch]:
        """Iterate from batch ``start`` of this epoch's shard — the
        step-granular resume path (ft/): the sampler's (seed, epoch)
        permutation is recomputed, the first ``start`` batches are skipped
        by *index arithmetic* (no fetch, no decode), and the stream
        continues exactly where the checkpointed run left off."""
        indices, valid = self.sampler.shard()
        nb = len(self)
        if not 0 <= start <= nb:
            raise ValueError(
                f"resume step {start} out of range for {nb} batches/epoch")
        if self.worker_type == "process":
            yield from self._iter_process(indices, valid, nb, start)
            return
        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            for b in range(start, nb):
                idx, val = self._batch_indices(indices, valid, b)
                samples = list(pool.map(self._fetch, idx, val))
                yield self._assemble(b, val, samples)

    def _ensure_pool(self):
        """The spawn pool persists across epochs (advisor r3: a per-__iter__
        pool re-pays full worker spawn + dataset pickling every epoch) —
        rebuilt when ``self.dataset`` is rebound to a different object or
        the worker count changes; ``close()``/``__del__`` tear it down, and
        a module atexit reaper terminates any still-live pool so process
        exit never hangs joining pool machinery (observed: the full test
        suite wedging after its last test with workers still up).

        The key holds a STRONG reference to the keyed dataset and compares
        by identity, so a freed-then-reallocated object can never alias the
        key (id() alone can be reused by CPython).  Workers hold a pickled
        SNAPSHOT of the dataset: in-place mutation (e.g. swapping
        ``dataset.transform`` mid-training) is not re-shipped — call
        ``close()`` after mutating to force a fresh pool next epoch."""
        import multiprocessing as mp

        if (self._pool is not None
                and self._pool_key is not None
                and self._pool_key[0] is self.dataset
                and self._pool_key[1] == self.num_workers):
            return self._pool
        self.close()
        ctx = mp.get_context("spawn")
        _install_pool_reaper()  # after mp's own atexit hook → ours runs first
        self._pool = ctx.Pool(self.num_workers, initializer=_process_init,
                              initargs=(self.dataset,))
        _LIVE_POOLS.append(self._pool)
        self._pool_key = (self.dataset, self.num_workers)
        return self._pool

    def close(self) -> None:
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            if self._pool in _LIVE_POOLS:
                _LIVE_POOLS.remove(self._pool)
            self._pool = None
            self._pool_key = None

    def __del__(self):  # best-effort; close() is the deterministic path
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter may be tearing down
            pass

    def _iter_process(self, indices, valid, nb: int,
                      start: int = 0) -> Iterator[Batch]:
        """Worker *processes* for the per-sample fetch — the GIL-proof mode
        for Python/PIL decode (the reference's ``DataLoader(num_workers=…)``
        process pool, reference distributed.py:176-180).  The native-decode
        path doesn't need this: its C++ batch decode already releases the
        GIL (``_assemble_native``).

        Spawn start method, NOT fork: this runtime pre-imports jax (which is
        multithreaded) into every interpreter, and forking a threaded parent
        can deadlock the children.  The dataset ships to each worker once
        via the pool initializer (transforms are plain picklable classes).

        Dispatch is **batch-level, not item-level** (VERDICT r3 item 6):
        each worker gets one contiguous chunk of the batch per task — one
        pickle round-trip per worker per batch instead of one per sample —
        so on a host where processes cannot actually parallelize (1 core)
        the IPC overhead stays a constant per batch, not per image."""
        pool = self._ensure_pool()
        W = self.num_workers
        for b in range(start, nb):
            idx, val = self._batch_indices(indices, valid, b)
            args = [
                (int(i), int(v), self.seed, self.sampler.epoch)
                for i, v in zip(idx, val)
            ]
            bounds = [(len(args) * w // W, len(args) * (w + 1) // W)
                      for w in range(W)]
            chunks = [args[lo:hi] for lo, hi in bounds if hi > lo]
            samples = [
                s for chunk in pool.map(_process_fetch_chunk, chunks)
                for s in chunk
            ]
            yield self._assemble(b, val, samples)


_LIVE_POOLS: list = []
_REAPER_INSTALLED = False


def _install_pool_reaper() -> None:
    """Terminate any still-live worker pool at interpreter exit.  atexit
    hooks run LIFO, so installing ours lazily (after multiprocessing has
    registered its own) guarantees pools are already dead when the stdlib's
    exit machinery would otherwise block joining their queue threads."""
    global _REAPER_INSTALLED
    if _REAPER_INSTALLED:
        return
    import atexit
    # Force multiprocessing.util's atexit.register(_exit_function) to
    # happen BEFORE ours: it is lazily imported only inside Pool(...), so
    # without this import the first-ever pool would register our hook
    # first and LIFO would run mp's exit machinery before the reap —
    # exactly the inversion this function exists to prevent.
    import multiprocessing.util  # noqa: F401

    def _reap():
        for p in list(_LIVE_POOLS):
            try:
                p.terminate()
                p.join()
            except Exception:  # noqa: BLE001 — exit path, best effort
                pass
        _LIVE_POOLS.clear()

    atexit.register(_reap)
    _REAPER_INSTALLED = True


_PROC_DATASET = None  # per-worker global, set by _process_init


def _process_init(dataset) -> None:
    global _PROC_DATASET
    _PROC_DATASET = dataset


def _process_fetch(args):
    index, valid, seed, epoch = args
    if not valid:
        return None  # padding slot
    rng = np.random.default_rng((seed, epoch, index))
    ds = _PROC_DATASET
    if hasattr(ds, "get"):
        return ds.get(index, rng)
    return ds[index]


def _process_fetch_chunk(chunk):
    """One task per worker per batch: fetch a whole contiguous chunk."""
    return [_process_fetch(a) for a in chunk]


class AsyncFeeder:
    """Generic async host→device pipeline with prefetch depth ≥ 2.

    A producer thread pulls host items, runs ``put`` on each (host work +
    async device transfer dispatch), and queues the results; the consumer
    generator yields them.  ``DeviceFeeder`` (image batches) and the LM
    token pipeline (train/lm.py) are both instances — the machinery that
    replaces the apex CUDA-stream ``data_prefetcher``
    (reference apex_distributed.py:115-169).

    Wait accounting (obs/stepattr.py's data_wait component, ISSUE 20):
    the feeder times how long the *consumer* sat blocked on an empty
    queue — ``wait_ms_last`` / ``wait_ms_ema`` read as "the producer
    couldn't keep up by this much".  Zero when prefetch hides the host
    work entirely; the number an input-starved rank shows in its
    heartbeats.
    """

    _EMA_ALPHA = 0.1

    def __init__(self, put, prefetch: int = 2):
        self.put = put
        self.prefetch = max(1, prefetch)
        self.wait_ms_last = 0.0
        self.wait_ms_ema: Optional[float] = None

    def _note_wait(self, waited_s: float) -> None:
        self.wait_ms_last = waited_s * 1e3
        if self.wait_ms_ema is None:
            self.wait_ms_ema = self.wait_ms_last
        else:
            self.wait_ms_ema += self._EMA_ALPHA * (
                self.wait_ms_last - self.wait_ms_ema)

    def __call__(self, host_iter) -> Iterator:
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = object()

        dead = threading.Event()

        def offer(item) -> bool:
            """Put with a liveness check so an abandoned consumer (early
            ``break``/``close()`` out of the epoch loop) can't leave this
            thread blocked forever on a full queue."""
            while not dead.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            # Exceptions must surface at the consumer, not die in the thread —
            # otherwise a bad batch silently truncates the epoch.
            try:
                for batch in host_iter:
                    if dead.is_set() or not offer(self.put(batch)):
                        return
                offer(stop)
            except BaseException as e:  # noqa: BLE001 — re-raised at consumer
                offer(e)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                t0 = time.perf_counter()
                item = q.get()
                self._note_wait(time.perf_counter() - t0)
                if item is stop:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            dead.set()
            t.join(timeout=5.0)


class DeviceFeeder:
    """Async host→device pipeline with prefetch depth ≥ 2.

    Wraps a host-batch iterable; yields global ``jax.Array``s laid out as
    ``PartitionSpec('data')`` over the mesh's data axis.  In multi-process
    jobs each process contributes its local shard
    (``jax.make_array_from_process_local_data``), the TPU-native equivalent of
    per-rank DistributedSampler shards landing on per-rank GPUs.
    """

    def __init__(self, mesh: Mesh, data_axis: str = "data", prefetch: int = 2):
        self.mesh = mesh
        self.data_axis = data_axis
        self.prefetch = max(1, prefetch)
        self._dev_norm = None  # built lazily on first uint8 batch

    def _shardings(self) -> Dict[str, NamedSharding]:
        spec = P(self.data_axis)
        return {
            "images": NamedSharding(self.mesh, spec),
            "labels": NamedSharding(self.mesh, spec),
            "weights": NamedSharding(self.mesh, spec),
        }

    def _put(self, batch: Batch) -> Dict[str, jax.Array]:
        n_shards = self.mesh.shape[self.data_axis]
        bsz = next(iter(batch.values())).shape[0] * jax.process_count()
        if bsz % n_shards:
            raise ValueError(
                f"global batch {bsz} must divide the '{self.data_axis}' mesh "
                f"axis ({n_shards} shards); pick a per-process batch that is a "
                f"multiple of {n_shards // jax.process_count() or 1}"
            )
        sh = self._shardings()
        out = {
            k: jax.make_array_from_process_local_data(sh[k], v)
            for k, v in batch.items()
        }
        if out["images"].dtype == jnp.uint8:
            # u8_wire mode: the batch crossed the wire as uint8; normalize on
            # device (fused by XLA; replaces the apex GPU-side sub_/div_,
            # reference apex_distributed.py:123-158 — minus its
            # double-normalize quirk, SURVEY.md §7.5).
            if self._dev_norm is None:
                from pytorch_distributed_tpu.data.transforms import (
                    IMAGENET_MEAN,
                    IMAGENET_STD,
                )

                mean = jnp.asarray(IMAGENET_MEAN)
                std = jnp.asarray(IMAGENET_STD)
                self._dev_norm = jax.jit(
                    lambda x: (x.astype(jnp.float32) / 255.0 - mean) / std
                )
            out["images"] = self._dev_norm(out["images"])
        return out

    def __call__(self, host_iter) -> Iterator[Dict[str, jax.Array]]:
        return AsyncFeeder(self._put, self.prefetch)(host_iter)
