"""Deterministic per-rank index sharding with epoch reshuffle.

Capability parity with ``torch.utils.data.distributed.DistributedSampler``
as the reference uses it (reference distributed.py:174-175,190-195 and the
``set_epoch`` calls at :202-203):

- global permutation seeded by ``(seed, epoch)`` — every rank computes the
  same permutation with no communication
- pad by wrapping from the start so length divides evenly, then strided
  assignment ``indices[rank::world]``
- ``shuffle=False`` mode for validation (sequential, still padded+sharded)

TPU-first deltas:

- also emits a 0/1 *validity* mask per index so padded duplicates can be
  masked out in-graph, making sharded eval exact (SURVEY.md §7.4 item 3);
- the permutation uses numpy's seeded Generator (host-side), keeping the
  device program free of data-dependent shapes.
"""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np


class DistributedShardSampler:
    def __init__(
        self,
        dataset_len: int,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if not 0 <= rank < num_replicas:
            raise ValueError(f"rank {rank} out of range for world {num_replicas}")
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.num_samples = dataset_len // num_replicas
        else:
            self.num_samples = -(-dataset_len // num_replicas)  # ceil
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle for a new epoch (reference distributed.py:202-203)."""
        self.epoch = epoch

    def state_dict(self) -> dict:
        """The iterator RNG state a step-granular checkpoint records:
        ``(seed, epoch)`` fully determines the global permutation (computed
        identically on every rank with no communication), so restoring
        these two integers + a step offset reproduces the exact remaining
        index stream — no index lists on disk."""
        return {"seed": int(self.seed), "epoch": int(self.epoch)}

    def load_state_dict(self, state: dict) -> None:
        """Restore ``(seed, epoch)`` from a checkpoint's ft record."""
        self.seed = int(state["seed"])
        self.epoch = int(state["epoch"])

    def global_indices(self) -> Tuple[np.ndarray, np.ndarray]:
        """(indices, valid) after shuffle+pad, before rank sharding."""
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            idx = rng.permutation(self.dataset_len)
        else:
            idx = np.arange(self.dataset_len)
        valid = np.ones(self.dataset_len, dtype=np.int32)
        if self.drop_last:
            idx = idx[: self.total_size]
            valid = valid[: self.total_size]
        elif self.total_size > self.dataset_len:
            pad = self.total_size - self.dataset_len
            # Wrap-pad like DistributedSampler: repeat from the front.
            reps = -(-pad // self.dataset_len)
            extra = np.tile(idx, reps)[:pad]
            idx = np.concatenate([idx, extra])
            valid = np.concatenate([valid, np.zeros(pad, dtype=np.int32)])
        return idx, valid

    def shard(self) -> Tuple[np.ndarray, np.ndarray]:
        """This rank's (indices, valid), strided like DistributedSampler."""
        idx, valid = self.global_indices()
        return idx[self.rank :: self.num_replicas], valid[self.rank :: self.num_replicas]

    def __iter__(self) -> Iterator[int]:
        return iter(self.shard()[0].tolist())

    def __len__(self) -> int:
        return self.num_samples
