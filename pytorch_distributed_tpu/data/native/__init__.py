"""Native (C++) host data plane — see ptd_data.cpp / binding.py."""

from pytorch_distributed_tpu.data.native.binding import (
    decode_crop_resize_batch,
    jpeg_native_available,
    native_available,
    normalize_batch,
)

__all__ = [
    "decode_crop_resize_batch",
    "jpeg_native_available",
    "native_available",
    "normalize_batch",
]
