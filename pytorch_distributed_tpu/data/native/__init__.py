"""Native (C++) host-side data plane.  See native.py for the ctypes binding."""

from pytorch_distributed_tpu.data.native.binding import (
    native_available,
    normalize_batch,
)

__all__ = ["native_available", "normalize_batch"]
