// Native host-side data plane: batch assembly + augmentation hot loop.
//
// The reference's input-pipeline native layer is CUDA streams + GPU-side
// normalize inside apex's data_prefetcher (reference apex_distributed.py:
// 115-169: side-stream H2D copy overlap, sub_/div_ on device).  On TPU the
// copy overlap lives in the DeviceFeeder's async transfers; the *byte-level*
// per-sample work (uint8 -> float normalize, horizontal flip, NHWC batch
// assembly) is the host hot loop, and doing it per-sample in Python/numpy
// costs more CPU than JPEG decode itself at v5e feed rates (SURVEY.md §7.4
// item 4).  This library does that work in C++ with the GIL released,
// multithreaded, writing straight into the caller-provided batch buffer.
//
// Exposed via ctypes (no pybind11 in the image); see native.py.
//
// Build: g++ -O3 -march=native -shared -fPIC -o libptd_data.so ptd_data.cpp -lpthread

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Normalize + optional horizontal flip for one contiguous uint8 NHWC batch.
//   in:    [n, h, w, 3] uint8
//   out:   [n, h, w, 3] float32, out = (in/255 - mean[c]) / std[c]
//   flip:  [n] uint8, nonzero => mirror horizontally
// n_threads <= 0 picks hardware_concurrency.
void ptd_normalize_batch(const uint8_t* in, float* out, int64_t n, int64_t h,
                         int64_t w, const float* mean, const float* stddev,
                         const uint8_t* flip, int n_threads) {
  // Precompute the 256-entry lookup table per channel: (v/255 - mean)/std.
  float lut[3][256];
  for (int c = 0; c < 3; ++c) {
    const float inv = 1.0f / stddev[c];
    for (int v = 0; v < 256; ++v) {
      lut[c][v] = (static_cast<float>(v) * (1.0f / 255.0f) - mean[c]) * inv;
    }
  }
  const int64_t row = w * 3;
  const int64_t img = h * row;

  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* src = in + i * img;
      float* dst = out + i * img;
      const bool f = flip != nullptr && flip[i] != 0;
      for (int64_t y = 0; y < h; ++y) {
        const uint8_t* srow = src + y * row;
        float* drow = dst + y * row;
        if (!f) {
          for (int64_t x = 0; x < row; x += 3) {
            drow[x] = lut[0][srow[x]];
            drow[x + 1] = lut[1][srow[x + 1]];
            drow[x + 2] = lut[2][srow[x + 2]];
          }
        } else {
          for (int64_t x = 0; x < w; ++x) {
            const uint8_t* sp = srow + (w - 1 - x) * 3;
            float* dp = drow + x * 3;
            dp[0] = lut[0][sp[0]];
            dp[1] = lut[1][sp[1]];
            dp[2] = lut[2][sp[2]];
          }
        }
      }
    }
  };

  int threads = n_threads > 0
                    ? n_threads
                    : static_cast<int>(std::thread::hardware_concurrency());
  if (threads <= 1 || n <= 1) {
    work(0, n);
    return;
  }
  if (threads > n) threads = static_cast<int>(n);
  std::vector<std::thread> pool;
  const int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back(work, lo, hi);
  }
  for (auto& th : pool) th.join();
}

int ptd_data_abi_version() { return 1; }

}  // extern "C"
