// Native host-side data plane: batch assembly + augmentation hot loop.
//
// The reference's input-pipeline native layer is CUDA streams + GPU-side
// normalize inside apex's data_prefetcher (reference apex_distributed.py:
// 115-169: side-stream H2D copy overlap, sub_/div_ on device).  On TPU the
// copy overlap lives in the DeviceFeeder's async transfers; the *byte-level*
// per-sample work (uint8 -> float normalize, horizontal flip, NHWC batch
// assembly) is the host hot loop, and doing it per-sample in Python/numpy
// costs more CPU than JPEG decode itself at v5e feed rates (SURVEY.md §7.4
// item 4).  This library does that work in C++ with the GIL released,
// multithreaded, writing straight into the caller-provided batch buffer.
//
// Exposed via ctypes (no pybind11 in the image); see native.py.
//
// Build: g++ -O3 -march=native -shared -fPIC -o libptd_data.so ptd_data.cpp -lpthread

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Normalize + optional horizontal flip for one contiguous uint8 NHWC batch.
//   in:    [n, h, w, 3] uint8
//   out:   [n, h, w, 3] float32, out = (in/255 - mean[c]) / std[c]
//   flip:  [n] uint8, nonzero => mirror horizontally
// n_threads <= 0 picks hardware_concurrency.
void ptd_normalize_batch(const uint8_t* in, float* out, int64_t n, int64_t h,
                         int64_t w, const float* mean, const float* stddev,
                         const uint8_t* flip, int n_threads) {
  // Precompute the 256-entry lookup table per channel: (v/255 - mean)/std.
  float lut[3][256];
  for (int c = 0; c < 3; ++c) {
    const float inv = 1.0f / stddev[c];
    for (int v = 0; v < 256; ++v) {
      lut[c][v] = (static_cast<float>(v) * (1.0f / 255.0f) - mean[c]) * inv;
    }
  }
  const int64_t row = w * 3;
  const int64_t img = h * row;

  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* src = in + i * img;
      float* dst = out + i * img;
      const bool f = flip != nullptr && flip[i] != 0;
      for (int64_t y = 0; y < h; ++y) {
        const uint8_t* srow = src + y * row;
        float* drow = dst + y * row;
        if (!f) {
          for (int64_t x = 0; x < row; x += 3) {
            drow[x] = lut[0][srow[x]];
            drow[x + 1] = lut[1][srow[x + 1]];
            drow[x + 2] = lut[2][srow[x + 2]];
          }
        } else {
          for (int64_t x = 0; x < w; ++x) {
            const uint8_t* sp = srow + (w - 1 - x) * 3;
            float* dp = drow + x * 3;
            dp[0] = lut[0][sp[0]];
            dp[1] = lut[1][sp[1]];
            dp[2] = lut[2][sp[2]];
          }
        }
      }
    }
  };

  int threads = n_threads > 0
                    ? n_threads
                    : static_cast<int>(std::thread::hardware_concurrency());
  if (threads <= 1 || n <= 1) {
    work(0, n);
    return;
  }
  if (threads > n) threads = static_cast<int>(n);
  std::vector<std::thread> pool;
  const int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back(work, lo, hi);
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"

// ---------------------------------------------------------------------------
// JPEG decode + crop + bilinear resize (the expensive half of the input
// pipeline the round-1 build left in Python/PIL).  libjpeg(-turbo) with DCT
// scaling: when the crop region is still larger than the output, decoding at
// 1/2, 1/4 or 1/8 DCT scale skips most of the IDCT work before the bilinear
// pass — the standard fast-loader trick.
// ---------------------------------------------------------------------------

#ifndef PTD_NO_JPEG

#include <csetjmp>
#include <cmath>
#include <cstdio>
#include <jpeglib.h>

namespace {

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Bilinear sample of src (sh x sw x 3, u8) region [y0,y0+ch) x [x0,x0+cw)
// into dst (oh x ow x 3).
void bilinear_crop_resize(const uint8_t* src, int sw, int sh, float x0,
                          float y0, float cw, float ch, uint8_t* dst, int ow,
                          int oh) {
  const float sx = cw / ow;
  const float sy = ch / oh;
  for (int oy = 0; oy < oh; ++oy) {
    float fy = y0 + (oy + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    if (fy > sh - 1) fy = static_cast<float>(sh - 1);
    const int y_lo = static_cast<int>(fy);
    const int y_hi = y_lo + 1 < sh ? y_lo + 1 : sh - 1;
    const float wy = fy - y_lo;
    const uint8_t* r0 = src + static_cast<int64_t>(y_lo) * sw * 3;
    const uint8_t* r1 = src + static_cast<int64_t>(y_hi) * sw * 3;
    uint8_t* drow = dst + static_cast<int64_t>(oy) * ow * 3;
    for (int ox = 0; ox < ow; ++ox) {
      float fx = x0 + (ox + 0.5f) * sx - 0.5f;
      if (fx < 0) fx = 0;
      if (fx > sw - 1) fx = static_cast<float>(sw - 1);
      const int x_lo = static_cast<int>(fx);
      const int x_hi = x_lo + 1 < sw ? x_lo + 1 : sw - 1;
      const float wx = fx - x_lo;
      const float w00 = (1 - wy) * (1 - wx), w01 = (1 - wy) * wx;
      const float w10 = wy * (1 - wx), w11 = wy * wx;
      for (int c = 0; c < 3; ++c) {
        const float v = w00 * r0[x_lo * 3 + c] + w01 * r0[x_hi * 3 + c] +
                        w10 * r1[x_lo * 3 + c] + w11 * r1[x_hi * 3 + c];
        drow[ox * 3 + c] = static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

// Decode one JPEG; returns 0 on success.  Output crop+resize semantics:
//   params != null (train): single-attempt RandomResizedCrop — params =
//     (area_frac, log_ratio, u, v); crop size from the ORIGINAL dims, then
//     clamped; position from (u, v).
//   params == null (eval): resize shorter side to `resize_short`, center
//     crop (out_w, out_h).
int decode_one(const uint8_t* blob, int64_t len, const float* params,
               int out_w, int out_h, int resize_short, uint8_t* out,
               std::vector<uint8_t>& scratch) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(blob),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  const int W = static_cast<int>(cinfo.image_width);
  const int H = static_cast<int>(cinfo.image_height);

  // Crop box in original coordinates.
  float cw, ch, cx0, cy0;
  if (params != nullptr) {
    const float area_frac = params[0];
    const float ratio = std::exp(params[1]);
    const float target_area = area_frac * W * H;
    cw = std::sqrt(target_area * ratio);
    ch = std::sqrt(target_area / ratio);
    if (cw > W) cw = static_cast<float>(W);
    if (ch > H) ch = static_cast<float>(H);
    if (cw < 1) cw = 1;
    if (ch < 1) ch = 1;
    cx0 = params[2] * (W - cw);
    cy0 = params[3] * (H - ch);
  } else {
    // eval: emulate Resize(short)+CenterCrop(out) as one crop+resize: the
    // crop is the centered region that maps onto out under short-side scale.
    const float scale = static_cast<float>(resize_short) /
                        (W < H ? W : H);
    cw = out_w / scale;
    ch = out_h / scale;
    if (cw > W) cw = static_cast<float>(W);
    if (ch > H) ch = static_cast<float>(H);
    cx0 = (W - cw) * 0.5f;
    cy0 = (H - ch) * 0.5f;
  }

  // DCT scale: decode at 1/k while the scaled crop still covers the output.
  int denom = 1;
  while (denom < 8 && cw / (denom * 2) >= out_w && ch / (denom * 2) >= out_h)
    denom *= 2;
  cinfo.scale_num = 1;
  cinfo.scale_denom = static_cast<unsigned>(denom);
  cinfo.out_color_space = JCS_RGB;
  cinfo.dct_method = JDCT_IFAST;
  jpeg_start_decompress(&cinfo);
  const int sw = static_cast<int>(cinfo.output_width);
  const int sh = static_cast<int>(cinfo.output_height);
  scratch.resize(static_cast<size_t>(sw) * sh * 3);
  JSAMPROW rows[1];
  while (cinfo.output_scanline < cinfo.output_height) {
    rows[0] = scratch.data() + static_cast<size_t>(cinfo.output_scanline) * sw * 3;
    jpeg_read_scanlines(&cinfo, rows, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);

  // Map the crop box into the scaled image's coordinates.
  const float fx = static_cast<float>(sw) / W;
  const float fy = static_cast<float>(sh) / H;
  bilinear_crop_resize(scratch.data(), sw, sh, cx0 * fx, cy0 * fy, cw * fx,
                       ch * fy, out, out_w, out_h);
  return 0;
}

}  // namespace

extern "C" {

// Batch JPEG decode+crop+resize into a caller-provided [n, out_h, out_w, 3]
// uint8 buffer.  blobs = concatenated JPEG bytes, offsets = n+1 boundaries.
// params: [n, 4] train crop draws, or null for eval semantics.
// failed: optional [n] u8 flags, set to 1 for slots that failed to decode
// (those slots are zeroed).  Returns the failure count.
int ptd_decode_crop_resize_batch(const uint8_t* blobs, const int64_t* offsets,
                                 int64_t n, const float* params, int out_h,
                                 int out_w, int resize_short, uint8_t* out,
                                 uint8_t* failed, int n_threads) {
  const int64_t img_bytes = static_cast<int64_t>(out_h) * out_w * 3;
  std::vector<int> failures_per_thread;
  auto work = [&](int64_t lo, int64_t hi, int* failures) {
    std::vector<uint8_t> scratch;
    for (int64_t i = lo; i < hi; ++i) {
      uint8_t* dst = out + i * img_bytes;
      const float* p = params != nullptr ? params + i * 4 : nullptr;
      const int64_t len = offsets[i + 1] - offsets[i];
      const bool ok =
          len > 0 && decode_one(blobs + offsets[i], len, p, out_w, out_h,
                                resize_short, dst, scratch) == 0;
      if (failed != nullptr) failed[i] = ok ? 0 : 1;
      if (!ok) {
        std::memset(dst, 0, static_cast<size_t>(img_bytes));
        ++*failures;
      }
    }
  };
  int threads = n_threads > 0
                    ? n_threads
                    : static_cast<int>(std::thread::hardware_concurrency());
  if (threads > n) threads = static_cast<int>(n);
  if (threads <= 1) {
    int failures = 0;
    work(0, n, &failures);
    return failures;
  }
  failures_per_thread.assign(threads, 0);
  std::vector<std::thread> pool;
  const int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back(work, lo, hi, &failures_per_thread[t]);
  }
  for (auto& th : pool) th.join();
  int failures = 0;
  for (int f : failures_per_thread) failures += f;
  return failures;
}

}  // extern "C"

#else  // PTD_NO_JPEG: platform without libjpeg; decode reports unavailable.

extern "C" int ptd_decode_crop_resize_batch(const uint8_t*, const int64_t*,
                                            int64_t, const float*, int, int,
                                            int, uint8_t*, uint8_t*, int) {
  return -1;
}

#endif  // PTD_NO_JPEG

extern "C" int ptd_data_abi_version() { return 3; }
