"""ctypes binding for the C++ data-plane library (ptd_data.cpp).

Builds the shared library on first use if g++ is available (the image bakes
the native toolchain; pybind11 is not present, hence ctypes).  Falls back to
a numpy implementation with identical semantics when no compiler exists, so
the framework stays importable anywhere.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "ptd_data.cpp")
_LIB_PATH = os.path.join(_HERE, "libptd_data.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


_ABI = 3
_ABI_SIDECAR = _LIB_PATH + ".abi"


def _build() -> bool:
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
            "-o", _LIB_PATH, _SRC, "-lpthread"]
    for cmd in (base + ["-ljpeg"], base + ["-DPTD_NO_JPEG"]):
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            # ABI sidecar lets _load verify the artifact WITHOUT dlopening:
            # dlopen dedupes by pathname, so a rebuild after a bad in-process
            # load could never take effect (round-2 review finding).
            with open(_ABI_SIDECAR, "w") as f:
                f.write(str(_ABI))
            return True
        except (OSError, subprocess.SubprocessError):
            continue
    return False


def _sidecar_ok() -> bool:
    try:
        with open(_ABI_SIDECAR) as f:
            return int(f.read().strip()) == _ABI
    except (OSError, ValueError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        stale = (
            not os.path.exists(_LIB_PATH)
            or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
            or not _sidecar_ok()
        )
        if stale and not _build():
            return None
        lib = _open()
        if lib is not None and lib.ptd_data_abi_version() != _ABI:
            lib = None  # sidecar lied (hand-copied .so); disable
        _lib = lib
        return _lib


def _open() -> Optional[ctypes.CDLL]:
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.ptd_normalize_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
    ]
    lib.ptd_normalize_batch.restype = None
    lib.ptd_decode_crop_resize_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
    ]
    lib.ptd_decode_crop_resize_batch.restype = ctypes.c_int
    lib.ptd_data_abi_version.restype = ctypes.c_int
    return lib


def native_available() -> bool:
    return _load() is not None


def normalize_batch(
    images_u8: np.ndarray,
    mean: np.ndarray,
    std: np.ndarray,
    flip: Optional[np.ndarray] = None,
    n_threads: int = 0,
) -> np.ndarray:
    """(u8 NHWC batch / 255 - mean) / std, with optional per-sample hflip.

    C++ fast path when available; numpy fallback otherwise (bit-identical up
    to f32 rounding — tested in tests/test_native.py).
    """
    assert images_u8.dtype == np.uint8 and images_u8.ndim == 4
    n, h, w, c = images_u8.shape
    assert c == 3, "NHWC RGB expected"
    mean = np.ascontiguousarray(mean, dtype=np.float32)
    std = np.ascontiguousarray(std, dtype=np.float32)
    lib = _load()
    if lib is not None:
        images_u8 = np.ascontiguousarray(images_u8)
        out = np.empty((n, h, w, c), dtype=np.float32)
        flip_arr = (
            np.ascontiguousarray(flip, dtype=np.uint8) if flip is not None else None
        )
        lib.ptd_normalize_batch(
            images_u8.ctypes.data, out.ctypes.data,
            n, h, w,
            mean.ctypes.data, std.ctypes.data,
            flip_arr.ctypes.data if flip_arr is not None else None,
            n_threads,
        )
        return out
    # numpy fallback, same semantics
    imgs = images_u8.astype(np.float32) / 255.0
    if flip is not None:
        idx = np.nonzero(flip)[0]
        imgs[idx] = imgs[idx, :, ::-1, :]
    return (imgs - mean) / std


def jpeg_native_available() -> bool:
    """True when the library is loaded AND was built against libjpeg."""
    lib = _load()
    if lib is None:
        return False
    # A PTD_NO_JPEG build returns -1 unconditionally; probe with n=0.
    empty = np.zeros(1, np.int64)
    return lib.ptd_decode_crop_resize_batch(
        None, empty.ctypes.data, 0, None, 1, 1, 1, None, None, 1) == 0


def decode_crop_resize_batch(
    blobs,
    out_size: int,
    params: Optional[np.ndarray] = None,
    resize_short: int = 0,
    n_threads: int = 0,
    return_failed: bool = False,
):
    """Batch JPEG decode + crop + bilinear resize → uint8 [n, S, S, 3].

    ``blobs``: list of JPEG byte strings.  ``params``: [n, 4] float32 train
    crop draws (area_frac, log_ratio, u, v) for single-attempt
    RandomResizedCrop semantics; None = eval (short-side ``resize_short`` +
    center crop).  Corrupt blobs come back as zeroed slots; pass
    ``return_failed=True`` to also get the per-image failure mask (the
    loader uses it to zero those samples' weights so they drop out of
    loss/metrics instead of training on black images).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native data plane unavailable (no compiler?)")
    n = len(blobs)
    offsets = np.zeros(n + 1, np.int64)
    for i, b in enumerate(blobs):
        offsets[i + 1] = offsets[i] + len(b)
    concat = np.frombuffer(b"".join(blobs), dtype=np.uint8) if n else np.zeros(0, np.uint8)
    out = np.empty((n, out_size, out_size, 3), np.uint8)
    failed = np.zeros(n, np.uint8)
    p = None
    if params is not None:
        p = np.ascontiguousarray(params, dtype=np.float32)
        assert p.shape == (n, 4)
    rc = lib.ptd_decode_crop_resize_batch(
        concat.ctypes.data if n else None,
        offsets.ctypes.data, n,
        p.ctypes.data if p is not None else None,
        out_size, out_size,
        resize_short or int(out_size * 256 / 224),
        out.ctypes.data, failed.ctypes.data, n_threads,
    )
    if rc < 0:
        raise RuntimeError("native library built without libjpeg")
    return (out, failed.astype(bool)) if return_failed else out
