"""ctypes binding for the C++ data-plane library (ptd_data.cpp).

Builds the shared library on first use if g++ is available (the image bakes
the native toolchain; pybind11 is not present, hence ctypes).  Falls back to
a numpy implementation with identical semantics when no compiler exists, so
the framework stays importable anywhere.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "ptd_data.cpp")
_LIB_PATH = os.path.join(_HERE, "libptd_data.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        "-o", _LIB_PATH, _SRC, "-lpthread",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) or os.path.getmtime(
            _LIB_PATH
        ) < os.path.getmtime(_SRC):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.ptd_normalize_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        lib.ptd_normalize_batch.restype = None
        lib.ptd_data_abi_version.restype = ctypes.c_int
        if lib.ptd_data_abi_version() != 1:
            return None
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def normalize_batch(
    images_u8: np.ndarray,
    mean: np.ndarray,
    std: np.ndarray,
    flip: Optional[np.ndarray] = None,
    n_threads: int = 0,
) -> np.ndarray:
    """(u8 NHWC batch / 255 - mean) / std, with optional per-sample hflip.

    C++ fast path when available; numpy fallback otherwise (bit-identical up
    to f32 rounding — tested in tests/test_native.py).
    """
    assert images_u8.dtype == np.uint8 and images_u8.ndim == 4
    n, h, w, c = images_u8.shape
    assert c == 3, "NHWC RGB expected"
    mean = np.ascontiguousarray(mean, dtype=np.float32)
    std = np.ascontiguousarray(std, dtype=np.float32)
    lib = _load()
    if lib is not None:
        images_u8 = np.ascontiguousarray(images_u8)
        out = np.empty((n, h, w, c), dtype=np.float32)
        flip_arr = (
            np.ascontiguousarray(flip, dtype=np.uint8) if flip is not None else None
        )
        lib.ptd_normalize_batch(
            images_u8.ctypes.data, out.ctypes.data,
            n, h, w,
            mean.ctypes.data, std.ctypes.data,
            flip_arr.ctypes.data if flip_arr is not None else None,
            n_threads,
        )
        return out
    # numpy fallback, same semantics
    imgs = images_u8.astype(np.float32) / 255.0
    if flip is not None:
        idx = np.nonzero(flip)[0]
        imgs[idx] = imgs[idx, :, ::-1, :]
    return (imgs - mean) / std
