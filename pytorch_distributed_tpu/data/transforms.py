"""Host-side image transforms (numpy/PIL), torchvision-equivalent.

Capability parity with the reference's transform stacks:

- train: ``RandomResizedCrop(224) → RandomHorizontalFlip → ToTensor →
  Normalize(mean, std)`` (reference distributed.py:166-173)
- eval:  ``Resize(256) → CenterCrop(224) → ToTensor → Normalize``
  (reference distributed.py:182-189)

TPU-first layout delta: output is **NHWC float32 in [0,1] then normalized**
(channels-last is XLA's preferred conv layout on TPU), where torch uses NCHW.
Normalization constants are the same ImageNet mean/std.

Each transform is a callable ``(rng, image) -> image`` on numpy arrays or PIL
images; randomness is an explicit ``np.random.Generator`` so per-epoch
determinism flows from the sampler seed (reference ``--seed`` semantics,
distributed.py:116-124).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], dtype=np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], dtype=np.float32)


def _to_pil(img):
    from PIL import Image

    if isinstance(img, np.ndarray):
        return Image.fromarray(img)
    return img


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, rng: np.random.Generator, img):
        for t in self.transforms:
            img = t(rng, img)
        return img


class RandomResizedCrop:
    """Random area/aspect crop then resize — torchvision semantics
    (scale 0.08-1.0, log-uniform aspect 3/4-4/3, 10 tries then center fallback)."""

    def __init__(self, size: int, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = size
        self.scale = scale
        self.ratio = ratio

    def __call__(self, rng: np.random.Generator, img):
        img = _to_pil(img)
        w, h = img.size
        area = w * h
        for _ in range(10):
            target_area = area * rng.uniform(*self.scale)
            log_ratio = (np.log(self.ratio[0]), np.log(self.ratio[1]))
            aspect = np.exp(rng.uniform(*log_ratio))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                x = int(rng.integers(0, w - cw + 1))
                y = int(rng.integers(0, h - ch + 1))
                img = img.crop((x, y, x + cw, y + ch))
                return img.resize((self.size, self.size), resample=2)  # BILINEAR
        # Fallback: center crop of the constrained aspect.
        in_ratio = w / h
        if in_ratio < self.ratio[0]:
            cw, ch = w, int(round(w / self.ratio[0]))
        elif in_ratio > self.ratio[1]:
            cw, ch = int(round(h * self.ratio[1])), h
        else:
            cw, ch = w, h
        x, y = (w - cw) // 2, (h - ch) // 2
        return img.crop((x, y, x + cw, y + ch)).resize(
            (self.size, self.size), resample=2
        )


class RandomHorizontalFlip:
    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, rng: np.random.Generator, img):
        if rng.random() < self.p:
            img = _to_pil(img).transpose(0)  # FLIP_LEFT_RIGHT
        return img


class Resize:
    """Shorter side → ``size`` keeping aspect (torchvision Resize(int))."""

    def __init__(self, size: int):
        self.size = size

    def __call__(self, rng: np.random.Generator, img):
        img = _to_pil(img)
        w, h = img.size
        if w <= h:
            nw, nh = self.size, max(1, int(round(h * self.size / w)))
        else:
            nw, nh = max(1, int(round(w * self.size / h))), self.size
        return img.resize((nw, nh), resample=2)


class CenterCrop:
    def __init__(self, size: int):
        self.size = size

    def __call__(self, rng: np.random.Generator, img):
        img = _to_pil(img)
        w, h = img.size
        x = max(0, (w - self.size) // 2)
        y = max(0, (h - self.size) // 2)
        return img.crop((x, y, x + self.size, y + self.size))


class ToArray:
    """PIL/uint8 → float32 NHWC in [0,1] (torchvision ToTensor, minus the
    NCHW permute — TPU convs want channels-last)."""

    def __call__(self, rng: np.random.Generator, img):
        arr = np.asarray(img, dtype=np.float32) / 255.0
        if arr.ndim == 2:
            arr = np.stack([arr] * 3, axis=-1)
        return arr


class Normalize:
    def __init__(self, mean=IMAGENET_MEAN, std=IMAGENET_STD):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)

    def __call__(self, rng: np.random.Generator, img):
        return (np.asarray(img, dtype=np.float32) - self.mean) / self.std


class ToU8:
    """PIL → uint8 HWC array (the u8-pipeline terminal: normalization and
    flip happen at batch level — C++ host path or on-device)."""

    def __call__(self, rng: np.random.Generator, img):
        arr = np.asarray(img, dtype=np.uint8)
        if arr.ndim == 2:
            arr = np.stack([arr] * 3, axis=-1)
        return arr


def train_transform(size: int = 224) -> Compose:
    """The reference's training stack (distributed.py:166-173)."""
    return Compose(
        [RandomResizedCrop(size), RandomHorizontalFlip(), ToArray(), Normalize()]
    )


def eval_transform(size: int = 224, resize: int = 256) -> Compose:
    """The reference's validation stack (distributed.py:182-189)."""
    return Compose([Resize(resize), CenterCrop(size), ToArray(), Normalize()])


def train_transform_u8(size: int = 224) -> Compose:
    """Training stack ending in uint8 (flip+normalize at batch level)."""
    return Compose([RandomResizedCrop(size), ToU8()])


def eval_transform_u8(size: int = 224, resize: int = 256) -> Compose:
    """Validation stack ending in uint8 (normalize at batch level)."""
    return Compose([Resize(resize), CenterCrop(size), ToU8()])
