"""Sharded, epoch-reshuffled, prefetching input pipeline.

Replaces the reference's ``ImageFolder`` + torchvision transforms +
``DistributedSampler`` + ``DataLoader`` (+ the apex CUDA-stream
``data_prefetcher``) stack (reference distributed.py:161-195,
apex_distributed.py:115-169) with a host-side numpy/PIL pipeline feeding
devices through double-buffered async transfers.
"""

from pytorch_distributed_tpu.data.sampler import DistributedShardSampler
from pytorch_distributed_tpu.data.datasets import SyntheticImageDataset, ImageFolder
from pytorch_distributed_tpu.data.loader import DataLoader, DeviceFeeder

__all__ = [
    "DistributedShardSampler",
    "SyntheticImageDataset",
    "ImageFolder",
    "DataLoader",
    "DeviceFeeder",
]
