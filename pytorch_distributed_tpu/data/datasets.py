"""Datasets: ImageFolder-compatible directory trees and synthetic data.

``ImageFolder`` has the reference's dataset semantics
(``datasets.ImageFolder(traindir, transform)``, reference
distributed.py:163-175): one subdirectory per class, sorted class names →
contiguous label ids, (image, label) samples.

``SyntheticImageDataset`` is the CI/bench workload the reference lacks
(SURVEY.md §7.2 step 2 "synthetic-data mode"): deterministic
pseudo-random images keyed by index, so tests and benchmarks never need
ImageNet on disk and input IO can be excluded from device benchmarks.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Tuple

import numpy as np

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".webp")


class ImageFolder:
    def __init__(
        self,
        root: str,
        transform: Optional[Callable] = None,
        native_decode: bool = False,
        image_size: int = 224,
        native_augment: bool = True,
    ):
        """``native_decode=True``: samples come back as raw JPEG bytes plus
        crop-draw parameters, and the loader decodes the whole batch in the
        C++ data plane (libjpeg + DCT-scaled crop/resize) — the expensive
        half of the input pipeline off Python (round-1 left only
        normalize/flip native).  Train augmentation (``native_augment``) is
        single-attempt RandomResizedCrop (torchvision draws with clamping
        instead of 10-attempt rejection — documented delta); eval is
        short-side-256/224·size + center crop.  Non-JPEG files fall back to
        the PIL u8 transform per sample."""
        self.root = root
        self.transform = transform
        self.native_decode = native_decode
        self.image_size = image_size
        self.native_augment = native_augment
        if native_decode:
            from pytorch_distributed_tpu.data.transforms import (
                eval_transform_u8,
                train_transform_u8,
            )

            # flip lives at the batch level (loader random_flip); the u8
            # stacks are already flip-free.  Eval resize scales with the
            # output size (256/224 ratio), matching the native JPEG path so
            # mixed JPEG/PNG val sets get one consistent preprocessing.
            self._fallback_tf = (
                train_transform_u8(image_size)
                if native_augment
                else eval_transform_u8(
                    image_size, resize=int(image_size * 256 / 224)
                )
            )
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        if not classes:
            raise FileNotFoundError(f"no class directories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: List[Tuple[str, int]] = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for f in sorted(files):
                    if f.lower().endswith(IMG_EXTENSIONS):
                        self.samples.append((os.path.join(dirpath, f), self.class_to_idx[c]))

    def __len__(self) -> int:
        return len(self.samples)

    def get(self, index: int, rng: Optional[np.random.Generator] = None):
        """Fetch with an explicit augmentation RNG; the loader passes a
        ``(seed, epoch, index)``-keyed generator so augmentations differ per
        epoch yet stay reproducible."""
        from PIL import Image

        path, label = self.samples[index]
        if rng is None:
            rng = np.random.default_rng(index)
        if self.native_decode:
            if path.lower().endswith((".jpg", ".jpeg")):
                with open(path, "rb") as f:
                    blob = f.read()
                if self.native_augment:
                    params = np.array(
                        [rng.uniform(0.08, 1.0),
                         rng.uniform(np.log(3 / 4), np.log(4 / 3)),
                         rng.random(), rng.random()],
                        np.float32,
                    )
                else:
                    params = None
                return ("jpeg", blob, params, label)
            with Image.open(path) as im:
                arr = np.asarray(self._fallback_tf(rng, im.convert("RGB")))
            return ("u8", arr, None, label)
        with Image.open(path) as im:
            img = im.convert("RGB")
            if self.transform is not None:
                img = np.asarray(self.transform(rng, img))
            else:
                img = np.asarray(img, dtype=np.float32) / 255.0
        # Preserve uint8 from the *_u8 stacks; everything else is f32.
        return (img if img.dtype == np.uint8 else img.astype(np.float32)), label

    def __getitem__(self, index: int) -> Tuple[np.ndarray, int]:
        return self.get(index)


class SyntheticImageDataset:
    """Deterministic fake (image, label) pairs, ImageFolder-shaped."""

    def __init__(
        self,
        length: int = 1280,
        num_classes: int = 1000,
        image_size: int = 224,
        transform: Optional[Callable] = None,
        seed: int = 0,
    ):
        self.length = length
        self.num_classes = num_classes
        self.image_size = image_size
        self.transform = transform
        self.seed = seed
        self.classes = [f"class_{i:04d}" for i in range(num_classes)]

    def __len__(self) -> int:
        return self.length

    def get(self, index: int, rng: Optional[np.random.Generator] = None) -> Tuple[np.ndarray, int]:
        # Content is keyed by (seed, index) only — the same sample every
        # epoch, like files on disk; ``rng`` drives augmentation randomness.
        content_rng = np.random.default_rng((self.seed, index))
        img = content_rng.integers(
            0, 256, size=(self.image_size, self.image_size, 3)
        ).astype(np.uint8)
        label = int(content_rng.integers(0, self.num_classes))
        if rng is None:
            rng = content_rng
        if self.transform is not None:
            out = np.asarray(self.transform(rng, img))
            return (out if out.dtype == np.uint8 else out.astype(np.float32)), label
        return img.astype(np.float32) / 255.0, label

    def __getitem__(self, index: int) -> Tuple[np.ndarray, int]:
        return self.get(index)
