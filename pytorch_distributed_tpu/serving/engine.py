"""Continuous-batching serving loop over the paged KV cache.

One jitted decode step per engine iteration, always at the full
``[max_batch]`` static shape: sequences join and leave the batch by
flipping slots and block-table rows, never by changing tensor shapes, so
the step compiles exactly once (the zero-recompile soak test pins this
with obs/watchdog.py).  The only host sync per decode iteration is the
single ``np.asarray`` pull of the sampled tokens.

Layers underneath compose transparently: int8 weight-only quant rides
the ``quant="int8"`` model variant (models/quant.py), and greedy
speculative decoding (``gamma > 0``) runs gamma+1 draft micro-steps plus
one target verification inside a single jitted round — rejection
correction keeps target-greedy outputs regardless of draft quality
(models/speculative.py semantics, re-derived over paged state).

``_make_steps`` is the shared lowering surface: the engine jits what it
returns, and analysis/core.py registers the same builders as the
``serve_prefill`` / ``serve_decode`` recipes so shardlint, the
comm/memory ledgers, and the compile budget all see serving traffic.
"""

from __future__ import annotations

import contextlib
import functools
import time
import types
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from pytorch_distributed_tpu.obs.metrics import _percentile
from pytorch_distributed_tpu.serving.kvpool import (
    BlockPool,
    apply_permutation,
    init_pools,
)
from pytorch_distributed_tpu.serving.loadgen import LoadConfig, generate_load
from pytorch_distributed_tpu.serving.scheduler import Request, Scheduler

MODES = ("continuous", "static")


def _pct_ms(samples, q: float) -> Optional[float]:
    """Nearest-rank percentile of a seconds-sample deque, in ms."""
    if not samples:
        return None
    return _percentile(sorted(samples), q) * 1e3


@functools.lru_cache(maxsize=8)
def _make_steps(vocab_size: int, d_model: int, n_heads: int, n_layers: int,
                block_size: int, temperature: float, top_k: int,
                top_p: float, quant: str):
    """Model + jitted prefill/decode step functions for one model config.

    lru_cached so the engine, the A/B experiment, and the analysis
    recipes all lower the SAME jitted callables — one compile per
    (config, shape) across the whole process, and the recipe lowerings
    in analysis/core.py are literally the functions the engine runs.
    """
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.generate import filter_logits
    from pytorch_distributed_tpu.serving.model import PagedTransformerLM

    model = PagedTransformerLM(
        vocab_size=vocab_size, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, block_size=block_size, quant=quant)

    def _pick(logits, key):
        if temperature <= 0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, filter_logits(logits, temperature, top_k, top_p)
        ).astype(jnp.int32)

    @jax.jit
    def decode_step(params, pk, pv, tokens, offsets, table, key):
        """tokens [B] fed at positions ``offsets`` -> next token [B]."""
        pos = offsets[:, None].astype(jnp.int32)
        logits, pk, pv = model.apply(
            {"params": params}, tokens[:, None], pk, pv, table, pos)
        return _pick(logits[:, -1, :], key), pk, pv

    @jax.jit
    def prefill_step(params, pk, pv, tokens, start, n_valid, table, key):
        """One prompt chunk ``tokens [1, C]`` at absolute positions
        ``start..start+C-1``; only the first ``n_valid`` lanes carry real
        prompt (padding writes land past the committed window and are
        overwritten before any mask exposes them).  Returns the seed
        token sampled at the last valid position."""
        C = tokens.shape[1]
        pos = (start + jnp.arange(C, dtype=jnp.int32))[None, :]
        logits, pk, pv = model.apply(
            {"params": params}, tokens, pk, pv, table, pos)
        last = jax.lax.dynamic_slice(
            logits, (0, n_valid - 1, 0), (1, 1, logits.shape[-1]))
        return _pick(last[:, -1, :], key)[0], pk, pv

    return types.SimpleNamespace(
        model=model, decode=decode_step, prefill=prefill_step)


def _make_spec_round(tsteps, dsteps, gamma: int):
    """One jitted greedy speculative round over paged state.

    gamma+1 draft micro-steps (the last feed exists only to commit the
    final draft token's KV), one target verification over
    ``[t_last, d_1..d_gamma]``, and in-jit acceptance: ``out[b, j]`` is
    the j-th committed token, ``-1`` past the accepted-plus-correction
    prefix — so the host pulls ONE array per round.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def spec_round(tp, dp, pk_t, pv_t, pk_d, pv_d, t_last, offsets, table):
        toks = [t_last]
        cur = t_last
        offs = offsets.astype(jnp.int32)
        for i in range(gamma):
            pos = (offs + i)[:, None]
            logits, pk_d, pv_d = dsteps.model.apply(
                {"params": dp}, cur[:, None], pk_d, pv_d, table, pos)
            cur = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            toks.append(cur)
        # extra feed: writes KV(d_gamma); its sampled output is discarded
        pos = (offs + gamma)[:, None]
        _, pk_d, pv_d = dsteps.model.apply(
            {"params": dp}, cur[:, None], pk_d, pv_d, table, pos)
        ver = jnp.stack(toks, axis=1)                       # [B, gamma+1]
        L = gamma + 1
        pos = offs[:, None] + jnp.arange(L, dtype=jnp.int32)[None, :]
        logits, pk_t, pv_t = tsteps.model.apply(
            {"params": tp}, ver, pk_t, pv_t, table, pos)
        g = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # target greedy
        p = ver[:, 1:]                                      # draft proposals
        eq = (g[:, :L - 1] == p).astype(jnp.int32)
        n_acc = jnp.cumprod(eq, axis=1).sum(axis=1)         # [B]
        corr = jnp.take_along_axis(g, n_acc[:, None], axis=1)
        j = jnp.arange(L, dtype=jnp.int32)[None, :]
        pfull = jnp.pad(p, ((0, 0), (0, 1)))
        out = jnp.where(j < n_acc[:, None], pfull,
                        jnp.where(j == n_acc[:, None], corr, -1))
        return out, pk_t, pv_t, pk_d, pv_d

    return spec_round


class ServingEngine:
    """Continuous-batching engine: paged KV + scheduler + jitted steps.

    ``mode="static"`` is the naive wave-batching baseline the A/B
    experiment measures against: a new wave is admitted only once every
    slot has drained, so short sequences idle behind the longest one.
    """

    def __init__(self, params, *, vocab_size: int, d_model: int,
                 n_heads: int, n_layers: int,
                 max_batch: int = 4, kv_blocks: int = 64,
                 block_size: int = 16, blocks_per_seq: int = 8,
                 chunk_size: int = 8, max_new_tokens: int = 16,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, quant: str = "",
                 gamma: int = 0, draft_params=None,
                 policy: str = "fcfs", mode: str = "continuous",
                 defrag_threshold_pct: float = 50.0,
                 obs=None, watchdog=None, chaos=None, trace=None,
                 stream: Optional[Callable[[int, int, str], None]] = None,
                 time_fn: Callable[[], float] = time.monotonic,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 seed: int = 0):
        import jax
        import jax.numpy as jnp

        if mode not in MODES:
            raise ValueError(f"unknown mode {mode!r}; expected {MODES}")
        if gamma > 0 and temperature > 0:
            raise ValueError("speculative serving is greedy-only: "
                             "gamma > 0 requires temperature <= 0")
        if gamma > 0 and draft_params is None:
            raise ValueError("gamma > 0 requires draft_params")
        self.params = params
        self.mode = mode
        self.gamma = int(gamma)
        self.max_batch = int(max_batch)
        self.block_size = int(block_size)
        self.chunk_size = int(chunk_size)
        self.max_new_tokens = int(max_new_tokens)
        self.defrag_threshold_pct = float(defrag_threshold_pct)
        self.obs = obs
        self.watchdog = watchdog
        self.chaos = chaos
        # per-request tracer (obs/reqtrace.ReqTracer).  Every hook below
        # is guarded by ``is not None`` so the untraced hot path pays
        # one branch; traced hooks are tuple appends + clock reads —
        # fenced <2% tokens/s in RESULTS_reqtrace.json.
        self.trace = trace
        self.stream = stream
        self._time_fn = time_fn
        self._sleep_fn = sleep_fn
        self._jnp = jnp
        self._key = jax.random.PRNGKey(seed)

        self.steps = _make_steps(vocab_size, d_model, n_heads, n_layers,
                                 block_size, float(temperature), int(top_k),
                                 float(top_p), quant)
        self.pool = BlockPool(kv_blocks, block_size, blocks_per_seq)
        head_dim = d_model // n_heads
        self.pk, self.pv = init_pools(
            n_layers, kv_blocks, block_size, n_heads, head_dim)
        self.sched = Scheduler(max_batch, policy=policy)

        self._spec_round = None
        if self.gamma > 0:
            d_layers = sum(1 for k in draft_params if k.startswith("block_"))
            d_model_d = draft_params["embed"]["embedding"].shape[1]
            self.draft_params = draft_params
            self.dsteps = _make_steps(
                vocab_size, int(d_model_d), n_heads, d_layers, block_size,
                float(temperature), int(top_k), float(top_p), "")
            self.dpk, self.dpv = init_pools(
                d_layers, kv_blocks, block_size, n_heads,
                int(d_model_d) // n_heads)
            self._spec_round = _make_spec_round(
                self.steps, self.dsteps, self.gamma)

        # per-slot device-batch state (host mirrors)
        self._offsets = np.zeros(self.max_batch, np.int32)
        self._last = np.zeros(self.max_batch, np.int32)
        self._last_emit = [0.0] * self.max_batch

        # SLO samples + counters
        self.ttft_samples: deque = deque(maxlen=512)
        self.itl_samples: deque = deque(maxlen=2048)
        self.total_tokens = 0
        self.finished: List[Request] = []
        self._step = 0
        self._t0: Optional[float] = None

    # ------------------------------------------------------------------ time
    def _now(self) -> float:
        if self._t0 is None:
            self._t0 = self._time_fn()
        return self._time_fn() - self._t0

    def _watch(self, label: str):
        if self.watchdog is None:
            return contextlib.nullcontext()
        return self.watchdog.watch(label, step=self._step)

    def _next_key(self):
        import jax

        self._key, sub = jax.random.split(self._key)
        return sub

    # ---------------------------------------------------------------- submit
    def submit(self, req: Request) -> None:
        cap = self.pool.capacity_tokens
        P = len(req.prompt)
        limit = cap - P + 1 - self.gamma
        if P > cap or limit < 1:
            raise ValueError(
                f"prompt of {P} tokens does not fit a {cap}-token block "
                f"table (gamma={self.gamma})")
        req.max_new_tokens = min(req.max_new_tokens, limit)
        now = self._now()   # one stamp: tracer submit_t == arrival_time
        if self.trace is not None:
            req.trace_ctx = self.trace.on_submit(
                req.rid, now, priority=req.priority)
        self.sched.submit(req, now=now)

    # --------------------------------------------------------------- prefill
    def _prefill(self, slot: int, req: Request) -> None:
        P = len(req.prompt)
        ok = self.pool.ensure(req.rid, P)
        assert ok, "admission checked block availability"
        C = self.chunk_size
        # stage every host->device input BEFORE entering the watch scope:
        # first-use eager compiles (asarray, key splits) must land as
        # unattributed warmups, not as step-label anomalies.
        table = self._jnp.asarray(self.pool.table([req.rid]))
        n_chunks = -(-P // C)
        chunks = []
        for i in range(n_chunks):
            lo = i * C
            valid = min(C, P - lo)
            chunk = np.zeros((1, C), np.int32)
            chunk[0, :valid] = req.prompt[lo:lo + valid]
            chunks.append((self._jnp.asarray(chunk), np.int32(lo),
                           np.int32(valid), self._next_key(),
                           self._next_key()))
        tok = None
        t_marks = [self._now()] if self.trace is not None else None
        with self._watch("serve_prefill"):
            for chunk, lo, valid, key, dkey in chunks:
                tok, self.pk, self.pv = self.steps.prefill(
                    self.params, self.pk, self.pv, chunk, lo, valid,
                    table, key)
                if self._spec_round is not None:
                    _, self.dpk, self.dpv = self.dsteps.prefill(
                        self.draft_params, self.dpk, self.dpv,
                        chunk, lo, valid, table, dkey)
                if t_marks is not None:
                    t_marks.append(self._now())   # chunk dispatch boundary
        seed = int(np.asarray(tok))
        now = self._now()
        if t_marks is not None:
            # fold the host sync into the last chunk's span; the prefill
            # end mark IS the first-token stamp below, so the tracer's
            # TTFT equals the engine's sample exactly.
            t_marks[-1] = now
            self.trace.on_prefill(req.rid, t_marks,
                                  redo=req.first_token_time is not None)
        if req.first_token_time is None:
            req.first_token_time = now
            self.ttft_samples.append(now - req.arrival_time)
        self._emit(slot, req, seed, now, first=True)
        self._offsets[slot] = P
        self._last[slot] = seed
        self._last_emit[slot] = now
        if req.done:
            self._finish(slot)

    # ----------------------------------------------------------------- emit
    def _emit(self, slot: int, req: Request, token: int, now: float,
              first: bool = False) -> None:
        req.generated.append(token)
        self.total_tokens += 1
        if self.trace is not None:
            self.trace.on_emit(req.rid, now, first)
        if self.stream is not None:
            self.stream(req.rid, token, "first" if first else "token")

    def _finish(self, slot: int) -> None:
        req = self.sched.finish(slot, now=self._now())
        self.pool.free(req.rid)
        self._offsets[slot] = 0
        self._last[slot] = 0
        if self.trace is not None:
            self.trace.on_complete(req.rid, req.finish_time,
                                   tokens=len(req.generated),
                                   preemptions=req.preemptions)
        self.finished.append(req)

    # ------------------------------------------------------------ preemption
    def _preempt(self, slot: int) -> None:
        req = self.sched.slots[slot]
        self.pool.free(req.rid)
        self._offsets[slot] = 0
        self._last[slot] = 0
        self.sched.preempt(slot)
        if self.trace is not None:
            self.trace.on_preempt(req.rid, self._now())
        if self.obs is not None:
            self.obs.log_event("serve_preempt", step=self._step, rid=req.rid)

    def _ensure_or_preempt(self, slot: int, rid, need_tokens: int,
                           protect: Sequence[int] = ()) -> bool:
        """Grow ``rid`` to ``need_tokens``; on exhaustion preempt victims
        (possibly the requester itself, never a ``protect`` slot) until
        it fits or the requester is gone.  Returns False when the
        requesting slot was evicted."""
        while not self.pool.ensure(rid, need_tokens):
            victim = self.sched.pick_victim(protect=protect)
            if victim is None:
                return False
            self._preempt(victim)
            if victim == slot:
                return False
        return True

    # ----------------------------------------------------------------- step
    def step(self) -> int:
        """One engine iteration; returns tokens emitted."""
        t_start = self._now()
        self._step += 1
        if self.chaos is not None:
            self.chaos.on_step(self, self._step)

        # admission: continuous fills free lanes anytime; static (the
        # naive baseline) only opens the door once the whole wave drains.
        if self.mode == "continuous" or not self.sched.active:
            # Blocks are only allocated at prefill, below — so each
            # candidate must be probed against the free count minus what
            # earlier admits in this same loop have already pledged.
            pledged = 0

            def can_admit(r: Request) -> bool:
                nonlocal pledged
                need = self.pool.blocks_needed(len(r.prompt))
                if self.pool.free_blocks - pledged < need:
                    return False
                pledged += need
                return True

            for slot, req in self.sched.admit(can_admit):
                if self.trace is not None:
                    self.trace.on_admit(req.rid, self._now())
                self._prefill(slot, req)

        emitted = 0
        active = list(self.sched.active)
        if active:
            grow = self.gamma + 1
            live = []
            held = set()
            for slot, req in active:
                if self.sched.slots[slot] is not req:
                    continue          # evicted by an earlier lane's growth
                # protect already-validated lanes: a later lane's growth
                # must never evict a slot this same decode will read.
                if self._ensure_or_preempt(
                        slot, req.rid, int(self._offsets[slot]) + grow,
                        protect=held):
                    live.append((slot, req))
                    held.add(slot)
            if live:
                emitted += self._decode(live)

        if self.pool.fragmentation_pct() > self.defrag_threshold_pct:
            t_df = self._now() if self.trace is not None else 0.0
            self._defrag()
            if self.trace is not None:
                self.trace.on_defrag(t_df, self._now())

        if emitted or active:
            self._log_metrics(self._now() - t_start, emitted)
        return emitted

    def _decode(self, live) -> int:
        t_dec = self._now() if self.trace is not None else 0.0
        sids = [None] * self.max_batch
        for slot, req in live:
            sids[slot] = req.rid
        table = self._jnp.asarray(self.pool.table(sids))
        tokens = self._jnp.asarray(self._last)
        offsets = self._jnp.asarray(self._offsets)
        key = self._next_key()
        with self._watch("serve_decode"):
            if self._spec_round is not None:
                out, self.pk, self.pv, self.dpk, self.dpv = self._spec_round(
                    self.params, self.draft_params, self.pk, self.pv,
                    self.dpk, self.dpv, tokens, offsets, table)
            else:
                out, self.pk, self.pv = self.steps.decode(
                    self.params, self.pk, self.pv, tokens, offsets, table,
                    key)
        arr = np.asarray(out)          # the one host sync of the iteration
        now = self._now()
        emitted = 0
        for slot, req in live:
            toks = (arr[slot][arr[slot] >= 0].tolist()
                    if arr.ndim == 2 else [int(arr[slot])])
            if self.trace is not None:
                self.trace.on_decode(req.rid, t_dec, now, len(toks))
            gap = now - self._last_emit[slot]
            for t in toks:
                self._emit(slot, req, t, now)
                self.itl_samples.append(gap / len(toks))
                emitted += 1
                if req.done:
                    break
            self._offsets[slot] += len(toks)
            self._last[slot] = req.generated[-1]
            self._last_emit[slot] = now
            if req.done:
                self._finish(slot)
        return emitted

    def _defrag(self) -> None:
        perm = self.pool.defrag()
        if np.array_equal(perm, np.arange(self.pool.n_blocks)):
            return
        # eager gathers outside any watch() scope: the watchdog books
        # them as unattributed warmups, never anomalies.
        p = self._jnp.asarray(perm)
        self.pk = apply_permutation(self.pk, p)
        self.pv = apply_permutation(self.pv, p)
        if self._spec_round is not None:
            self.dpk = apply_permutation(self.dpk, p)
            self.dpv = apply_permutation(self.dpv, p)
        if self.obs is not None:
            self.obs.log_event("serve_defrag", step=self._step,
                               defrags=self.pool.defrags)

    # -------------------------------------------------------------- metrics
    def _log_metrics(self, step_time: float, emitted: int) -> None:
        if self.obs is None:
            return
        now = max(self._now(), 1e-9)
        extra = {
            "serving": 1.0,
            "queue_depth": float(self.sched.queue_depth),
            "active_seqs": float(len(self.sched.active)),
            "kv_occupancy_pct": self.pool.occupancy_pct(),
            "kv_frag_pct": self.pool.fragmentation_pct(),
            "preemptions": float(self.sched.preemptions),
            "requests_completed": float(self.sched.completed),
            "tokens_per_s": self.total_tokens / now,
        }
        for name, samples in (("ttft", self.ttft_samples),
                              ("itl", self.itl_samples)):
            for q in (0.5, 0.95, 0.99):
                v = _pct_ms(samples, q)
                if v is not None:
                    extra[f"{name}_p{int(q * 100)}_ms"] = v
        if self.trace is not None:
            extra.update(self.trace.step_fields())
        self.obs.log_step(self._step, step_time, n_items=emitted,
                          extra=extra)
        self._drain_traces()

    def _drain_traces(self) -> None:
        """Lazy flush: book completed trace records as ``reqtrace``
        ft_events, one per request, once per step — never per token."""
        if self.trace is None or self.obs is None:
            return
        for ev in self.trace.drain():
            self.obs.log_event("reqtrace", step=self._step, **ev)

    # ------------------------------------------------------------------- run
    def run(self, load: List, max_steps: int = 100000) -> Dict[str, Any]:
        """Drive a loadgen trace to completion: submit each request when
        its arrival time passes on the engine clock, step until drained."""
        pending = sorted(load, key=lambda x: x[0])
        i = 0
        for _ in range(max_steps):
            now = self._now()
            while i < len(pending) and pending[i][0] <= now:
                self.submit(pending[i][1])
                i += 1
            if not self.sched.active and not self.sched.queue_depth:
                if i >= len(pending):
                    break
                self._sleep_fn(max(min(pending[i][0] - self._now(), 1e-3),
                                   0.0))
                continue
            self.step()
        # final drain: a request that completes on the run's last step
        # (or a step whose metrics record was skipped) must still land.
        self._drain_traces()
        return self.summary()

    def summary(self) -> Dict[str, Any]:
        wall = max(self._now(), 1e-9)
        out = {
            "mode": self.mode,
            "completed": self.sched.completed,
            "tokens": self.total_tokens,
            "wall_s": wall,
            "tokens_per_s": self.total_tokens / wall,
            "preemptions": self.sched.preemptions,
            "defrags": self.pool.defrags,
            "alloc_failures": self.pool.alloc_failures,
            "steps": self._step,
        }
        for name, samples in (("ttft", self.ttft_samples),
                              ("itl", self.itl_samples)):
            for q in (0.5, 0.95, 0.99):
                out[f"{name}_p{int(q * 100)}_ms"] = _pct_ms(samples, q)
        return out


def init_lm_params(vocab_size: int, d_model: int, n_heads: int,
                   n_layers: int, block_size: int = 16, seed: int = 0):
    """Random-init params for the paged model (identical tree to
    ``TransformerLM.init``, so either side's init works for both)."""
    import jax
    import jax.numpy as jnp

    steps = _make_steps(vocab_size, d_model, n_heads, n_layers, block_size,
                        0.0, 0, 1.0, "")
    pk, pv = init_pools(n_layers, 4, block_size, n_heads,
                        d_model // n_heads)
    table = jnp.zeros((1, 2), jnp.int32)
    tokens = jnp.zeros((1, 1), jnp.int32)
    pos = jnp.zeros((1, 1), jnp.int32)
    variables = steps.model.init(
        jax.random.PRNGKey(seed), tokens, pk, pv, table, pos)
    return variables["params"]
