"""Paged KV cache: a fixed-size block pool + per-sequence block tables.

vLLM-style paged attention (arXiv 2309.06180) adapted to this repo's
static-shape discipline: the device holds one KV pool per side,
``[n_layers, n_blocks, block_size, n_heads, head_dim]``, and every
sequence owns an ordered list of physical blocks recorded in a
``[B_max, blocks_per_seq]`` int32 block table.  The jitted serving steps
(serving/engine.py) scatter new k/v through the table and gather each
slot's logical window back out — both at static shapes, so the decode
step compiles exactly once no matter how sequences churn.

Split of responsibilities:

- ``BlockPool`` is pure host state (no jax): the free list, per-sequence
  block lists, alloc/free/defrag, and the occupancy/fragmentation
  counters the SLO metrics report.
- The module-level device helpers (``init_pools``, ``lookup_blocks``,
  ``paged_scatter``, ``paged_gather``, ``apply_permutation``) are the
  pure jnp functions the paged model composes inside jit.

Physical block 0 is reserved as the null/garbage sink: the allocator
never hands it out, unset table entries are 0, and every out-of-window
or inactive-slot write routes there.  Reads never see it unmasked — a
slot only attends to logical positions below its committed offset, and
those always map to really-allocated blocks.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class BlockPool:
    """Host-side allocator over ``n_blocks`` fixed-size KV blocks.

    Block 0 is reserved (the null sink), so usable capacity is
    ``n_blocks - 1`` blocks of ``block_size`` tokens each.  Sequences
    grow monotonically via ``ensure`` and release everything at once via
    ``free`` (preempt-and-requeue restarts from scratch — recompute, not
    swap).  ``defrag`` compacts used blocks to the low end of the pool
    and returns the gather permutation the engine applies on device.
    """

    def __init__(self, n_blocks: int, block_size: int, blocks_per_seq: int):
        if n_blocks < 2:
            raise ValueError(f"n_blocks must be >= 2 (block 0 is reserved), "
                             f"got {n_blocks}")
        if block_size < 1 or blocks_per_seq < 1:
            raise ValueError("block_size and blocks_per_seq must be >= 1")
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self.blocks_per_seq = int(blocks_per_seq)
        # LIFO free stack: low block ids come back first, which is what
        # makes fragmentation (and defrag) observable after churn.
        self._free: List[int] = list(range(self.n_blocks - 1, 0, -1))
        self._seqs: Dict[Any, List[int]] = {}
        self.alloc_failures = 0
        self.defrags = 0

    # ------------------------------------------------------------- capacity
    @property
    def capacity_blocks(self) -> int:
        return self.n_blocks - 1

    @property
    def capacity_tokens(self) -> int:
        """Max tokens a single sequence can commit (its table width)."""
        return self.blocks_per_seq * self.block_size

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.capacity_blocks - len(self._free)

    def blocks_needed(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    def can_alloc(self, n_blocks: int) -> bool:
        return len(self._free) >= n_blocks

    def occupancy_pct(self) -> float:
        return 100.0 * self.used_blocks / self.capacity_blocks

    def fragmentation_pct(self) -> float:
        """Spread of the used region past its compact size: with U used
        blocks spanning up to id S, ``100 * (S - U) / S``.  0 when the
        used blocks sit contiguously at the low end (or nothing is
        used); defrag drives it back to 0."""
        used = [b for blocks in self._seqs.values() for b in blocks]
        if not used:
            return 0.0
        span = max(used)
        return 100.0 * (span - len(used)) / span

    # ----------------------------------------------------------- alloc/free
    def blocks_of(self, sid: Any) -> List[int]:
        return list(self._seqs.get(sid, ()))

    def ensure(self, sid: Any, n_tokens: int) -> bool:
        """Grow ``sid``'s allocation to cover ``n_tokens`` committed
        positions.  Returns False (books an alloc failure, changes
        nothing) when the pool is exhausted — the scheduler's cue to
        preempt."""
        if n_tokens > self.capacity_tokens:
            raise ValueError(
                f"sequence needs {n_tokens} tokens > table capacity "
                f"{self.capacity_tokens} ({self.blocks_per_seq} blocks × "
                f"{self.block_size}); admission should have clamped it")
        have = len(self._seqs.get(sid, ()))
        need = self.blocks_needed(n_tokens) - have
        if need <= 0:
            return True
        if len(self._free) < need:
            self.alloc_failures += 1
            return False
        blocks = self._seqs.setdefault(sid, [])
        for _ in range(need):
            blocks.append(self._free.pop())
        return True

    def free(self, sid: Any) -> int:
        """Release every block ``sid`` holds; returns how many."""
        blocks = self._seqs.pop(sid, [])
        self._free.extend(blocks)
        return len(blocks)

    # ----------------------------------------------------------- block table
    def table(self, sids: Sequence[Optional[Any]]) -> np.ndarray:
        """The ``[len(sids), blocks_per_seq]`` int32 block table for the
        given slot->sequence assignment (None = empty slot, all-zero row
        -> every access routes to the null block)."""
        out = np.zeros((len(sids), self.blocks_per_seq), np.int32)
        for row, sid in enumerate(sids):
            if sid is None:
                continue
            for j, b in enumerate(self._seqs.get(sid, ())):
                out[row, j] = b
        return out

    # --------------------------------------------------------------- defrag
    def defrag(self) -> np.ndarray:
        """Compact used blocks to ids ``1..used`` (sequence order
        preserved) and return the length-``n_blocks`` permutation to
        apply on device: ``new_pool = old_pool[perm]``.  Identity when
        already compact."""
        perm = np.arange(self.n_blocks, dtype=np.int32)
        new_id = 1
        moved = False
        used_old = set()
        for blocks in self._seqs.values():
            for j, old in enumerate(blocks):
                if old != new_id:
                    moved = True
                perm[new_id] = old
                blocks[j] = new_id
                used_old.add(old)
                new_id += 1
        if not moved:
            return perm
        spare = [b for b in range(1, self.n_blocks) if b not in used_old]
        for j, old in enumerate(spare):
            perm[new_id + j] = old
        # free list over the compacted tail, low ids popped first
        self._free = list(range(self.n_blocks - 1, new_id - 1, -1))
        self.defrags += 1
        return perm


# ------------------------------------------------------- device-side helpers
# Pure jnp functions the paged model (serving/engine.py) composes inside
# jit.  jax is imported lazily so the host half of this module (BlockPool,
# used by the scheduler tests and the report plumbing) stays jax-free.

def init_pools(n_layers: int, n_blocks: int, block_size: int, n_heads: int,
               head_dim: int, dtype=None):
    """Zeroed ``(pool_k, pool_v)``, each
    ``[n_layers, n_blocks, block_size, n_heads, head_dim]``.  Zero init
    matters for exactness: masked attention weights are exactly 0.0, and
    0.0 × finite is 0.0 — never-NaN garbage reads."""
    import jax.numpy as jnp

    shape = (n_layers, n_blocks, block_size, n_heads, head_dim)
    dt = jnp.float32 if dtype is None else dtype
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


def lookup_blocks(table, pos, block_size: int):
    """Physical block id for each logical position: ``table [B, W]``,
    ``pos [B, L]`` -> ``[B, L]``.  Positions past the table window route
    to the null block 0 (out-of-range writes land in garbage, never in a
    live block)."""
    import jax.numpy as jnp

    idx = pos // block_size
    w = table.shape[1]
    safe = jnp.clip(idx, 0, w - 1)
    blk = jnp.take_along_axis(table, safe, axis=1)
    return jnp.where(idx < w, blk, 0)


def paged_scatter(pool_l, blk, off, val):
    """Write ``val [B, L, H, D]`` at ``(blk, off) [B, L]`` into one
    layer's pool ``[NB, BS, H, D]``.  Distinct live slots never collide
    (the allocator hands each sequence disjoint blocks); only null-block
    writes can duplicate, and block 0 is garbage by contract."""
    return pool_l.at[blk, off].set(val)


def paged_gather(pool_l, table):
    """Gather a slot-major logical KV window: ``[B, W] -> [B, W*BS, H, D]``
    — the static-shape keys/values tensor paged attention masks against."""
    g = pool_l[table]                      # [B, W, BS, H, D]
    b, w, bs, h, d = g.shape
    return g.reshape(b, w * bs, h, d)


def apply_permutation(pool, perm):
    """Relocate blocks after a host-side ``BlockPool.defrag()``:
    ``pool [n_layers, NB, ...][:, perm]`` in one static-shape gather."""
    return pool[:, perm]
