"""Continuous-batching LM serving (ISSUE 15).

The serving twin of the training stack: a paged KV cache (kvpool),
admission/preemption scheduling (scheduler), the jitted step loop
(engine), and a seeded synthetic load harness (loadgen), fronted by
``scripts/serve_lm.py``.  Import submodules directly — this package
stays import-time light so host-side pieces (scheduler, loadgen) load
without jax.
"""
