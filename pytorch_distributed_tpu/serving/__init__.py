"""Continuous-batching LM serving (ISSUE 15) and the fleet (ISSUE 19).

The serving twin of the training stack: a paged KV cache (kvpool),
admission/preemption scheduling (scheduler), the jitted step loop
(engine), and a seeded synthetic load harness (loadgen), fronted by
``scripts/serve_lm.py``.  On top of one engine sits the fleet plane
(``scripts/serve_fleet.py``): per-replica HTTP servers with rid-replay
caches (replica), and the health-checked request router with
retry/hedging/backoff, graceful drain, an exactly-once completion
ledger, and the elastic scale arbiter (router).  Import submodules
directly — this package stays import-time light so host-side pieces
(scheduler, loadgen, router, replica) load without jax.
"""
