"""Seeded synthetic load for the serving engine.

Poisson arrivals (exponential inter-arrival gaps at ``rate_rps``) with
mixed prompt/output-length distributions.  Everything flows from one
``np.random.default_rng(seed)``, so a trace is a pure function of its
config — the A/B experiment (experiments/serving_ab.py) replays the
identical trace against both batching modes, and the scheduler
determinism tests replay it across runs.

The "mixed" profile is the serving-shaped one: mostly short outputs with
a long tail.  Static batching pays E[max over batch] per wave while
continuous batching pays E[length] per slot, which is exactly the gap
the >=2x tokens/s acceptance bar measures.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import numpy as np

from pytorch_distributed_tpu.serving.scheduler import Request

PROFILES = ("mixed", "uniform")


@dataclasses.dataclass(frozen=True)
class LoadConfig:
    n_requests: int = 32
    rate_rps: float = 50.0       # mean arrival rate (requests/second)
    profile: str = "mixed"
    vocab_size: int = 64
    prompt_min: int = 4
    prompt_max: int = 12
    short_min: int = 2           # "mixed": ~80% of outputs land here
    short_max: int = 8
    long_min: int = 32           # ...and ~20% here (the tail static pays for)
    long_max: int = 48
    long_frac: float = 0.2
    seed: int = 0


def generate_load(cfg: LoadConfig) -> List[Tuple[float, Request]]:
    """``[(arrival_time_s, Request)]`` sorted by arrival time."""
    if cfg.profile not in PROFILES:
        raise ValueError(f"unknown profile {cfg.profile!r}; "
                         f"expected {PROFILES}")
    rng = np.random.default_rng(cfg.seed)
    t = 0.0
    out: List[Tuple[float, Request]] = []
    for rid in range(cfg.n_requests):
        t += float(rng.exponential(1.0 / cfg.rate_rps))
        plen = int(rng.integers(cfg.prompt_min, cfg.prompt_max + 1))
        prompt = rng.integers(0, cfg.vocab_size, size=plen).tolist()
        if cfg.profile == "mixed" and rng.random() < cfg.long_frac:
            new = int(rng.integers(cfg.long_min, cfg.long_max + 1))
        else:
            new = int(rng.integers(cfg.short_min, cfg.short_max + 1))
        out.append((t, Request(rid=rid, prompt=prompt, max_new_tokens=new)))
    return out
