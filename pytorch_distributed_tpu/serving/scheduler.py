"""Admission, slot assignment, and preempt-and-requeue for the serving
engine.

Pure host logic (no jax): requests queue FCFS or by priority, admit into
a fixed ``[B_max]`` slot array (the active mask the static-shape decode
step runs over), and — when the KV pool exhausts mid-decode — a victim
is preempted: its blocks freed, its generation discarded, the request
requeued at its original queue position (recompute semantics, the
restart-from-scratch half of vLLM's recompute-vs-swap choice; greedy
decoding makes the regenerated tokens identical).

Determinism contract (tier-1 tested): admission order, slot assignment,
and victim choice are pure functions of the submitted request sequence —
no wall clock, no dict-order dependence.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
from typing import Any, Callable, List, Optional, Sequence, Tuple

POLICIES = ("fcfs", "priority")


@dataclasses.dataclass
class Request:
    """One generation request plus its runtime bookkeeping."""

    rid: int
    prompt: Sequence[int]
    max_new_tokens: int
    priority: int = 0            # larger = more important ("priority" policy)
    arrival_time: float = 0.0    # stamped by Scheduler.submit (engine clock)
    # -- runtime state (engine-owned) --
    generated: List[int] = dataclasses.field(default_factory=list)
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    preemptions: int = 0
    # Propagatable trace identity (obs/reqtrace.TraceContext, or any
    # object with a ``hops`` list).  Duck-typed on purpose: serving/
    # stays import-free of obs/, and a router can hand in its own
    # context record — the scheduler just appends lifecycle hops.
    trace_ctx: Optional[Any] = None

    def _hop(self, name: str) -> None:
        if self.trace_ctx is not None:
            self.trace_ctx.hops.append(name)

    @property
    def done(self) -> bool:
        return len(self.generated) >= self.max_new_tokens


class Scheduler:
    """Queue + fixed slot batch.  ``slots[i]`` is the Request decoding in
    batch lane ``i`` (None = free lane, inactive in the step's mask)."""

    def __init__(self, max_batch: int, policy: str = "fcfs"):
        if policy not in POLICIES:
            raise ValueError(f"unknown policy {policy!r}; expected {POLICIES}")
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.policy = policy
        self.slots: List[Optional[Request]] = [None] * self.max_batch
        self._heap: List[Tuple[tuple, int, Request]] = []
        self._seq = itertools.count()
        # heap tiebreaker: equal keys pop FIFO instead of falling through
        # to comparing Request objects (which defines no ordering)
        self._tiebreak = itertools.count()
        self._order: dict = {}       # rid -> submit sequence number
        self._admit_seq = itertools.count()
        self._admitted_at: dict = {}  # rid -> admission sequence (victim age)
        self.preemptions = 0
        self.admitted = 0
        self.completed = 0

    # ---------------------------------------------------------------- queue
    def submit(self, req: Request, now: float = 0.0) -> None:
        req.arrival_time = now
        self._order[req.rid] = next(self._seq)
        req._hop("queue")
        heapq.heappush(self._heap,
                       (self._key(req), next(self._tiebreak), req))

    def _key(self, req: Request) -> tuple:
        # A preempted request re-enters with its ORIGINAL submit order,
        # so requeue lands it ahead of everything that arrived after it.
        if self.policy == "priority":
            return (-req.priority, self._order[req.rid])
        return (self._order[req.rid],)

    @property
    def queue_depth(self) -> int:
        return len(self._heap)

    @property
    def active(self) -> List[Tuple[int, Request]]:
        return [(i, r) for i, r in enumerate(self.slots) if r is not None]

    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.slots) if r is None]

    # ------------------------------------------------------------ admission
    def admit(self, can_admit: Callable[[Request], bool]
              ) -> List[Tuple[int, Request]]:
        """Fill free slots from the queue head while ``can_admit`` (the
        engine's block-availability probe) accepts.  Head-of-line
        blocking is deliberate: skipping over a too-big head request
        would starve it forever on a busy pool."""
        placed: List[Tuple[int, Request]] = []
        for slot in self.free_slots():
            if not self._heap:
                break
            req = self._heap[0][-1]
            if not can_admit(req):
                break
            heapq.heappop(self._heap)
            self.slots[slot] = req
            self._admitted_at[req.rid] = next(self._admit_seq)
            self.admitted += 1
            req._hop("admit")
            placed.append((slot, req))
        return placed

    # ----------------------------------------------------------- preemption
    def pick_victim(self, protect: Sequence[int] = ()) -> Optional[int]:
        """The slot to preempt when the pool exhausts: lowest priority
        first, then youngest admission (most recently admitted loses the
        least recomputation).  ``protect`` slots are exempt (e.g. the
        lane being prefilled this instant)."""
        candidates = [(r.priority, -self._admitted_at[r.rid], i)
                      for i, r in self.active if i not in protect]
        if not candidates:
            return None
        _, _, slot = min(candidates)
        return slot

    def preempt(self, slot: int) -> Request:
        """Evict ``slots[slot]``: discard its generation and requeue it
        (caller frees the KV blocks).  Returns the evicted request."""
        req = self.slots[slot]
        if req is None:
            raise ValueError(f"slot {slot} is empty")
        self.slots[slot] = None
        self._admitted_at.pop(req.rid, None)
        req.generated = []
        req.preemptions += 1
        self.preemptions += 1
        req._hop("requeue")
        heapq.heappush(self._heap,
                       (self._key(req), next(self._tiebreak), req))
        return req

    def finish(self, slot: int, now: float = 0.0) -> Request:
        req = self.slots[slot]
        if req is None:
            raise ValueError(f"slot {slot} is empty")
        self.slots[slot] = None
        self._admitted_at.pop(req.rid, None)
        req.finish_time = now
        self.completed += 1
        req._hop("finish")
        return req
