"""Paged-attention twin of ``models/transformer.TransformerLM``.

``PagedTransformerLM`` keeps the exact parameter tree of the training
model — same scope names (``embed``, ``block_i.{ln1,ln2}``,
``attn.{qkv,proj}``, ``fc1``/``fc2``, ``ln_f``), same tied head — so a
trained (or int8-quantized, models/quant.py) params pytree applies
unchanged.  Only the attention inner changes: the per-call flax cache of
``SelfAttention._decode_attend`` becomes an explicit paged KV pool
threaded through ``__call__`` (serving/kvpool.py), because a serving
batch mixes sequences at different offsets and lifetimes — one scalar
cache index cannot describe it.

Exactness contract (the bit-exact-greedy parity test rides on this): the
score/softmax/value math is copied line-for-line from ``_decode_attend``
— f32 score accumulation, ``/ sqrt(D)``, ``-1e30`` mask then softmax
(masked lanes underflow to exactly 0.0 in f32, so garbage KV reads
contribute exactly nothing), same einsum contractions.  ``rope_at`` is
``transformer.rope`` with the scalar offset generalized to a per-token
position matrix; the per-element float math is identical.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.models.transformer import _dense_cls
from pytorch_distributed_tpu.serving.kvpool import (
    lookup_blocks,
    paged_gather,
    paged_scatter,
)


def rope_at(x: jnp.ndarray, pos: jnp.ndarray,
            base: float = 10000.0) -> jnp.ndarray:
    """``transformer.rope`` with explicit absolute positions.

    ``x [B, L, H, D]``, ``pos [B, L]`` (int).  Each (batch, token) lane
    gets the rotation for its own position — the vector-offset form a
    mixed-offset serving batch needs.  Elementwise math matches
    ``rope(x, offset=idx)`` bit-for-bit at equal positions."""
    half = x.shape[-1] // 2
    freqs = 1.0 / (base ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    ang = pos.astype(jnp.float32)[:, :, None] * freqs[None, None, :]
    cos = jnp.cos(ang)[:, :, None, :]                         # [B, L, 1, half]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1
    ).astype(x.dtype)


class PagedSelfAttention(nn.Module):
    n_heads: int
    block_size: int
    dtype: Any = jnp.float32
    quant: str = ""

    @nn.compact
    def __call__(self, x, pool_k, pool_v, table, pos):
        B, L, C = x.shape
        D = C // self.n_heads
        dense = _dense_cls(self.quant)
        qkv = dense(3 * C, use_bias=False, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (B, L, self.n_heads, D)
        q, k, v = (t.reshape(shape) for t in (q, k, v))
        q = rope_at(q, pos)
        k = rope_at(k, pos)
        blk = lookup_blocks(table, pos, self.block_size)
        off = pos % self.block_size
        pool_k = paged_scatter(pool_k, blk, off, k.astype(pool_k.dtype))
        pool_v = paged_scatter(pool_v, blk, off, v.astype(pool_v.dtype))
        keys = paged_gather(pool_k, table)                    # [B, KV, H, D]
        values = paged_gather(pool_v, table)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32),
            keys.astype(jnp.float32)) / (D ** 0.5)
        kpos = jnp.arange(keys.shape[1])
        # self-inclusive causal mask over logical positions, per slot:
        # position j attends to committed positions 0..j (matches
        # _decode_attend's kpos <= qpos).
        mask = kpos[None, None, None, :] <= pos[:, None, :, None]
        scores = jnp.where(mask, scores, -1e30)
        w = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum(
            "bhqk,bkhd->bqhd", w, values.astype(jnp.float32)
        ).astype(q.dtype).reshape(B, L, C)
        out = dense(C, use_bias=False, dtype=self.dtype, name="proj")(out)
        return out, pool_k, pool_v


class PagedBlock(nn.Module):
    n_heads: int
    block_size: int
    dtype: Any = jnp.float32
    quant: str = ""

    @nn.compact
    def __call__(self, x, pool_k, pool_v, table, pos):
        C = x.shape[-1]
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        a, pool_k, pool_v = PagedSelfAttention(
            self.n_heads, self.block_size, self.dtype, self.quant,
            name="attn")(h, pool_k, pool_v, table, pos)
        x = x + a
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        dense = _dense_cls(self.quant)
        h = dense(4 * C, dtype=self.dtype, name="fc1")(h)
        h = nn.gelu(h)
        h = dense(C, dtype=self.dtype, name="fc2")(h)
        return x + h, pool_k, pool_v


class PagedTransformerLM(nn.Module):
    """``__call__(tokens[B, L], pool_k, pool_v, table[B, W], pos[B, L])
    -> (logits[B, L, vocab], pool_k, pool_v)``.

    Pools are explicit function state, not flax variables: the engine
    threads them through every jitted step, so one compiled step serves
    every sequence the pool will ever hold."""

    vocab_size: int = 64
    d_model: int = 32
    n_heads: int = 4
    n_layers: int = 1
    block_size: int = 16
    dtype: Any = jnp.float32
    quant: str = ""

    @nn.compact
    def __call__(self, tokens, pool_k, pool_v, table, pos):
        embed = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                         name="embed")
        x = embed(tokens)
        new_k, new_v = [], []
        for i in range(self.n_layers):
            x, k_l, v_l = PagedBlock(
                self.n_heads, self.block_size, self.dtype, self.quant,
                name=f"block_{i}")(x, pool_k[i], pool_v[i], table, pos)
            new_k.append(k_l)
            new_v.append(v_l)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        logits = embed.attend(x.astype(jnp.float32)).astype(jnp.float32)
        return logits, jnp.stack(new_k), jnp.stack(new_v)
