"""Serving replica: the fleet-facing HTTP wrapper around one engine
(ISSUE 19).

``ReplicaServer`` puts a small JSON surface in front of a backend:

- ``POST /generate`` — synchronous decode; forwarded ``TraceContext``
  wire dicts gain replica-side hops and ride back on the response.
  Completed results are cached by rid, so a replay (router retry after
  a lost response, or a restarted router re-dispatching) returns the
  original tokens bit-for-bit without recomputing — the replica half of
  the fleet's exactly-once story.
- ``GET /healthz`` / ``GET /metrics`` — the same liveness + ``ptd_serving_*``
  gauge surface ``serve_lm`` exports, so the router's registry scrapes
  replicas uniformly.
- ``POST /drain`` — stop admission, let in-flight lanes finish, then
  flag drained (the arbiter deregisters after).
- ``POST /cancel`` — best-effort abort of an in-flight rid (hedge
  losers); a cancelled request is *not* cached, a later replay
  recomputes.

Two backends share the ``generate``/``cancel``/``stats_record`` duck
type:

- ``SimEngineBackend`` — import-time jax-free, deterministic stand-in:
  tokens are a pure function of ``(prompt, seed)`` (``sim_tokens``), so
  two replicas with the same seed produce bit-identical outputs — the
  property the replica-kill drill's bit-exactness fence measures
  end-to-end.  Lanes are handler threads gated by a ``max_batch``
  semaphore with real (sleep-based) prefill/ITL costs, so queue depth,
  TTFT tails, and replica-for-replica throughput scaling behave
  honestly on a 1-core CI host.
- ``EngineBackend`` — the real ``ServingEngine`` behind the same wire
  (lazy jax import), driven by a background step thread.
"""

from __future__ import annotations

import collections
import importlib
import importlib.util
import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional


def _serving_module(name: str):
    """Path-load a ``serving/`` sibling jax-free (router discipline)."""
    full = f"pytorch_distributed_tpu.serving.{name}"
    if full in sys.modules:
        return sys.modules[full]
    if "pytorch_distributed_tpu" in sys.modules:
        return importlib.import_module(full)
    alias = f"_ptd_serving_{name}"
    if alias in sys.modules:
        return sys.modules[alias]
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"{name}.py")
    spec = importlib.util.spec_from_file_location(alias, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[alias] = mod
    spec.loader.exec_module(mod)
    return mod


def sim_tokens(prompt: List[int], n: int, vocab: int, seed: int) -> List[int]:
    """Deterministic pseudo-decode: a pure function of (prompt, seed).

    Every replica with the same seed emits the same tokens for the same
    prompt — the invariant that lets the kill drill assert bit-exact
    outputs across a redispatch to a different replica.
    """
    h = (seed * 0x9E3779B1 + 0x85EBCA6B) & 0xFFFFFFFF
    for t in prompt:
        h = (h * 1000003 ^ (int(t) + 0x9E37)) & 0xFFFFFFFF
    out = []
    for i in range(n):
        h = (h * 1103515245 + 12345 + i) & 0xFFFFFFFF
        out.append((h >> 7) % max(1, vocab))
    return out


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


class SimEngineBackend:
    """Deterministic jax-free engine stand-in with honest queueing.

    ``max_batch`` lanes are a semaphore; a request waits (queue), takes
    a lane (admit), pays ``len(prompt) * prefill_ms_per_token`` of
    prefill, then one ``itl_ms`` sleep per token after the first.  All
    sleeps release the GIL, so N replicas on one host scale close to
    linearly until cores saturate — the property the bench fences.
    """

    def __init__(self, *, replica_id: int = 0, vocab_size: int = 64,
                 max_batch: int = 4, prefill_ms_per_token: float = 0.2,
                 itl_ms: float = 2.0, seed: int = 0,
                 slo_ttft_ms: Optional[float] = None, obs=None,
                 time_fn: Callable[[], float] = time.monotonic,
                 sleep_fn: Callable[[float], None] = time.sleep):
        self.replica_id = int(replica_id)
        self.vocab_size = int(vocab_size)
        self.max_batch = int(max_batch)
        self.prefill_ms_per_token = float(prefill_ms_per_token)
        self.itl_ms = float(itl_ms)
        self.seed = int(seed)
        self.slo_ttft_ms = slo_ttft_ms
        self.obs = obs
        self._now = time_fn
        self._sleep = sleep_fn
        self._sem = threading.Semaphore(self.max_batch)
        self._lock = threading.Lock()
        self._queued = 0
        self._active = 0
        self.completed = 0
        self.cancelled = 0
        self.tokens_total = 0
        self._ttft_ms: collections.deque = collections.deque(maxlen=512)
        self._e2e_ms: collections.deque = collections.deque(maxlen=512)
        self._cancel: Dict[int, threading.Event] = {}
        self.t0 = self._now()

    def cancel(self, rid: int) -> bool:
        ev = self._cancel.get(int(rid))
        if ev is None:
            return False
        ev.set()
        return True

    def generate(self, rid: int, prompt: List[int], max_new_tokens: int,
                 ctx=None) -> Dict[str, Any]:
        submit = self._now()
        cancel_ev = threading.Event()
        with self._lock:
            self._queued += 1
            self._cancel[int(rid)] = cancel_ev
        if ctx is not None:
            ctx.hops.append("queue")
        self._sem.acquire()
        admit = self._now()
        with self._lock:
            self._queued -= 1
            self._active += 1
        try:
            if ctx is not None:
                ctx.hops.append("admit")
            self._sleep(len(prompt) * self.prefill_ms_per_token / 1000.0)
            first = self._now()
            toks = sim_tokens(prompt, int(max_new_tokens), self.vocab_size,
                              self.seed)
            emitted: List[int] = []
            for i, tok in enumerate(toks):
                if cancel_ev.is_set():
                    self.cancelled += 1
                    return {"ok": False, "rid": rid, "error": "cancelled",
                            "cancelled": True}
                if i > 0:
                    self._sleep(self.itl_ms / 1000.0)
                emitted.append(tok)
            finish = self._now()
            ttft_ms = (first - submit) * 1000.0
            e2e_ms = (finish - submit) * 1000.0
            with self._lock:
                self.completed += 1
                self.tokens_total += len(emitted)
                self._ttft_ms.append(ttft_ms)
                self._e2e_ms.append(e2e_ms)
            if ctx is not None:
                ctx.hops.append("finish")
            self._book_trace(rid, ctx, submit, admit, first, ttft_ms,
                             e2e_ms, len(emitted))
            return {"ok": True, "rid": rid, "tokens": emitted,
                    "ttft_ms": round(ttft_ms, 4), "e2e_ms": round(e2e_ms, 4)}
        finally:
            with self._lock:
                self._active -= 1
                self._cancel.pop(int(rid), None)
            self._sem.release()

    def _book_trace(self, rid: int, ctx, submit: float, admit: float,
                    first: float, ttft_ms: float, e2e_ms: float,
                    tokens: int) -> None:
        """Book a reqtrace-shaped completion event so ``obs_trace`` can
        reconcile the router's echoed ``engine_ttft_ms`` against the
        replica's own record — exact TTFT decomposition included
        (``other_wait_ms`` soaks float ulps, keeping recon err at 0)."""
        if self.obs is None:
            return
        queue_wait_ms = (admit - submit) * 1000.0
        prefill_ms = (first - admit) * 1000.0
        other_wait_ms = ttft_ms - queue_wait_ms - prefill_ms
        violated = int(self.slo_ttft_ms is not None
                       and ttft_ms > self.slo_ttft_ms)
        trace_id = (ctx.trace_id if ctx is not None
                    else f"ptd-engine:{self.replica_id}-{rid:08x}")
        self.obs.log_event(
            "reqtrace", rid=rid, trace_id=trace_id,
            submit_t=round(submit, 6), ttft_ms=round(ttft_ms, 4),
            e2e_ms=round(e2e_ms, 4), tokens=tokens, preemptions=0,
            queue_wait_ms=round(queue_wait_ms, 4),
            prefill_ms=round(prefill_ms, 4),
            redo_wait_ms=0.0, defrag_wait_ms=0.0,
            other_wait_ms=round(other_wait_ms, 4),
            decode_ms=round(e2e_ms - ttft_ms, 4),
            redo_own_ms=0.0, defrag_run_ms=0.0, other_run_ms=0.0,
            preempt_redo_ms=0.0,
            queue_wait_share_pct=round(
                100.0 * queue_wait_ms / max(ttft_ms, 1e-9), 2),
            violated=violated, n_spans=0, spans_dropped=0, sampled=0,
            ctx=json.dumps(ctx.to_wire()) if ctx is not None else "")

    def stats_record(self) -> Dict[str, float]:
        with self._lock:
            ttft = sorted(self._ttft_ms)
            queued = float(self._queued)
            active = float(self._active)
            completed = float(self.completed)
            tokens = float(self.tokens_total)
        wall = max(self._now() - self.t0, 1e-9)
        return {"queue_depth": queued, "active_seqs": active,
                "kv_occupancy_pct": 100.0 * active / self.max_batch,
                "ttft_p50_ms": _quantile(ttft, 0.50),
                "ttft_p95_ms": _quantile(ttft, 0.95),
                "ttft_p99_ms": _quantile(ttft, 0.99),
                "requests_completed": completed,
                "tokens_per_s": tokens / wall}

    def close(self) -> None:
        pass


class EngineBackend:
    """The real ``ServingEngine`` behind the replica wire (lazy jax).

    A background thread steps the engine whenever it has queued or
    active work; ``generate`` submits and blocks on completion.  Cancel
    is unsupported here (the ledger/rid-cache still guarantee a hedge
    loser is never double-delivered — it just runs to completion).
    """

    def __init__(self, *, replica_id: int = 0, vocab_size: int = 64,
                 d_model: int = 32, n_heads: int = 4, n_layers: int = 2,
                 max_batch: int = 4, kv_blocks: int = 64,
                 block_size: int = 16, blocks_per_seq: int = 8,
                 chunk_size: int = 8, max_new_tokens: int = 16,
                 seed: int = 0, obs=None, trace=None):
        from pytorch_distributed_tpu.serving.engine import (
            ServingEngine, init_lm_params)
        from pytorch_distributed_tpu.serving.scheduler import Request
        self.replica_id = int(replica_id)
        self._Request = Request
        params = init_lm_params(vocab_size, d_model, n_heads, n_layers,
                                block_size=block_size, seed=seed)
        self.eng = ServingEngine(
            params, vocab_size=vocab_size, d_model=d_model, n_heads=n_heads,
            n_layers=n_layers, max_batch=max_batch, kv_blocks=kv_blocks,
            block_size=block_size, blocks_per_seq=blocks_per_seq,
            chunk_size=chunk_size, max_new_tokens=max_new_tokens,
            obs=obs, trace=trace, seed=seed)
        self.obs = obs
        self.completed = 0
        self.cancelled = 0
        self._lock = threading.Lock()
        self._done: Dict[int, threading.Event] = {}
        self._reqs: Dict[int, Any] = {}
        self._seen = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._step_loop, daemon=True)
        self._thread.start()

    def _step_loop(self) -> None:
        while not self._stop.is_set():
            busy = False
            with self._lock:
                if self.eng.sched.active or self.eng.sched.queue_depth:
                    self.eng.step()
                    busy = True
                for req in self.eng.finished[self._seen:]:
                    self._seen += 1
                    ev = self._done.get(req.rid)
                    if ev is not None:
                        ev.set()
            if not busy:
                time.sleep(0.002)

    def cancel(self, rid: int) -> bool:  # noqa: ARG002
        return False

    def generate(self, rid: int, prompt: List[int], max_new_tokens: int,
                 ctx=None) -> Dict[str, Any]:
        ev = threading.Event()
        with self._lock:
            req = self._Request(rid=int(rid), prompt=list(prompt),
                                max_new_tokens=int(max_new_tokens),
                                arrival_time=time.monotonic(),
                                trace_ctx=ctx)
            self._done[int(rid)] = ev
            self._reqs[int(rid)] = req
            self.eng.submit(req)
        ev.wait(timeout=600.0)
        with self._lock:
            self._done.pop(int(rid), None)
            self._reqs.pop(int(rid), None)
        if req.finish_time is None:
            return {"ok": False, "rid": rid, "error": "engine timeout"}
        self.completed += 1
        ttft_ms = 1000.0 * ((req.first_token_time or req.arrival_time)
                            - req.arrival_time)
        e2e_ms = 1000.0 * (req.finish_time - req.arrival_time)
        # graft engine-side hops onto the forwarded context: submit()
        # replaces trace_ctx when a tracer is armed, so the wire chain
        # is forwarded hops + whatever the engine recorded.
        if ctx is not None and req.trace_ctx is not None \
                and req.trace_ctx is not ctx:
            ctx.hops.extend(req.trace_ctx.hops)
        return {"ok": True, "rid": rid,
                "tokens": [int(t) for t in req.generated],
                "ttft_ms": round(ttft_ms, 4), "e2e_ms": round(e2e_ms, 4)}

    def stats_record(self) -> Dict[str, float]:
        with self._lock:
            q = float(self.eng.sched.queue_depth)
            active = float(len(self.eng.sched.active))
            occ = float(self.eng.pool.occupancy_pct())
        ttft = sorted(1000.0 * ((r.first_token_time or 0.0) - r.arrival_time)
                      for r in self.eng.finished if r.first_token_time)
        s = self.eng.summary() if self.eng.finished else {}
        return {"queue_depth": q, "active_seqs": active,
                "kv_occupancy_pct": occ,
                "ttft_p50_ms": _quantile(ttft, 0.50),
                "ttft_p95_ms": _quantile(ttft, 0.95),
                "ttft_p99_ms": _quantile(ttft, 0.99),
                "requests_completed": float(len(self.eng.finished)),
                "tokens_per_s": float(s.get("tokens_per_s", 0.0))}

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


class ReplicaServer:
    """HTTP surface for one replica backend (see module docstring)."""

    def __init__(self, backend, *, replica_id: int = 0, port: int = 0,
                 host: str = "127.0.0.1", hb_dir: Optional[str] = None,
                 hb_interval_s: float = 1.0, epoch: int = 0,
                 world: Optional[int] = None, max_cache: int = 65536):
        self.backend = backend
        self.replica_id = int(replica_id)
        self.port = int(port)
        self.host = host
        self.draining = False
        self.drained = False
        self.inflight = 0
        self.cache_hits = 0
        self._cache: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()
        self.max_cache = int(max_cache)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._server: Optional[ThreadingHTTPServer] = None
        self._router_mod = _serving_module("router")
        self._reqtrace = self._router_mod._obs_module("reqtrace")
        self._export = self._router_mod._obs_module("export")
        self._hb = None
        if hb_dir:
            hb_mod = self._router_mod._obs_module("heartbeat")
            self._hb = hb_mod.HeartbeatWriter(
                hb_dir, process_index=self.replica_id, interval_s=0.0,
                world=world, epoch=epoch)
        self._hb_interval_s = float(hb_interval_s)

    # -- request handling -------------------------------------------------

    def handle_generate(self, payload: dict):
        try:
            rid = int(payload["rid"])
            prompt = [int(t) for t in payload.get("prompt", [])]
            n = int(payload.get("max_new_tokens", 8))
        except (KeyError, TypeError, ValueError):
            return 400, {"ok": False, "error": "bad request"}
        with self._lock:
            cached = self._cache.get(rid)
            if cached is not None:
                # idempotent replay: the original result, bit-for-bit.
                self.cache_hits += 1
                out = dict(cached)
                out["cached"] = True
                return 200, out
            if self.draining:
                return 200, {"ok": False, "rid": rid, "error": "draining",
                             "draining": True}
            self.inflight += 1
        try:
            ctx = None
            if payload.get("ctx"):
                try:
                    ctx = self._reqtrace.TraceContext.from_wire(
                        payload["ctx"])
                except (KeyError, TypeError, ValueError):
                    ctx = None
            if ctx is not None:
                ctx.hops.append(f"replica{self.replica_id}:recv")
            res = self.backend.generate(rid, prompt, n, ctx=ctx)
            if res.get("ok"):
                res["replica"] = self.replica_id
                res["cached"] = False
                if ctx is not None:
                    res["ctx"] = ctx.to_wire()
                with self._lock:
                    self._cache[rid] = res
                    while len(self._cache) > self.max_cache:
                        self._cache.popitem(last=False)
            return 200, res
        finally:
            with self._lock:
                self.inflight -= 1
                if self.draining and self.inflight == 0:
                    self.drained = True

    def handle_drain(self, wait: bool = False, timeout_s: float = 30.0):
        with self._lock:
            self.draining = True
            if self.inflight == 0:
                self.drained = True
        if wait:
            t_end = time.monotonic() + timeout_s
            while not self.drained and time.monotonic() < t_end:
                time.sleep(0.01)
        return {"ok": True, "draining": True, "drained": self.drained,
                "inflight": self.inflight, "replica": self.replica_id}

    def healthz(self) -> dict:
        return {"ok": True, "replica": self.replica_id,
                "draining": self.draining, "drained": self.drained,
                "inflight": self.inflight,
                "completed": getattr(self.backend, "completed", 0)}

    def stats(self) -> dict:
        return {"replica": self.replica_id, "inflight": self.inflight,
                "draining": self.draining,
                "computed": getattr(self.backend, "completed", 0),
                "cancelled": getattr(self.backend, "cancelled", 0),
                "cache_hits": self.cache_hits,
                "cache_size": len(self._cache)}

    def render_metrics(self) -> str:
        line = self._export._line
        rec = self.backend.stats_record()
        lbl = {"rank": str(self.replica_id)}
        out = [line("ptd_up", lbl, 1.0),
               line("ptd_serving_queue_depth", lbl, rec["queue_depth"]),
               line("ptd_serving_active_seqs", lbl, rec["active_seqs"]),
               line("ptd_serving_kv_occupancy_pct", lbl,
                    rec["kv_occupancy_pct"]),
               line("ptd_serving_requests_completed_total", lbl,
                    rec["requests_completed"]),
               line("ptd_serving_tokens_per_second", lbl,
                    rec["tokens_per_s"])]
        for q in ("p50", "p95", "p99"):
            out.append(line("ptd_serving_ttft_ms",
                            {**lbl, "quantile": q}, rec[f"ttft_{q}_ms"]))
        return "\n".join(out) + "\n"

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802
                pass

            def _send(self, code: int, body: str,
                      ctype: str = "application/json") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                if self.path.startswith("/healthz"):
                    self._send(200, json.dumps(server.healthz()))
                elif self.path.startswith("/metrics"):
                    self._send(200, server.render_metrics(),
                               "text/plain; version=0.0.4")
                elif self.path.startswith("/stats"):
                    self._send(200, json.dumps(server.stats()))
                else:
                    self._send(404, json.dumps({"ok": False}))

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._send(400, json.dumps(
                        {"ok": False, "error": "bad json"}))
                    return
                if self.path.startswith("/generate"):
                    code, body = server.handle_generate(payload)
                    self._send(code, json.dumps(body))
                elif self.path.startswith("/drain"):
                    self._send(200, json.dumps(server.handle_drain(
                        wait=bool(payload.get("wait")))))
                elif self.path.startswith("/cancel"):
                    ok = server.backend.cancel(payload.get("rid", -1))
                    self._send(200, json.dumps(
                        {"ok": True, "cancelled": bool(ok)}))
                else:
                    self._send(404, json.dumps({"ok": False}))

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        if self._hb is not None:
            threading.Thread(target=self._beat_loop, daemon=True).start()

    def _beat_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._hb.beat(getattr(self.backend, "completed", 0),
                              force=True)
            except OSError:
                pass
            self._stop.wait(self._hb_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        self.backend.close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
