"""Resilient fleet router: health-checked dispatch over N serving replicas
(ISSUE 19 tentpole).

Stdlib-only and import-time jax-free (same ``_sibling_module`` discipline
as ``obs/alerts.py``): the router, the registry, and the arbiter decision
logic all load by file path with no package import, so ``serve_fleet.py
--selftest`` and the chaoskit kill drills run on a bare CPU host without
paying a jax import.

The pieces, bottom up:

- ``ReplicaRegistry`` — health-checked membership over the replicas'
  existing ``/healthz`` + ``/metrics`` surface (``ptd_serving_*`` gauges:
  queue depth, kv occupancy, ttft_p99) and ``obs/heartbeat`` beat age.
  Least-loaded ``pick()``; a failing replica is QUARANTINED and re-probed
  with exponential backoff, and the first UP→QUARANTINED transition fires
  ``on_down`` (the router books the ``replica_down`` ft_event + alert).
- ``RouterPolicy`` — the per-request robustness envelope: deadline
  budget, bounded retries with jittered backoff routed to a *different*
  replica, optional tail hedging (duplicate the request after a
  p95-derived delay; the first success cancels the loser).
- ``CompletionLedger`` — exactly-once bookkeeping keyed on rid: the
  first completion wins, replays return the cached result, duplicates
  are counted, never double-delivered.
- ``FleetRouter`` — the HTTP front: ``POST /generate`` (dispatch),
  ``GET /healthz``, ``GET /metrics`` (``ptd_fleet_*`` gauges for
  obs_live), ``POST /drain`` (stop admission, let in-flight finish).
- ``decide_scale`` / ``FleetArbiter`` — elastic autoscaling against
  measured SLO headroom, reusing ``ft/elastic.py``'s membership protocol
  (the PR 14 alert→eviction loop) for grow/shrink; scale events are
  booked as ft_events.

Tracing: a ``TraceContext`` rides every hop.  The router appends
``router:recv`` / ``dispatch:replicaN`` / ``retry:replicaM`` /
``hedge:replicaK`` hops and forwards the wire dict; the winning replica
returns the context extended with its engine-side hops, so one trace
spans router queue → (retries/hedges as sibling hops) → engine admission
→ completion.  Per-request ``fleettrace`` ft_events decompose router
latency into ``router_wait_ms`` / ``redispatch_ms`` / ``hedge_wait_ms``
such that ``router_ttft_ms == router_wait + redispatch + hedge_wait +
engine_ttft_ms`` *exactly*; ``obs_trace`` reconciles the echoed
``engine_ttft_ms`` against the replica's own reqtrace record.
"""

from __future__ import annotations

import collections
import dataclasses
import importlib
import importlib.util
import json
import os
import random
import socket
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from queue import Empty, Queue
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _obs_module(name: str):
    """Load ``obs/<name>.py`` without importing the (jax-heavy) package.

    Same resolution order as ``obs/alerts.py``'s ``_sibling_module``: a
    package-imported module wins, then the path-loaded alias, then a
    fresh path load — so in-process objects are shared with any caller
    that already has the real package up.
    """
    full = f"pytorch_distributed_tpu.obs.{name}"
    if full in sys.modules:
        return sys.modules[full]
    if "pytorch_distributed_tpu" in sys.modules:
        return importlib.import_module(full)
    alias = f"_ptd_obs_{name}"
    if alias in sys.modules:
        return sys.modules[alias]
    path = os.path.join(_PKG_ROOT, "obs", f"{name}.py")
    spec = importlib.util.spec_from_file_location(alias, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[alias] = mod
    spec.loader.exec_module(mod)
    return mod


def _ft_elastic():
    """Load ``ft/elastic.py`` jax-free.

    ``elastic.py`` imports ``ft.chaos`` at module top and
    ``obs.heartbeat`` lazily — both by dotted name.  Seeding those dotted
    names in ``sys.modules`` from path loads satisfies the imports
    without touching the package ``__init__`` (Python resolves the full
    dotted name against ``sys.modules`` before importing parents), so
    the arbiter shares the one membership/eviction code path with
    ``elastic_agent.py`` instead of reimplementing it.
    """
    full = "pytorch_distributed_tpu.ft.elastic"
    if full in sys.modules:
        return sys.modules[full]
    if "pytorch_distributed_tpu" in sys.modules:
        return importlib.import_module(full)
    for dotted, rel in (
            ("pytorch_distributed_tpu.ft.chaos", os.path.join("ft", "chaos.py")),
            ("pytorch_distributed_tpu.obs.heartbeat",
             os.path.join("obs", "heartbeat.py")),
            (full, os.path.join("ft", "elastic.py"))):
        if dotted in sys.modules:
            continue
        spec = importlib.util.spec_from_file_location(
            dotted, os.path.join(_PKG_ROOT, rel))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[dotted] = mod
        spec.loader.exec_module(mod)
    return sys.modules[full]


# ---------------------------------------------------------------------------
# wire helpers


def http_json(method: str, url: str, payload: Optional[dict],
              timeout: float) -> dict:
    """One JSON request/response round trip; raises on transport failure."""
    data = None
    headers = {"Accept": "application/json"}
    if payload is not None:
        data = json.dumps(payload).encode()
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=data, headers=headers,
                                 method=method)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def http_text(url: str, timeout: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


#: transport-level failures a retry is allowed to absorb.  HTTP error
#: statuses (urllib raises HTTPError, an URLError subclass) are included:
#: a 5xx/503 from a draining or dying replica must route elsewhere.
TRANSPORT_ERRORS = (urllib.error.URLError, ConnectionError, socket.timeout,
                    OSError, json.JSONDecodeError)


# ---------------------------------------------------------------------------
# registry

UP = "UP"
DOWN = "DOWN"
DRAINING = "DRAINING"
QUARANTINED = "QUARANTINED"

REPLICA_STATES = (UP, DOWN, DRAINING, QUARANTINED)


@dataclasses.dataclass
class ReplicaInfo:
    """One replica's registry row: identity, health, and load gauges."""

    rid: int
    base_url: str
    state: str = DOWN               # unknown until the first probe
    failures: int = 0               # consecutive probe/dispatch failures
    backoff_s: float = 0.5          # current quarantine re-probe delay
    next_probe_t: float = 0.0       # monotonic; QUARANTINED gate
    # scraped gauges (None until the first successful probe)
    queue_depth: Optional[float] = None
    kv_occupancy_pct: Optional[float] = None
    ttft_p99_ms: Optional[float] = None
    beat_age_s: Optional[float] = None
    # router-side counters
    inflight: int = 0               # attempts currently outstanding
    dispatched: int = 0             # attempts ever sent here
    completed: int = 0              # successes returned from here
    down_count: int = 0             # UP -> QUARANTINED transitions

    def score(self) -> float:
        """Least-loaded dispatch key: in-flight + queued work, with kv
        pressure as the tiebreak-scale term."""
        q = self.queue_depth if self.queue_depth is not None else 0.0
        kv = self.kv_occupancy_pct if self.kv_occupancy_pct is not None else 0.0
        return self.inflight + q + kv / 100.0

    def row(self) -> Dict[str, Any]:
        return {"rid": self.rid, "url": self.base_url, "state": self.state,
                "queue_depth": self.queue_depth,
                "kv_occupancy_pct": self.kv_occupancy_pct,
                "ttft_p99_ms": self.ttft_p99_ms,
                "beat_age_s": self.beat_age_s,
                "inflight": self.inflight, "dispatched": self.dispatched,
                "completed": self.completed, "failures": self.failures}


class ReplicaRegistry:
    """Health-checked replica set with quarantine + backoff re-probe.

    ``probe()`` drives state from three signals: ``/healthz`` (liveness +
    draining flag), scraped ``ptd_serving_*`` gauges (load), and
    heartbeat beat-age from ``hb_dir`` (a wedged process keeps its HTTP
    thread alive; the beat goes stale).  Dispatch failures feed back
    through ``mark_failure`` into the same quarantine path.
    """

    def __init__(self, replicas: Dict[int, str], *, hb_dir: Optional[str] = None,
                 probe_timeout: float = 2.0, backoff_initial_s: float = 0.5,
                 backoff_max_s: float = 30.0, max_beat_age_s: float = 60.0,
                 on_down: Optional[Callable[[ReplicaInfo, str], None]] = None,
                 time_fn: Callable[[], float] = time.monotonic):
        self.replicas: Dict[int, ReplicaInfo] = {
            int(rid): ReplicaInfo(rid=int(rid), base_url=url.rstrip("/"),
                                  backoff_s=backoff_initial_s)
            for rid, url in replicas.items()}
        self.hb_dir = hb_dir
        self.probe_timeout = float(probe_timeout)
        self.backoff_initial_s = float(backoff_initial_s)
        self.backoff_max_s = float(backoff_max_s)
        self.max_beat_age_s = float(max_beat_age_s)
        self.on_down = on_down
        self._now = time_fn
        self._lock = threading.Lock()

    # -- membership -------------------------------------------------------

    def add(self, rid: int, url: str) -> ReplicaInfo:
        with self._lock:
            rep = ReplicaInfo(rid=int(rid), base_url=url.rstrip("/"),
                              backoff_s=self.backoff_initial_s)
            self.replicas[rep.rid] = rep
            return rep

    def remove(self, rid: int) -> None:
        with self._lock:
            self.replicas.pop(int(rid), None)

    # -- health -----------------------------------------------------------

    def probe(self, now: Optional[float] = None) -> None:
        now = self._now() if now is None else now
        export = _obs_module("export")
        beats = {}
        if self.hb_dir:
            hb = _obs_module("heartbeat")
            beats = hb.read_heartbeats(self.hb_dir)
        wall = time.time()
        for rep in list(self.replicas.values()):
            if rep.state == QUARANTINED and now < rep.next_probe_t:
                continue
            try:
                hz = http_json("GET", rep.base_url + "/healthz", None,
                               self.probe_timeout)
                ok = bool(hz.get("ok"))
                draining = bool(hz.get("draining"))
            except TRANSPORT_ERRORS:
                ok, draining = False, False
            if not ok:
                self._fail(rep, now, "healthz probe failed")
                continue
            try:
                samples = export.parse_prometheus(
                    http_text(rep.base_url + "/metrics", self.probe_timeout))
                rep.queue_depth = export.sample_value(
                    samples, "ptd_serving_queue_depth")
                rep.kv_occupancy_pct = export.sample_value(
                    samples, "ptd_serving_kv_occupancy_pct")
                rep.ttft_p99_ms = export.sample_value(
                    samples, "ptd_serving_ttft_ms", quantile="p99")
            except TRANSPORT_ERRORS:
                pass  # healthy but gauges unreadable: keep last values
            beat = beats.get(rep.rid)
            rep.beat_age_s = (wall - float(beat["t"])) if beat else None
            if (rep.beat_age_s is not None
                    and rep.beat_age_s > self.max_beat_age_s):
                self._fail(rep, now,
                           f"heartbeat stale ({rep.beat_age_s:.0f}s)")
                continue
            rep.state = DRAINING if draining else UP
            rep.failures = 0
            rep.backoff_s = self.backoff_initial_s

    def _fail(self, rep: ReplicaInfo, now: float, reason: str) -> None:
        was_up = rep.state in (UP, DRAINING)
        rep.failures += 1
        rep.state = QUARANTINED
        rep.next_probe_t = now + rep.backoff_s
        rep.backoff_s = min(rep.backoff_s * 2.0, self.backoff_max_s)
        if was_up:
            rep.down_count += 1
            if self.on_down is not None:
                self.on_down(rep, reason)

    def mark_failure(self, rid: int, reason: str = "dispatch failed") -> None:
        rep = self.replicas.get(int(rid))
        if rep is not None:
            self._fail(rep, self._now(), reason)

    def mark_success(self, rid: int) -> None:
        rep = self.replicas.get(int(rid))
        if rep is not None:
            rep.state = UP
            rep.failures = 0
            rep.backoff_s = self.backoff_initial_s

    # -- dispatch ---------------------------------------------------------

    def pick(self, exclude: Sequence[int] = ()) -> Optional[ReplicaInfo]:
        """Least-loaded UP replica not in ``exclude`` (deterministic
        tiebreak on rid)."""
        with self._lock:
            ups = [r for r in self.replicas.values()
                   if r.state == UP and r.rid not in exclude]
            if not ups:
                return None
            return min(ups, key=lambda r: (r.score(), r.rid))

    def up(self) -> List[ReplicaInfo]:
        return [r for r in self.replicas.values() if r.state == UP]

    def quarantined(self) -> List[ReplicaInfo]:
        return [r for r in self.replicas.values() if r.state == QUARANTINED]

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.row() for r in
                    sorted(self.replicas.values(), key=lambda r: r.rid)]


# ---------------------------------------------------------------------------
# policy + ledger


@dataclasses.dataclass
class RouterPolicy:
    """Per-request robustness envelope."""

    deadline_s: float = 30.0        # total budget per request
    max_retries: int = 2            # re-dispatches after the first attempt
    retry_backoff_s: float = 0.05   # base, doubled per retry
    retry_jitter: float = 0.5       # +U(0, jitter) multiplier on backoff
    hedge: bool = False             # arm tail hedging
    hedge_quantile: float = 0.95    # latency quantile deriving the delay
    hedge_min_s: float = 0.02       # floor under the derived delay
    hedge_floor_samples: int = 8    # reservoir size before hedging arms
    seed: int = 0                   # jitter determinism (xor'd with rid)


class CompletionLedger:
    """Exactly-once completion bookkeeping keyed on rid.

    ``book`` returns True only for the first completion of a rid; later
    completions (hedge losers, replays after a router-visible retry
    raced a slow success) are suppressed and counted.  ``get`` serves
    idempotent replay: a client re-sending a completed rid receives the
    original result bit-for-bit.
    """

    def __init__(self, max_entries: int = 65536):
        self.max_entries = int(max_entries)
        self._done: "collections.OrderedDict[int, dict]" = collections.OrderedDict()
        self.duplicates = 0
        self._lock = threading.Lock()

    def book(self, rid: int, result: dict) -> bool:
        with self._lock:
            if rid in self._done:
                self.duplicates += 1
                return False
            self._done[rid] = result
            while len(self._done) > self.max_entries:
                self._done.popitem(last=False)
            return True

    def get(self, rid: int) -> Optional[dict]:
        with self._lock:
            return self._done.get(rid)

    def __len__(self) -> int:
        return len(self._done)


class FleetStats:
    """Router-level counters surfaced as ``ptd_fleet_*`` gauges, the
    periodic fleet step record, and the ``== fleet ==`` report fold."""

    FIELDS = ("requests_routed", "requests_completed", "requests_failed",
              "retries", "hedges", "hedges_won", "hedges_lost",
              "duplicates_suppressed", "replica_down_events",
              "drain_events", "scale_up_events", "scale_down_events")

    def __init__(self):
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)
        self.last_scale = "none"

    def bump(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            d = {f: getattr(self, f) for f in self.FIELDS}
            d["last_scale"] = self.last_scale
            return d


# ---------------------------------------------------------------------------
# router


def _new_ctx(reqtrace, rid: int, t: float):
    return reqtrace.TraceContext(trace_id=f"ptd-router-{rid:08x}", rid=rid,
                                 submit_t=t, hops=["router:0"])


class FleetRouter:
    """HTTP request router over a ``ReplicaRegistry``.

    Call ``submit(payload)`` in-process (drills, selftests) or run
    ``serve()`` for the HTTP surface; both share one dispatch path.
    """

    def __init__(self, registry: ReplicaRegistry,
                 policy: Optional[RouterPolicy] = None, *,
                 obs=None, alert_engine=None,
                 port: int = 0, host: str = "127.0.0.1",
                 probe_interval_s: float = 1.0,
                 time_fn: Callable[[], float] = time.monotonic,
                 sleep_fn: Callable[[float], None] = time.sleep):
        self.registry = registry
        self.policy = policy or RouterPolicy()
        self.obs = obs
        self.alert_engine = alert_engine
        self.port = int(port)
        self.host = host
        self.probe_interval_s = float(probe_interval_s)
        self._now = time_fn
        self._sleep = sleep_fn
        self.ledger = CompletionLedger()
        self.stats = FleetStats()
        self.draining = False
        self.inflight = 0
        self._lock = threading.Lock()
        self._latency_ms: collections.deque = collections.deque(maxlen=512)
        self._reqtrace = _obs_module("reqtrace")
        self._cycle = 0
        self._server: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        if registry.on_down is None:
            registry.on_down = self._on_replica_down

    # -- health/bookkeeping ----------------------------------------------

    def _on_replica_down(self, rep: ReplicaInfo, reason: str) -> None:
        """First UP→QUARANTINED transition: book the ft_event + alert."""
        self.stats.bump("replica_down_events")
        if self.obs is not None:
            self.obs.log_event("replica_down", replica=rep.rid,
                               url=rep.base_url, reason=reason)
            if self.alert_engine is not None:
                self.alert_engine.observe(
                    {"ft_event": "replica_down", "replica": rep.rid,
                     "reason": reason, "t": time.time(),
                     "process": self.obs.process_index})

    def log_cycle(self, dt_s: float) -> None:
        """One probe cycle's fleet step record (flush-time sinks see it)."""
        if self.obs is None:
            return
        self._cycle += 1
        d = self.stats.as_dict()
        extra = {"fleet": 1.0,
                 "replicas_up": float(len(self.registry.up())),
                 "replicas_quarantined": float(len(self.registry.quarantined())),
                 "replicas_total": float(len(self.registry.replicas))}
        for f in FleetStats.FIELDS:
            extra[f"fleet_{f}"] = float(d[f])
        routed = max(1, d["requests_routed"])
        extra["retry_rate_pct"] = 100.0 * d["retries"] / routed
        hedges = max(1, d["hedges"])
        extra["hedge_win_rate_pct"] = 100.0 * d["hedges_won"] / hedges
        self.obs.log_step(self._cycle, max(dt_s, 1e-9), extra=extra)

    # -- hedging ----------------------------------------------------------

    def _hedge_delay(self) -> Optional[float]:
        """p95-ish delay from the completed-latency reservoir; None until
        the reservoir has enough samples to trust."""
        if not self.policy.hedge:
            return None
        lat = sorted(self._latency_ms)
        if len(lat) < self.policy.hedge_floor_samples:
            return None
        q = min(max(self.policy.hedge_quantile, 0.0), 1.0)
        idx = min(len(lat) - 1, int(q * len(lat)))
        return max(self.policy.hedge_min_s, lat[idx] / 1000.0)

    # -- dispatch ---------------------------------------------------------

    def _call_replica(self, rep: ReplicaInfo, payload: dict, ctx,
                      timeout: float) -> Tuple[bool, dict]:
        body = dict(payload)
        body["ctx"] = ctx.to_wire()
        rep.inflight += 1
        rep.dispatched += 1
        try:
            resp = http_json("POST", rep.base_url + "/generate", body,
                             max(0.05, timeout))
            if not resp.get("ok"):
                return False, {"error": resp.get("error", "replica refused")}
            rep.completed += 1
            self.registry.mark_success(rep.rid)
            return True, resp
        except TRANSPORT_ERRORS as e:
            self.registry.mark_failure(rep.rid, f"dispatch: {e!r}")
            return False, {"error": repr(e)}
        finally:
            rep.inflight -= 1

    def submit(self, payload: dict) -> Tuple[int, dict]:
        """Dispatch one request; returns ``(http_status, response_dict)``."""
        try:
            rid = int(payload["rid"])
        except (KeyError, TypeError, ValueError):
            return 400, {"ok": False, "error": "missing/invalid rid"}
        with self._lock:
            if self.draining:
                return 503, {"ok": False, "error": "router draining"}
            self.inflight += 1
        try:
            return self._dispatch(payload, rid)
        finally:
            with self._lock:
                self.inflight -= 1

    def _dispatch(self, payload: dict, rid: int) -> Tuple[int, dict]:
        t0 = self._now()
        cached = self.ledger.get(rid)
        if cached is not None:
            self.stats.bump("duplicates_suppressed")
            out = dict(cached)
            out["replayed"] = True
            return 200, out
        self.stats.bump("requests_routed")
        policy = self.policy
        rt = self._reqtrace
        if payload.get("ctx"):
            ctx = rt.TraceContext.from_wire(payload["ctx"])
        else:
            ctx = _new_ctx(rt, rid, t0)
        ctx.hops.append("router:recv")
        deadline = t0 + policy.deadline_s
        rng = random.Random(policy.seed ^ (rid * 0x9E3779B1))
        tried: set = set()
        attempts = 0
        router_wait_ms: Optional[float] = None
        redispatch_ms = 0.0
        last_err = "no replica available"
        while self._now() < deadline and attempts <= policy.max_retries:
            rep = self.registry.pick(exclude=tried)
            if rep is None and tried:
                # every distinct replica tried: allow a second lap rather
                # than failing a request the fleet could still serve.
                rep = self.registry.pick()
            if rep is None:
                self._sleep(min(0.05, max(0.0, deadline - self._now())))
                self.registry.probe()
                continue
            attempt_start = self._now()
            if router_wait_ms is None:
                router_wait_ms = (attempt_start - t0) * 1000.0
            ctx.hops.append(("dispatch" if attempts == 0 else "retry")
                            + f":replica{rep.rid}")
            if attempts > 0:
                self.stats.bump("retries")
            attempts += 1
            ok, res, hedge_wait_ms, won_rep = self._attempt_with_hedge(
                rep, payload, ctx, deadline, tried)
            if ok:
                return self._complete(payload, rid, t0, ctx, res, won_rep,
                                      attempts, router_wait_ms,
                                      redispatch_ms, hedge_wait_ms)
            last_err = res.get("error", "attempt failed")
            tried.add(rep.rid)
            redispatch_ms += (self._now() - attempt_start) * 1000.0
            backoff = (policy.retry_backoff_s * (2 ** (attempts - 1))
                       * (1.0 + rng.random() * policy.retry_jitter))
            wait = min(backoff, max(0.0, deadline - self._now()))
            if wait > 0:
                self._sleep(wait)
                redispatch_ms += wait * 1000.0
        self.stats.bump("requests_failed")
        return 504, {"ok": False, "rid": rid, "error": last_err,
                     "attempts": attempts,
                     "deadline_exceeded": self._now() >= deadline}

    def _attempt_with_hedge(self, rep: ReplicaInfo, payload: dict, ctx,
                            deadline: float, tried: set):
        """One attempt, optionally shadowed by a tail hedge.

        Returns ``(ok, result, hedge_wait_ms, winner_replica)`` where
        ``hedge_wait_ms`` is the time the winning *hedge* spent waiting
        to launch (0 when the primary wins — the decomposition stays
        exact)."""
        results: Queue = Queue()
        budget = max(0.05, deadline - self._now())

        def run(target: ReplicaInfo, is_hedge: bool):
            ok, res = self._call_replica(target, payload, ctx, budget)
            results.put((ok, res, target, is_hedge))

        t_launch = self._now()
        threading.Thread(target=run, args=(rep, False), daemon=True).start()
        outstanding = 1
        hedge_rep: Optional[ReplicaInfo] = None
        hedge_wait_ms = 0.0
        delay = self._hedge_delay()
        if delay is not None:
            try:
                first = results.get(timeout=min(delay, budget))
                outstanding -= 1
                return self._settle(first, None, results, outstanding)
            except Empty:
                hedge_rep = self.registry.pick(
                    exclude=tried | {rep.rid})
                if hedge_rep is not None:
                    hedge_wait_ms = (self._now() - t_launch) * 1000.0
                    ctx.hops.append(f"hedge:replica{hedge_rep.rid}")
                    self.stats.bump("hedges")
                    threading.Thread(target=run, args=(hedge_rep, True),
                                     daemon=True).start()
                    outstanding += 1
        while outstanding > 0 and self._now() < deadline + 1.0:
            try:
                got = results.get(timeout=max(0.05,
                                              deadline + 1.0 - self._now()))
            except Empty:
                break
            outstanding -= 1
            ok, res, target, is_hedge = got
            if ok:
                return self._settle(got, hedge_rep, results, outstanding,
                                    hedge_wait_ms=hedge_wait_ms)
            if outstanding == 0:
                return False, res, 0.0, None
        return False, {"error": "attempt timed out"}, 0.0, None

    def _settle(self, winner, hedge_rep, results: Queue, outstanding: int,
                hedge_wait_ms: float = 0.0):
        ok, res, target, is_hedge = winner
        if hedge_rep is not None:
            self.stats.bump("hedges_won" if is_hedge else "hedges_lost")
            # first winner cancels the loser (best-effort; the ledger
            # suppresses a loser that completes anyway).
            loser_rep = (hedge_rep if not is_hedge else None)
            self._cancel_loser(res.get("rid"), loser_rep, results, outstanding)
        return ok, res, (hedge_wait_ms if is_hedge else 0.0), target

    def _cancel_loser(self, rid, loser_rep: Optional[ReplicaInfo],
                      results: Queue, outstanding: int) -> None:
        """POST /cancel to whichever replica still holds the duplicate."""
        targets = ([loser_rep] if loser_rep is not None
                   else list(self.registry.up()))
        def _go():
            for t in targets:
                try:
                    http_json("POST", t.base_url + "/cancel",
                              {"rid": rid}, 1.0)
                except TRANSPORT_ERRORS:
                    pass
            # drain the loser's eventual result so the queue thread exits
            for _ in range(outstanding):
                try:
                    results.get(timeout=5.0)
                except Empty:
                    break
        threading.Thread(target=_go, daemon=True).start()

    def _complete(self, payload: dict, rid: int, t0: float, ctx, res,
                  won_rep: Optional[ReplicaInfo], attempts: int,
                  router_wait_ms: float, redispatch_ms: float,
                  hedge_wait_ms: float) -> Tuple[int, dict]:
        now = self._now()
        router_e2e_ms = (now - t0) * 1000.0
        self._latency_ms.append(router_e2e_ms)
        # the winning replica returns the forwarded context extended with
        # its engine-side hops: adopt it so the final chain is one trace.
        if res.get("ctx"):
            try:
                ctx = self._reqtrace.TraceContext.from_wire(res["ctx"])
            except (KeyError, TypeError, ValueError):
                pass
        ctx.hops.append("router:done")
        engine_ttft_ms = float(res.get("ttft_ms", 0.0))
        engine_e2e_ms = float(res.get("e2e_ms", 0.0))
        router_ttft_ms = (router_wait_ms + redispatch_ms + hedge_wait_ms
                          + engine_ttft_ms)
        out = {"ok": True, "rid": rid, "tokens": res.get("tokens", []),
               "replica": won_rep.rid if won_rep else res.get("replica"),
               "attempts": attempts, "hedged": hedge_wait_ms > 0.0,
               "cached": bool(res.get("cached")),
               "ttft_ms": engine_ttft_ms, "e2e_ms": engine_e2e_ms,
               "router_ttft_ms": router_ttft_ms,
               "router_e2e_ms": router_e2e_ms,
               "ctx": ctx.to_wire()}
        first = self.ledger.book(rid, out)
        if not first:
            self.stats.bump("duplicates_suppressed")
            prior = self.ledger.get(rid)
            replay = dict(prior)
            replay["replayed"] = True
            return 200, replay
        self.stats.bump("requests_completed")
        if self.obs is not None:
            self.obs.log_event(
                "fleettrace", rid=rid, trace_id=ctx.trace_id,
                replica=out["replica"], attempts=attempts,
                hedged=int(out["hedged"]),
                router_wait_ms=round(router_wait_ms, 4),
                redispatch_ms=round(redispatch_ms, 4),
                hedge_wait_ms=round(hedge_wait_ms, 4),
                engine_ttft_ms=round(engine_ttft_ms, 4),
                engine_e2e_ms=round(engine_e2e_ms, 4),
                router_ttft_ms=round(router_ttft_ms, 4),
                router_e2e_ms=round(router_e2e_ms, 4),
                ctx=json.dumps(ctx.to_wire()))
        return 200, out

    # -- drain ------------------------------------------------------------

    def drain(self, wait: bool = False, timeout_s: float = 30.0) -> dict:
        with self._lock:
            self.draining = True
        self.stats.bump("drain_events")
        if self.obs is not None:
            self.obs.log_event("drain", scope="router",
                               inflight=self.inflight)
        if wait:
            t_end = self._now() + timeout_s
            while self.inflight > 0 and self._now() < t_end:
                self._sleep(0.01)
        return {"ok": True, "draining": True, "inflight": self.inflight}

    # -- metrics ----------------------------------------------------------

    def render_metrics(self) -> str:
        return render_fleet_metrics(self.registry, self.stats,
                                    draining=self.draining,
                                    inflight=self.inflight)

    # -- HTTP surface ------------------------------------------------------

    def start(self) -> None:
        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802
                pass

            def _send(self, code: int, body: str,
                      ctype: str = "application/json") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                if self.path.startswith("/healthz"):
                    ok = not router.draining and bool(router.registry.up())
                    self._send(200 if ok else 503, json.dumps(
                        {"ok": ok, "role": "router",
                         "draining": router.draining,
                         "replicas_up": len(router.registry.up())}))
                elif self.path.startswith("/metrics"):
                    self._send(200, router.render_metrics(),
                               "text/plain; version=0.0.4")
                elif self.path.startswith("/stats"):
                    self._send(200, json.dumps(
                        {"stats": router.stats.as_dict(),
                         "replicas": router.registry.snapshot(),
                         "ledger": len(router.ledger)}))
                else:
                    self._send(404, json.dumps({"ok": False}))

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._send(400, json.dumps(
                        {"ok": False, "error": "bad json"}))
                    return
                if self.path.startswith("/generate"):
                    code, body = router.submit(payload)
                    self._send(code, json.dumps(body))
                elif self.path.startswith("/drain"):
                    self._send(200, json.dumps(router.drain(
                        wait=bool(payload.get("wait")))))
                else:
                    self._send(404, json.dumps({"ok": False}))

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        probe = threading.Thread(target=self._probe_loop, daemon=True)
        probe.start()
        self._threads.append(probe)

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            t0 = self._now()
            try:
                self.registry.probe()
            except Exception:
                pass
            self.log_cycle(self._now() - t0)
            self._stop.wait(self.probe_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def render_fleet_metrics(registry: ReplicaRegistry, stats: FleetStats, *,
                         draining: bool = False, inflight: int = 0) -> str:
    """Prometheus exposition for the router (``ptd_fleet_*`` namespace —
    names pinned in ``obs/export.py`` ``FLEET_GAUGES``)."""
    export = _obs_module("export")

    def line(name, labels, value):
        if not labels:
            return f"{name} {float(value):g}"
        return export._line(name, labels, value)

    out = [line("ptd_fleet_up", {}, 0.0 if draining else 1.0),
           line("ptd_fleet_inflight", {}, float(inflight))]
    d = stats.as_dict()
    out.append(line("ptd_fleet_requests_total", {},
                    float(d["requests_routed"])))
    out.append(line("ptd_fleet_completed_total", {},
                    float(d["requests_completed"])))
    out.append(line("ptd_fleet_failed_total", {},
                    float(d["requests_failed"])))
    out.append(line("ptd_fleet_retries_total", {}, float(d["retries"])))
    out.append(line("ptd_fleet_hedges_total", {}, float(d["hedges"])))
    out.append(line("ptd_fleet_hedges_won_total", {},
                    float(d["hedges_won"])))
    out.append(line("ptd_fleet_hedges_lost_total", {},
                    float(d["hedges_lost"])))
    out.append(line("ptd_fleet_duplicates_suppressed_total", {},
                    float(d["duplicates_suppressed"])))
    out.append(line("ptd_fleet_replica_down_total", {},
                    float(d["replica_down_events"])))
    out.append(line("ptd_fleet_last_scale", {"decision": d["last_scale"]},
                    1.0))
    rows = registry.snapshot()
    out.append(line("ptd_fleet_replicas", {}, float(len(rows))))
    out.append(line("ptd_fleet_quarantined", {},
                    float(sum(1 for r in rows if r["state"] == QUARANTINED))))
    for r in rows:
        lbl = {"replica": str(r["rid"])}
        out.append(line("ptd_fleet_replica_state",
                        {**lbl, "state": r["state"]}, 1.0))
        for field, gauge in (
                ("queue_depth", "ptd_fleet_replica_queue_depth"),
                ("kv_occupancy_pct", "ptd_fleet_replica_kv_occupancy_pct"),
                ("ttft_p99_ms", "ptd_fleet_replica_ttft_p99_ms"),
                ("beat_age_s", "ptd_fleet_replica_beat_age_seconds")):
            if r[field] is not None:
                out.append(line(gauge, lbl, float(r[field])))
        out.append(line("ptd_fleet_replica_dispatched_total", lbl,
                        float(r["dispatched"])))
        out.append(line("ptd_fleet_replica_completed_total", lbl,
                        float(r["completed"])))
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# elastic autoscaling


def decide_scale(rows: List[Dict[str, Any]], *, slo_ttft_ms: float,
                 scale_up_pct: float = 85.0, scale_down_pct: float = 30.0,
                 min_replicas: int = 1, max_replicas: int = 8,
                 queue_hi: float = 8.0) -> Tuple[Optional[str],
                                                 Optional[int], str]:
    """Pure scale decision from registry snapshot rows.

    Headroom is measured as worst-replica ``ttft_p99`` against the SLO
    (plus a queue-depth pressure valve).  Returns ``(decision,
    victim_rid, reason)`` where decision is ``"up"``, ``"down"`` or
    ``None`` and ``victim_rid`` names the least-loaded UP replica when
    shrinking.
    """
    ups = [r for r in rows if r["state"] == UP]
    n = len(rows)
    if not ups:
        if n < max_replicas:
            return "up", None, "no UP replicas: grow to restore capacity"
        return None, None, "no UP replicas and at max_replicas"
    ttfts = [r["ttft_p99_ms"] for r in ups if r["ttft_p99_ms"] is not None]
    queues = [r["queue_depth"] or 0.0 for r in ups]
    worst_pct = (100.0 * max(ttfts) / slo_ttft_ms) if ttfts else 0.0
    worst_q = max(queues) if queues else 0.0
    if (worst_pct > scale_up_pct or worst_q > queue_hi) and n < max_replicas:
        return ("up", None,
                f"SLO headroom exhausted: ttft_p99 at {worst_pct:.0f}% of "
                f"SLO, max queue {worst_q:.0f}")
    if worst_pct < scale_down_pct and worst_q == 0.0 and len(ups) > min_replicas:
        victim = min(ups, key=lambda r: ((r["queue_depth"] or 0.0)
                                         + (r["inflight"] or 0), r["rid"]))
        return ("down", victim["rid"],
                f"SLO headroom ample: ttft_p99 at {worst_pct:.0f}% of SLO, "
                f"queues empty")
    return None, None, f"hold: ttft_p99 at {worst_pct:.0f}% of SLO"


class FleetArbiter:
    """Elastic replica-set arbiter (sibling of ``elastic_agent.py``).

    Reuses ``ft/elastic.py``'s membership protocol verbatim: replicas
    beat into ``hb_dir``, membership lives in ``membership.json``, and
    scale-downs/evictions go through ``ElasticCoordinator.decide``'s one
    eviction path (``extra_dead``), exactly like the PR 14
    alert→eviction loop.  Scale events are booked as ft_events.
    """

    def __init__(self, registry: ReplicaRegistry, hb_dir: str, *,
                 slo_ttft_ms: float = 500.0, min_replicas: int = 1,
                 max_replicas: int = 8, scale_up_pct: float = 85.0,
                 scale_down_pct: float = 30.0, obs=None,
                 spawn_cb: Optional[Callable[[int], Optional[str]]] = None,
                 drain_cb: Optional[Callable[[int], bool]] = None,
                 stats: Optional[FleetStats] = None,
                 dead_failures: int = 2,
                 time_fn: Callable[[], float] = time.monotonic):
        elastic = _ft_elastic()
        self.registry = registry
        self.hb_dir = hb_dir
        self.slo_ttft_ms = float(slo_ttft_ms)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_pct = float(scale_up_pct)
        self.scale_down_pct = float(scale_down_pct)
        self.obs = obs
        self.spawn_cb = spawn_cb
        self.drain_cb = drain_cb
        self.stats = stats or FleetStats()
        self.dead_failures = int(dead_failures)
        self._now = time_fn
        self.co = elastic.ElasticCoordinator(
            hb_dir, world=max(len(registry.replicas), self.min_replicas, 1),
            min_ranks=self.min_replicas)
        # a fresh membership file defaults to range(world); the fleet's
        # identities are replica ids, so bootstrap epoch 0 to match.
        want = sorted(registry.replicas)
        m = self.co.membership()
        if want and m.epoch == 0 and set(m.ranks) != set(want):
            elastic.atomic_write_json(
                self.co.path,
                elastic.Membership(epoch=0, ranks=tuple(want)).to_json())

    def _book(self, kind: str, **fields) -> None:
        if self.obs is not None:
            self.obs.log_event(kind, **fields)

    def evict_dead(self) -> List[int]:
        """Quarantined-beyond-doubt replicas leave the membership through
        the coordinator's one eviction path."""
        members = set(self.co.membership().ranks)
        dead = {r.rid: f"replica_down x{r.failures}"
                for r in self.registry.quarantined()
                if r.failures >= self.dead_failures and r.rid in members}
        if not dead:
            return []
        change = self.co.decide(extra_dead=dead)
        if change is None:
            return []
        evicted = sorted(set(change.old.ranks) - set(change.new.ranks))
        for rid in evicted:
            self._book("replica_evict", replica=rid,
                       reason=dead.get(rid, ""), epoch=change.new.epoch)
        return evicted

    def cycle(self) -> Tuple[Optional[str], str]:
        """One arbiter pass: probe, evict the dead, then scale on
        measured headroom.  Returns ``(decision, reason)``."""
        self.registry.probe()
        self.evict_dead()
        rows = self.registry.snapshot()
        live_rows = [r for r in rows
                     if r["rid"] in set(self.co.membership().ranks)
                     or r["state"] == UP]
        decision, victim, reason = decide_scale(
            live_rows, slo_ttft_ms=self.slo_ttft_ms,
            scale_up_pct=self.scale_up_pct,
            scale_down_pct=self.scale_down_pct,
            min_replicas=self.min_replicas, max_replicas=self.max_replicas)
        if decision == "up":
            new_rid = (max(self.registry.replicas) + 1
                       if self.registry.replicas else 0)
            url = self.spawn_cb(new_rid) if self.spawn_cb else None
            if url:
                self.registry.add(new_rid, url)
                self.co.request_join(new_rid)
                self.co.decide()
                self.stats.bump("scale_up_events")
                self.stats.last_scale = f"up:replica{new_rid}"
                self._book("scale_up", replica=new_rid, url=url,
                           reason=reason)
            else:
                decision = None
                reason += " (no spawn capacity)"
        elif decision == "down" and victim is not None:
            drained = self.drain_cb(victim) if self.drain_cb else True
            if drained:
                change = self.co.decide(
                    extra_dead={victim: "scale_down drain"})
                self.registry.remove(victim)
                self.stats.bump("scale_down_events")
                self.stats.bump("drain_events")
                self.stats.last_scale = f"down:replica{victim}"
                self._book("scale_down", replica=victim, reason=reason,
                           epoch=(change.new.epoch if change else -1))
            else:
                decision = None
                reason += " (drain refused)"
        return decision, reason
