"""Deterministic, seedable fault injectors — the chaos half of the FT
subsystem (ISSUE 4 pillar 4).

Every injector is a pure function of ``(seed, step)``: two runs with the
same schedule inject the same faults at the same steps, so the end-to-end
survival tests are reproducible and a failing chaos run can be replayed
byte-for-byte.  Injectors hook into the trainers through the ``chaos=``
parameter (``Trainer``/``LMTrainer``), which calls

- ``on_step(trainer, step)``   once per loop iteration, before the step —
  signal/kill/delay/lr faults;
- ``on_batch(step, batch)``    on the device batch — data corruption (NaN
  poisoning for float inputs);
- ``on_collective(trainer, step)``  inside the recorded collective region,
  between the flight recorder's ``coll_enter`` and the compiled step call
  — stalled-rank faults the hang watchdog must catch (``HangAt``).

File-level corruption (``corrupt_file``) is trainer-independent; it backs
``scripts/chaoskit.py`` and the checkpoint-integrity tests.

jax is imported lazily (inside ``NaNBatchAt.on_batch``) so chaoskit's
no-mesh selftest path never pays a jax import.
"""

from __future__ import annotations

import os
import signal as _signal
import time
from typing import Dict, Iterable, Optional, Sequence, Tuple


class ChaosInjector:
    """Base injector: no-op hooks, subclasses override what they need."""

    def on_step(self, trainer, step: int) -> None:  # noqa: ARG002
        return None

    def on_batch(self, step: int, batch):  # noqa: ARG002
        return batch

    def on_collective(self, trainer, step: int) -> None:  # noqa: ARG002
        return None


class SignalAt(ChaosInjector):
    """Deliver ``signum`` to this process when the loop reaches ``at_step``
    — the deterministic stand-in for a pod preemption notice (SIGTERM at
    step k) or an interactive Ctrl-C (SIGINT)."""

    def __init__(self, at_step: int, signum: int = _signal.SIGTERM,
                 pid: Optional[int] = None):
        self.at_step = int(at_step)
        self.signum = int(signum)
        self.pid = pid
        self.fired = False

    def on_step(self, trainer, step: int) -> None:  # noqa: ARG002
        if not self.fired and step == self.at_step:
            self.fired = True
            os.kill(self.pid if self.pid is not None else os.getpid(),
                    self.signum)


class KillAt(SignalAt):
    """SIGKILL at ``at_step`` — no grace window, no handler, the process
    just disappears (the dead-rank scenario for the live-mesh tests; only
    ``--save-steps`` checkpoints survive this one)."""

    def __init__(self, at_step: int, rank: Optional[int] = None):
        super().__init__(at_step, _signal.SIGKILL)
        self.rank = rank  # None = every rank

    def on_step(self, trainer, step: int) -> None:
        if self.rank is not None:
            import jax

            if jax.process_index() != self.rank:
                return
        super().on_step(trainer, step)


class NaNBatchAt(ChaosInjector):
    """Replace the float leaves of the device batch with NaN at the given
    steps — the divergence-guard trigger for float-input (image) trainers.
    Integer leaves (labels, tokens) pass through untouched."""

    def __init__(self, at_steps: Iterable[int], keys: Optional[Sequence[str]] = None):
        self.at_steps = frozenset(int(s) for s in at_steps)
        self.keys = tuple(keys) if keys is not None else None
        self.injected: list = []

    def on_batch(self, step: int, batch):
        if step not in self.at_steps:
            return batch
        import jax.numpy as jnp

        def poison(k, v):
            if self.keys is not None and k not in self.keys:
                return v
            if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
                return jnp.full_like(v, jnp.nan)
            return v

        self.injected.append(step)
        if isinstance(batch, dict):
            return {k: poison(k, v) for k, v in batch.items()}
        return poison("", batch)


class LRSpikeAt(ChaosInjector):
    """Set ``trainer.lr`` to an absurd value for exactly one step, then
    restore it — models a transient schedule/overflow bug.  One poisoned
    update corrupts the parameters to inf/NaN; every later step is then
    non-finite, which is precisely the K-consecutive pattern the divergence
    guard answers with a rollback + LR backoff (LMTrainer path; the image
    trainer's per-epoch schedule uses ``NaNBatchAt`` instead)."""

    def __init__(self, at_step: int, value: float = 1e30):
        self.at_step = int(at_step)
        self.value = float(value)
        self._saved: Optional[float] = None

    def on_step(self, trainer, step: int) -> None:
        if step == self.at_step:
            self._saved = trainer.lr
            trainer.lr = self.value
        elif self._saved is not None and step == self.at_step + 1:
            trainer.lr = self._saved
            self._saved = None


class DelayRank(ChaosInjector):
    """Sleep ``seconds`` on each step for the given ranks (None = all) —
    the deterministic straggler for heartbeat/step-lag tests."""

    def __init__(self, seconds: float, ranks: Optional[Sequence[int]] = None,
                 every: int = 1):
        self.seconds = float(seconds)
        self.ranks = frozenset(ranks) if ranks is not None else None
        self.every = max(1, int(every))

    def on_step(self, trainer, step: int) -> None:  # noqa: ARG002
        if step % self.every:
            return
        if self.ranks is not None:
            import jax

            if jax.process_index() not in self.ranks:
                return
        time.sleep(self.seconds)


class SlowLoader(ChaosInjector):
    """Sleep ``seconds`` in the batch path for the given ranks (None =
    all) — the deterministic input-starved loader.  The delay lands in
    ``on_batch``, which both trainers call *inside* the step-attribution
    ``data_wait`` window (obs/stepattr.py), so a ``--step-attr`` run
    measures the injected stall as data_wait, not compute: the
    attribution plane must name ``data_wait`` dominant and the
    ``data_wait_share`` alert must fire — that contract is what
    ``chaoskit drill slow-loader`` verifies end to end."""

    def __init__(self, seconds: float, every: int = 1,
                 ranks: Optional[Sequence[int]] = None):
        self.seconds = float(seconds)
        self.every = max(1, int(every))
        self.ranks = frozenset(ranks) if ranks is not None else None
        self.injected = 0

    def on_batch(self, step: int, batch):
        if step % self.every:
            return batch
        if self.ranks is not None:
            import jax

            if jax.process_index() not in self.ranks:
                return batch
        self.injected += 1
        time.sleep(self.seconds)
        return batch


class HangAt(ChaosInjector):
    """Stall ``rank`` for ``seconds`` when the loop reaches ``at_step`` —
    inside the collective region (after the flight recorder's
    ``coll_enter``, before the compiled step call), so the stall is
    exactly what a desynced/stuck collective looks like to the rest of
    the stack.  The hang watchdog must flag it within its window, emit a
    ``hang`` ft_event, and dump the ring pre-mortem; ``postmortem.py``
    must then name the rank.  Fires once (latched), like ``SignalAt``."""

    def __init__(self, at_step: int, seconds: float,
                 rank: Optional[int] = None):
        self.at_step = int(at_step)
        self.seconds = float(seconds)
        self.rank = rank  # None = every rank
        self.fired = False

    def on_collective(self, trainer, step: int) -> None:  # noqa: ARG002
        if self.fired or step != self.at_step:
            return
        if self.rank is not None:
            import jax

            if jax.process_index() != self.rank:
                return
        self.fired = True
        time.sleep(self.seconds)


class ChaosSchedule(ChaosInjector):
    """Compose injectors; trainers call the schedule, it fans out."""

    def __init__(self, *injectors: ChaosInjector):
        self.injectors = list(injectors)

    def on_step(self, trainer, step: int) -> None:
        for inj in self.injectors:
            inj.on_step(trainer, step)

    def on_batch(self, step: int, batch):
        for inj in self.injectors:
            batch = inj.on_batch(step, batch)
        return batch

    def on_collective(self, trainer, step: int) -> None:
        for inj in self.injectors:
            inj.on_collective(trainer, step)


def corrupt_file(path: str, mode: str = "flip", seed: int = 0,
                 nbytes: int = 1) -> Dict[str, object]:
    """Byte-level checkpoint corruption, deterministic in ``seed``.

    - ``mode="flip"``: XOR a random bit in each of ``nbytes`` seed-chosen
      byte offsets (the cosmic-ray / bad-DIMM model);
    - ``mode="truncate"``: cut the file to a seed-chosen 10–90% of its
      size (the torn-write / out-of-quota model).

    Returns a description dict (mode, offsets or new size) so tests and
    chaoskit can log exactly what was injected.  Offsets depend only on
    ``(seed, file size)`` — identical files corrupt identically."""
    import numpy as np

    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot corrupt empty file '{path}'")
    rng = np.random.default_rng((int(seed), size))
    if mode == "flip":
        offsets = sorted(
            int(o) for o in rng.choice(size, size=min(nbytes, size),
                                       replace=False)
        )
        masks = [1 << int(b) for b in rng.integers(0, 8, size=len(offsets))]
        with open(path, "r+b") as f:
            for off, mask in zip(offsets, masks):
                f.seek(off)
                byte = f.read(1)[0]
                f.seek(off)
                f.write(bytes([byte ^ mask]))
        return {"mode": "flip", "offsets": offsets, "masks": masks}
    if mode == "truncate":
        new_size = max(1, int(size * rng.uniform(0.1, 0.9)))
        with open(path, "r+b") as f:
            f.truncate(new_size)
        return {"mode": "truncate", "old_size": size, "new_size": new_size}
    raise ValueError(f"unknown corruption mode {mode!r}: expected "
                     "'flip' or 'truncate'")
