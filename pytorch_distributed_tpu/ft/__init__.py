"""Runtime fault-tolerance subsystem (ISSUE 4): failures as routine events.

Complements the detection layers — ``obs/`` (stragglers, metrics) and
``analysis/`` (static sharding hazards) — with *recovery*:

- ``integrity``  — sha256 sidecars, atomic writes, bounded I/O retries;
  a flipped bit or torn write is detected before deserialization, and
  the loader falls back to the previous retained checkpoint.
- ``divergence`` — ``DivergenceGuard``: the host policy over the step's
  in-graph ``nonfinite`` flag (skip the bad batch; K consecutive → roll
  back to the last-good state with an LR backoff), plus ``StateKeeper``
  (the host-RAM last-good snapshot).
- ``chaos``      — deterministic, seedable fault injectors (SIGTERM/
  SIGKILL at step k, NaN batches, LR spikes, per-rank delay, byte-level
  checkpoint corruption) driving the survival tests and
  ``scripts/chaoskit.py``.
- ``elastic``    — membership-epoch coordination and exact cross-world
  re-sharding (ISSUE 10): ``ElasticCoordinator`` folds heartbeat liveness
  into join/leave decisions, ``ElasticSim`` drives the in-process drills,
  ``LoseRankAt``/``JoinRankAt`` inject membership changes, and the regrid
  helpers move stacked ZeRO-WUS momentum / error-feedback residuals
  between world sizes losslessly.

Step-granular resume itself lives in the trainers + ``train/checkpoint``
(``--save-steps``, iterator state in the checkpoint's ``ft`` record).
"""

from pytorch_distributed_tpu.ft.chaos import (
    ChaosInjector,
    ChaosSchedule,
    DelayRank,
    KillAt,
    LRSpikeAt,
    NaNBatchAt,
    SignalAt,
    corrupt_file,
)
from pytorch_distributed_tpu.ft.divergence import DivergenceGuard, StateKeeper
from pytorch_distributed_tpu.ft.elastic import (
    ElasticCoordinator,
    ElasticSim,
    JoinRankAt,
    LoseRankAt,
    Membership,
    MembershipChange,
    regrid_stacked_residual,
    regrid_wus_momentum,
    rescale_batch,
    rescale_lr,
)
from pytorch_distributed_tpu.ft.integrity import (
    CheckpointCorruptError,
    check_integrity,
    file_sha256,
    read_sidecar,
    replace_with_sidecar,
    retrying,
    sidecar_path,
    verify_sidecar,
    write_sidecar,
)

__all__ = [
    "CheckpointCorruptError",
    "ChaosInjector",
    "ChaosSchedule",
    "DelayRank",
    "DivergenceGuard",
    "ElasticCoordinator",
    "ElasticSim",
    "JoinRankAt",
    "KillAt",
    "LRSpikeAt",
    "LoseRankAt",
    "Membership",
    "MembershipChange",
    "NaNBatchAt",
    "SignalAt",
    "StateKeeper",
    "check_integrity",
    "corrupt_file",
    "regrid_stacked_residual",
    "regrid_wus_momentum",
    "rescale_batch",
    "rescale_lr",
    "file_sha256",
    "read_sidecar",
    "replace_with_sidecar",
    "retrying",
    "sidecar_path",
    "verify_sidecar",
    "write_sidecar",
]
