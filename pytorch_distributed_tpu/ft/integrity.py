"""Checkpoint integrity primitives: sha256 sidecars, atomic writes, and
bounded-backoff I/O retries.

The failure modes these close (ISSUE 4 pillar 3): a single flipped bit in
``checkpoint.msgpack`` previously killed ``--resume`` with a cryptic msgpack
error deep inside flax, a torn write left a half-checkpoint that parsed as
garbage, and one transient NFS hiccup aborted the whole run at save time.
Every checkpoint file now carries a ``<name>.sha256`` sidecar written after
the payload's atomic rename; verification happens *before* deserialization,
so corruption is reported as corruption — and the loader can fall back to
the previous retained checkpoint instead of crashing.

Stdlib-only on purpose: ``scripts/chaoskit.py`` imports this module without
pulling in jax, so the integrity selftest stays a no-mesh fast path.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Callable, Optional, Tuple, Type

SIDECAR_SUFFIX = ".sha256"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed sidecar verification or deserialization."""


def file_sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def sidecar_path(path: str) -> str:
    return path + SIDECAR_SUFFIX


def write_sidecar(path: str) -> str:
    """Write ``<path>.sha256`` atomically (tmp + rename) and return it.

    Written AFTER the payload's own atomic rename: a crash between the two
    leaves a payload without a sidecar (treated as legacy/unverified), never
    a sidecar pointing at a torn payload."""
    digest = file_sha256(path)
    side = sidecar_path(path)
    tmp = side + ".tmp"
    with open(tmp, "w") as f:
        f.write(digest + "\n")
    os.replace(tmp, side)
    return side


def read_sidecar(path: str) -> Optional[str]:
    """The recorded digest for ``path``, or None when no sidecar exists."""
    side = sidecar_path(path)
    if not os.path.exists(side):
        return None
    with open(side) as f:
        return f.read().strip() or None


def verify_sidecar(path: str) -> Optional[bool]:
    """True = digest matches, False = mismatch (corrupt/truncated/stale),
    None = no sidecar to check (pre-FT legacy checkpoint)."""
    want = read_sidecar(path)
    if want is None:
        return None
    return file_sha256(path) == want


def check_integrity(path: str) -> None:
    """Raise ``CheckpointCorruptError`` on a failed sidecar check; silent on
    a match or a missing sidecar (legacy files stay loadable)."""
    ok = verify_sidecar(path)
    if ok is False:
        raise CheckpointCorruptError(
            f"checkpoint '{path}' fails sha256 sidecar verification "
            f"(expected {read_sidecar(path)}, file hashes to "
            f"{file_sha256(path)}): corrupted or truncated on disk"
        )


def replace_with_sidecar(src: str, dst: str) -> None:
    """``os.replace(src, dst)`` moving the sidecar along (if any) — keeps a
    rotated ``checkpoint.prev.msgpack`` independently verifiable."""
    side_src = sidecar_path(src)
    has_side = os.path.exists(side_src)
    os.replace(src, dst)
    if has_side:
        os.replace(side_src, sidecar_path(dst))


def retrying(
    fn: Callable,
    attempts: int = 3,
    base_delay: float = 0.05,
    exceptions: Tuple[Type[BaseException], ...] = (OSError,),
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
):
    """Run ``fn()`` with bounded exponential backoff (``base_delay * 2**k``
    between attempts) — the flaky-shared-filesystem wrapper for checkpoint
    I/O.  Retries only ``exceptions`` (default OSError: NFS ESTALE/EIO
    class); anything else — including ``CheckpointCorruptError``, which
    retrying cannot fix — propagates immediately.  Re-raises the last error
    once ``attempts`` are exhausted."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    for k in range(attempts):
        try:
            return fn()
        except exceptions as e:
            if k == attempts - 1:
                raise
            if on_retry is not None:
                on_retry(k, e)
            sleep(base_delay * (2 ** k))
