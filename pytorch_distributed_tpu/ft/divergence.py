"""Divergence guard: in-graph non-finite detection, host-side skip/rollback
policy, and the last-known-good state snapshot (ISSUE 4 pillar 2).

Division of labor:

- **Inside the jitted step** (``guard_nonfinite=True`` on
  ``make_train_step`` / ``make_lm_train_step``): a ``nonfinite`` flag is
  computed from the loss and global grad norm, and the parameter /
  momentum / BN-stats update is gated with ``jnp.where`` — a bad batch's
  update is *structurally* skipped before the host ever hears about it, so
  NaNs can never enter the weights through a single poisoned batch.
- **Host side** (this module): ``DivergenceGuard`` watches the flag with
  the obs-layer's lazy-sync discipline — flags buffer as *unconverted*
  device scalars and drain in one amortized host sync every
  ``check_every`` observations, so the hot loop never blocks per step.
  The policy: every flagged step is recorded as a ``skip`` ft_event; K
  *consecutive* flagged steps mean skipping isn't working (the state
  itself is corrupt — e.g. an earlier overflow) and the guard asks the
  trainer to roll back to the last-good snapshot with an LR backoff.

``StateKeeper`` holds that snapshot in host RAM (gathered with the
checkpoint module's multi-host-safe ``_to_host``, so every rank must call
``update`` at the same cadence on multi-process meshes — the trainers
refresh it at each ``--save-steps`` boundary).  ``restore`` returns a
host-numpy ``TrainState``; the jitted step's ``in_shardings`` re-place it
on device at the next call, exactly like a ``--resume`` load.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple


class DivergenceGuard:
    """Skip-and-rollback policy over the step's ``nonfinite`` flag.

    >>> guard = DivergenceGuard(rollback_k=3, check_every=10, obs=logger)
    >>> ...
    >>> if guard.observe(step, metrics.get("nonfinite")):
    ...     state = keeper.restore()          # trainer-side rollback
    ...     guard.note_rollback(step, keeper.step)

    ``observe`` returns True when a rollback is due (decided at drain
    cadence, so up to ``check_every - 1`` steps late — the documented price
    of never syncing per step; the in-graph gate has already prevented any
    of those steps from touching the weights).
    """

    def __init__(self, rollback_k: int = 3, check_every: int = 10,
                 lr_backoff: float = 0.5, obs=None):
        if rollback_k < 1:
            raise ValueError(f"rollback_k must be >= 1, got {rollback_k}")
        if not 0.0 < lr_backoff <= 1.0:
            raise ValueError(
                f"lr_backoff must be in (0, 1], got {lr_backoff}")
        self.rollback_k = int(rollback_k)
        self.check_every = max(1, int(check_every))
        self.lr_backoff = float(lr_backoff)
        self.obs = obs
        self.lr_scale = 1.0
        self.consecutive = 0
        self.rollbacks = 0
        self.skipped: List[int] = []      # steps whose update was gated off
        self._pending: List[Tuple[int, Any]] = []  # unconverted flags

    def observe(self, step: int, flag) -> bool:
        """Buffer one step's (possibly unready device) flag; drains every
        ``check_every`` observations.  Returns True when the drain decided
        a rollback is needed."""
        if flag is None:
            return False
        self._pending.append((int(step), flag))
        if len(self._pending) >= self.check_every:
            return self.drain()
        return False

    def drain(self) -> bool:
        """Convert buffered flags (the one amortized host sync) and apply
        the policy.  Idempotent when the buffer is empty."""
        if not self._pending:
            return False
        pending, self._pending = self._pending, []
        rollback = False
        for step, flag in pending:
            bad = float(flag) > 0.0
            if not bad:
                self.consecutive = 0
                continue
            self.consecutive += 1
            self.skipped.append(step)
            self._emit("skip", step=step, consecutive=self.consecutive)
            if self.consecutive >= self.rollback_k:
                rollback = True
        return rollback

    def note_rollback(self, step: int, restored_step: Optional[int]) -> float:
        """Record a completed rollback: backs off the LR scale, resets the
        consecutive counter, emits the ft_event.  Returns the new scale."""
        self.lr_scale *= self.lr_backoff
        self.consecutive = 0
        self.rollbacks += 1
        self._emit("rollback", step=int(step),
                   restored_step=(int(restored_step)
                                  if restored_step is not None else -1),
                   lr_scale=self.lr_scale)
        return self.lr_scale

    def _emit(self, kind: str, **fields) -> None:
        if self.obs is not None and hasattr(self.obs, "log_event"):
            self.obs.log_event(kind, **fields)


class StateKeeper:
    """Host-RAM snapshot of the last-known-good ``TrainState``.

    Rollback source of last resort when no on-disk checkpoint exists yet
    (and the fast path when one does — no filesystem round-trip).  Uses the
    checkpoint module's ``_to_host``, which all-gathers non-addressable
    (multi-host-sharded) leaves, so on multi-process meshes every rank must
    call ``update`` at the same step — the trainers do it at the
    ``--save-steps`` cadence, right where ``save_checkpoint`` already has
    the same collective requirement."""

    def __init__(self):
        self._host = None
        self.step: Optional[int] = None

    @property
    def has_snapshot(self) -> bool:
        return self._host is not None

    def update(self, state, step: int) -> None:
        from pytorch_distributed_tpu.train.checkpoint import _to_host

        self._host = _to_host(state)
        self.step = int(step)

    def restore(self):
        """The snapshot as a host-numpy TrainState (caller assigns it; the
        jitted step's in_shardings re-shard on the next call)."""
        if self._host is None:
            raise RuntimeError(
                "StateKeeper has no snapshot to restore (update() never "
                "called)")
        import jax

        # Copy: the trainer will donate the restored leaves into the step.
        return jax.tree_util.tree_map(lambda x: x.copy(), self._host)
