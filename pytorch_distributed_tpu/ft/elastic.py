"""Elastic training: membership epochs, re-mesh decisions, and exact
state re-sharding across world sizes (ISSUE 10 tentpole).

PR 4's fault tolerance survives failures by dying and resuming at the same
world size; production fleets shrink and grow.  This module supplies the
three missing pieces:

- **Membership coordination.**  ``ElasticCoordinator`` maintains a
  monotonically increasing *membership epoch* over a shared heartbeat
  directory: it folds ``obs.heartbeat.find_stragglers``' dead/slow split
  into leave decisions (dead → evict, slow → keep-but-flag — a dragging
  host rate-limits the mesh but does not corrupt it) and admits ranks that
  filed a join request.  Every decision is an atomic ``membership.json``
  rewrite, and beats stamped with an older epoch are *stale incarnations*
  — a rank from a pre-re-mesh world must never read as live
  (``read_heartbeats(min_epoch=...)``).  ``ElasticSim`` implements the
  same ``poll()`` protocol in-process, driven by the chaos injectors, so
  single-process tests exercise the identical trainer path the
  file-based coordinator drives across real processes.

- **Rescale rules.**  On a world change N→M the run must decide what the
  global batch and LR mean now.  ``rule='none'`` holds the *global* batch
  constant (per-rank rows change; the gradient estimator — and therefore
  the LR — is untouched: the parity-fence default).  ``'linear'``/
  ``'sqrt'`` hold the *per-rank* batch constant (global batch scales with
  the world) and scale the LR by (M/N) or sqrt(M/N) — the Goyal et al. /
  Krizhevsky pairings.

- **Exact re-sharding.**  Checkpoints already prove params + param-shaped
  momentum restore across mesh shapes (gather-on-save).  What does NOT
  cross worlds for free is the explicit-path state whose *layout* bakes in
  n_data: ZeRO-WUS stacked momentum chunks ``(n, chunk)`` (buf and the
  quantized all-gather's agerr twin) re-grid losslessly — the flat
  concatenation of chunks IS the padded param vector, so truncate-and-
  re-chunk is exact (``regrid_wus_momentum``).  Stacked per-rank
  error-feedback residuals ``(n, *shape)`` are pending corrections whose
  *sum* is the semantic content (each rank adds its slot to its local
  gradient before quantizing); ``regrid_stacked_residual`` preserves that
  sum exactly by folding it into slot 0 of the new world.

The trainers (train/trainer.py, train/lm.py) own the re-mesh mechanics —
teardown + rebuild of mesh/shardings/steps/feeder and re-sharding the
``StateKeeper`` snapshot — and book every shrink/grow as a ``remesh``
ft_event that the goodput ledger charges as badput (obs/goodput.py).

jax/numpy are imported lazily so the coordinator/agent side
(``scripts/elastic_agent.py``) stays stdlib-only, like obs/heartbeat.py.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, Iterable, Optional, Set, Tuple

from pytorch_distributed_tpu.ft.chaos import ChaosInjector

MEMBERSHIP_NAME = "membership.json"
_JOIN_PREFIX = "join-"

RESCALE_RULES = ("none", "linear", "sqrt")


@dataclasses.dataclass(frozen=True)
class Membership:
    """One membership epoch: which ranks form the mesh right now."""

    epoch: int
    ranks: Tuple[int, ...]

    @property
    def world(self) -> int:
        return len(self.ranks)

    def to_json(self) -> dict:
        return {"epoch": int(self.epoch),
                "ranks": [int(r) for r in self.ranks]}

    @staticmethod
    def from_json(obj: dict) -> "Membership":
        return Membership(int(obj["epoch"]),
                          tuple(sorted(int(r) for r in obj["ranks"])))


@dataclasses.dataclass(frozen=True)
class MembershipChange:
    """A committed epoch transition (what the trainers act on)."""

    old: Membership
    new: Membership
    reason: str

    @property
    def kind(self) -> str:
        return "shrink" if self.new.world < self.old.world else "grow"


def atomic_write_json(path: str, obj: dict) -> None:
    """tmp + ``os.replace``: readers never observe a torn file (the same
    discipline checkpoint sidecars use — ft/integrity.py)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def split_liveness(flagged: Dict[int, str]) -> Tuple[Set[int], Set[int]]:
    """Partition ``find_stragglers``' reasons into ``(dead, slow)`` pids.

    Reuses the monitor's own classification strings rather than
    re-deriving the thresholds: *dead* ranks (stale beats — "dead or
    hung") are candidates for eviction; *slow* ranks (fresh beats, fat
    step-time EMA) stay members — they rate-limit the mesh but their
    state is intact, the "replace the host later" case."""
    dead = {pid for pid, why in flagged.items() if "dead or hung" in why}
    slow = {pid for pid, why in flagged.items()
            if pid not in dead and "slow rank" in why}
    return dead, slow


def rescale_lr(lr: float, old_world: int, new_world: int,
               rule: str = "none") -> float:
    """LR under a world change per the rescale rule (see module doc)."""
    if rule not in RESCALE_RULES:
        raise ValueError(
            f"rescale rule must be one of {RESCALE_RULES}, got {rule!r}")
    if rule == "none" or old_world == new_world:
        return lr
    ratio = new_world / old_world
    return lr * (ratio if rule == "linear" else ratio ** 0.5)


def rescale_batch(batch: int, old_world: int, new_world: int,
                  rule: str = "none") -> int:
    """Global batch under a world change: ``'none'`` holds it constant;
    the LR-scaling rules hold the *per-rank* batch constant instead."""
    if rule not in RESCALE_RULES:
        raise ValueError(
            f"rescale rule must be one of {RESCALE_RULES}, got {rule!r}")
    if rule == "none":
        return batch
    if batch % old_world:
        raise ValueError(
            f"global batch {batch} not divisible by world {old_world}")
    return (batch // old_world) * new_world


# ------------------------------------------------------- exact re-sharding

def regrid_wus_momentum(host_momentum, params, n_new: int,
                        block: Optional[int] = None):
    """Re-grid stacked ZeRO-WUS optimizer state ``(n_old, chunk_old)`` →
    ``(n_new, chunk_new)``, exactly.

    The stacked layout is the padded flat param vector cut into n whole-
    block chunks (parallel/zero.py ``init_wus_momentum``), so flattening,
    truncating to the true leaf size, and re-chunking for the new world is
    lossless — momentum round-trips N→M→N bit-exactly.  Applies the same
    transform to the quantized all-gather's ``agerr`` twin, whose flat
    layout is identical (per-position pending deltas of the padded param
    vector).  Host-side numpy, like ``gather_momentum``."""
    import numpy as np

    from pytorch_distributed_tpu.ops import qcomm
    from pytorch_distributed_tpu.parallel import zero as zero_lib

    blk = qcomm.DEFAULT_BLOCK if block is None else int(block)
    if not zero_lib.is_wus_momentum(host_momentum):
        raise ValueError("regrid_wus_momentum expects the stacked "
                         "{'buf': ...} WUS layout")

    import jax

    def regrid(b, p):
        size = int(np.prod(np.shape(p), dtype=np.int64))
        flat = np.asarray(b, np.float32).reshape(-1)[:size]
        chunk = zero_lib.chunk_size(size, n_new, blk)
        out = np.zeros(n_new * chunk, np.float32)
        out[:size] = flat
        return out.reshape(n_new, chunk)

    out = {"buf": jax.tree_util.tree_map(regrid, host_momentum["buf"],
                                         params)}
    if "agerr" in host_momentum:
        out["agerr"] = jax.tree_util.tree_map(
            regrid, host_momentum["agerr"], params)
    return out


def regrid_stacked_residual(host_residual, n_new: int):
    """Re-grid stacked per-rank error-feedback residuals ``(n_old, *shape)``
    → ``(n_new, *shape)``, preserving the total pending correction.

    Each rank's slot is the quantization error it will add back to its
    local gradient contribution before the next sync; the collective sums
    contributions, so the *sum over slots* is the semantic content.  The
    new world carries that sum in slot 0 (zeros elsewhere) — exact in the
    only sense that survives a change of rank identity."""
    import numpy as np

    import jax

    def regrid(leaf):
        arr = np.asarray(leaf, np.float32)
        total = arr.sum(axis=0)
        out = np.zeros((n_new,) + total.shape, np.float32)
        out[0] = total
        return out

    return jax.tree_util.tree_map(regrid, host_residual)


# ---------------------------------------------------------- coordination

class ElasticSim:
    """In-process membership controller: the single-process stand-in for
    ``ElasticCoordinator`` that the chaos injectors drive.

    The trainers see one protocol — ``poll(step) -> MembershipChange?`` —
    so the tier-1 drills exercise the identical re-mesh path the
    file-based coordinator triggers on a real fleet.  ``min_ranks`` is the
    shrink floor: a loss that would take the world below it is *refused*
    (recorded in ``refused``), matching the coordinator's behavior."""

    def __init__(self, world: int, min_ranks: int = 1):
        if world < 1 or min_ranks < 1 or min_ranks > world:
            raise ValueError(
                f"need 1 <= min_ranks <= world, got min_ranks={min_ranks} "
                f"world={world}")
        self.min_ranks = int(min_ranks)
        self.membership = Membership(0, tuple(range(int(world))))
        self._desired: Set[int] = set(self.membership.ranks)
        self._reasons: list = []
        self.refused: list = []
        self.history: list = []

    def force_lose(self, rank: int, reason: str = "chaos") -> None:
        if rank in self._desired:
            if len(self._desired) - 1 < self.min_ranks:
                self.refused.append((int(rank), reason))
                return
            self._desired.discard(int(rank))
            self._reasons.append(f"lost rank {rank} ({reason})")

    def force_join(self, rank: int, reason: str = "chaos") -> None:
        if rank not in self._desired:
            self._desired.add(int(rank))
            self._reasons.append(f"rank {rank} joined ({reason})")

    def poll(self, step: int) -> Optional[MembershipChange]:  # noqa: ARG002
        if self._desired == set(self.membership.ranks):
            return None
        old = self.membership
        new = Membership(old.epoch + 1, tuple(sorted(self._desired)))
        reason = "; ".join(self._reasons) or "membership change"
        self._reasons = []
        self.membership = new
        chg = MembershipChange(old, new, reason)
        self.history.append(chg)
        return chg


class ElasticCoordinator:
    """File-based membership-epoch coordinator over a shared heartbeat
    directory (the multi-process real path; ``scripts/elastic_agent.py``
    is its CLI).

    Liveness comes from the beats themselves: ``decide()`` reads the
    current epoch's heartbeats, runs ``find_stragglers``, evicts *dead*
    members (keeps *slow* ones), admits pending join requests, and — when
    membership actually changes — commits the new epoch atomically.
    Stdlib-only, like the heartbeat module: runs on a login node or in a
    cron job without touching the TPU runtime."""

    def __init__(self, hb_dir: str, world: int, min_ranks: int = 1,
                 max_step_lag: int = 3, max_age_s: float = 60.0,
                 slow_ema_factor: float = 2.0):
        if min_ranks < 1 or min_ranks > world:
            raise ValueError(
                f"need 1 <= min_ranks <= world, got min_ranks={min_ranks} "
                f"world={world}")
        self.dir = hb_dir
        self.min_ranks = int(min_ranks)
        self.max_step_lag = int(max_step_lag)
        self.max_age_s = float(max_age_s)
        self.slow_ema_factor = float(slow_ema_factor)
        os.makedirs(hb_dir, exist_ok=True)
        self.path = os.path.join(hb_dir, MEMBERSHIP_NAME)
        if not os.path.exists(self.path):
            atomic_write_json(
                self.path,
                Membership(0, tuple(range(int(world)))).to_json())

    # -- membership state ---------------------------------------------
    def membership(self) -> Membership:
        with open(self.path) as f:
            return Membership.from_json(json.load(f))

    def _commit(self, new: Membership) -> None:
        atomic_write_json(self.path, new.to_json())

    # -- join protocol ------------------------------------------------
    def join_path(self, rank: int) -> str:
        return os.path.join(self.dir, f"{_JOIN_PREFIX}{int(rank):05d}.json")

    def request_join(self, rank: int) -> None:
        """A restarted/new rank files its admission request (atomic; the
        next ``decide()`` folds it in and bumps the epoch)."""
        atomic_write_json(self.join_path(rank),
                          {"rank": int(rank), "t": time.time()})

    def pending_joins(self) -> Set[int]:
        out: Set[int] = set()
        for name in os.listdir(self.dir):
            if name.startswith(_JOIN_PREFIX) and name.endswith(".json"):
                try:
                    with open(os.path.join(self.dir, name)) as f:
                        out.add(int(json.load(f)["rank"]))
                except (ValueError, KeyError, OSError):
                    continue
        return out

    # -- decisions ----------------------------------------------------
    def decide(self, now: Optional[float] = None,
               beats: Optional[Dict[int, dict]] = None,
               extra_dead: Optional[Set[int]] = None,
               ) -> Optional[MembershipChange]:
        """One coordination round → a committed ``MembershipChange`` or
        None.  ``beats`` is injectable for tests; by default the current
        epoch's heartbeats are read from ``hb_dir`` (older epochs are
        stale incarnations and never count as live).

        ``extra_dead``: ranks an external observer already declared dead
        — today the live alert plane (``obs/alerts.py`` ``dead_rank``
        ft_events consumed by ``elastic_agent watch --alerts-from``).
        They merge into the same eviction set this round computes from
        heartbeats, so alert-driven eviction rides the one decision path
        (floor check, epoch bump, commit) instead of growing a second
        liveness policy."""
        from pytorch_distributed_tpu.obs.heartbeat import (
            find_stragglers,
            read_heartbeats,
        )

        cur = self.membership()
        if beats is None:
            beats = read_heartbeats(self.dir, min_epoch=cur.epoch)
        flagged = find_stragglers(
            beats, now=now, max_step_lag=self.max_step_lag,
            max_age_s=self.max_age_s,
            slow_ema_factor=self.slow_ema_factor)
        dead, _slow = split_liveness(flagged)
        for r in (extra_dead or ()):
            r = int(r)
            dead.add(r)
            flagged.setdefault(r, "alert: dead_rank ft_event")
        # A member with NO beat at the current epoch yet is in flight
        # (just re-meshed), not dead — only a stale beat marks death.
        leave = {r for r in cur.ranks if r in dead}
        joins = {r for r in self.pending_joins() if r not in cur.ranks}
        survivors = (set(cur.ranks) - leave) | joins
        if survivors == set(cur.ranks):
            return None
        reasons = [f"evict rank {r}: {flagged[r]}" for r in sorted(leave)]
        reasons += [f"admit rank {r} (join request)" for r in sorted(joins)]
        if len(survivors) < self.min_ranks:
            # Refusing is itself a decision worth surfacing, but the
            # membership (and epoch) must not move below the floor.
            return None
        new = Membership(cur.epoch + 1, tuple(sorted(survivors)))
        self._commit(new)
        for r in joins:
            try:
                os.remove(self.join_path(r))
            except OSError:
                pass
        return MembershipChange(cur, new, "; ".join(reasons))


# -------------------------------------------------------- chaos injectors

class LoseRankAt(ChaosInjector):
    """Remove ``rank`` from the membership when the loop reaches
    ``at_step`` — the deterministic stand-in for a dead host.  Drives the
    trainer's elastic controller (``trainer.elastic``); a trainer without
    one ignores the injection (matching ``KillAt``'s rank gating)."""

    def __init__(self, at_step: int, rank: int, reason: str = "chaos"):
        self.at_step = int(at_step)
        self.rank = int(rank)
        self.reason = str(reason)
        self.fired = False

    def on_step(self, trainer, step: int) -> None:
        if not self.fired and step == self.at_step:
            self.fired = True
            ctl = getattr(trainer, "elastic", None)
            if ctl is not None:
                ctl.force_lose(self.rank, reason=self.reason)


class JoinRankAt(ChaosInjector):
    """Re-admit ``rank`` into the membership at ``at_step`` — the
    recovered-host half of the shrink/grow drill."""

    def __init__(self, at_step: int, rank: int, reason: str = "chaos"):
        self.at_step = int(at_step)
        self.rank = int(rank)
        self.reason = str(reason)
        self.fired = False

    def on_step(self, trainer, step: int) -> None:
        if not self.fired and step == self.at_step:
            self.fired = True
            ctl = getattr(trainer, "elastic", None)
            if ctl is not None:
                ctl.force_join(self.rank, reason=self.reason)


def elastic_controller_from_config(cfg, world: int):
    """Build the in-process controller a trainer uses under ``--elastic``
    (recipes thread cfg here; tests drive ``ElasticSim`` directly)."""
    if not getattr(cfg, "elastic", False):
        return None
    return ElasticSim(world, min_ranks=int(getattr(cfg, "min_ranks", 1)))
