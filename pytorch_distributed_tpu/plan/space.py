"""Plan space: workload specs, the Plan point, and enumeration.

A ``Plan`` is one point in the dp x tp x pp x fsdp x remat x
fused-ce-mode x zero x grad-compress lattice for a concrete ``ModelSpec``
at a concrete world size.  Enumeration is family-aware — the image
trainer's flag surface (train/config.py) has no tp/pp/fsdp axes, and the
LM recipe (recipes/lm_pretrain.py) is where tensor/pipeline parallelism
and ZeRO-3 live — so each family only generates points its real CLI can
express, and ``Plan.flags()`` emits exactly those spellings.

Axis naming note: the repo carries TWO ZeRO axes, matching lm_pretrain's
flags — ``zero='wus'`` is weight-update sharding (ZeRO-1: momentum 1/N,
parallel/zero.py) and ``fsdp=True`` is the parameter+optimizer sharding
(ZeRO-3 layout, parallel/fsdp.py).  They are separate plan dimensions
because they are separate flags with different comm/memory signatures.

This module is jax-free by design: enumeration and flag emission run in
the analytic autoplan path with no accelerator or backend import.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, Iterator, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """One concrete workload the planner lays out.

    ``family`` selects the cost models and the flag surface: "image"
    (obs.flops image_step_cost + train/config.py flags) or "lm"
    (lm_step_cost + recipes/lm_pretrain.py flags).  Shape fields unused
    by a family stay at their zero defaults."""

    name: str
    family: str                 # "image" | "lm"
    batch: int                  # GLOBAL batch (reference semantics)
    arch: str = ""              # image: obs.flops analytic-model key
    image_size: int = 224
    num_classes: int = 1000
    vocab: int = 0
    d_model: int = 0
    n_layers: int = 0
    n_heads: int = 0
    seq: int = 0
    mlp_ratio: int = 4


def resnet50_spec(batch: int = 256, image_size: int = 224) -> ModelSpec:
    """The headline bench config (bench.py: global batch 256, bf16)."""
    return ModelSpec(name="resnet50", family="image", batch=batch,
                     arch="resnet50", image_size=image_size)


def lm_spec(vocab: int = 32000, d_model: int = 2048, n_layers: int = 16,
            n_heads: int = 16, seq: int = 2048,
            batch: int = 256) -> ModelSpec:
    """A GPT-2-large-ish pretraining config — big enough that the planner
    has real memory/comm trade-offs to rank at pod scale."""
    return ModelSpec(name="lm", family="lm", batch=batch, vocab=vocab,
                     d_model=d_model, n_layers=n_layers, n_heads=n_heads,
                     seq=seq)


def tiny_lm_spec() -> ModelSpec:
    """The shardlint sweep's tiny LM (analysis/core.py ``_LM``): the
    shapes the top-k validation cross-checks against the real lowered
    recipes on the simulated CPU mesh."""
    return ModelSpec(name="lm-tiny", family="lm", batch=8, vocab=64,
                     d_model=32, n_layers=1, n_heads=4, seq=16)


MODELS = {
    "resnet50": resnet50_spec,
    "lm": lm_spec,
    "lm-tiny": tiny_lm_spec,
}


@dataclasses.dataclass(frozen=True)
class Plan:
    """One candidate layout: mesh factorization + the recipe knobs."""

    spec: ModelSpec
    chips: int                  # world size this plan runs on
    dp: int = 1
    tp: int = 1
    pp: int = 1
    fsdp: bool = False          # ZeRO-3 param+opt sharding (--fsdp, LM)
    remat: bool = False
    fused_ce_mode: str = "none"  # "none"|"replicated"|"dp"|"tp"
    zero: str = "none"           # "none"|"wus" (ZeRO-1 WUS, --zero)
    grad_compress: str = "none"  # "none"|"bf16"|"int8"|"fp8" (image)

    @property
    def microbatches(self) -> int:
        """Pipeline microbatches: the largest divisor of the per-data-
        shard batch at or under the 4x-stages gpipe rule of thumb (enough
        to drown the bubble without fragmenting the matmuls).  0 when no
        count >= pp divides the shard — feasibility pruning rejects the
        plan on that."""
        if self.pp <= 1:
            return 1
        per_dp = self.spec.batch // max(1, self.dp)
        for m in range(min(4 * self.pp, per_dp), self.pp - 1, -1):
            if m > 0 and per_dp % m == 0:
                return m
        return 0

    def axes(self) -> Dict[str, int]:
        return {"dp": self.dp, "tp": self.tp, "pp": self.pp}

    def key(self) -> str:
        """Stable short id for logs/tables."""
        bits = [f"c{self.chips}", f"dp{self.dp}"]
        if self.tp > 1:
            bits.append(f"tp{self.tp}")
        if self.pp > 1:
            bits.append(f"pp{self.pp}")
        if self.fsdp:
            bits.append("fsdp")
        if self.remat:
            bits.append("remat")
        if self.fused_ce_mode != "none":
            bits.append(f"ce-{self.fused_ce_mode}")
        if self.zero != "none":
            bits.append(f"zero-{self.zero}")
        if self.grad_compress != "none":
            bits.append(self.grad_compress)
        return "/".join(bits)

    def flags(self, fused_ce_chunks: int = 8) -> List[str]:
        """The exact recipe CLI flags for this plan — spellings match
        train/config.py (image) / recipes/lm_pretrain.py (LM) verbatim,
        so the emitted line is runnable as-is."""
        spec = self.spec
        if spec.family == "image":
            out = ["-a", spec.arch, "--batch-size", str(spec.batch),
                   "--image-size", str(spec.image_size)]
            if self.zero != "none":
                out += ["--zero", self.zero]
            if self.grad_compress != "none":
                out += ["--grad-compress", self.grad_compress]
            return out
        out = ["--vocab", str(spec.vocab), "--d-model", str(spec.d_model),
               "--n-layers", str(spec.n_layers),
               "--n-heads", str(spec.n_heads), "--seq-len", str(spec.seq),
               "--batch-size", str(spec.batch)]
        if self.tp > 1:
            out += ["--tp", str(self.tp)]
        if self.pp > 1:
            out += ["--pp", str(self.pp),
                    "--microbatches", str(self.microbatches)]
        if self.fsdp:
            out += ["--fsdp"]
        if self.remat:
            out += ["--remat"]
        if self.fused_ce_mode != "none":
            out += ["--fused-ce", str(fused_ce_chunks),
                    "--fused-ce-mode", self.fused_ce_mode]
        if self.zero != "none":
            out += ["--zero", self.zero]
        if self.grad_compress != "none":
            out += ["--grad-compress", self.grad_compress]
        return out

    def cli(self) -> str:
        prog = ("pytorch_distributed_tpu.recipes.lm_pretrain"
                if self.spec.family == "lm" else "main.py")
        if self.spec.family == "lm":
            return "python -m " + prog + " " + " ".join(self.flags())
        return "python " + prog + " " + " ".join(self.flags())

    def to_dict(self) -> Dict:
        return {
            "chips": self.chips, "dp": self.dp, "tp": self.tp,
            "pp": self.pp, "fsdp": self.fsdp, "remat": self.remat,
            "fused_ce_mode": self.fused_ce_mode, "zero": self.zero,
            "grad_compress": self.grad_compress, "key": self.key(),
            "microbatches": self.microbatches,
            "flags": self.flags(), "cli": self.cli(),
        }


def _factorizations(n: int, ways: int) -> Iterator[Tuple[int, ...]]:
    """All ordered tuples of ``ways`` positive ints whose product is n."""
    if ways == 1:
        yield (n,)
        return
    for d in range(1, n + 1):
        if n % d == 0:
            for rest in _factorizations(n // d, ways - 1):
                yield (d,) + rest


def elastic_worlds(chips: int, min_ranks: int = 1) -> List[int]:
    """World sizes the elastic layer (ft/elastic.py) might land on: the
    requested world, the one-rank-loss survivor count, and the half-pod
    shrink — the planner pre-plans each so a re-mesh has a ready layout
    instead of a human mid-incident."""
    worlds = {chips}
    if chips - 1 >= min_ranks:
        worlds.add(chips - 1)
    if chips // 2 >= max(1, min_ranks):
        worlds.add(chips // 2)
    return sorted(worlds, reverse=True)


def enumerate_plans(spec: ModelSpec, chips: int) -> List[Plan]:
    """Every lattice point the family's CLI can express at this world
    size.  Feasibility is NOT applied here — plan/cost.py prunes — but
    structurally-inexpressible combos (image tp/pp, tp without the
    vocab-sharded fused head) are never generated."""
    plans: List[Plan] = []
    if spec.family == "image":
        for zero, gc in itertools.product(
                ("none", "wus"), ("none", "bf16", "int8", "fp8")):
            plans.append(Plan(spec=spec, chips=chips, dp=chips, zero=zero,
                              grad_compress=gc))
        return plans
    for dp, tp, pp in _factorizations(chips, 3):
        for fsdp, remat, ce, zero in itertools.product(
                (False, True), (False, True),
                ("none", "replicated", "dp", "tp"), ("none", "wus")):
            if tp > 1 and ce != "tp":
                continue  # Megatron TP requires the vocab-sharded head
            if tp == 1 and ce == "tp":
                continue
            if fsdp and zero == "wus":
                continue  # ZeRO-3 already shards what WUS would
            plans.append(Plan(spec=spec, chips=chips, dp=dp, tp=tp, pp=pp,
                              fsdp=fsdp, remat=remat, fused_ce_mode=ce,
                              zero=zero))
    return plans
