"""Static layout planning (``scripts/autoplan.py``'s engine).

Layering contract: ``space``, ``cost``, and ``planner`` are jax-free —
they run on a login node or in CI with no accelerator, importing only
the analytic halves of ``obs`` (flops tables, EQuARX wire arithmetic).
``validate`` is the one jax-dependent module (it lowers top-k candidates
on the simulated mesh via the shared ``analysis.lowering`` service) and
is imported lazily by ``planner.autoplan(validate=True)`` only.
"""

from pytorch_distributed_tpu.plan.space import (  # noqa: F401
    MODELS,
    ModelSpec,
    Plan,
    elastic_worlds,
    enumerate_plans,
    lm_spec,
    resnet50_spec,
    tiny_lm_spec,
)
from pytorch_distributed_tpu.plan.cost import (  # noqa: F401
    HW,
    PlanScore,
    comm_entries,
    comm_totals,
    feasibility,
    hw_for,
    mem_cost_for,
    plan_complexity,
    score_plan,
)
from pytorch_distributed_tpu.plan.planner import (  # noqa: F401
    autoplan,
    best_plan,
    predicted_mfu,
    rank_plans,
)
