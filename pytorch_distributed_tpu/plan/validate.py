"""Top-k plan validation: lower the candidate on the simulated mesh and
cross-check the planner's analytic predictions against compiled truth.

The planner's scores are only as trustworthy as the cost models under
them, so the top-k survivors are not shipped on faith: each candidate
that has a lowerable twin in the ``analysis.core.RECIPES`` matrix is
compiled once (riding the shared lowering sweep — zero extra compiles
when the sweep already ran) and the analytic per-step comm payload and
peak-HBM predictions are compared against the real ``CommLedger`` /
``MemLedger`` extracted from that compiled step.

Fences reuse the repo's existing acceptance thresholds verbatim
(tests/test_comms.py / tests/test_memory.py): ±15% on collective payload
bytes, ±15% on the analytic peak vs the static ledger, ±10% on the
ledger's own residual vs ``memory_analysis()``.  Recipes whose analytic
formulas are not yet test-fenced (the compressed/zero image variants,
the replicated/dp fused-CE modes) are still validated and recorded, but
their residuals are informational (``fenced: false``) — the planner's
rank tie-break (plan/cost.py ``plan_complexity``) deliberately prefers
plans whose recipes ARE fenced at equal predicted step time.

Validation is the one jax-dependent corner of the plan package: the
analytic enumerate/prune/score path never imports it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from pytorch_distributed_tpu.plan.space import Plan, tiny_lm_spec

# The existing acceptance thresholds, unchanged.
COMM_FENCE_PCT = 15.0      # analytic payload vs compiled comm ledger
MEM_FENCE_PCT = 15.0       # analytic peak vs static memory ledger
LEDGER_FENCE_PCT = 10.0    # static ledger vs memory_analysis() truth

# Recipes whose analytic formulas tier-1 already fences at the above
# thresholds; everything else is recorded informationally.
COMM_FENCED = frozenset({
    "lm_train_dp", "lm_fused_ce_tp", "train_image_gspmd"})
MEM_FENCED = frozenset({"lm_train_dp", "train_lm_zero"})


def recipe_for(plan: Plan) -> Optional[str]:
    """The lowerable RECIPES twin of a candidate plan, or None when the
    recipe matrix has no step with this knob combination (pp plans, fp8
    image compression, fsdp, remat — validated only analytically)."""
    if plan.spec.family == "image":
        if plan.dp != plan.chips:
            return None
        table = {("none", "none"): "train_image_gspmd",
                 ("none", "bf16"): "train_image_bf16",
                 ("none", "int8"): "train_image_int8",
                 ("wus", "none"): "train_image_zero"}
        return table.get((plan.zero, plan.grad_compress))
    if plan.pp > 1 or plan.fsdp or plan.remat:
        return None
    if plan.tp == 2 and plan.dp == 2 and plan.fused_ce_mode == "tp" \
            and plan.zero == "none":
        return "lm_fused_ce_tp"
    if plan.tp > 1:
        return None
    if plan.zero == "wus":
        return "train_lm_zero" if plan.fused_ce_mode == "none" else None
    return {"none": "lm_train_dp",
            "replicated": "lm_fused_ce_replicated",
            "dp": "lm_fused_ce_dp"}.get(plan.fused_ce_mode)


def proxy_plan_for(recipe: str) -> Optional[Plan]:
    """The tiny-shape Plan whose analytic cost the recipe's lowering
    checks — same knobs, the sweep's proxy shapes (core._LM / TinyMLP).
    Image recipes return None: TinyMLP has no analytic arch model, so
    their predictions come from ``_recipe_predictions`` instead."""
    spec = tiny_lm_spec()
    table = {
        "lm_train_dp": Plan(spec=spec, chips=4, dp=4),
        "lm_fused_ce_replicated": Plan(spec=spec, chips=4, dp=4,
                                       fused_ce_mode="replicated"),
        "lm_fused_ce_dp": Plan(spec=spec, chips=4, dp=4,
                               fused_ce_mode="dp"),
        "lm_fused_ce_tp": Plan(spec=spec, chips=4, dp=2, tp=2,
                               fused_ce_mode="tp"),
        "train_lm_zero": Plan(spec=spec, chips=4, dp=4, zero="wus"),
    }
    return table.get(recipe)


def _leaf_sizes(low) -> List[int]:
    import jax

    state = low.args[0]
    return [int(x.size) for x in jax.tree_util.tree_leaves(state.params)]


def _recipe_predictions(recipe: str, low) -> Dict[str, Optional[float]]:
    """Analytic (comm payload, peak HBM) for one recipe at its own proxy
    shapes.  LM recipes go through the planner's cost model (plan/cost.py
    comm_entries/mem_cost_for), which reduces to the fenced obs/flops
    formulas in exactly these base cases; image recipes use the fenced
    formulas directly (TinyMLP constants from analysis/core's fixture)."""
    from pytorch_distributed_tpu.obs import flops
    from pytorch_distributed_tpu.plan import cost as cost_mod

    proxy = proxy_plan_for(recipe)
    if proxy is not None:
        step = cost_mod.step_cost_for(proxy)
        totals = cost_mod.comm_totals(cost_mod.comm_entries(proxy, step))
        return {"comm_bytes": totals["payload_bytes"],
                "peak_bytes": cost_mod.mem_cost_for(proxy, step).peak_bytes}
    # Image recipes: TinyMLP (analysis/core._recipe_train_image) —
    # Dense(192->32) + Dense(32->10), batch 16 of 8x8x3 on the 4-way mesh.
    params = sum(_leaf_sizes(low))
    leaves = _leaf_sizes(low)
    pb = 4.0 * params
    act = 4 * 4 * (192 + 32 + 32 + 10)
    data = 16 * 8 * 8 * 3 * 4 / 4 + 16 + 16 + 8
    if recipe == "train_image_gspmd":
        comm = flops.image_comm_bytes(params, dp=4).total_bytes
        peak = flops.train_mem_peak(pb, act, data, dp=4, zero=False,
                                    explicit_sync=False,
                                    metric_bytes=112.0).peak_bytes
    elif recipe == "train_image_zero":
        comm = flops.image_comm_bytes_zero(leaves, dp=4).total_bytes
        peak = flops.train_mem_peak(pb, act, data, dp=4, zero=True,
                                    explicit_sync=True,
                                    metric_bytes=112.0).peak_bytes
    elif recipe in ("train_image_bf16", "train_image_int8"):
        mode = recipe.rsplit("_", 1)[-1]
        comm = flops.image_comm_bytes_compressed(leaves, dp=4,
                                                 mode=mode).total_bytes
        peak = flops.train_mem_peak(pb, act, data, dp=4, zero=False,
                                    explicit_sync=True,
                                    metric_bytes=112.0).peak_bytes
    else:
        return {"comm_bytes": None, "peak_bytes": None}
    return {"comm_bytes": comm, "peak_bytes": peak}


def validate_plan(plan: Plan, service=None) -> Dict[str, Any]:
    """Lower (or reuse) the plan's recipe twin and fence the analytic
    predictions against its compiled ledgers.

    Returns a record with per-dimension residuals and verdicts; ``ok`` is
    None (not checkable), True, or False.  Rides the shared lowering
    sweep: when the recipe is already cached this adds zero compiles."""
    from pytorch_distributed_tpu.analysis import core, lowering

    recipe = recipe_for(plan)
    rec: Dict[str, Any] = {"plan": plan.key(), "recipe": recipe}
    if recipe is None:
        rec["ok"] = None
        rec["note"] = "no lowerable recipe twin; analytic-only candidate"
        return rec
    svc = service or lowering.service()
    low = svc.get(recipe)
    from pytorch_distributed_tpu.obs import flops

    pred = _recipe_predictions(recipe, low)
    comm = core.comm_ledger_for(recipe)
    mem = core.mem_ledger_for(recipe)

    checks: Dict[str, Any] = {}
    ok = True
    if pred["comm_bytes"] is not None:
        res = flops.comm_residual_pct(pred["comm_bytes"], comm.total_bytes)
        fenced = recipe in COMM_FENCED
        checks["comm"] = {
            "predicted_bytes": pred["comm_bytes"],
            "ledger_bytes": comm.total_bytes,
            "ledger_wire_bytes": comm.total_wire_bytes,
            "residual_pct": res, "fence_pct": COMM_FENCE_PCT,
            "fenced": fenced, "ok": (res <= COMM_FENCE_PCT
                                     if fenced else None)}
        if fenced and res > COMM_FENCE_PCT:
            ok = False
    if pred["peak_bytes"] is not None:
        res = flops.mem_residual_pct(pred["peak_bytes"], mem.peak_bytes)
        fenced = recipe in MEM_FENCED
        checks["mem"] = {
            "predicted_peak_bytes": pred["peak_bytes"],
            "ledger_peak_bytes": mem.peak_bytes,
            "measured_peak_bytes": mem.measured_peak_bytes,
            "residual_pct": res, "fence_pct": MEM_FENCE_PCT,
            "fenced": fenced, "ok": (res <= MEM_FENCE_PCT
                                     if fenced else None)}
        if fenced and res > MEM_FENCE_PCT:
            ok = False
    # The ledger's own residual against memory_analysis() ground truth —
    # fenced for every validated recipe (the ±10% tier-1 fence).
    led = mem.residual_pct()
    checks["ledger_vs_measured"] = {
        "residual_pct": led, "fence_pct": LEDGER_FENCE_PCT,
        "ok": led <= LEDGER_FENCE_PCT}
    if led > LEDGER_FENCE_PCT:
        ok = False
    rec["checks"] = checks
    rec["ok"] = ok
    return rec


def validate_top_k(plans: List[Plan], k: int = 3,
                   service=None) -> List[Dict[str, Any]]:
    """Validate the first ``k`` ranked plans (the planner's top-k)."""
    return [validate_plan(p, service=service) for p in plans[:k]]
