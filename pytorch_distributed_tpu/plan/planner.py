"""The planner: enumerate → prune → score → rank → (optionally) validate.

``autoplan`` is the module's one entry point and what ``scripts/
autoplan.py`` drives: for a model and a chip count it enumerates every
recipe-expressible plan (plan/space.py), statically prunes the infeasible
ones with itemized reasons (plan/cost.py ``feasibility``), scores the
survivors analytically, and emits a ranked ``plan.json`` payload whose
top entries carry predicted MFU, the full per-step prediction breakdown,
and the exact recipe CLI line that runs the plan.

Ranking is (predicted step time, knob complexity, predicted peak HBM,
key): fastest wins; at a tie the plan with FEWER non-default knobs wins
(simpler recipes have more proven fences and fewer failure modes — at
tiny shapes ZeRO-1 ties plain DP on wire bytes by construction, and the
tie-break keeps the fenced plain-DP recipe on top); remaining ties go to
the lower memory plan, then the stable key.  Elastic worlds
(plan/space.py ``elastic_worlds``) are pre-planned so a re-mesh after
rank loss has a ready layout.

Everything here is jax-free; only ``validate=True`` touches the
simulated mesh, via plan/validate.py off the shared lowering sweep.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from pytorch_distributed_tpu.plan import cost as cost_mod
from pytorch_distributed_tpu.plan.space import (
    MODELS,
    ModelSpec,
    Plan,
    elastic_worlds,
    enumerate_plans,
)

PLAN_SCHEMA_VERSION = 1


def rank_key(plan: Plan, score: cost_mod.PlanScore) -> Tuple:
    return (score.step_time_s, cost_mod.plan_complexity(plan),
            score.peak_hbm_bytes, plan.key())


def rank_plans(spec: ModelSpec, chips: int, hw: cost_mod.HW,
               hbm_budget: Optional[float] = None,
               overlap: Optional[float] = None
               ) -> Tuple[List[Tuple[Plan, cost_mod.PlanScore]],
                          Dict[str, int]]:
    """(ranked feasible plans with scores, pruned-reason histogram).

    ``overlap`` is the backward-overlap fraction for the scorer; None
    keeps the cost model's assumed default (a measured value comes from
    ``autoplan.py --overlap-from <timeline.json>``)."""
    ranked: List[Tuple[Plan, cost_mod.PlanScore]] = []
    pruned: Dict[str, int] = {}
    for plan in enumerate_plans(spec, chips):
        reasons = cost_mod.feasibility(plan, hw, hbm_budget=hbm_budget)
        if reasons:
            for r in reasons:
                # histogram by reason class, not the full message
                if "exceeds" in r:
                    key = "peak HBM over budget"
                elif "not divisible" in r or "no microbatch" in r:
                    key = "indivisible shape"
                else:
                    key = r.split(";")[0]
                pruned[key] = pruned.get(key, 0) + 1
            continue
        ranked.append((plan, cost_mod.score_plan(
            plan, hw,
            overlap=(cost_mod.DEFAULT_OVERLAP if overlap is None
                     else overlap))))
    ranked.sort(key=lambda ps: rank_key(*ps))
    return ranked, pruned


def plan_entry(plan: Plan, score: cost_mod.PlanScore) -> Dict[str, Any]:
    return {"plan": plan.to_dict(), "predicted": score.to_dict()}


def autoplan(model: str, chips: int, *, chip: Optional[str] = None,
             top_k: int = 5, elastic: bool = True, validate: bool = False,
             validate_k: int = 3, hbm_budget: Optional[float] = None,
             overlap: Optional[float] = None,
             overlap_source: Optional[str] = None,
             attr_profile: Optional[Dict[str, Any]] = None,
             spec: Optional[ModelSpec] = None) -> Dict[str, Any]:
    """The full pipeline for one (model, world size).  Returns the
    ``plan.json`` payload; never imports jax unless ``validate=True``.

    ``overlap`` replaces the assumed backward-overlap fraction with a
    measured one (0-1); the payload records which was used.
    ``overlap_source`` overrides that provenance label — the autoplan
    CLI passes ``"schedule"`` when the value came from the bucketed
    overlap model (``cost.bucketed_overlap``) rather than a profiler
    measurement, and ``"measured-attr"`` when it came from a step-
    attribution profile (``--attr-from``).  ``attr_profile`` is that
    profile (obs/stepattr.py ``load_attr``); the payload records its
    ``attr_source`` and measured bottleneck so a plan ranked with
    measured constants says where they came from."""
    if spec is None:
        if model not in MODELS:
            raise KeyError(f"unknown model {model!r}; known: "
                           f"{sorted(MODELS)}")
        spec = MODELS[model]()
    hw = cost_mod.hw_for(chip)
    ranked, pruned = rank_plans(spec, chips, hw, hbm_budget=hbm_budget,
                                overlap=overlap)
    payload: Dict[str, Any] = {
        "schema_version": PLAN_SCHEMA_VERSION,
        "model": spec.name,
        "family": spec.family,
        "chips": chips,
        "hw": {"name": hw.name, "peak_flops": hw.peak_flops,
               "hbm_bytes": hw.hbm_bytes, "link_bytes": hw.link_bytes},
        "overlap": (cost_mod.DEFAULT_OVERLAP if overlap is None
                    else float(overlap)),
        "overlap_source": (overlap_source if overlap_source is not None
                           else ("assumed" if overlap is None
                                 else "measured")),
        "enumerated": len(ranked) + sum(pruned.values()),
        "feasible": len(ranked),
        "pruned": pruned,
        "ranked": [plan_entry(p, s) for p, s in ranked[:top_k]],
    }
    if attr_profile is not None:
        payload["attr_source"] = attr_profile.get("attr_source")
        payload["measured"] = {
            "bottleneck": attr_profile.get("bottleneck"),
            "shares_pct": attr_profile.get("shares_pct"),
            "data_wait_share_p95": attr_profile.get("data_wait_share_p95"),
            "host_sync_ms_p95": attr_profile.get("host_sync_ms_p95"),
            "step_ms_p50": attr_profile.get("step_ms_p50"),
        }
    if elastic:
        worlds: Dict[str, Any] = {}
        for w in elastic_worlds(chips):
            if w == chips:
                continue
            sub, _ = rank_plans(spec, w, hw, hbm_budget=hbm_budget,
                                overlap=overlap)
            worlds[str(w)] = (plan_entry(*sub[0]) if sub else None)
        payload["elastic"] = worlds
    if validate:
        from pytorch_distributed_tpu.plan import validate as validate_mod

        records = validate_mod.validate_top_k(
            [p for p, _ in ranked], k=validate_k)
        payload["validation"] = records
        payload["validation_ok"] = all(
            r["ok"] is not False for r in records)
    return payload


def best_plan(model: str, chips: int,
              chip: Optional[str] = None) -> Optional[Plan]:
    """Just the winning Plan (None when nothing is feasible)."""
    spec = MODELS[model]()
    ranked, _ = rank_plans(spec, chips, cost_mod.hw_for(chip))
    return ranked[0][0] if ranked else None


def predicted_mfu(model: str, chips: int, *, chip: Optional[str] = None,
                  spec: Optional[ModelSpec] = None) -> Optional[float]:
    """Predicted MFU (%) of the top-ranked plan — what bench.py stamps
    into its events so the staleness report can show prediction drift."""
    if spec is None:
        spec = MODELS[model]()
    ranked, _ = rank_plans(spec, chips, cost_mod.hw_for(chip))
    return ranked[0][1].mfu_pct if ranked else None
