"""Plan costing: feasibility pruning + analytic scoring.

Every number here comes from the already-fenced cost models in
``obs/flops.py`` — ``StepCost`` (±10% vs XLA ``cost_analysis()``),
``CommCost`` arithmetic (±15% vs the compiled ledger), ``MemCost``
(±15% vs the static HBM watermark) — composed over the plan's mesh
factorization.  AMP-style strategy search (arXiv:2210.07297) works
exactly when the cost model is trustworthy, which is why the planner
refuses to invent new magnitudes: each collective a plan implies is an
``(kind, per-device result bytes, group, overlappable)`` entry whose
bytes reuse the fenced formulas, and time scoring is overlap-centric
(arXiv:1810.11112) — wire bytes a backward-phase gradient sync can hide
under compute don't count against the step, boundary psums and pipeline
hops on the critical path do.

Jax-free by design: ``HW`` capabilities come from the device-kind
string tables in obs/flops.py (env-overridable), never a live backend.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from pytorch_distributed_tpu.obs import flops
from pytorch_distributed_tpu.obs.comms import wire_bytes
from pytorch_distributed_tpu.plan.space import ModelSpec, Plan

# Fraction of compute time backward-phase gradient collectives can hide
# under (bucketed sync overlaps the tail of backward; arXiv:1810.11112).
# Env PTD_PLAN_OVERLAP overrides everything; a measured value flows in
# via ``autoplan.py --overlap-from <timeline.json>`` (the profiler's
# observed overlap_pct_mean) through the ``overlap=`` kwarg below.
DEFAULT_OVERLAP = 0.6

# Fraction of per-chip HBM a plan may fill before pruning: headroom for
# the allocator, framework scratch, and the compiler's fusion temps the
# analytic model doesn't itemize.
HBM_FILL_FRACTION = 0.9

_FUSED_CE_CHUNKS = 8  # the chunk count Plan.flags() emits


@dataclasses.dataclass(frozen=True)
class HW:
    """Per-chip capabilities the scorer divides by."""

    name: str
    peak_flops: float
    hbm_bytes: float
    link_bytes: float


def hw_for(chip: Optional[str] = None) -> HW:
    """HW record for a chip name ("v4", "v5e", "tpu v5p", ... or None/
    "cpu" for the simulated-mesh placeholder).  Unknown names fall back
    to the CPU placeholders — the planner still ranks, the absolute
    times are then nominal."""
    if chip is None or chip.lower() in ("cpu", "host"):
        kind, name = None, "cpu"
    else:
        name = chip.lower()
        kind = name if name.startswith("tpu") else f"tpu {name}"
    return HW(name=name,
              peak_flops=flops.chip_peak_flops(kind),
              hbm_bytes=flops.chip_hbm_bytes(kind),
              link_bytes=flops.chip_link_bytes(kind))


def step_cost_for(plan: Plan) -> flops.StepCost:
    """The fenced per-step FLOPs model at the plan's recompute knobs."""
    spec = plan.spec
    if spec.family == "image":
        return flops.image_step_cost(spec.arch, spec.batch, spec.image_size,
                                     spec.num_classes, remat=plan.remat)
    return flops.lm_step_cost(spec.vocab, spec.d_model, spec.n_layers,
                              spec.batch, spec.seq,
                              mlp_ratio=spec.mlp_ratio,
                              fused_ce=plan.fused_ce_mode != "none",
                              remat=plan.remat)


def bucketed_overlap(grad_bytes: float, bucket_mb: float = 4.0,
                     max_overlap: float = 0.95) -> float:
    """Schedule-derived backward-overlap fraction for the bucketed
    comm-overlap scheduler (parallel/overlap.py) — the replacement for
    the assumed ``DEFAULT_OVERLAP`` guess when ``--overlap bucketed``
    is actually in the recipe.

    With ``K = ceil(grad_bytes / bucket_mb·MiB)`` reverse-autodiff
    buckets, every bucket's collective except the final one is issued
    while backward compute remains, so the hideable fraction is
    ``(K-1)/K`` — capped at ``max_overlap`` because the tail bucket (and
    ramp effects) always stay exposed."""
    import math

    if bucket_mb <= 0:
        raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
    k = max(1, math.ceil(float(grad_bytes) / (bucket_mb * 1024.0 * 1024.0)))
    return min(max_overlap, (k - 1) / k)


def spec_bucketed_overlap(spec: ModelSpec, bucket_mb: float = 4.0) -> float:
    """``bucketed_overlap`` over a spec's full f32 gradient bytes (the
    DP sync payload before any tp/pp sharding — the conservative,
    plan-independent schedule estimate the autoplan CLI uses)."""
    plan = Plan(spec=spec, chips=1)
    return bucketed_overlap(4.0 * step_cost_for(plan).params, bucket_mb)


# --------------------------------------------------------------- comms

@dataclasses.dataclass(frozen=True)
class CommEntry:
    kind: str
    payload: float        # per-device result bytes (ledger convention)
    group: int
    overlappable: bool    # backward grad sync: hideable under compute
    what: str

    @property
    def wire(self) -> float:
        return wire_bytes(self.kind, self.payload, self.group)


def _chunk_layout(size: int, n: int, block: int = 256) -> Tuple[int, int]:
    """(padded_total, blocks_per_chunk) — the pure arithmetic of
    ops/qcomm.py ``chunk_layout``, restated here so the analytic path
    never imports jax."""
    chunk = -(-size // n)
    blk = min(block, chunk)
    chunk = -(-chunk // blk) * blk
    return n * chunk, chunk // blk


def comm_entries(plan: Plan, cost: flops.StepCost) -> List[CommEntry]:
    """Every collective the plan implies, with fenced byte magnitudes."""
    spec, dp, tp, pp = plan.spec, plan.dp, plan.tp, plan.pp
    out: List[CommEntry] = []
    if spec.family == "image":
        params = cost.params
        if dp > 1:
            scalars = 4.0 * 5
            gc = plan.grad_compress
            if gc in ("int8", "fp8"):
                padded, nb = _chunk_layout(params, dp)
                per_hop = padded + 4.0 * dp * nb
                out.append(CommEntry("all-to-all", per_hop, dp, True,
                                     "grad_sync_q_scatter"))
                out.append(CommEntry("all-gather", per_hop, dp, True,
                                     "grad_sync_q_gather"))
            elif plan.zero == "wus":
                elem = 2.0 if gc == "bf16" else 4.0
                padded, _ = _chunk_layout(params, dp)
                out.append(CommEntry("reduce-scatter", elem * padded / dp,
                                     dp, True, "wus_grad_scatter"))
                out.append(CommEntry("all-gather", elem * padded, dp, True,
                                     "wus_delta_gather"))
            else:
                elem = 2.0 if gc == "bf16" else 4.0
                out.append(CommEntry("all-reduce", elem * params, dp, True,
                                     "grad_sync"))
            out.append(CommEntry("all-reduce", scalars, dp, False,
                                 "metric_scalars"))
        return out
    # LM: the fenced lm_comm_bytes terms, decomposed per mesh axis.
    V, D, L = spec.vocab, spec.d_model, spec.n_layers
    grad = 4.0 * (cost.params + V * D) / max(1, tp) / max(1, pp)
    act = (spec.batch / max(1, dp)) * spec.seq * D * 4.0
    if dp > 1:
        if plan.fsdp:
            # ZeRO-3: params gather forward + re-gather backward, grads
            # reduce-scatter back — replaces the gradient all-reduce.
            out.append(CommEntry("all-gather", grad, dp, False,
                                 "fsdp_param_gather_fwd"))
            out.append(CommEntry("all-gather", grad, dp, True,
                                 "fsdp_param_gather_bwd"))
            out.append(CommEntry("reduce-scatter", grad / dp, dp, True,
                                 "fsdp_grad_scatter"))
        elif plan.zero == "wus":
            out.append(CommEntry("reduce-scatter", grad / dp, dp, True,
                                 "wus_grad_scatter"))
            out.append(CommEntry("all-gather", grad, dp, True,
                                 "wus_delta_gather"))
        else:
            out.append(CommEntry("all-reduce", grad, dp, True, "grad_sync"))
        out.append(CommEntry("all-reduce", 8.0, dp, False, "loss_scalars"))
    if tp > 1:
        out.append(CommEntry("all-reduce", 4.0 * L * act, tp, False,
                             "tp_layer_psums"))
        out.append(CommEntry("all-reduce", 1.5 * act, tp, False,
                             "tp_embed_psums"))
        out.append(CommEntry("collective-permute", 3.0 * L * act, 2, False,
                             "tp_head_permutes"))
    if pp > 1:
        # Stage-boundary activations: (pp-1) hops forward + (pp-1)
        # gradient hops backward, full per-data-shard activation block.
        out.append(CommEntry("collective-permute", 2.0 * (pp - 1) * act, 2,
                             False, "pp_boundary_acts"))
    return out


def comm_totals(entries: List[CommEntry]) -> Dict[str, float]:
    payload = sum(e.payload for e in entries)
    wire = sum(e.wire for e in entries)
    exposed = sum(e.wire for e in entries if not e.overlappable)
    return {"payload_bytes": payload, "wire_bytes": wire,
            "exposed_wire_bytes": exposed,
            "overlappable_wire_bytes": wire - exposed}


# -------------------------------------------------------------- memory

def mem_cost_for(plan: Plan, cost: Optional[flops.StepCost] = None
                 ) -> flops.MemCost:
    """Per-chip peak-HBM model at the plan's layout.

    The pure-DP base cases reduce EXACTLY to the fenced obs/flops models
    (``lm_train_mem_peak`` / ``train_mem_peak``), so the planner's
    feasibility pruning inherits their ±15% ledger fences; tp/pp/fsdp
    extend them by sharding the same terms over the extra axes."""
    spec = plan.spec
    cost = cost or step_cost_for(plan)
    dp, tp, pp = max(1, plan.dp), max(1, plan.tp), max(1, plan.pp)
    if spec.family == "image":
        params = cost.params
        # StepCost.bytes = 24*params + 2*(4*act_elts*batch): recover the
        # activation side and shard it over dp with the batch.
        act = max(0.0, (cost.bytes - 24.0 * params) / 2.0) / dp
        data = (spec.batch / dp) * spec.image_size ** 2 * 3 * 4.0
        explicit = (plan.zero != "none" or plan.grad_compress != "none")
        return flops.train_mem_peak(4.0 * params, act, data_bytes=data,
                                    dp=dp, zero=plan.zero == "wus",
                                    explicit_sync=explicit)
    V, D, L, H = spec.vocab, spec.d_model, spec.n_layers, spec.n_heads
    m = spec.mlp_ratio
    b = spec.batch / dp
    shard = tp * pp * (dp if plan.fsdp else 1)
    param_bytes = 4.0 * cost.params / shard
    momentum = param_bytes / (dp if (plan.zero == "wus" and not plan.fsdp)
                              else 1)
    grads = param_bytes
    # Activation schedule (lm_act_bytes terms, remat/fused/pp/tp aware):
    per_token = 9.0 * D + 2.0 * m * D
    scores = 2.0 * H * spec.seq
    L_stage = L / pp
    if plan.remat:
        # stash block inputs only + one live block in recompute
        stack = b * spec.seq * (L_stage * D + per_token + scores)
    else:
        stack = b * spec.seq * L_stage * (per_token + scores)
    head = 3.0 * b * spec.seq * V
    if plan.fused_ce_mode != "none":
        head = head / _FUSED_CE_CHUNKS + b * spec.seq * D
    act = 4.0 * (stack + head) / tp
    tokens = 4.0 * b * spec.seq + 8.0
    return flops.MemCost(
        argument_bytes=param_bytes + momentum + tokens,
        output_bytes=param_bytes + momentum + 256.0,
        temp_bytes=grads + act,
        breakdown={"params": param_bytes, "momentum": momentum,
                   "data": tokens, "grads": grads, "activations": act,
                   "grad_sync_scratch": 0.0, "metrics": 256.0})


# --------------------------------------------------------- feasibility

def feasibility(plan: Plan, hw: HW,
                hbm_budget: Optional[float] = None) -> List[str]:
    """Static reasons this plan cannot run (empty list = feasible)."""
    spec = plan.spec
    reasons: List[str] = []
    if plan.dp * plan.tp * plan.pp != plan.chips:
        reasons.append(f"dp*tp*pp = {plan.dp * plan.tp * plan.pp} "
                       f"!= {plan.chips} chips")
    if spec.batch % max(1, plan.dp):
        reasons.append(f"global batch {spec.batch} not divisible by "
                       f"dp={plan.dp}")
    if spec.family == "lm":
        if plan.tp > 1 and spec.vocab % plan.tp:
            reasons.append(f"vocab {spec.vocab} not divisible by "
                           f"tp={plan.tp}")
        if plan.tp > 1 and spec.n_heads % plan.tp:
            reasons.append(f"n_heads {spec.n_heads} not divisible by "
                           f"tp={plan.tp}")
        if plan.pp > 1 and spec.n_layers % plan.pp:
            reasons.append(f"n_layers {spec.n_layers} not divisible by "
                           f"pp={plan.pp} stages")
        if plan.pp > 1 and plan.microbatches == 0:
            reasons.append(
                f"no microbatch count >= pp={plan.pp} divides the "
                f"per-shard batch {spec.batch // max(1, plan.dp)}")
        if plan.fused_ce_mode == "tp" and plan.tp <= 1:
            reasons.append("fused-ce-mode tp needs a model axis (tp > 1)")
    if plan.zero == "wus" and plan.dp <= 1:
        reasons.append("--zero wus shards over the data axis; needs dp > 1")
    if plan.fsdp and plan.dp <= 1:
        reasons.append("--fsdp shards over the data axis; needs dp > 1")
    budget = (hbm_budget if hbm_budget is not None
              else HBM_FILL_FRACTION * hw.hbm_bytes)
    peak = mem_cost_for(plan).peak_bytes
    if peak > budget:
        reasons.append(
            f"predicted per-chip peak {peak / 1e9:.2f} GB exceeds the "
            f"{budget / 1e9:.2f} GB HBM budget on {hw.name}")
    return reasons


# ------------------------------------------------------------- scoring

@dataclasses.dataclass(frozen=True)
class PlanScore:
    """Analytic per-step prediction for one feasible plan."""

    compute_s: float
    comm_s: float
    exposed_comm_s: float
    bubble_s: float
    step_time_s: float
    payload_bytes: float
    wire_bytes: float
    peak_hbm_bytes: float
    mfu_pct: float
    hfu_pct: float

    def to_dict(self) -> Dict[str, float]:
        return {
            "step_time_ms": 1e3 * self.step_time_s,
            "compute_ms": 1e3 * self.compute_s,
            "comm_ms": 1e3 * self.comm_s,
            "exposed_comm_ms": 1e3 * self.exposed_comm_s,
            "bubble_ms": 1e3 * self.bubble_s,
            "payload_bytes": self.payload_bytes,
            "wire_bytes": self.wire_bytes,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "mfu_pct": self.mfu_pct,
            "hfu_pct": self.hfu_pct,
        }


def plan_complexity(plan: Plan) -> int:
    """Non-default knob count — the rank tie-break: at equal predicted
    step time the *simplest* recipe wins (fewer knobs to go wrong;
    memory headroom is a constraint, not an objective)."""
    return (int(plan.tp > 1) + int(plan.pp > 1) + int(plan.fsdp)
            + int(plan.remat) + int(plan.fused_ce_mode != "none")
            + int(plan.zero != "none") + int(plan.grad_compress != "none"))


def score_plan(plan: Plan, hw: HW,
               overlap: float = DEFAULT_OVERLAP) -> PlanScore:
    import os

    overlap = float(os.environ.get("PTD_PLAN_OVERLAP", overlap))
    cost = step_cost_for(plan)
    chips = max(1, plan.chips)
    compute = cost.hardware_flops / (chips * hw.peak_flops)
    entries = comm_entries(plan, cost)
    totals = comm_totals(entries)
    comm = totals["wire_bytes"] / hw.link_bytes
    exposed = (totals["exposed_wire_bytes"] / hw.link_bytes
               + max(0.0, totals["overlappable_wire_bytes"] / hw.link_bytes
                     - overlap * compute))
    bubble = 0.0
    if plan.pp > 1 and plan.microbatches > 0:
        bubble = compute * (plan.pp - 1) / plan.microbatches
    step = compute + bubble + exposed
    denom = step * chips * hw.peak_flops
    return PlanScore(
        compute_s=compute, comm_s=comm, exposed_comm_s=exposed,
        bubble_s=bubble, step_time_s=step,
        payload_bytes=totals["payload_bytes"],
        wire_bytes=totals["wire_bytes"],
        peak_hbm_bytes=mem_cost_for(plan, cost).peak_bytes,
        mfu_pct=100.0 * cost.model_flops / denom,
        hfu_pct=100.0 * cost.hardware_flops / denom)
