"""Cross-process heartbeats + straggler detection.

The reference's multi-node observability is one nvidia-smi CSV per node
(statistics.sh), eyeballed after the fact.  Here every mesh process appends
periodic ``{pid, step, t}`` beats to a shared run directory, and a monitor
(``find_stragglers`` / ``scripts/obs_report.py``) flags processes whose
latest step lags the front-runner or whose newest beat has gone stale —
the signals that distinguish "one slow host" from "everyone is slow"
before a hung collective turns into a silent pod-wide stall.

Deliberately stdlib-only (no jax import): the monitor side runs anywhere —
a login node, a cron job, a test harness — without touching the TPU
runtime, and the writer adds no device work to the hot loop (one small
append per ``interval_s``).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

_PREFIX = "heartbeat-"


class HeartbeatWriter:
    """Appends ``{pid, step, t}`` beats for one process to
    ``<hb_dir>/heartbeat-<pid>.jsonl``.

    ``beat(step)`` is safe to call every step: writes are rate-limited to
    one per ``interval_s`` (0 = every call, for tests).  ``close(step)``
    forces a final beat so the monitor sees the true last step even when
    the run ends mid-interval.
    """

    def __init__(self, hb_dir: str, process_index: int = 0,
                 interval_s: float = 5.0):
        self.dir = hb_dir
        self.process_index = int(process_index)
        self.interval_s = float(interval_s)
        self.path = os.path.join(hb_dir, f"{_PREFIX}{self.process_index:05d}.jsonl")
        os.makedirs(hb_dir, exist_ok=True)
        self._last = float("-inf")

    def beat(self, step: int, force: bool = False,
             step_time_ema: Optional[float] = None,
             last_ft: Optional[str] = None) -> bool:
        """Record a beat at ``step``; returns True when a line was written.

        ``step_time_ema`` (seconds) and ``last_ft`` (the most recent
        ft_event kind) ride along when given, so the monitor can tell a
        *slow* rank (fresh beats, fat EMA) from a *dead* one (stale beats)
        and see whether the rank already said why it is behind."""
        now = time.time()
        if not force and now - self._last < self.interval_s:
            return False
        self._last = now
        rec = {"pid": self.process_index, "step": int(step), "t": now}
        if step_time_ema is not None:
            rec["ema"] = float(step_time_ema)
        if last_ft is not None:
            rec["last_ft"] = str(last_ft)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        return True

    def close(self, step: Optional[int] = None,
              step_time_ema: Optional[float] = None,
              last_ft: Optional[str] = None) -> None:
        if step is not None:
            self.beat(step, force=True, step_time_ema=step_time_ema,
                      last_ft=last_ft)


def read_heartbeats(hb_dir: str) -> Dict[int, dict]:
    """Latest beat per process: ``{pid: {"pid", "step", "t"}}``.

    Tolerates a torn final line (a writer killed mid-append) by walking
    back to the newest parseable record.
    """
    beats: Dict[int, dict] = {}
    if not os.path.isdir(hb_dir):
        return beats
    for name in sorted(os.listdir(hb_dir)):
        if not (name.startswith(_PREFIX) and name.endswith(".jsonl")):
            continue
        with open(os.path.join(hb_dir, name)) as f:
            lines = f.read().splitlines()
        for line in reversed(lines):
            try:
                rec = json.loads(line)
                beats[int(rec["pid"])] = rec
                break
            except (ValueError, KeyError, TypeError):
                continue
    return beats


def _median(vals):
    s = sorted(vals)
    return s[len(s) // 2] if s else None


def find_stragglers(
    beats: Dict[int, dict],
    now: Optional[float] = None,
    max_step_lag: int = 3,
    max_age_s: float = 60.0,
    slow_ema_factor: float = 2.0,
) -> Dict[int, str]:
    """Flag straggling processes → ``{pid: human-readable reason}``.

    Three signals, distinguishing *slow* ranks from *dead* ranks:
    - **step lag**: the process's latest step trails the front-runner by
      more than ``max_step_lag`` (collectives will rate-limit everyone to
      it).  When beats carry a step-time EMA, a fat EMA vs the fleet
      median (> ``slow_ema_factor``x) marks the rank as *slow* — alive
      but dragging, the "replace the host" case;
    - **beat age**: the newest beat is older than ``max_age_s`` — *dead or
      hung*, the one the lock-stepped mesh cannot see from step counters
      alone, since a stuck rank stalls every rank's step;
    - a beat's ``last_ft`` event kind is appended to the reason when
      present, so a rank that already said why it is behind (preempt,
      rollback) reads differently from a silent one.
    """
    if not beats:
        return {}
    if now is None:
        now = time.time()
    lead = max(b["step"] for b in beats.values())
    # Fleet-median EMA over *fresh* ranks only: a dead rank's stale EMA
    # must not drag the baseline.
    med_ema = _median([b["ema"] for b in beats.values()
                       if "ema" in b and now - b["t"] <= max_age_s])
    flagged: Dict[int, str] = {}
    for pid in sorted(beats):
        b = beats[pid]
        reasons = []
        lag = lead - b["step"]
        age = now - b["t"]
        if lag > max_step_lag:
            reason = (f"step lag {lag} > {max_step_lag} "
                      f"(at step {b['step']}, lead {lead})")
            ema = b.get("ema")
            if (age <= max_age_s and ema is not None and med_ema
                    and ema > slow_ema_factor * med_ema):
                reason += (f"; slow rank: step-time ema {ema:.3f}s vs "
                           f"fleet median {med_ema:.3f}s")
            reasons.append(reason)
        if age > max_age_s:
            reasons.append(
                f"beat age {age:.1f}s > {max_age_s:.0f}s (dead or hung)")
        if reasons and b.get("last_ft"):
            reasons.append(f"last ft_event: {b['last_ft']}")
        if reasons:
            flagged[pid] = "; ".join(reasons)
    return flagged
