"""Cross-process heartbeats + straggler detection.

The reference's multi-node observability is one nvidia-smi CSV per node
(statistics.sh), eyeballed after the fact.  Here every mesh process appends
periodic ``{pid, step, t}`` beats to a shared run directory, and a monitor
(``find_stragglers`` / ``scripts/obs_report.py``) flags processes whose
latest step lags the front-runner or whose newest beat has gone stale —
the signals that distinguish "one slow host" from "everyone is slow"
before a hung collective turns into a silent pod-wide stall.

Deliberately stdlib-only (no jax import): the monitor side runs anywhere —
a login node, a cron job, a test harness — without touching the TPU
runtime, and the writer adds no device work to the hot loop (one small
file rewrite per ``interval_s``).

Beats are now *liveness evidence* for elastic membership decisions
(ft/elastic.py), which hardens two soft spots of the original appender:

- **Atomic writes.**  Each beat rewrites the whole (capped) line buffer to
  a tmp file and ``os.replace``s it into place, so a reader never sees a
  torn line and a SIGKILLed writer leaves a fully-parseable file — the
  walk-back in ``read_heartbeats`` is now a belt, not the load-bearing
  strap.
- **Membership epoch.**  Every beat stamps the writer's ``epoch`` and
  ``world``.  After a re-mesh bumps the epoch, beats from a prior
  incarnation (an evicted rank still flushing, a stale file from before a
  restart) are filtered by ``read_heartbeats(min_epoch=...)`` instead of
  masquerading as live members.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

_PREFIX = "heartbeat-"


def sample_process_memory() -> Optional[int]:
    """Best-effort per-process memory sample, in bytes.

    Device-first: when jax is already imported (the trainer side), the
    first local device's ``memory_stats()`` ``bytes_in_use`` is the
    number that matters — live HBM, the thing that OOMs.  Backends
    without stats (CPU, the simulated mesh) fall back to the process RSS
    from ``/proc/self/status`` — still enough for the monitor to see one
    rank's memory balloon away from the fleet.  Never imports jax itself
    (this module stays stdlib-only for the monitor side) and never
    raises; returns None when nothing is measurable."""
    import sys

    if "jax" in sys.modules:
        try:
            stats = sys.modules["jax"].local_devices()[0].memory_stats()
            if stats and "bytes_in_use" in stats:
                return int(stats["bytes_in_use"])
        except Exception:
            pass
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return None


class HeartbeatWriter:
    """Appends ``{pid, step, t}`` beats for one process to
    ``<hb_dir>/heartbeat-<pid>.jsonl``.

    ``beat(step)`` is safe to call every step: writes are rate-limited to
    one per ``interval_s`` (0 = every call, for tests).  ``close(step)``
    forces a final beat so the monitor sees the true last step even when
    the run ends mid-interval.
    """

    #: Lines retained per heartbeat file; the monitor only ever reads the
    #: newest parseable record, older lines are debugging history.
    MAX_LINES = 512

    def __init__(self, hb_dir: str, process_index: int = 0,
                 interval_s: float = 5.0, world: Optional[int] = None,
                 epoch: int = 0):
        self.dir = hb_dir
        self.process_index = int(process_index)
        self.interval_s = float(interval_s)
        # Membership identity: the trainer bumps these on re-mesh so every
        # subsequent beat is attributable to the new incarnation.
        self.world = None if world is None else int(world)
        self.epoch = int(epoch)
        self.path = os.path.join(hb_dir, f"{_PREFIX}{self.process_index:05d}.jsonl")
        os.makedirs(hb_dir, exist_ok=True)
        self._last = float("-inf")
        self._lines: list = []
        if os.path.exists(self.path):
            # A restarted incarnation inherits the file; keep its tail as
            # history rather than clobbering forensic context.
            try:
                with open(self.path) as f:
                    self._lines = f.read().splitlines()[-self.MAX_LINES:]
            except OSError:
                self._lines = []

    def set_membership(self, world: int, epoch: int) -> None:
        """Called by the trainer on re-mesh: subsequent beats carry the new
        world size and membership epoch."""
        self.world = int(world)
        self.epoch = int(epoch)

    def beat(self, step: int, force: bool = False,
             step_time_ema: Optional[float] = None,
             last_ft: Optional[str] = None,
             mem_bytes: Optional[int] = None,
             data_wait_ms: Optional[float] = None) -> bool:
        """Record a beat at ``step``; returns True when a line was written.

        ``step_time_ema`` (seconds) and ``last_ft`` (the most recent
        ft_event kind) ride along when given, so the monitor can tell a
        *slow* rank (fresh beats, fat EMA) from a *dead* one (stale beats)
        and see whether the rank already said why it is behind.
        ``mem_bytes`` (``sample_process_memory``) rides along the same
        way: a rank creeping toward OOM announces it beats ahead.
        ``data_wait_ms`` (the --step-attr data-wait EMA) lets
        ``find_stragglers`` name an *input-starved* rank — slow because
        its loader is, not because its device is."""
        now = time.time()
        if not force and now - self._last < self.interval_s:
            return False
        self._last = now
        rec = {"pid": self.process_index, "step": int(step), "t": now,
               "epoch": self.epoch}
        if self.world is not None:
            rec["world"] = self.world
        if step_time_ema is not None:
            rec["ema"] = float(step_time_ema)
        if last_ft is not None:
            rec["last_ft"] = str(last_ft)
        if mem_bytes is not None:
            rec["mem"] = int(mem_bytes)
        if data_wait_ms is not None:
            rec["data_wait"] = round(float(data_wait_ms), 3)
        self._lines.append(json.dumps(rec))
        del self._lines[:-self.MAX_LINES]
        # Atomic rewrite: liveness decisions (elastic eviction) must never
        # act on a torn record, and a writer killed mid-beat must leave
        # the previous complete file behind, not a half-written line.
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write("\n".join(self._lines) + "\n")
        os.replace(tmp, self.path)
        return True

    def close(self, step: Optional[int] = None,
              step_time_ema: Optional[float] = None,
              last_ft: Optional[str] = None,
              mem_bytes: Optional[int] = None,
              data_wait_ms: Optional[float] = None) -> None:
        if step is not None:
            self.beat(step, force=True, step_time_ema=step_time_ema,
                      last_ft=last_ft, mem_bytes=mem_bytes,
                      data_wait_ms=data_wait_ms)


def read_heartbeats(hb_dir: str,
                    min_epoch: Optional[int] = None) -> Dict[int, dict]:
    """Latest beat per process: ``{pid: {"pid", "step", "t", ...}}``.

    Tolerates a torn final line (a writer killed mid-append, or a file
    from before the atomic-rewrite hardening) by walking back to the
    newest parseable record.

    ``min_epoch`` filters out beats stamped with an older membership
    epoch: after a re-mesh, a prior incarnation's beats must not be
    mistaken for live ranks.  Beats without an epoch field (pre-elastic
    writers) count as epoch 0.
    """
    beats: Dict[int, dict] = {}
    if not os.path.isdir(hb_dir):
        return beats
    for name in sorted(os.listdir(hb_dir)):
        if not (name.startswith(_PREFIX) and name.endswith(".jsonl")):
            continue
        with open(os.path.join(hb_dir, name)) as f:
            lines = f.read().splitlines()
        for line in reversed(lines):
            try:
                rec = json.loads(line)
                if (min_epoch is not None
                        and int(rec.get("epoch", 0)) < min_epoch):
                    break  # newest record is stale; older ones are too
                beats[int(rec["pid"])] = rec
                break
            except (ValueError, KeyError, TypeError):
                continue
    return beats


def _median(vals):
    s = sorted(vals)
    return s[len(s) // 2] if s else None


def fleet_rollup(beats: Dict[int, dict],
                 now: Optional[float] = None) -> Dict[str, object]:
    """Aggregate one ``read_heartbeats`` snapshot into the fleet-level
    scalars the live dashboard (``scripts/obs_live.py``) renders: rank
    count, step front/back, oldest beat age, median step-time EMA, and
    total sampled memory.  Empty snapshot → ``{}``."""
    if not beats:
        return {}
    if now is None:
        now = time.time()
    steps = [int(b.get("step", 0)) for b in beats.values()]
    ages = [max(0.0, now - float(b.get("t", now))) for b in beats.values()]
    emas = [float(b["ema"]) for b in beats.values() if "ema" in b]
    mems = [int(b["mem"]) for b in beats.values()
            if b.get("mem") is not None]
    return {
        "ranks": len(beats),
        "min_step": min(steps),
        "max_step": max(steps),
        "oldest_beat_age_s": max(ages),
        "median_ema_s": _median(emas),
        "total_mem_bytes": sum(mems) if mems else None,
        "worlds": sorted({b["world"] for b in beats.values()
                          if b.get("world") is not None}),
        "epochs": sorted({int(b.get("epoch", 0))
                          for b in beats.values()}),
    }


def find_stragglers(
    beats: Dict[int, dict],
    now: Optional[float] = None,
    max_step_lag: int = 3,
    max_age_s: float = 60.0,
    slow_ema_factor: float = 2.0,
) -> Dict[int, str]:
    """Flag straggling processes → ``{pid: human-readable reason}``.

    Three signals, distinguishing *slow* ranks from *dead* ranks:
    - **step lag**: the process's latest step trails the front-runner by
      more than ``max_step_lag`` (collectives will rate-limit everyone to
      it).  When beats carry a step-time EMA, a fat EMA vs the fleet
      median (> ``slow_ema_factor``x) marks the rank as *slow* — alive
      but dragging, the "replace the host" case;
    - **beat age**: the newest beat is older than ``max_age_s`` — *dead or
      hung*, the one the lock-stepped mesh cannot see from step counters
      alone, since a stuck rank stalls every rank's step;
    - a beat's ``last_ft`` event kind is appended to the reason when
      present, so a rank that already said why it is behind (preempt,
      rollback) reads differently from a silent one;
    - a beat's per-process memory sample (``mem``, bytes) is appended
      the same way — a flagged rank whose memory sits far above the
      fleet's reads as "about to OOM", not merely slow;
    - a beat's ``data_wait`` EMA (milliseconds, from ``--step-attr``)
      reclassifies a slow rank as **input-starved** when the wait is the
      majority of its step time — "fix the loader", not "replace the
      host".
    """
    if not beats:
        return {}
    if now is None:
        now = time.time()
    lead = max(b["step"] for b in beats.values())
    # Fleet-median EMA over *fresh* ranks only: a dead rank's stale EMA
    # must not drag the baseline.
    med_ema = _median([b["ema"] for b in beats.values()
                       if "ema" in b and now - b["t"] <= max_age_s])
    flagged: Dict[int, str] = {}
    for pid in sorted(beats):
        b = beats[pid]
        reasons = []
        lag = lead - b["step"]
        age = now - b["t"]
        if lag > max_step_lag:
            reason = (f"step lag {lag} > {max_step_lag} "
                      f"(at step {b['step']}, lead {lead})")
            ema = b.get("ema")
            if (age <= max_age_s and ema is not None and med_ema
                    and ema > slow_ema_factor * med_ema):
                dw = b.get("data_wait")
                if dw is not None and dw > 0.5 * float(ema) * 1e3:
                    reason += (f"; input-starved rank: data_wait ema "
                               f"{dw:.1f}ms of step-time ema "
                               f"{float(ema) * 1e3:.1f}ms — loader, "
                               f"not device")
                else:
                    reason += (f"; slow rank: step-time ema {ema:.3f}s vs "
                               f"fleet median {med_ema:.3f}s")
            reasons.append(reason)
        if age > max_age_s:
            reasons.append(
                f"beat age {age:.1f}s > {max_age_s:.0f}s (dead or hung)")
        if reasons and b.get("last_ft"):
            reasons.append(f"last ft_event: {b['last_ft']}")
        if reasons and b.get("mem") is not None:
            reasons.append(f"mem {b['mem'] / 2**20:.0f} MiB")
        if reasons:
            flagged[pid] = "; ".join(reasons)
    return flagged
