"""Unified observability layer (SURVEY.md §0: the reference's entire story
is three ``.item()`` calls per batch plus a 500 ms nvidia-smi CSV).

- ``metrics``   — ``MetricsLogger``: one structured JSONL record per step
  (step-time EMA/percentiles, throughput, loss/lr, in-graph grad/param
  norms), with lazy device-scalar conversion and sink registration so the
  epoch CSV and telemetry sampler hang off one entry point.
- ``trace``     — ``scope()``/``ProfileWindow``: TraceAnnotation +
  named_scope under one idiom, and epoch/step-windowed profiler capture.
- ``heartbeat`` — per-process ``{pid, step, t, ema, last_ft}`` beats to a
  shared run directory + cross-process straggler detection that tells
  *slow* ranks from *dead* ones (stdlib-only monitor).
- ``flops``     — analytic per-step FLOPs/bytes models for the registered
  model families, cross-checkable against XLA ``cost_analysis()``, a
  per-chip peak table, and the ``MFUReporter`` that turns step seconds
  into MFU/HFU fields.
- ``goodput``   — the goodput/badput ledger over the metrics JSONL
  (nan-skips, rollback discards, preemption gaps, recompiles, stalls).
- ``watchdog``  — ``RecompileWatchdog``: jax.monitoring-hooked counter
  that flags any post-warmup recompilation of a jitted step-fn.

``scripts/obs_report.py`` folds a run's JSONL + heartbeats + telemetry CSV
into one human-readable summary, and ``--diff A B`` fences two runs
against each other with PASS/REGRESS verdicts.
"""

from pytorch_distributed_tpu.obs.flops import (
    MFUReporter,
    StepCost,
    device_peak_flops,
    image_step_cost,
    lm_step_cost,
    lm_step_cost_for,
    xla_step_flops,
)
from pytorch_distributed_tpu.obs.goodput import (
    GoodputTracker,
    compute_goodput,
    summarize_goodput,
)
from pytorch_distributed_tpu.obs.heartbeat import (
    HeartbeatWriter,
    find_stragglers,
    read_heartbeats,
)
from pytorch_distributed_tpu.obs.metrics import (
    REQUIRED_FIELDS,
    MetricsLogger,
    read_metrics,
)
from pytorch_distributed_tpu.obs.trace import (
    ProfileWindow,
    annotate,
    parse_span,
    scope,
)
from pytorch_distributed_tpu.obs.watchdog import RecompileWatchdog

__all__ = [
    "REQUIRED_FIELDS",
    "MetricsLogger",
    "read_metrics",
    "HeartbeatWriter",
    "read_heartbeats",
    "find_stragglers",
    "scope",
    "annotate",
    "parse_span",
    "ProfileWindow",
    "StepCost",
    "MFUReporter",
    "image_step_cost",
    "lm_step_cost",
    "lm_step_cost_for",
    "xla_step_flops",
    "device_peak_flops",
    "GoodputTracker",
    "compute_goodput",
    "summarize_goodput",
    "RecompileWatchdog",
]
