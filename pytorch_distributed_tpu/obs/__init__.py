"""Unified observability layer (SURVEY.md §0: the reference's entire story
is three ``.item()`` calls per batch plus a 500 ms nvidia-smi CSV).

- ``metrics``   — ``MetricsLogger``: one structured JSONL record per step
  (step-time EMA/percentiles, throughput, loss/lr, in-graph grad/param
  norms), with lazy device-scalar conversion and sink registration so the
  epoch CSV and telemetry sampler hang off one entry point.
- ``trace``     — ``scope()``/``ProfileWindow``: TraceAnnotation +
  named_scope under one idiom, and epoch/step-windowed profiler capture.
- ``heartbeat`` — per-process ``{pid, step, t, ema, last_ft}`` beats to a
  shared run directory + cross-process straggler detection that tells
  *slow* ranks from *dead* ones (stdlib-only monitor).
- ``flops``     — analytic per-step FLOPs/bytes models for the registered
  model families, cross-checkable against XLA ``cost_analysis()``, a
  per-chip peak table, and the ``MFUReporter`` that turns step seconds
  into MFU/HFU fields.
- ``goodput``   — the goodput/badput ledger over the metrics JSONL
  (nan-skips, rollback discards, preemption gaps, recompiles, stalls).
- ``watchdog``  — ``RecompileWatchdog``: jax.monitoring-hooked counter
  that flags any post-warmup recompilation of a jitted step-fn.
- ``comms``     — the static communication ledger: every collective in a
  compiled step with payload/wire bytes, replica-group fan-out, and jax
  scope attribution (``CommLedger``), emitted per run as
  ``comm_ledger.json`` and stamped into the metrics JSONL.
- ``flightrec`` — per-rank crash forensics: a bounded in-memory event ring
  (step/collective/ft/membership events, ~zero hot-path cost) dumped
  atomically to ``flightrec_rank<k>.json`` on any death path, plus the
  collective-hang watchdog daemon; ``scripts/postmortem.py`` merges the
  per-rank dumps into a cross-rank root-cause report.
- ``timeline``  — the runtime side: a pure-python XPlane decoder turning
  profiler captures into per-stream spans, per-step comm/compute/overlap
  accounting (exposed-comm), heartbeat-based cross-rank clock alignment,
  and Chrome-trace/Perfetto export (``scripts/obs_timeline.py``).
- ``export``    — the live plane, rank side: a stdlib HTTP exporter
  serving the latest drained record as Prometheus text exposition on
  ``--metrics-port`` (one daemon thread, zero hot-path syncs).
- ``alerts``    — declarative alert rules over the same stream (step-time
  / goodput / exposed-comm / memory ceilings, dead/slow rank, hang,
  recompile anomaly, bench staleness), latched per episode and booked as
  ``alert`` ft_events; ``scripts/obs_live.py`` is the fleet aggregator
  (scrape every rank + heartbeats → dashboard, exit-1-on-alert for CI).
- ``reqtrace``  — the request-scoped plane for the serving engine: a
  bounded per-request span recorder with a propagatable
  ``TraceContext``, exact TTFT/e2e critical-path attribution
  (queue wait / prefill / preempt-redo / defrag), tail-based sampling,
  and Perfetto request tracks; ``scripts/obs_trace.py`` is the
  jax-free analyzer CLI.

``scripts/obs_report.py`` folds a run's JSONL + heartbeats + telemetry CSV
into one human-readable summary (``--format json`` for machines), and
``--diff A B`` fences two runs against each other with PASS/REGRESS
verdicts — step time, throughput, MFU, goodput, exposed comm, wire bytes.
"""

from pytorch_distributed_tpu.obs.comms import (
    CommEntry,
    CommLedger,
    ledger_from_hlo_text,
    ledger_from_jitted,
    load_ledgers,
    wire_bytes,
    write_ledgers,
)
from pytorch_distributed_tpu.obs.flops import (
    CommCost,
    MFUReporter,
    StepCost,
    comm_residual_pct,
    device_peak_flops,
    image_comm_bytes,
    image_step_cost,
    lm_comm_bytes,
    lm_step_cost,
    lm_step_cost_for,
    xla_step_flops,
)
from pytorch_distributed_tpu.obs.timeline import (
    Span,
    StepComm,
    Timeline,
    aggregate_steps,
    analyze_steps,
    clock_offsets_from_heartbeats,
    marry_ledger,
    parse_xspace,
    to_chrome_trace,
)
from pytorch_distributed_tpu.obs.alerts import (
    Alert,
    AlertEngine,
    AlertRuleError,
    Rule,
    alerts_data,
    dead_ranks_from_events,
    default_rules,
    evaluate_stream,
    load_rules,
    summarize_alerts,
)
from pytorch_distributed_tpu.obs.export import (
    MetricsExporter,
    parse_prometheus,
    sample_value,
)
from pytorch_distributed_tpu.obs.flightrec import (
    FlightRecorder,
    FlightSignalDump,
    HangWatchdog,
)
from pytorch_distributed_tpu.obs.goodput import (
    GoodputTracker,
    compute_goodput,
    summarize_goodput,
)
from pytorch_distributed_tpu.obs.heartbeat import (
    HeartbeatWriter,
    find_stragglers,
    fleet_rollup,
    read_heartbeats,
    sample_process_memory,
)
from pytorch_distributed_tpu.obs.metrics import (
    REQUIRED_FIELDS,
    MetricsLogger,
    read_metrics,
)
from pytorch_distributed_tpu.obs.trace import (
    ProfileWindow,
    annotate,
    capture,
    parse_span,
    scope,
)
from pytorch_distributed_tpu.obs.watchdog import RecompileWatchdog

__all__ = [
    "REQUIRED_FIELDS",
    "MetricsLogger",
    "read_metrics",
    "HeartbeatWriter",
    "read_heartbeats",
    "find_stragglers",
    "sample_process_memory",
    "scope",
    "annotate",
    "capture",
    "parse_span",
    "ProfileWindow",
    "StepCost",
    "MFUReporter",
    "image_step_cost",
    "lm_step_cost",
    "lm_step_cost_for",
    "xla_step_flops",
    "device_peak_flops",
    "GoodputTracker",
    "compute_goodput",
    "summarize_goodput",
    "RecompileWatchdog",
    "FlightRecorder",
    "FlightSignalDump",
    "HangWatchdog",
    "CommEntry",
    "CommLedger",
    "ledger_from_hlo_text",
    "ledger_from_jitted",
    "load_ledgers",
    "wire_bytes",
    "write_ledgers",
    "CommCost",
    "comm_residual_pct",
    "image_comm_bytes",
    "lm_comm_bytes",
    "Span",
    "StepComm",
    "Timeline",
    "aggregate_steps",
    "analyze_steps",
    "clock_offsets_from_heartbeats",
    "marry_ledger",
    "parse_xspace",
    "to_chrome_trace",
    "fleet_rollup",
    "Alert",
    "AlertEngine",
    "AlertRuleError",
    "Rule",
    "alerts_data",
    "dead_ranks_from_events",
    "default_rules",
    "evaluate_stream",
    "load_rules",
    "summarize_alerts",
    "MetricsExporter",
    "parse_prometheus",
    "sample_value",
]
