"""Unified observability layer (SURVEY.md §0: the reference's entire story
is three ``.item()`` calls per batch plus a 500 ms nvidia-smi CSV).

- ``metrics``   — ``MetricsLogger``: one structured JSONL record per step
  (step-time EMA/percentiles, throughput, loss/lr, in-graph grad/param
  norms), with lazy device-scalar conversion and sink registration so the
  epoch CSV and telemetry sampler hang off one entry point.
- ``trace``     — ``scope()``/``ProfileWindow``: TraceAnnotation +
  named_scope under one idiom, and epoch/step-windowed profiler capture.
- ``heartbeat`` — per-process ``{pid, step, t}`` beats to a shared run
  directory + cross-process straggler detection (stdlib-only monitor).

``scripts/obs_report.py`` folds a run's JSONL + heartbeats + telemetry CSV
into one human-readable summary.
"""

from pytorch_distributed_tpu.obs.heartbeat import (
    HeartbeatWriter,
    find_stragglers,
    read_heartbeats,
)
from pytorch_distributed_tpu.obs.metrics import (
    REQUIRED_FIELDS,
    MetricsLogger,
    read_metrics,
)
from pytorch_distributed_tpu.obs.trace import (
    ProfileWindow,
    annotate,
    parse_span,
    scope,
)

__all__ = [
    "REQUIRED_FIELDS",
    "MetricsLogger",
    "read_metrics",
    "HeartbeatWriter",
    "read_heartbeats",
    "find_stragglers",
    "scope",
    "annotate",
    "parse_span",
    "ProfileWindow",
]
