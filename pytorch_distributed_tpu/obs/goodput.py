"""Goodput ledger: how much wall-clock actually became training.

Folds one metrics-JSONL stream — per-step records plus the FT subsystem's
``ft_event`` records (ft/divergence.py, the trainers' preemption path) and
the watchdog's ``recompile`` events — into a badput taxonomy:

- ``nan_skip``          steps whose update the divergence guard gated off
                        (the step ran, the arithmetic was wasted);
- ``rollback_discard``  steps trained past the restored snapshot and then
                        thrown away by a rollback;
- ``preempt_gap``       wall time between a preemption event and the first
                        step of the resumed run (the restart appends to
                        the same JSONL, so the gap is visible in one file);
- ``recompile``         post-warmup compilation time (obs/watchdog.py);
- ``remesh``            wall time between an elastic membership change
                        (shrink/grow, ft/elastic.py) and the first step on
                        the rebuilt mesh — teardown, re-shard, and the
                        recompile at the new world size all land here;
- ``stall``             inter-step wall gaps far beyond the step-time p95
                        with no event explaining them — data starvation,
                        checkpoint I/O, or eval, all "not training".

``goodput_pct`` = productive step seconds / total wall span.  The same
arithmetic backs the post-hoc report (``scripts/obs_report.py``) and the
live ``GoodputTracker`` a trainer registers under ``--goodput``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

BADPUT_KINDS = ("nan_skip", "rollback_discard", "preempt_gap", "recompile",
                "remesh", "stall")


@dataclasses.dataclass
class GoodputReport:
    wall_s: float
    productive_s: float
    badput_s: Dict[str, float]
    counts: Dict[str, int]
    steps: int
    # `alert` ft_events (obs/alerts.py) folded from the same stream.
    # Alerts are a symptom channel, not a badput class — the wall time
    # they describe is already booked by the kinds above.
    alerts: int = 0

    @property
    def goodput_pct(self) -> float:
        return 100.0 * self.productive_s / self.wall_s if self.wall_s > 0 else 0.0

    @property
    def untracked_s(self) -> float:
        """Wall time neither productive nor attributed badput (host-side
        loop overhead, flushes, display)."""
        return max(0.0, self.wall_s - self.productive_s
                   - sum(self.badput_s.values()))


def _pct(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def compute_goodput(records: List[dict], stall_factor: float = 5.0,
                    stall_min_s: float = 1.0) -> GoodputReport:
    """Fold a run's records (step + event, any order) into the ledger.

    ``stall_factor``/``stall_min_s``: an inter-step gap counts as a stall
    only when it exceeds both ``stall_factor`` x step-time p95 and the
    absolute floor — per-step jitter must not masquerade as starvation.
    """
    steps = sorted((r for r in records
                    if "step_time" in r and "ft_event" not in r
                    and "bench_event" not in r),
                   key=lambda r: r.get("t", 0.0))
    events = sorted((r for r in records if "ft_event" in r),
                    key=lambda r: r.get("t", 0.0))
    badput = {k: 0.0 for k in BADPUT_KINDS}
    counts = {k: 0 for k in BADPUT_KINDS}
    alerts = sum(1 for e in events if str(e["ft_event"]) == "alert")

    times = sorted(r["step_time"] for r in steps)
    median = _pct(times, 0.5)
    p95 = _pct(times, 0.95)

    by_step: Dict[int, dict] = {}
    for r in steps:
        if "step" in r:
            # keep the first occurrence: a re-trained step after rollback
            # appends a second record for the same index
            by_step.setdefault(int(r["step"]), r)

    productive = sum(r["step_time"] for r in steps)
    booked: set = set()  # step indices already moved out of productive

    for e in events:
        kind = str(e["ft_event"])
        if kind == "skip":
            counts["nan_skip"] += 1
            s = int(e.get("step", -1))
            rec = by_step.get(s)
            if rec is not None and s not in booked:
                booked.add(s)
                badput["nan_skip"] += rec["step_time"]
                productive -= rec["step_time"]
            elif rec is None:
                badput["nan_skip"] += median  # event without its record
        elif kind == "rollback":
            counts["rollback_discard"] += 1
            hi = int(e.get("step", -1))
            lo = int(e.get("restored_step", -1))
            for s in range(max(lo + 1, 0), hi + 1):
                rec = by_step.get(s)
                # a nan-skipped step in the window is already badput
                if rec is not None and s not in booked:
                    booked.add(s)
                    badput["rollback_discard"] += rec["step_time"]
                    productive -= rec["step_time"]
        elif kind == "preempt":
            counts["preempt_gap"] += 1
            t0 = e.get("t")
            nxt = [r["t"] for r in steps if r.get("t", 0.0) > (t0 or 0.0)]
            if t0 is not None and nxt:
                badput["preempt_gap"] += min(nxt) - t0
        elif kind == "recompile":
            counts["recompile"] += 1
            badput["recompile"] += float(e.get("duration_s", 0.0))
        elif kind == "remesh":
            # Like preempt: the cost is the gap between the membership
            # change and the first step on the rebuilt mesh (re-shard +
            # recompile at the new world size).
            counts["remesh"] += 1
            t0 = e.get("t")
            nxt = [r["t"] for r in steps if r.get("t", 0.0) > (t0 or 0.0)]
            if t0 is not None and nxt:
                badput["remesh"] += min(nxt) - t0

    # Stall scan: unexplained inter-step wall gaps.  Gaps that contain a
    # preemption or remesh event are already booked above.
    event_ts = [e.get("t", 0.0) for e in events
                if str(e["ft_event"]) in ("preempt", "remesh")]
    floor = max(stall_min_s, stall_factor * p95)
    for a, b in zip(steps, steps[1:]):
        if "t" not in a or "t" not in b:
            continue
        gap = b["t"] - a["t"]
        if gap <= floor:
            continue
        if any(a["t"] <= t <= b["t"] for t in event_ts):
            continue
        counts["stall"] += 1
        badput["stall"] += gap - b.get("step_time", 0.0)

    wall = 0.0
    ts = [r["t"] for r in records if "t" in r]
    if ts:
        first = min(ts)
        last = max(ts)
        # the first record's own step time happened before its timestamp
        wall = (last - first) + (steps[0].get("step_time", 0.0) if steps else 0.0)
    return GoodputReport(wall_s=wall, productive_s=max(0.0, productive),
                         badput_s=badput, counts=counts, steps=len(steps),
                         alerts=alerts)


def summarize_goodput(records: List[dict]) -> List[str]:
    """Human-readable ledger section for scripts/obs_report.py."""
    rep = compute_goodput(records)
    if rep.steps == 0 and not any(rep.counts.values()):
        return []
    lines = [
        "== goodput ==",
        f"  wall span         {rep.wall_s:.1f}s",
        f"  productive        {rep.productive_s:.1f}s",
        f"  goodput           {rep.goodput_pct:.1f}%",
    ]
    for kind in BADPUT_KINDS:
        if rep.counts[kind] or rep.badput_s[kind] > 0:
            lines.append(f"  badput/{kind:<17} {rep.badput_s[kind]:.1f}s "
                         f"({rep.counts[kind]}x)")
    if rep.untracked_s > 0.05 * rep.wall_s:
        lines.append(f"  untracked         {rep.untracked_s:.1f}s "
                     "(eval/ckpt/host overhead)")
    if rep.alerts:
        lines.append(f"  alerts fired      {rep.alerts} "
                     "(see the alerts section)")
    return lines


class GoodputTracker:
    """Live in-process ledger: registers as a MetricsLogger step sink
    (callable — invoked once per drained record) and reports at end of
    fit.  Bounded memory: keeps at most ``max_records`` records (a multi-
    day run folds the tail; the authoritative full-run number comes from
    ``obs_report`` over the JSONL)."""

    def __init__(self, max_records: int = 200_000):
        self.max_records = int(max_records)
        self.records: List[dict] = []
        self._dropped = 0

    def __call__(self, record: dict) -> None:
        if len(self.records) >= self.max_records:
            self._dropped += 1
            return
        self.records.append(dict(record))

    def report(self) -> GoodputReport:
        return compute_goodput(self.records)

    def format_summary(self) -> str:
        rep = self.report()
        bad = ", ".join(f"{k} {v:.1f}s" for k, v in rep.badput_s.items()
                        if v > 0) or "none"
        tail = f" ({self._dropped} records past cap untracked)" \
            if self._dropped else ""
        if rep.alerts:
            tail += f"; {rep.alerts} alert(s) fired"
        return (f"goodput {rep.goodput_pct:.1f}% over {rep.wall_s:.1f}s "
                f"({rep.steps} steps; badput: {bad}){tail}")
