"""Recompile watchdog: runtime detection of silent re-compilation.

The static half of this story is shardlint's host-sync/hazard detection
(analysis/); this is the runtime twin.  A steady-state training loop
should compile each jitted step function exactly once — every further
compilation means a shape, dtype, or donation signature quietly changed
(a dynamic batch tail, an accidental Python-scalar operand, a resharded
resume) and the run just paid seconds-to-minutes of XLA time it will pay
again on every recurrence.

Hook: ``jax.monitoring``'s cache-miss instrumentation.  jax records a
duration event on every *actual* backend compilation
(``/jax/core/compile/backend_compile_duration``) and on every tracing-
cache miss (``/jax/core/compile/jaxpr_trace_duration``); the watchdog
listens for both and attributes them to whichever labelled region the
current thread is inside (``watch("train_step")`` around the step call).
Compiles beyond ``warmup_compiles`` per label are anomalies: counted,
printed, and emitted as ``recompile`` events into the metrics JSONL so
``obs_report``'s goodput ledger books the time as badput.

Host-transfer note: jax 0.4.x emits no monitoring event for device→host
copies, so runtime transfer detection is out of scope here — the shardlint
AST lint covers the hot loops statically, and the obs layer's lazy-scalar
discipline keeps intentional syncs off the per-step path.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"

UNATTRIBUTED = "<unattributed>"


class _Watch:
    """Reentrant-per-call context: sets the calling thread's label."""

    def __init__(self, wd: "RecompileWatchdog", label: str,
                 step: Optional[int]):
        self.wd, self.label, self.step = wd, label, step

    def __enter__(self):
        tl = self.wd._tl
        self.prev = (getattr(tl, "label", None), getattr(tl, "step", None))
        tl.label, tl.step = self.label, self.step
        return self

    def __exit__(self, *exc):
        self.wd._tl.label, self.wd._tl.step = self.prev
        return False


class RecompileWatchdog:
    """Counts compilations/retraces per labelled region; flags any
    compilation past ``warmup_compiles`` for that label as an anomaly.

    >>> wd = RecompileWatchdog(obs=logger).install()
    >>> with wd.watch("train_step", step=i):
    ...     state, metrics = train_step(state, batch, lr)
    ...
    >>> wd.uninstall()

    The first compile under each label is warm-up (one compile per jitted
    step-fn is the contract); attribution is thread-local, so a background
    feeder thread's transfers can never be booked to the step.  Compiles
    outside any ``watch`` land under ``<unattributed>`` and are counted
    but never flagged — one-shot helpers (eval builders, checkpoint
    gathers) are not anomalies.
    """

    def __init__(self, obs: Any = None, warmup_compiles: int = 1):
        if warmup_compiles < 1:
            raise ValueError(
                f"warmup_compiles must be >= 1, got {warmup_compiles}")
        self.obs = obs
        self.warmup_compiles = int(warmup_compiles)
        self.compiles: Dict[str, int] = {}
        self.retraces: Dict[str, int] = {}
        self.anomalies: List[dict] = []
        self._tl = threading.local()
        self._installed = False
        self._lock = threading.Lock()

    # ------------------------------------------------------------- lifecycle
    def install(self) -> "RecompileWatchdog":
        if not self._installed:
            import jax.monitoring as monitoring

            monitoring.register_event_duration_secs_listener(self._on_event)
            self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        from jax._src import monitoring as _m

        try:
            _m._unregister_event_duration_listener_by_callback(self._on_event)
        except (AssertionError, AttributeError, ValueError):
            # Listener list API drifted or already gone: leave the dead
            # listener registered; _on_event no-ops once uninstalled.
            pass

    def __enter__(self) -> "RecompileWatchdog":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ------------------------------------------------------------ attribution
    def watch(self, label: str, step: Optional[int] = None) -> _Watch:
        """Attribute compiles fired by this thread inside the context to
        ``label`` (typically wrapped right around the jitted step call)."""
        return _Watch(self, str(label), step)

    # ---------------------------------------------------------------- events
    def _on_event(self, event: str, duration_secs: float, **kw) -> None:
        if not self._installed:
            return
        if event == TRACE_EVENT:
            label = getattr(self._tl, "label", None) or UNATTRIBUTED
            with self._lock:
                self.retraces[label] = self.retraces.get(label, 0) + 1
            return
        if event != BACKEND_COMPILE_EVENT:
            return
        label = getattr(self._tl, "label", None) or UNATTRIBUTED
        step = getattr(self._tl, "step", None)
        with self._lock:
            n = self.compiles.get(label, 0) + 1
            self.compiles[label] = n
        if label == UNATTRIBUTED or n <= self.warmup_compiles:
            return
        anomaly = {"label": label, "compile_index": n,
                   "duration_s": float(duration_secs)}
        if step is not None:
            anomaly["step"] = int(step)
        self.anomalies.append(anomaly)
        print(f"!! recompile watchdog: {label} compiled again "
              f"(#{n}, {duration_secs:.2f}s"
              + (f", step {step}" if step is not None else "") + ") — "
              "shape/dtype/donation signature changed after warmup",
              flush=True)
        if self.obs is not None and hasattr(self.obs, "log_event"):
            self.obs.log_event("recompile", step=step, label=label,
                               compile_index=n,
                               duration_s=float(duration_secs))
