"""Declarative alert rules over the unified metrics stream (ISSUE 14).

Every fence this repo has shipped so far is post-hoc: `obs_report --diff`
verdicts, the goodput ledger, the straggler monitor, the bench staleness
WARN — all read artifacts after the run.  This module is the live half:
a small set of *declarative* rules, each anchored to an existing fence or
baseline, evaluated incrementally over the same record stream
``MetricsLogger`` already drains — zero new hot-path work (the engine is
a flush-time step sink, like ``GoodputTracker``).

Rule kinds (anchors in parentheses):

- ``step_time_p95``   step-time quantile ceiling in ms (the
  ``obs_report --diff`` step-time fence / ``BENCH_LKG.json`` trajectory);
- ``goodput_floor``   live productive-seconds / wall-span estimate below
  ``min_pct`` (obs/goodput.py);
- ``exposed_comm``    un-overlapped collective ms per step above
  ``max_ms`` (the PR-6 ``exposed_comm_ms`` fence);
- ``mem_peak``        compiled per-device peak above ``max_bytes``
  (``analysis/baseline.json`` ``peak_hbm_bytes``);
- ``dead_rank`` / ``slow_rank``  heartbeat liveness via the *same*
  ``find_stragglers`` thresholds the elastic coordinator uses — one
  liveness policy, not two;
- ``hang``            the collective-hang watchdog's ``hang`` ft_event
  (obs/flightrec.py);
- ``replica_down``    the fleet router's ``replica_down`` ft_event — a
  serving replica failed its health probe and was quarantined
  (serving/router.py ``ReplicaRegistry``); fires once per replica;
- ``recompile``       post-warmup recompile ft_events beyond
  ``max_events`` (obs/watchdog.py);
- ``bench_stale``     days since the last good benchmark capture beyond
  ``max_days`` (scripts/benchlib.py ``bench_staleness``) — the live twin
  of the ``obs_report --strict`` fence;
- ``ttft_p99``        serving time-to-first-token p99 above ``max_ms``
  (the serving engine's ``ttft_p99_ms`` SLO field, serving/engine.py);
- ``kv_occupancy``    paged KV pool occupancy above ``max_pct`` — the
  early-warning fence before the pool exhausts and preemption starts
  (serving/kvpool.py ``kv_occupancy_pct``);
- ``queue_wait_share``  rolling p99 share of TTFT spent in pure queue
  wait above ``max_pct`` (obs/reqtrace.py attribution — *why* TTFT is
  breaching: admission backlog, not compute);
- ``preempt_redo``    rolling p99 preempt-redo cost per request above
  ``max_ms`` (obs/reqtrace.py — recompute-storm attribution: the KV
  pool is thrashing, grow it or cap admission);
- ``data_wait_share``  per-step share of wall time spent waiting on the
  input pipeline above ``max_pct`` (obs/stepattr.py ``--step-attr``
  attribution — the step is input-starved: fix the loader, not the
  device).

Firing alerts are **booked as ``alert`` ft_events** into the same JSONL
through the engine's ``emit`` callback (the trainers wire it to
``obs.log_event("alert", ...)``), so goodput, postmortem, the flight
ring, and ``obs_report`` fold them with zero new plumbing.  Rules latch:
one alert per breach episode, re-armed when the condition clears.

Deliberately stdlib-only and import-time jax-free: the fleet aggregator
(``scripts/obs_live.py``) evaluates the same rules on a login node.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

SEVERITIES = ("warn", "page")

#: quantile name -> metrics-record field for the step-time rule
_QUANTILE_FIELDS = {
    "p50": "step_time_p50",
    "p95": "step_time_p95",
    "max": "step_time_max",
    "ema": "step_time_ema",
    "last": "step_time",
}

# kind -> (required params, optional params).  Unknown kinds and unknown
# or missing params are hard errors at load time — a typo'd rules file
# must fail loudly, not silently never fire.
_RULE_SPECS: Dict[str, tuple] = {
    "step_time_p95": ({"max_ms"}, {"quantile", "warmup_steps"}),
    "goodput_floor": ({"min_pct"}, {"min_steps"}),
    "exposed_comm": ({"max_ms"}, set()),
    "mem_peak": ({"max_bytes"}, set()),
    "dead_rank": (set(), {"max_age_s"}),
    "slow_rank": (set(), {"max_step_lag", "slow_ema_factor", "max_age_s"}),
    "hang": (set(), set()),
    "replica_down": (set(), set()),
    "recompile": (set(), {"max_events"}),
    "bench_stale": ({"max_days"}, {"lkg_path", "events_path"}),
    "ttft_p99": ({"max_ms"}, set()),
    "kv_occupancy": ({"max_pct"}, set()),
    "queue_wait_share": ({"max_pct"}, set()),
    "preempt_redo": ({"max_ms"}, set()),
    "data_wait_share": ({"max_pct"}, {"warmup_steps"}),
}
RULE_KINDS = tuple(sorted(_RULE_SPECS))

_STEP_RULE_KINDS = ("step_time_p95", "goodput_floor", "exposed_comm",
                    "mem_peak", "ttft_p99", "kv_occupancy",
                    "queue_wait_share", "preempt_redo",
                    "data_wait_share")


class AlertRuleError(ValueError):
    """A rules file that cannot be trusted: unreadable, not JSON, an
    unknown rule kind, or a missing/mistyped parameter."""


def _sibling_module(name: str):
    """Import a sibling ``obs`` module without dragging in jax.

    The top-level package ``__init__`` imports jax (the shard_map compat
    bridge), so ``from pytorch_distributed_tpu.obs import heartbeat``
    would pull the whole runtime into a login-node aggregator process.
    When the package is already loaded (the trainer side) use it; when it
    is not (``obs_live``, the jax-free tests) load the sibling file
    directly."""
    import importlib
    import importlib.util
    import sys

    full = f"pytorch_distributed_tpu.obs.{name}"
    if full in sys.modules:
        return sys.modules[full]
    if "pytorch_distributed_tpu" in sys.modules:
        return importlib.import_module(full)
    alias = f"_ptd_obs_{name}"
    if alias in sys.modules:
        return sys.modules[alias]
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"{name}.py")
    spec = importlib.util.spec_from_file_location(alias, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[alias] = mod
    spec.loader.exec_module(mod)
    return mod


def _split_liveness(flagged: Dict[int, str]):
    """``ft.elastic.split_liveness`` when the package is loaded; its
    documented reason-string contract otherwise (ft/elastic.py imports
    the package, which imports jax)."""
    import sys

    if "pytorch_distributed_tpu" in sys.modules:
        try:
            from pytorch_distributed_tpu.ft.elastic import split_liveness

            return split_liveness(flagged)
        except Exception:
            pass
    dead = {pid for pid, why in flagged.items() if "dead or hung" in why}
    slow = {pid for pid, why in flagged.items()
            if pid not in dead and "slow rank" in why}
    return dead, slow


@dataclasses.dataclass
class Rule:
    """One declarative rule: a kind, a display name, a severity, and the
    kind's parameters (validated against ``_RULE_SPECS``)."""

    kind: str
    name: str
    severity: str = "warn"
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Alert:
    """One firing: booked as an ``alert`` ft_event via ``Alert.fields``."""

    name: str
    kind: str
    severity: str
    detail: str
    step: Optional[int] = None
    value: Optional[float] = None
    threshold: Optional[float] = None
    rank: Optional[int] = None
    t: float = 0.0

    def fields(self) -> Dict[str, Any]:
        """ft_event payload for ``obs.log_event("alert", **fields)``."""
        out: Dict[str, Any] = {"alert": self.name, "rule": self.kind,
                               "severity": self.severity,
                               "detail": self.detail}
        if self.step is not None:
            out["step"] = int(self.step)
        if self.value is not None:
            out["value"] = float(self.value)
        if self.threshold is not None:
            out["threshold"] = float(self.threshold)
        if self.rank is not None:
            out["rank"] = int(self.rank)
        return out


def _parse_rule(raw: Any, index: int) -> Rule:
    where = f"rules[{index}]"
    if not isinstance(raw, dict):
        raise AlertRuleError(f"{where}: expected an object, got "
                             f"{type(raw).__name__}")
    kind = raw.get("kind")
    if kind not in _RULE_SPECS:
        raise AlertRuleError(
            f"{where}: unknown kind {kind!r} (known: {', '.join(RULE_KINDS)})")
    required, optional = _RULE_SPECS[kind]
    severity = raw.get("severity", "warn")
    if severity not in SEVERITIES:
        raise AlertRuleError(f"{where} ({kind}): severity must be one of "
                             f"{SEVERITIES}, got {severity!r}")
    params = {k: v for k, v in raw.items()
              if k not in ("kind", "name", "severity")}
    missing = required - set(params)
    if missing:
        raise AlertRuleError(f"{where} ({kind}): missing required "
                             f"parameter(s) {sorted(missing)}")
    unknown = set(params) - required - optional
    if unknown:
        raise AlertRuleError(
            f"{where} ({kind}): unknown parameter(s) {sorted(unknown)} "
            f"(accepted: {sorted(required | optional)})")
    for k, v in params.items():
        if k in ("lkg_path", "events_path"):
            if not isinstance(v, str):
                raise AlertRuleError(f"{where} ({kind}): {k} must be a "
                                     f"path string, got {type(v).__name__}")
        elif k == "quantile":
            if v not in _QUANTILE_FIELDS:
                raise AlertRuleError(
                    f"{where} ({kind}): quantile must be one of "
                    f"{sorted(_QUANTILE_FIELDS)}, got {v!r}")
        elif not isinstance(v, (int, float)) or isinstance(v, bool):
            raise AlertRuleError(f"{where} ({kind}): {k} must be a number, "
                                 f"got {v!r}")
    return Rule(kind=kind, name=str(raw.get("name", kind)),
                severity=severity, params=params)


def load_rules(path: str) -> List[Rule]:
    """Parse + validate a JSON rules file: ``{"rules": [{...}, ...]}``
    (a bare list also works).  Raises ``AlertRuleError`` with the rule
    index and reason on anything malformed."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except OSError as e:
        raise AlertRuleError(f"cannot read rules file '{path}': {e}")
    except ValueError as e:
        raise AlertRuleError(f"rules file '{path}' is not valid JSON: {e}")
    if isinstance(payload, dict) and isinstance(payload.get("rules"), list):
        raw_rules = payload["rules"]
    elif isinstance(payload, list):
        raw_rules = payload
    else:
        raise AlertRuleError(
            f"rules file '{path}': expected {{\"rules\": [...]}} or a "
            "top-level list of rule objects")
    rules = [_parse_rule(r, i) for i, r in enumerate(raw_rules)]
    names = [r.name for r in rules]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise AlertRuleError(f"rules file '{path}': duplicate rule "
                             f"name(s) {sorted(dupes)} — give each a "
                             "distinct 'name'")
    return rules


def default_rules() -> List[Rule]:
    """The anchor-free built-in set (``--alerts default``): liveness,
    hang, recompile anomaly, a generous goodput floor, and bench
    staleness at the report's default 14-day window.  Threshold rules
    that need a run-specific anchor (step time, exposed comm, memory)
    belong in a rules file."""
    return [
        Rule("dead_rank", "dead_rank", "page", {"max_age_s": 60.0}),
        Rule("slow_rank", "slow_rank", "warn",
             {"max_step_lag": 3, "slow_ema_factor": 2.0,
              "max_age_s": 60.0}),
        Rule("hang", "hang", "page", {}),
        Rule("recompile", "recompile", "warn", {"max_events": 2}),
        Rule("goodput_floor", "goodput_floor", "warn",
             {"min_pct": 50.0, "min_steps": 50}),
        Rule("bench_stale", "bench_stale", "warn", {"max_days": 14.0}),
    ]


def _bench_staleness(params: Dict[str, Any],
                     now: Optional[float]) -> Optional[Dict]:
    """``scripts/benchlib.bench_staleness`` via a lazy path insert (this
    package must not import from scripts/ at module load)."""
    import sys

    scripts = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    from benchlib import bench_staleness

    return bench_staleness(lkg_path=params.get("lkg_path"),
                           events_path=params.get("events_path"), now=now)


class AlertEngine:
    """Incremental rule evaluation with per-episode latching.

    - ``observe(record)`` — one drained metrics record (step or
      ft_event); the engine is callable, so ``obs.register(engine)``
      wires it as a flush-time step sink (zero hot-path syncs: records
      arrive already host-converted, every ``flush_every`` steps).
    - ``observe_heartbeats(beats, now)`` — the aggregator/monitor side:
      dead/slow-rank rules over ``read_heartbeats`` output.
    - ``check_bench(now)`` — bench-staleness rules; also run once lazily
      on the first observed record so a trainer-side engine books it.
    - ``emit`` — called once per firing with the ft_event payload; the
      trainers pass ``lambda **f: obs.log_event("alert", **f)``.

    A rule fires once per breach episode (latched), clears when its
    condition goes back under threshold, and may fire again on the next
    breach.  Evaluation errors never propagate into the training loop.
    """

    def __init__(self, rules: Iterable[Rule],
                 emit: Optional[Callable[..., None]] = None,
                 process_index: int = 0):
        self.rules = list(rules)
        self.emit = emit
        self.process_index = int(process_index)
        self.firing: Dict[Any, Alert] = {}
        self.history: List[Alert] = []
        self._by_kind: Dict[str, List[Rule]] = {}
        for r in self.rules:
            self._by_kind.setdefault(r.kind, []).append(r)
        self._event_counts: Dict[str, int] = {}
        self._bench_checked = False
        # live goodput estimate: productive step seconds vs wall span
        self._steps = 0
        self._prod = 0.0
        self._first_st: Optional[float] = None
        self._t0: Optional[float] = None
        self._t1: Optional[float] = None

    # ------------------------------------------------------------- latching
    def _fire(self, rule: Rule, key: Any, detail: str,
              step: Optional[int] = None, value: Optional[float] = None,
              threshold: Optional[float] = None,
              rank: Optional[int] = None) -> List[Alert]:
        if key in self.firing:
            return []
        alert = Alert(name=rule.name, kind=rule.kind, severity=rule.severity,
                      detail=detail, step=step, value=value,
                      threshold=threshold, rank=rank, t=time.time())
        self.firing[key] = alert
        self.history.append(alert)
        if self.emit is not None:
            try:
                self.emit(**alert.fields())
            except Exception:
                pass  # alerting must never take down the training loop
        return [alert]

    def _clear(self, key: Any) -> None:
        self.firing.pop(key, None)

    def active(self) -> List[Alert]:
        """Currently-firing alerts (latched, condition not yet cleared)."""
        return list(self.firing.values())

    # ------------------------------------------------------------ the stream
    def __call__(self, record: dict) -> None:
        self.observe(record)

    def observe(self, rec: dict) -> List[Alert]:
        """Evaluate one drained record; returns any alerts fired by it."""
        fired: List[Alert] = []
        try:
            if "bench_event" in rec:
                return fired
            if not self._bench_checked:
                self._bench_checked = True
                fired += self.check_bench()
            if "ft_event" in rec:
                return fired + self._observe_event(rec)
            if "step_time" in rec:
                fired += self._observe_step(rec)
        except Exception:
            if self.emit is None:
                raise  # offline/test path: surface the bug
        return fired

    def _observe_event(self, rec: dict) -> List[Alert]:
        kind = str(rec["ft_event"])
        if kind == "alert":
            return []  # never alert on alerts (incl. our own bookings)
        self._event_counts[kind] = self._event_counts.get(kind, 0) + 1
        fired: List[Alert] = []
        if kind == "hang":
            for rule in self._by_kind.get("hang", ()):
                coll = rec.get("collective") or rec.get("kind")
                detail = (f"collective hang at step {rec.get('step')}"
                          + (f" ({coll})" if coll else ""))
                fired += self._fire(rule, key=rule.name, detail=detail,
                                    step=rec.get("step"),
                                    value=rec.get("elapsed_s"))
        elif kind == "replica_down":
            for rule in self._by_kind.get("replica_down", ()):
                rid = rec.get("replica")
                reason = rec.get("reason")
                detail = (f"serving replica {rid} quarantined"
                          + (f" ({reason})" if reason else ""))
                fired += self._fire(rule, key=(rule.name, rid),
                                    detail=detail,
                                    rank=rid if isinstance(rid, int)
                                    else None)
        elif kind == "recompile":
            n = self._event_counts[kind]
            for rule in self._by_kind.get("recompile", ()):
                cap = int(rule.params.get("max_events", 0))
                if n > cap:
                    fired += self._fire(
                        rule, key=rule.name, step=rec.get("step"),
                        value=float(n), threshold=float(cap),
                        detail=f"{n} post-warmup recompile(s) > {cap}")
        return fired

    def _observe_step(self, rec: dict) -> List[Alert]:
        fired: List[Alert] = []
        step = int(rec.get("step", -1))
        proc = int(rec.get("process", self.process_index))
        st = float(rec["step_time"])
        self._steps += 1
        self._prod += st
        if self._first_st is None:
            self._first_st = st
        t = rec.get("t")
        if isinstance(t, (int, float)):
            self._t0 = t if self._t0 is None else min(self._t0, t)
            self._t1 = t if self._t1 is None else max(self._t1, t)

        for rule in self._by_kind.get("step_time_p95", ()):
            q = rule.params.get("quantile", "p95")
            v = rec.get(_QUANTILE_FIELDS[q])
            warmup = int(rule.params.get("warmup_steps", 10))
            if v is None or step < warmup:
                continue
            ms = float(v) * 1e3
            cap = float(rule.params["max_ms"])
            key = (rule.name, proc)
            if ms > cap:
                fired += self._fire(
                    rule, key=key, step=step, value=ms, threshold=cap,
                    rank=proc,
                    detail=f"step time {q} {ms:.1f}ms > {cap:g}ms")
            else:
                self._clear(key)

        for rule in self._by_kind.get("exposed_comm", ()):
            v = rec.get("exposed_comm_ms")
            if v is None:
                continue
            cap = float(rule.params["max_ms"])
            key = (rule.name, proc)
            if float(v) > cap:
                fired += self._fire(
                    rule, key=key, step=step, value=float(v), threshold=cap,
                    rank=proc,
                    detail=f"exposed comm {float(v):.3f}ms > {cap:g}ms")
            else:
                self._clear(key)

        for rule in self._by_kind.get("ttft_p99", ()):
            v = rec.get("ttft_p99_ms")
            if v is None:
                continue
            cap = float(rule.params["max_ms"])
            key = (rule.name, proc)
            if float(v) > cap:
                fired += self._fire(
                    rule, key=key, step=step, value=float(v), threshold=cap,
                    rank=proc,
                    detail=f"TTFT p99 {float(v):.1f}ms > {cap:g}ms")
            else:
                self._clear(key)

        for rule in self._by_kind.get("kv_occupancy", ()):
            v = rec.get("kv_occupancy_pct")
            if v is None:
                continue
            cap = float(rule.params["max_pct"])
            key = (rule.name, proc)
            if float(v) > cap:
                fired += self._fire(
                    rule, key=key, step=step, value=float(v), threshold=cap,
                    rank=proc,
                    detail=f"KV occupancy {float(v):.1f}% > {cap:g}%")
            else:
                self._clear(key)

        for rule in self._by_kind.get("queue_wait_share", ()):
            v = rec.get("queue_wait_share_p99")
            if v is None:
                continue
            cap = float(rule.params["max_pct"])
            key = (rule.name, proc)
            if float(v) > cap:
                fired += self._fire(
                    rule, key=key, step=step, value=float(v), threshold=cap,
                    rank=proc,
                    detail=f"queue-wait share p99 {float(v):.1f}% of TTFT "
                           f"> {cap:g}%")
            else:
                self._clear(key)

        for rule in self._by_kind.get("preempt_redo", ()):
            v = rec.get("preempt_redo_ms_p99")
            if v is None:
                continue
            cap = float(rule.params["max_ms"])
            key = (rule.name, proc)
            if float(v) > cap:
                fired += self._fire(
                    rule, key=key, step=step, value=float(v), threshold=cap,
                    rank=proc,
                    detail=f"preempt-redo p99 {float(v):.1f}ms/request "
                           f"> {cap:g}ms")
            else:
                self._clear(key)

        for rule in self._by_kind.get("data_wait_share", ()):
            v = rec.get("data_wait_share")
            warmup = int(rule.params.get("warmup_steps", 5))
            if v is None or step < warmup:
                continue
            cap = float(rule.params["max_pct"])
            key = (rule.name, proc)
            if float(v) > cap:
                fired += self._fire(
                    rule, key=key, step=step, value=float(v), threshold=cap,
                    rank=proc,
                    detail=f"data-wait share {float(v):.1f}% of step time "
                           f"> {cap:g}% — input-starved (loader, not "
                           f"device)")
            else:
                self._clear(key)

        for rule in self._by_kind.get("mem_peak", ()):
            v = rec.get("mem_peak_bytes")
            if v is None:
                continue
            cap = float(rule.params["max_bytes"])
            key = (rule.name, proc)
            if float(v) > cap:
                fired += self._fire(
                    rule, key=key, step=step, value=float(v), threshold=cap,
                    rank=proc,
                    detail=(f"peak HBM {float(v) / 2**20:.1f} MiB > "
                            f"{cap / 2**20:.1f} MiB"))
            else:
                self._clear(key)

        for rule in self._by_kind.get("goodput_floor", ()):
            floor = float(rule.params["min_pct"])
            min_steps = int(rule.params.get("min_steps", 20))
            if (self._steps < min_steps or self._t0 is None
                    or self._t1 is None):
                continue
            wall = (self._t1 - self._t0) + (self._first_st or 0.0)
            if wall <= 0:
                continue
            est = 100.0 * self._prod / wall
            key = rule.name
            if est < floor:
                fired += self._fire(
                    rule, key=key, step=step, value=est, threshold=floor,
                    detail=(f"goodput estimate {est:.1f}% < {floor:g}% "
                            f"over {wall:.1f}s"))
            else:
                self._clear(key)
        return fired

    # -------------------------------------------------------- the heartbeats
    def observe_heartbeats(self, beats: Dict[int, dict],
                           now: Optional[float] = None) -> List[Alert]:
        """Dead/slow-rank rules over one ``read_heartbeats`` snapshot —
        the same ``find_stragglers``/``split_liveness`` thresholds the
        elastic coordinator evicts with (one liveness policy)."""
        find_stragglers = _sibling_module("heartbeat").find_stragglers

        fired: List[Alert] = []
        for rule in (list(self._by_kind.get("dead_rank", ()))
                     + list(self._by_kind.get("slow_rank", ()))):
            flagged = find_stragglers(
                beats, now=now,
                max_step_lag=int(rule.params.get("max_step_lag", 3)),
                max_age_s=float(rule.params.get("max_age_s", 60.0)),
                slow_ema_factor=float(
                    rule.params.get("slow_ema_factor", 2.0)))
            dead, slow = _split_liveness(flagged)
            hits = dead if rule.kind == "dead_rank" else slow
            for pid in sorted(beats):
                key = (rule.name, pid)
                if pid in hits:
                    fired += self._fire(
                        rule, key=key, rank=pid,
                        step=beats[pid].get("step"),
                        detail=f"rank {pid}: {flagged[pid]}")
                else:
                    self._clear(key)
        return fired

    # -------------------------------------------------------------- the bench
    def check_bench(self, now: Optional[float] = None) -> List[Alert]:
        """Bench-staleness rules (``benchlib.bench_staleness``): the live
        twin of the ``obs_report --strict`` stale-bench fence."""
        fired: List[Alert] = []
        for rule in self._by_kind.get("bench_stale", ()):
            try:
                info = _bench_staleness(rule.params, now)
            except Exception:
                continue  # missing/unreadable LKG: nothing to age
            if info is None:
                continue
            days = float(info["days_stale"])
            cap = float(rule.params["max_days"])
            key = rule.name
            if days > cap:
                ev = info.get("stale_events") or 0
                fired += self._fire(
                    rule, key=key, value=days, threshold=cap,
                    detail=(f"benchmark stale {days:.1f} days > {cap:g} "
                            f"(last good {info.get('last_good')}"
                            + (f", {ev} stale event(s)" if ev else "") + ")"))
            else:
                self._clear(key)
        return fired


def evaluate_stream(records: Iterable[dict], rules: Iterable[Rule],
                    beats: Optional[Dict[int, dict]] = None,
                    now: Optional[float] = None) -> AlertEngine:
    """One-shot offline evaluation (tests, CLIs): feed every record, then
    the heartbeat snapshot, then the bench age; returns the engine."""
    engine = AlertEngine(rules)
    for rec in records:
        engine.observe(rec)
    if beats:
        engine.observe_heartbeats(beats, now=now)
    engine._bench_checked = True  # evaluated below with the fixed clock
    engine.check_bench(now=now)
    return engine


# ----------------------------------------------------- stream folding helpers

def alert_events(records: Iterable[dict]) -> List[dict]:
    """The ``alert`` ft_events of a record stream, in order."""
    return [r for r in records if r.get("ft_event") == "alert"]


def dead_ranks_from_events(records: Iterable[dict],
                           since_t: float = 0.0) -> Dict[int, float]:
    """Ranks named by ``dead_rank`` alert events newer than ``since_t``
    → ``{rank: newest event t}``.  This is how ``elastic_agent watch``
    routes a dead-rank alert into the coordinator's one eviction path."""
    out: Dict[int, float] = {}
    for e in alert_events(records):
        if e.get("rule") != "dead_rank" or "rank" not in e:
            continue
        t = float(e.get("t", 0.0))
        if t <= since_t:
            continue
        r = int(e["rank"])
        out[r] = max(out.get(r, 0.0), t)
    return out


def alerts_data(records: Iterable[dict]) -> Dict[str, Any]:
    """Machine-readable fold of a stream's ``alert`` ft_events (the
    ``obs_report --format json`` twin of ``summarize_alerts``)."""
    events = alert_events(records)
    by_name: Dict[str, Dict[str, Any]] = {}
    for e in events:
        name = str(e.get("alert", e.get("rule", "?")))
        slot = by_name.setdefault(name, {
            "count": 0, "rule": e.get("rule"),
            "severity": e.get("severity", "warn"),
            "steps": [], "ranks": [], "last_detail": None, "last_t": None})
        slot["count"] += 1
        if "step" in e:
            slot["steps"].append(e["step"])
        if "rank" in e and e["rank"] not in slot["ranks"]:
            slot["ranks"].append(e["rank"])
        slot["last_detail"] = e.get("detail")
        slot["last_t"] = e.get("t")
    return {"total": len(events), "by_name": by_name}


def summarize_alerts(records: Iterable[dict]) -> List[str]:
    """The ``== alerts ==`` report section: per-rule counts, severity,
    the steps/ranks involved, and the latest detail line."""
    data = alerts_data(records)
    if not data["total"]:
        return []
    lines = ["== alerts =="]
    for name in sorted(data["by_name"]):
        slot = data["by_name"][name]
        bits = [f"[{slot['severity']}]"]
        steps = slot["steps"]
        if steps:
            shown = ",".join(str(s) for s in steps[:6])
            if len(steps) > 6:
                shown += ",…"
            bits.append(f"steps {shown}")
        if slot["ranks"]:
            bits.append("ranks " + ",".join(str(r) for r in slot["ranks"]))
        lines.append(f"  {name:<16}  {slot['count']}x  " + "  ".join(bits))
        if slot["last_detail"]:
            lines.append(f"    {slot['last_detail']}")
    return lines
