"""Static per-device memory ledger: live-range watermark + peak attribution.

The comm ledger (obs/comms.py) itemizes every *wire* byte; this module
does the same for *resident* bytes.  From one compiled step's
post-optimization HLO (``is_scheduled=true`` — the printed instruction
order IS the execution schedule) it walks the entry computation, computes
each value's definition/last-use live range from its shapes, and builds:

- a per-instruction **watermark curve** — ``argument + output + live
  temporaries`` at every schedule point — whose peak is fenced against
  ``compiled.memory_analysis()`` (temp + argument + output, the same
  accounting as ``comms.compiled_peak_bytes``) within ±10%;
- **peak attribution**: the top-k live buffers at the high-water mark,
  each with shape, dtype, and the ``named_scope`` phase
  (forward/backward/grad_sync/optimizer/pp_*) its producer lowered under;
- a classified breakdown — params / optimizer state / input data
  (argument classes, from the caller's args pytree), activations &
  saved residuals / collective scratch (temporaries, by opcode + phase),
  and outputs.

Accounting conventions (chosen to match XLA's buffer assignment, which
``memory_analysis`` reports):

- Arguments and outputs are whole-program allocations: ``argument_bytes``
  and ``output_bytes`` are constant terms under the curve.  Donated
  inputs alias output buffers at runtime, but ``memory_analysis`` sums
  the three allocation classes without deducting aliasing — the ledger
  mirrors that (``donated_bytes`` records the overlap separately).
- View/bookkeeping ops (``tuple``, ``get-tuple-element``, ``bitcast``,
  async ``*-done``) allocate nothing; they forward liveness to their
  operands.
- Values whose only consumer is a ``tuple``-shaped root are written
  straight into the output allocation (counted by ``output_bytes``),
  not the temp set.
- Elementwise ops and loop fusions may write in place over a dying
  operand (XLA's ``CanShareOperandBufferWithUser``): when such an op's
  operand takes its last use at the defining instruction and is at least
  result-sized, the result's bytes are credited back at that schedule
  point.

Like the rest of the ``analysis/hlo.py`` stack this is pure text
parsing — no jax import — so ledgers build (and unit-test) from HLO
fixtures; ``ledger_from_jitted`` / ``arg_classes_of`` are the only
entry points that touch jax, and import it lazily.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from pytorch_distributed_tpu.analysis import hlo as hlo_mod
from pytorch_distributed_tpu.obs.comms import (
    compiled_peak_bytes,
    phase_of_op_name,
)

# Buffer classes in the breakdown.  Argument buffers carry
# params/opt_state/data (from arg_classes_of, "data" when unknown);
# temporaries are activations or collective scratch; the root is output.
CLASSES = ("params", "opt_state", "data",
           "activations", "collective", "output")

# View/bookkeeping opcodes: no allocation, liveness forwards to operands.
# ``while`` belongs here because XLA requires loop state to alias in
# place (body parameters = body results = while result): the carried
# buffers are the init values, already counted at their own defs.
_ALIAS_OPCODES = frozenset({"tuple", "get-tuple-element", "bitcast", "while"})

# Opcodes whose result may share a dying operand's buffer (XLA's
# elementwise/loop-fusion sharing, plus the in-place-update family).
_SHAREABLE_OPCODES = frozenset({
    "fusion", "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "negate", "abs", "exponential", "log", "tanh", "sqrt", "rsqrt", "power",
    "select", "convert", "and", "or", "xor", "not", "clamp", "compare",
    "dynamic-update-slice", "scatter", "copy",
    # XLA:CPU wraps parallelized fusions in call(...,
    # to_apply=%parallel_*_fusion) — same sharing rules as the fusion
    "call",
})


def _is_alias(ins: hlo_mod.Instruction) -> bool:
    return ins.opcode in _ALIAS_OPCODES or ins.opcode.endswith("-done")


@dataclasses.dataclass
class MemBuffer:
    """One tracked buffer: an entry argument, a temporary, or an output."""

    name: str
    bytes: int
    dtype: str
    dims: List[int]
    klass: str            # one of CLASSES
    phase: str            # producer scope phase (phase_of_op_name)
    op_name: str          # full jax scope path from metadata
    source: str           # "file:line"
    defined_at: int       # schedule index (-1: live at entry — args/outputs)
    last_use: int         # schedule index of last consumer

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class MemLedger:
    """Everything the memory ledger knows about one compiled step."""

    step: str
    mesh_shape: Dict[str, int] = dataclasses.field(default_factory=dict)
    argument_bytes: int = 0
    output_bytes: int = 0
    donated_bytes: int = 0           # argument bytes aliased to outputs
    peak_bytes: int = 0              # watermark peak (arg + out + temps)
    peak_index: int = 0              # schedule index of the high-water mark
    n_instructions: int = 0
    # Compiled ground truth (comms.compiled_peak_bytes); 0.0 = unknown
    # (text fixtures, old ledger files).
    measured_peak_bytes: float = 0.0
    # Watermark change points [[schedule_index, bytes], ...] — the curve
    # is a step function; only points where the value moves are kept.
    watermark: List[List[int]] = dataclasses.field(default_factory=list)
    # Every tracked buffer, program order (args first at defined_at=-1).
    buffers: List[MemBuffer] = dataclasses.field(default_factory=list)

    @property
    def temp_peak_bytes(self) -> int:
        return self.peak_bytes - self.argument_bytes - self.output_bytes

    def residual_pct(self) -> float:
        """Watermark-vs-measured disagreement, % of measured (the ±10%
        fence); 0.0 when no measured peak is attached."""
        if not self.measured_peak_bytes:
            return 0.0
        return abs(self.peak_bytes - self.measured_peak_bytes) \
            / self.measured_peak_bytes * 100.0

    def live_at(self, index: int) -> List[MemBuffer]:
        """Buffers resident at one schedule point (args/outputs always)."""
        out = []
        for b in self.buffers:
            if b.defined_at < 0 or b.defined_at <= index <= b.last_use:
                out.append(b)
        return out

    def top_buffers(self, k: int = 10) -> List[MemBuffer]:
        """The top-k live buffers at the high-water mark, largest first."""
        live = sorted(self.live_at(self.peak_index),
                      key=lambda b: (-b.bytes, b.name))
        return live[:k]

    def class_peaks(self) -> Dict[str, int]:
        """Per-class peak resident bytes over the schedule.

        Argument and output classes are whole-program constants; temp
        classes (activations, collective) report the max of their own
        live curves — the number the ZeRO-reclaim and fused-CE fences
        compare across recipes."""
        return self._grouped_peaks(lambda b: b.klass)

    def phase_peaks(self) -> Dict[str, int]:
        """Per-producer-phase peak resident bytes (grad_sync, optimizer,
        backward, ...) over the temp set.  Whole-program buffers (args,
        outputs) carry no producer phase and land in ``"resident"``."""
        return self._grouped_peaks(
            lambda b: b.phase if b.defined_at >= 0 else "resident")

    def _grouped_peaks(self, key) -> Dict[str, int]:
        constant: Dict[str, int] = {}
        deltas_by_group: Dict[str, Dict[int, int]] = {}
        for b in self.buffers:
            g = key(b)
            if b.defined_at < 0:
                constant[g] = constant.get(g, 0) + b.bytes
            else:
                d = deltas_by_group.setdefault(g, {})
                d[b.defined_at] = d.get(b.defined_at, 0) + b.bytes
                d[b.last_use + 1] = d.get(b.last_use + 1, 0) - b.bytes
        out = dict(constant)
        for g, deltas in deltas_by_group.items():
            cur = peak = 0
            for i in sorted(deltas):
                cur += deltas[i]
                peak = max(peak, cur)
            out[g] = out.get(g, 0) + peak
        return out

    def metrics_fields(self) -> Dict[str, float]:
        """Per-step fields the trainers stamp into the metrics JSONL."""
        fields = {
            "mem_peak_bytes": float(self.peak_bytes),
            "mem_temp_peak_bytes": float(self.temp_peak_bytes),
        }
        if self.measured_peak_bytes:
            fields["mem_residual_pct"] = self.residual_pct()
        return fields

    def to_dict(self, top_k: int = 32) -> Dict[str, Any]:
        return {
            "step": self.step,
            "mesh_shape": dict(self.mesh_shape),
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "donated_bytes": self.donated_bytes,
            "peak_bytes": self.peak_bytes,
            "peak_index": self.peak_index,
            "n_instructions": self.n_instructions,
            "measured_peak_bytes": self.measured_peak_bytes,
            "residual_pct": self.residual_pct(),
            "class_peaks": self.class_peaks(),
            "phase_peaks": self.phase_peaks(),
            "watermark": [list(p) for p in self.watermark],
            "top": [b.to_dict() for b in self.top_buffers(top_k)],
        }


_GTE_INDEX_RE = re.compile(r"\bindex=(\d+)")
_CALLED_COMP_RE = re.compile(r"\b(?:body|to_apply)=%?([\w.\-]+)")


def _operand_map(
    instrs: Sequence[hlo_mod.Instruction],
) -> List[List[int]]:
    """Per-instruction operand indices (same-computation defs only)."""
    index = {ins.name: i for i, ins in enumerate(instrs)}
    return [[index[n] for n in hlo_mod.instruction_operands(ins)
             if n in index]
            for ins in instrs]


def _last_uses(
    instrs: Sequence[hlo_mod.Instruction],
    operands: Sequence[Sequence[int]],
) -> Tuple[List[List[int]], List[int], int]:
    """Element-aware live ranges over one computation's schedule.

    Returns ``(last_use, use_counts, root_idx)`` where ``last_use[i][k]``
    is the last schedule index at which element ``k`` of instruction
    ``i``'s result is read.  Tuple elements die independently: a
    ``get-tuple-element(index=k)`` consumer extends only element ``k``,
    a ``tuple`` maps its elements back onto its operands positionally,
    and a ``while`` (whose loop state aliases in place) forwards each
    result element's lifetime to the matching init element.  Any
    consumer the mapping can't see through extends every element."""
    n = len(instrs)
    m = [max(1, len(ins.shapes)) for ins in instrs]
    last = [[i] * m[i] for i in range(n)]
    use_counts = [0] * n
    root_idx = next((i for i in range(n - 1, -1, -1) if instrs[i].is_root),
                    n - 1)
    if n:
        last[root_idx] = [n - 1] * m[root_idx]
    for j in range(n - 1, -1, -1):
        ins = instrs[j]
        ops = operands[j]
        for t in set(ops):
            use_counts[t] += 1
        alias = _is_alias(ins)
        reach_all = max(last[j]) if alias else j
        if ins.opcode == "get-tuple-element" and ops:
            t = ops[0]
            k_m = _GTE_INDEX_RE.search(ins.line)
            k = int(k_m.group(1)) if k_m else None
            if k is not None and m[t] > 1 and k < m[t]:
                last[t][k] = max(last[t][k], reach_all)
            else:
                for e in range(m[t]):
                    last[t][e] = max(last[t][e], reach_all)
        elif ins.opcode == "tuple" and len(ops) == m[j]:
            for p, t in enumerate(ops):
                for e in range(m[t]):
                    last[t][e] = max(last[t][e], last[j][p])
        elif alias and len(ops) == 1 and m[ops[0]] == m[j]:
            # while / bitcast / *-done: elements map through 1:1
            t = ops[0]
            for e in range(m[t]):
                last[t][e] = max(last[t][e], last[j][e])
        else:
            for t in ops:
                for e in range(m[t]):
                    last[t][e] = max(last[t][e], reach_all)
    return last, use_counts, root_idx


@dataclasses.dataclass
class _TempSpec:
    """One temp allocation interval inside a computation walk."""

    index: int          # defining schedule index
    elem: int           # tuple element (0 for scalar results)
    bytes: int
    last_use: int
    body: bool = False  # True: a while/call body's working-set peak


def _collect_temps(
    instrs: Sequence[hlo_mod.Instruction],
    operands: Sequence[Sequence[int]],
    last: Sequence[Sequence[int]],
    use_counts: Sequence[int],
    root_idx: int,
    body_peak,  # (computation_name) -> int
) -> Tuple[List[_TempSpec], List[int]]:
    """Temp allocations + per-index in-place sharing credits.

    Skips parameters (argument/carried-state allocations), aliases
    (views), the root and values whose only consumer is a tuple root
    (written straight into the output/carried allocation).  ``while``
    and ``call`` instructions contribute their callee's working-set
    peak as a one-index allocation — the body runs entirely within
    that schedule slot."""
    n = len(instrs)
    root_is_tuple = bool(n) and instrs[root_idx].opcode == "tuple"
    root_operands = set(operands[root_idx]) if n else set()
    temps: List[_TempSpec] = []
    temp_total: Dict[int, int] = {}   # index -> own allocation bytes

    for i, ins in enumerate(instrs):
        if ins.opcode == "parameter":
            continue
        if ins.opcode in ("while", "call"):
            cm = _CALLED_COMP_RE.search(ins.line)
            extra = body_peak(cm.group(1)) if cm else 0
            if extra:
                temps.append(_TempSpec(index=i, elem=0, bytes=extra,
                                       last_use=i, body=True))
        if _is_alias(ins):
            continue
        if i == root_idx:
            continue  # the root's bytes are the output allocation
        if root_is_tuple and i in root_operands and use_counts[i] == 1:
            continue  # written straight into the output allocation
        shapes = ins.shapes or [("", ())]
        for k, s in enumerate(shapes):
            b = hlo_mod.shape_bytes(s)
            lu = last[i][k] if k < len(last[i]) else max(last[i])
            temps.append(_TempSpec(index=i, elem=k, bytes=b, last_use=lu))
            temp_total[i] = temp_total.get(i, 0) + b

    # in-place sharing: a shareable op whose operand takes its last use
    # at the defining instruction writes over that operand's buffer
    credit = [0] * n
    alias_src = {i: operands[i][0] for i, ins in enumerate(instrs)
                 if _is_alias(ins) and operands[i]}

    def _resolved(i: int) -> int:
        seen = set()
        while i in alias_src and i not in seen:
            seen.add(i)
            i = alias_src[i]
        return i

    for i, ins in enumerate(instrs):
        own = temp_total.get(i, 0)
        if not own or ins.opcode not in _SHAREABLE_OPCODES:
            continue
        for oi in operands[i]:
            src = _resolved(oi)
            src_bytes = temp_total.get(src, 0)
            if src_bytes >= own and max(last[src]) == i:
                credit[i] = own
                break
    return temps, credit


def _temps_peak(temps: Sequence[_TempSpec], credit: Sequence[int],
                n: int) -> Tuple[int, int, List[List[int]]]:
    """Sweep a computation's temp intervals into ``(peak, peak_index,
    change_points)``; body allocations live only at their own index."""
    start_add = [0] * (n + 1)
    end_sub = [0] * (n + 1)
    for t in temps:
        start_add[t.index] += t.bytes
        end_sub[t.last_use] += t.bytes
    points: List[List[int]] = []
    cur = 0
    peak, peak_index = 0, 0
    prev = None
    for i in range(n):
        cur += start_add[i]
        level = cur - (credit[i] if i < len(credit) else 0)
        if level > peak:
            peak, peak_index = level, i
        if level != prev:
            points.append([i, level])
            prev = level
        cur -= end_sub[i]
    return peak, peak_index, points


def _computation_peak(name: str, by_comp, memo: Dict[str, int]) -> int:
    """Working-set peak of one non-entry computation (a while/call body),
    recursing into nested bodies.  Parameters alias the caller's carried
    buffers and root-only values write back into them, so only genuine
    body temporaries count — the bytes XLA's heap must find *on top of*
    the carried state while the loop runs."""
    if name in memo:
        return memo[name]
    memo[name] = 0  # cycle guard
    instrs = by_comp.get(name, [])
    if not instrs:
        return 0
    operands = _operand_map(instrs)
    last, use_counts, root_idx = _last_uses(instrs, operands)
    temps, credit = _collect_temps(
        instrs, operands, last, use_counts, root_idx,
        lambda c: _computation_peak(c, by_comp, memo))
    peak, _, _ = _temps_peak(temps, credit, len(instrs))
    memo[name] = peak
    return peak


def ledger_from_hlo_text(
    hlo_text: str,
    step: str = "step",
    mesh_shape: Optional[Dict[str, int]] = None,
    arg_classes: Optional[Sequence[str]] = None,
    measured_peak_bytes: float = 0.0,
) -> MemLedger:
    """Build the memory ledger for one compiled module's text.

    ``arg_classes``: per-entry-parameter class labels (params/opt_state/
    data) in parameter-number order, from ``arg_classes_of`` on the
    caller's args pytree; unknown parameters default to "data"."""
    entry = hlo_mod.entry_computation_name(hlo_text)
    by_comp: Dict[str, List[hlo_mod.Instruction]] = {}
    for ins in hlo_mod.parse_instructions(hlo_text):
        by_comp.setdefault(ins.computation, []).append(ins)
    instrs = by_comp.get(entry, [])
    n = len(instrs)
    operands = _operand_map(instrs)
    last, use_counts, root_idx = _last_uses(instrs, operands)
    memo: Dict[str, int] = {}
    temps, credit = _collect_temps(
        instrs, operands, last, use_counts, root_idx,
        lambda c: _computation_peak(c, by_comp, memo))

    # ---- constant terms from the module header
    param_shapes = hlo_mod.entry_parameter_shapes(hlo_text)
    argument_bytes = sum(hlo_mod.shape_bytes(s) for s in param_shapes)
    out_shapes = hlo_mod.entry_output_shapes(hlo_text)
    output_bytes = sum(hlo_mod.shape_bytes(s) for s in out_shapes)
    donated_bytes = sum(
        hlo_mod.shape_bytes(param_shapes[p]) for p in
        hlo_mod.aliased_param_numbers(hlo_text) if p < len(param_shapes))
    base = argument_bytes + output_bytes

    # ---- attribution buffers: args, temps, outputs
    arg_classes = list(arg_classes or [])
    buffers: List[MemBuffer] = []
    for i, ins in enumerate(instrs):
        if ins.opcode != "parameter":
            continue
        op_name, source = hlo_mod.parse_op_metadata(ins.line)
        num = hlo_mod.parameter_number(ins)
        klass = arg_classes[num] if (
            num is not None and num < len(arg_classes)) else "data"
        dtype, dims = ins.shapes[0] if ins.shapes else ("", ())
        buffers.append(MemBuffer(
            name=ins.name, bytes=ins.result_bytes(), dtype=dtype,
            dims=list(dims), klass=klass, phase="", op_name=op_name,
            source=source, defined_at=-1, last_use=n - 1))
    for t in temps:
        ins = instrs[t.index]
        op_name, source = hlo_mod.parse_op_metadata(ins.line)
        phase = phase_of_op_name(op_name)
        if t.body:
            name, klass, dtype, dims = f"{ins.name}[body]", "activations", \
                "", []
        else:
            name = ins.name if len(ins.shapes) <= 1 \
                else f"{ins.name}#{t.elem}"
            klass = "collective" if (
                ins.opcode in hlo_mod._COLLECTIVE_SET
                or ins.opcode.endswith("-start")) else "activations"
            dtype, dims = ins.shapes[t.elem] if t.elem < len(ins.shapes) \
                else ("", ())
            dims = list(dims)
        buffers.append(MemBuffer(
            name=name, bytes=t.bytes, dtype=dtype, dims=dims, klass=klass,
            phase=phase, op_name=op_name, source=source,
            defined_at=t.index, last_use=t.last_use))
    if output_bytes:
        out_dtype, out_dims = out_shapes[0] if out_shapes else ("", ())
        buffers.append(MemBuffer(
            name="(outputs)", bytes=output_bytes, dtype=out_dtype,
            dims=list(out_dims), klass="output", phase="", op_name="",
            source="", defined_at=-1, last_use=n - 1))

    # ---- watermark
    temp_peak, peak_index, points = _temps_peak(temps, credit, n)
    watermark = [[i, base + v] for i, v in points]
    return MemLedger(
        step=step, mesh_shape=dict(mesh_shape or {}),
        argument_bytes=argument_bytes, output_bytes=output_bytes,
        donated_bytes=donated_bytes, peak_bytes=base + temp_peak,
        peak_index=peak_index, n_instructions=n,
        measured_peak_bytes=float(measured_peak_bytes),
        watermark=watermark, buffers=buffers)


# --------------------------------------------------------------- jax side

def arg_classes_of(args: Any) -> List[str]:
    """Per-flattened-leaf buffer classes of a step's argument pytree, in
    flatten order — which is jit's entry-parameter order.  Classification
    is by pytree key path: TrainState fields named ``params`` are model
    weights; ``momentum``/``mu``/``nu``/``opt``/``ef_*``/``residual`` are
    optimizer state (incl. error-feedback residuals, which live exactly
    as long as momentum does); everything else (batches, lr, rng) is
    input data."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(args)
    out = []
    for path, _leaf in flat:
        p = jax.tree_util.keystr(path).lower()
        if any(t in p for t in ("momentum", ".mu", ".nu", "opt_state",
                                "ef_", "residual")):
            out.append("opt_state")
        elif "param" in p or "batch_stats" in p:
            out.append("params")
        else:
            out.append("data")
    return out


def ledger_from_compiled(
    compiled,
    *,
    step: str = "step",
    mesh_shape: Optional[Dict[str, int]] = None,
    arg_classes: Optional[Sequence[str]] = None,
    hlo_text: Optional[str] = None,
) -> MemLedger:
    """Ledger for an already-compiled step: parses ``as_text()`` (or the
    caller's copy of it) and attaches the ``memory_analysis()`` ground
    truth — the path the trainers use so one AOT compile feeds both the
    comm and the memory ledger."""
    text = hlo_text if hlo_text is not None else compiled.as_text()
    return ledger_from_hlo_text(
        text, step=step, mesh_shape=mesh_shape, arg_classes=arg_classes,
        measured_peak_bytes=compiled_peak_bytes(compiled))


def ledger_from_jitted(jitted, args: Sequence[Any], *, step: str = "step",
                       mesh=None) -> MemLedger:
    """Lower + compile a jitted step and build its memory ledger.  Same
    caveat as ``comms.ledger_from_jitted``: the AOT path does not share
    the jit call cache — one extra compile, so trainers gate it behind
    ``--mem-ledger`` and reuse the comm ledger's lowering."""
    compiled = jitted.lower(*args).compile()
    mesh_shape = dict(mesh.shape) if mesh is not None else {}
    return ledger_from_compiled(
        compiled, step=step, mesh_shape=mesh_shape,
        arg_classes=arg_classes_of(tuple(args)))


# ------------------------------------------------------------ serialization

def write_ledgers(path: str, ledgers: Sequence[MemLedger],
                  top_k: int = 32) -> None:
    """``mem_ledger.json``: ``{step_name: ledger_dict}``.  The buffer list
    is truncated to the top-k at peak; the watermark curve keeps every
    change point."""
    data = {lg.step: lg.to_dict(top_k=top_k) for lg in ledgers}
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def load_ledgers(path: str) -> Dict[str, MemLedger]:
    """Round-trip of ``write_ledgers``.  The reconstructed ledger carries
    the serialized top-k buffers (enough for attribution rendering and
    every scalar fence); the full temp set is not persisted."""
    with open(path) as f:
        data = json.load(f)
    out: Dict[str, MemLedger] = {}
    for step, d in data.items():
        out[step] = MemLedger(
            step=step,
            mesh_shape=d.get("mesh_shape", {}),
            argument_bytes=int(d.get("argument_bytes", 0)),
            output_bytes=int(d.get("output_bytes", 0)),
            donated_bytes=int(d.get("donated_bytes", 0)),
            peak_bytes=int(d.get("peak_bytes", 0)),
            peak_index=int(d.get("peak_index", 0)),
            n_instructions=int(d.get("n_instructions", 0)),
            measured_peak_bytes=float(d.get("measured_peak_bytes", 0.0)),
            watermark=[list(p) for p in d.get("watermark", [])],
            buffers=[MemBuffer(**b) for b in d.get("top", [])])
    return out


# ------------------------------------------------------- Perfetto export

def watermark_counter_events(
    ledger: MemLedger,
    t0_us: float,
    t1_us: float,
    pid: int = 0,
    name: str = "hbm_watermark",
) -> List[Dict[str, Any]]:
    """The watermark curve as Chrome-trace counter events ("ph": "C") —
    the Perfetto counter track obs_timeline merges into the cross-rank
    trace.  The schedule has no wall-clock of its own, so change points
    spread linearly over the step's measured ``[t0_us, t1_us]`` span."""
    if not ledger.watermark or t1_us <= t0_us:
        return []
    span = t1_us - t0_us
    denom = max(1, ledger.n_instructions - 1)
    events = []
    for idx, level in ledger.watermark:
        events.append({
            "ph": "C", "pid": pid, "name": name,
            "ts": t0_us + span * (idx / denom),
            "args": {"bytes": int(level)},
        })
    return events
