"""Efficiency accounting: analytic per-step FLOPs/bytes models and MFU/HFU.

The obs layer (metrics.py) answers "how fast is each step"; this module
answers "how much of the hardware that speed represents".  For every
registered model family it builds an analytic ``StepCost`` — matmul/conv
core FLOPs for forward + backward + optimizer update, plus a rough HBM
bytes estimate — and divides achieved FLOP/s by the chip's peak:

- **MFU** uses *model* FLOPs: the algorithmically necessary work (the
  PaLM-appendix convention).  Recompute taxes do not inflate it.
- **HFU** uses *hardware* FLOPs: model FLOPs plus rematerialization /
  fused-CE chunk-recompute work the chips actually execute.  HFU ≥ MFU;
  the gap IS the recompute tax (e.g. ViT ``remat=True`` trades ~1/3 extra
  matmuls for activation residency — models/vit.py).

Counting conventions (chosen to match XLA's ``cost_analysis()`` so the
analytic model can be cross-checked, tests/test_efficiency.py):

- one multiply-add = 2 FLOPs;
- convolutions exclude padded taps (XLA's HloCostAnalysis counts only
  valid kernel applications — border pixels cost less);
- backward = 2x forward for the matmul/conv core (dgrad + wgrad);
- the SGD update is ~6 FLOPs/param and is **replicated** on every device
  under data parallelism — ``StepCost.per_device_flops`` accounts for
  that when comparing against a per-device ``cost_analysis()`` figure;
- elementwise/transcendental work (BN, layernorm, softmax, rope) is NOT
  counted: it is a few percent of the core on these families, and XLA
  books transcendentals separately anyway.  Parity is asserted at +-10%.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional, Sequence

# --------------------------------------------------------------------- peaks
# Dense-matmul peak per chip, FLOP/s, at the framework's bf16 compute
# policy (f32 for the v2/v3 generation is half of these — close enough for
# a utilization denominator).  Keys match jax Device.device_kind prefixes.
PEAK_FLOPS_PER_CHIP: Dict[str, float] = {
    "tpu v2": 45e12,
    "tpu v3": 123e12,
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,   # v5e device_kind spells it out
    "tpu v5e": 197e12,
    "tpu v5p": 459e12,
    "tpu v6e": 918e12,
    "tpu v6 lite": 918e12,
}

# CPU-test fallback: a nominal per-"device" figure so MFU math stays finite
# and deterministic on the simulated CPU mesh (the number is a placeholder,
# not a measurement — CI asserts plumbing, never CPU utilization).
CPU_FALLBACK_PEAK = 50e9

# Per-chip HBM capacity, bytes.  The planner's feasibility pruning
# (plan/cost.py) rejects layouts whose predicted MemCost peak exceeds
# this; same device_kind-prefix keying as the FLOPs table.
HBM_BYTES_PER_CHIP: Dict[str, float] = {
    "tpu v2": 8e9,
    "tpu v3": 16e9,
    "tpu v4": 32e9,
    "tpu v5 lite": 16e9,
    "tpu v5e": 16e9,
    "tpu v5p": 95e9,
    "tpu v6e": 32e9,
    "tpu v6 lite": 32e9,
}
CPU_FALLBACK_HBM = 4e9

# Nominal aggregate ICI bandwidth per chip, bytes/s — a *scoring*
# denominator for predicted comm time (plan/cost.py), not a measurement;
# figures are the published per-chip interconnect aggregates.
LINK_BYTES_PER_CHIP: Dict[str, float] = {
    "tpu v2": 62.5e9,
    "tpu v3": 87.5e9,
    "tpu v4": 300e9,
    "tpu v5 lite": 200e9,
    "tpu v5e": 200e9,
    "tpu v5p": 600e9,
    "tpu v6e": 448e9,
    "tpu v6 lite": 448e9,
}
CPU_FALLBACK_LINK = 10e9

# Per-chip HBM *bandwidth*, bytes/s — the memory-roofline denominator
# (obs/stepattr.py): a phase whose achieved bytes/s approaches this while
# its FLOP/s sit far under the matmul peak is HBM-bound, not compute-bound.
# Published per-chip figures; same device_kind-prefix keying as above.
HBM_BW_PER_CHIP: Dict[str, float] = {
    "tpu v2": 700e9,
    "tpu v3": 900e9,
    "tpu v4": 1228e9,
    "tpu v5 lite": 819e9,
    "tpu v5e": 819e9,
    "tpu v5p": 2765e9,
    "tpu v6e": 1640e9,
    "tpu v6 lite": 1640e9,
}
CPU_FALLBACK_HBM_BW = 20e9


def device_peak_flops(device=None) -> float:
    """Peak FLOP/s for one chip.  ``PTD_TPU_PEAK_FLOPS`` overrides (chips
    this table predates, or a measured-roofline denominator); unknown
    accelerators fall back to the CPU placeholder rather than failing the
    run — MFU is observability, not a gate."""
    env = os.environ.get("PTD_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = (getattr(device, "device_kind", "") or "").lower()
    for prefix, peak in PEAK_FLOPS_PER_CHIP.items():
        if kind.startswith(prefix):
            return peak
    return CPU_FALLBACK_PEAK


def _chip_table_lookup(table: Dict[str, float], kind: Optional[str],
                       fallback: float, env: str) -> float:
    """Shared device_kind-prefix lookup for the capability tables.
    ``kind=None`` stays jax-free (the planner's analytic path): the env
    override or the fallback, never a device query."""
    env_val = os.environ.get(env)
    if env_val:
        return float(env_val)
    kind = (kind or "").lower()
    for prefix, value in table.items():
        if kind.startswith(prefix):
            return value
    return fallback


def chip_hbm_bytes(kind: Optional[str] = None) -> float:
    """Per-chip HBM bytes for a device_kind string (``PTD_TPU_HBM_BYTES``
    overrides); unknown/absent kinds get the CPU placeholder."""
    return _chip_table_lookup(HBM_BYTES_PER_CHIP, kind, CPU_FALLBACK_HBM,
                              "PTD_TPU_HBM_BYTES")


def chip_link_bytes(kind: Optional[str] = None) -> float:
    """Nominal aggregate ICI bytes/s per chip (``PTD_TPU_LINK_BYTES``
    overrides)."""
    return _chip_table_lookup(LINK_BYTES_PER_CHIP, kind, CPU_FALLBACK_LINK,
                              "PTD_TPU_LINK_BYTES")


def chip_hbm_bw(kind: Optional[str] = None) -> float:
    """Per-chip HBM bandwidth, bytes/s (``PTD_TPU_HBM_BW`` overrides);
    unknown/absent kinds get the CPU placeholder — roofline labels on the
    simulated mesh assert plumbing, never real intensity."""
    return _chip_table_lookup(HBM_BW_PER_CHIP, kind, CPU_FALLBACK_HBM_BW,
                              "PTD_TPU_HBM_BW")


def chip_peak_flops(kind: Optional[str] = None) -> float:
    """Peak FLOP/s per chip from a device_kind *string* — the jax-free twin
    of ``device_peak_flops`` the planner uses (``PTD_TPU_PEAK_FLOPS``
    overrides)."""
    return _chip_table_lookup(PEAK_FLOPS_PER_CHIP, kind, CPU_FALLBACK_PEAK,
                              "PTD_TPU_PEAK_FLOPS")


# ---------------------------------------------------------------- step costs
@dataclasses.dataclass(frozen=True)
class StepCost:
    """Per-optimizer-step cost of one registered model family config.

    ``model_flops``    algorithmic FLOPs (MFU numerator);
    ``hardware_flops`` incl. remat / fused-CE recompute (HFU numerator);
    ``bytes``          rough HBM traffic (params+grads+optimizer r/w and
                       activations twice) — an arithmetic-intensity hint,
                       not cross-checked;
    ``update_flops``   optimizer portion (replicated per device under DP);
    ``params``         parameter count the update estimate used.
    """

    model_flops: float
    hardware_flops: float
    bytes: float
    update_flops: float
    params: int
    breakdown: Dict[str, float]

    def per_device_flops(self, n_devices: int) -> float:
        """XLA-comparable per-device estimate: the forward/backward core is
        sharded over the mesh but the optimizer update runs replicated on
        every device (the declared-DP layout shardlint calls
        replicated-state)."""
        n = max(1, int(n_devices))
        return (self.hardware_flops - self.update_flops) / n + self.update_flops


_SGD_FLOPS_PER_PARAM = 6.0  # wd mul-add, momentum mul-add, lr mul + sub


def _valid_taps(size: int, k: int, stride: int, pad: int) -> int:
    """Sum over output positions of in-bounds kernel taps along one spatial
    dim — the XLA convolution convention (padded taps cost nothing)."""
    out = (size + 2 * pad - k) // stride + 1
    total = 0
    for o in range(out):
        start = o * stride - pad
        total += max(0, min(start + k, size) - max(start, 0))
    return total


class _Walk:
    """Accumulator the per-family shape walks share."""

    def __init__(self):
        self.fwd = 0.0        # forward core FLOPs per sample
        self.params = 0
        self.act_elts = 0.0   # activation elements produced per sample

    def conv(self, h, w, cin, cout, kh, kw, stride=1, pad=None, groups=1,
             bn=True):
        if pad is None:
            pad = kh // 2
        th = _valid_taps(h, kh, stride, pad)
        tw = _valid_taps(w, kw, stride, pad)
        ho = (h + 2 * pad - kh) // stride + 1
        wo = (w + 2 * pad - kw) // stride + 1
        self.fwd += 2.0 * cout * (cin / groups) * th * tw
        self.params += kh * kw * (cin // groups) * cout + (2 * cout if bn else 0)
        self.act_elts += ho * wo * cout
        return ho, wo

    def dense(self, n_rows, cin, cout, params=True):
        self.fwd += 2.0 * n_rows * cin * cout
        if params:
            self.params += cin * cout + cout
        self.act_elts += n_rows * cout


# ResNet-family table mirroring models/resnet.py's functools.partial zoo:
# (stage_sizes, block, groups, base_width).
_RESNET_CFGS: Dict[str, tuple] = {
    "resnet18": ([2, 2, 2, 2], "basic", 1, 64),
    "resnet34": ([3, 4, 6, 3], "basic", 1, 64),
    "resnet50": ([3, 4, 6, 3], "bottleneck", 1, 64),
    "resnet101": ([3, 4, 23, 3], "bottleneck", 1, 64),
    "resnet152": ([3, 8, 36, 3], "bottleneck", 1, 64),
    "wide_resnet50_2": ([3, 4, 6, 3], "bottleneck", 1, 128),
    "wide_resnet101_2": ([3, 4, 23, 3], "bottleneck", 1, 128),
    "resnext50_32x4d": ([3, 4, 6, 3], "bottleneck", 32, 4),
    "resnext101_32x8d": ([3, 4, 23, 3], "bottleneck", 32, 8),
}

# ViT table mirroring models/vit.py: (patch, d_model, layers, heads, mlp).
_VIT_CFGS: Dict[str, tuple] = {
    "vit_b_16": (16, 768, 12, 12, 3072),
    "vit_b_32": (32, 768, 12, 12, 3072),
    "vit_l_16": (16, 1024, 24, 16, 4096),
}


def _resnet_walk(arch: str, image_size: int, num_classes: int) -> _Walk:
    stage_sizes, block, groups, base_width = _RESNET_CFGS[arch]
    exp = 1 if block == "basic" else 4
    wk = _Walk()
    h, w = wk.conv(image_size, image_size, 3, 64, 7, 7, stride=2, pad=3)
    h, w = (h + 2 - 3) // 2 + 1, (w + 2 - 3) // 2 + 1  # maxpool 3x3 s2 p1
    c = 64
    for i, nblk in enumerate(stage_sizes):
        filt = 64 * 2 ** i
        for j in range(nblk):
            s = 2 if (i > 0 and j == 0) else 1
            if block == "basic":
                h2, w2 = wk.conv(h, w, c, filt, 3, 3, stride=s)
                wk.conv(h2, w2, filt, filt, 3, 3)
            else:
                width = int(filt * base_width / 64) * groups
                wk.conv(h, w, c, width, 1, 1, pad=0)
                h2, w2 = wk.conv(h, w, width, width, 3, 3, stride=s,
                                 groups=groups)
                wk.conv(h2, w2, width, filt * exp, 1, 1, pad=0)
            if c != filt * exp or s > 1:
                wk.conv(h, w, c, filt * exp, 1, 1, stride=s, pad=0)
            h, w, c = h2, w2, filt * exp
    wk.dense(1, c, num_classes)
    return wk


def _transformer_core(wk: _Walk, tokens: float, d: int, mlp: int,
                      seq: float) -> None:
    """One transformer block's matmul core for ``tokens`` rows attending
    over a ``seq``-long context (dense attention: causal masking does not
    reduce the einsums XLA emits)."""
    wk.dense(tokens, d, 3 * d, params=False)      # qkv
    wk.params += 3 * d * d                        # transformer.py: no bias
    wk.fwd += 4.0 * tokens * seq * d              # scores + weighted sum
    wk.act_elts += tokens * seq                   # score matrix (per head sum)
    wk.dense(tokens, d, d, params=False)          # proj
    wk.params += d * d
    wk.dense(tokens, d, mlp)                      # fc1
    wk.dense(tokens, mlp, d)                      # fc2
    wk.params += 4 * d                            # two layernorms


def _vit_walk(arch: str, image_size: int, num_classes: int) -> _Walk:
    patch, d, layers, _heads, mlp = _VIT_CFGS[arch]
    grid = image_size // patch
    tokens = grid * grid + 1  # + class token
    wk = _Walk()
    wk.dense(grid * grid, patch * patch * 3, d)   # patch embed
    wk.params += d + tokens * d                   # cls token + pos embeddings
    for _ in range(layers):
        _transformer_core(wk, tokens, d, mlp, tokens)
    wk.dense(1, d, num_classes)                   # head (class token only)
    return wk


def _finish(wk: _Walk, batch: int, recompute_fwd: float = 0.0,
            breakdown: Optional[Dict[str, float]] = None) -> StepCost:
    fwd = wk.fwd * batch
    update = _SGD_FLOPS_PER_PARAM * wk.params
    model = 3.0 * fwd + update
    hardware = model + recompute_fwd * batch
    # Rough bytes: params+grads+momentum r/w (f32) + activations twice
    # (produce in fwd, re-read in bwd) at 4 bytes — an intensity hint only.
    nbytes = 6.0 * 4 * wk.params + 2.0 * 4 * wk.act_elts * batch
    bd = {"forward": fwd, "backward": 2.0 * fwd, "update": update,
          "recompute": recompute_fwd * batch}
    if breakdown:
        bd.update(breakdown)
    return StepCost(model_flops=model, hardware_flops=hardware, bytes=nbytes,
                    update_flops=update, params=wk.params, breakdown=bd)


def image_step_cost(arch: str, batch: int, image_size: int,
                    num_classes: int = 1000, remat: bool = False) -> StepCost:
    """Analytic train-step cost for the image families with an analytic
    model (ResNet zoo + ViT).  Other archs raise — silently guessing a
    denominator would make MFU numbers lies."""
    if arch in _RESNET_CFGS:
        wk = _resnet_walk(arch, image_size, num_classes)
        recompute = 0.0
    elif arch in _VIT_CFGS:
        wk = _vit_walk(arch, image_size, num_classes)
        # nn.remat on every encoder block replays the block forwards in
        # backward: ~+1x forward of the block stack (the ~1/3-extra-matmul
        # tax noted at models/vit.py).
        recompute = wk.fwd if remat else 0.0
    else:
        raise ValueError(
            f"no analytic FLOPs model for arch {arch!r}; --mfu supports "
            f"{sorted(_RESNET_CFGS) + sorted(_VIT_CFGS)} (obs/flops.py)")
    return _finish(wk, batch, recompute_fwd=recompute)


def lm_step_cost(vocab_size: int, d_model: int, n_layers: int, batch: int,
                 seq_len: int, mlp_ratio: int = 4, fused_ce: bool = False,
                 remat: bool = False, moe_experts: int = 0,
                 moe_top_k: int = 1) -> StepCost:
    """Analytic train-step cost for the transformer-LM family.

    ``fused_ce``: the chunked tied-head+CE backward (ops/fused_ce.py)
    recomputes each chunk's logits block instead of stashing the [T, V]
    tensor — +2·T·D·V hardware FLOPs, identical model FLOPs; the
    replicated/dp/tp sharding variants all do the same global arithmetic.
    ``remat``: block rematerialization (+1x block-stack forward, hardware
    only).  The pipeline schedules (gpipe/1f1b/interleaved) run the same
    math as the plain stack, so no schedule parameter: FLOPs don't change,
    only the bubble does — and the bubble is a *time* effect MFU already
    sees through the step-time denominator."""
    d, T = d_model, batch * seq_len
    wk = _Walk()
    wk.params += vocab_size * d                   # tied embedding
    block_fwd0 = wk.fwd
    for _ in range(n_layers):
        if moe_experts > 1:
            wk.dense(T // batch, d, 3 * d, params=False)
            wk.params += 3 * d * d
            wk.fwd += 4.0 * (T // batch) * seq_len * d
            wk.dense(T // batch, d, d, params=False)
            wk.params += d * d
            # router + top_k expert MLPs per token; expert params stack E-wide
            wk.dense(T // batch, d, moe_experts, params=False)
            wk.params += d * moe_experts
            wk.fwd += moe_top_k * (2.0 * (T // batch) * d * mlp_ratio * d * 2)
            wk.params += moe_experts * (2 * d * mlp_ratio * d
                                        + mlp_ratio * d + d)
            wk.params += 4 * d
        else:
            _transformer_core(wk, T // batch, d, mlp_ratio * d, seq_len)
    wk.params += 2 * d                            # final layernorm
    block_fwd = wk.fwd - block_fwd0               # per-sample block stack
    # Head: tied embed.attend over the full sequence unfused; the fused
    # path projects only the seq_len-1 loss rows.
    head_rows = (seq_len - 1) if fused_ce else seq_len
    wk.dense(head_rows, d, vocab_size, params=False)
    recompute = 0.0
    if remat:
        recompute += block_fwd
    if fused_ce:
        recompute += 2.0 * (seq_len - 1) * d * vocab_size
    return _finish(wk, batch, recompute_fwd=recompute)


def lm_step_cost_for(model: Any, batch: int, seq_len: int,
                     fused_ce_chunks: int = 0) -> StepCost:
    """Build the LM cost from a live model instance (TransformerLM or
    PipelinedTransformerLM — both carry the config attributes)."""
    n_layers = getattr(model, "n_layers", None)
    if n_layers is None:  # pipeline model: chunks x blocks-per-chunk
        n_layers = int(model.n_chunks) * int(model.n_blocks)
    remat = bool(getattr(model, "remat", False))
    if getattr(model, "has_manual_grads", lambda: False)():
        # 1F1B/interleaved stash stage *inputs* only and replay the stage
        # forward in backward — remat by construction.
        remat = True
    return lm_step_cost(
        vocab_size=int(model.vocab_size),
        d_model=int(model.d_model),
        n_layers=int(n_layers),
        batch=batch,
        seq_len=seq_len,
        fused_ce=bool(fused_ce_chunks),
        remat=remat,
        moe_experts=int(getattr(model, "moe_experts", 0) or 0),
        moe_top_k=int(getattr(model, "moe_top_k", 1) or 1),
    )


def xla_step_flops(jitted, *args) -> float:
    """Per-device FLOPs from the compiler's own cost model
    (``lower().compile().cost_analysis()``) — the cross-check oracle the
    analytic models are tested against (compare with
    ``StepCost.per_device_flops(n)``)."""
    analysis = jitted.lower(*args).compile().cost_analysis()
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0]
    return float(analysis["flops"])


# ----------------------------------------------------------- comm estimates
@dataclasses.dataclass(frozen=True)
class CommCost:
    """Analytic per-step collective payload bytes (per device), by kind.

    The comm-side twin of ``StepCost``: what the parallelism layout
    *should* move per optimizer step, cross-checked against the measured
    ledger (obs/comms.py) the same way FLOPs are fenced against
    ``cost_analysis()`` — tests/test_comms.py pins the residual at ±15%.
    """

    by_kind: Dict[str, float]
    breakdown: Dict[str, float]

    @property
    def total_bytes(self) -> float:
        return sum(self.by_kind.values())


def comm_residual_pct(predicted: float, measured: float) -> float:
    """Relative prediction error in percent (against the measurement)."""
    if not measured:
        return 0.0 if not predicted else float("inf")
    return 100.0 * abs(predicted - measured) / measured


def image_comm_bytes(params: int, dp: int = 4,
                     metric_scalars: int = 5) -> CommCost:
    """Pure-DP image train step: one gradient all-reduce per parameter
    leaf (f32) plus the handful of scalar loss/metric psums
    (train/steps.py's loss_and_metrics reductions).  ``dp == 1`` lowers
    no collectives at all."""
    if dp <= 1:
        return CommCost(by_kind={}, breakdown={})
    grad = 4.0 * params
    scalars = 4.0 * metric_scalars
    return CommCost(by_kind={"all-reduce": grad + scalars},
                    breakdown={"grad_sync": grad, "scalars": scalars})


def image_comm_bytes_compressed(
    leaf_sizes: Sequence[int],
    dp: int = 4,
    mode: str = "int8",
    block: Optional[int] = None,
    metric_scalars: int = 5,
) -> CommCost:
    """Explicit-collectives image step with compressed gradient sync
    (ops/qcomm.py).  Quantized modes lower the two-hop decomposition per
    parameter leaf: an all-to-all of the full padded int8/fp8 payload +
    f32 block scales (the reduce-scatter stage), then an all-gather of
    the re-quantized shards + scales.  Per-device result bytes per leaf,
    with ``(padded, nb) = qcomm.chunk_layout(size, dp, block)``:

    - all-to-all:  ``padded`` (1-byte payload) + ``4*dp*nb`` (scales)
    - all-gather:  ``padded``                  + ``4*dp*nb``

    so the per-kind totals need the *per-leaf* sizes — padding depends on
    each leaf, not the parameter sum.  ``bf16`` keeps the single
    all-reduce at 2 bytes/param; scalar count/metric psums stay f32."""
    from pytorch_distributed_tpu.ops import qcomm

    if dp <= 1:
        return CommCost(by_kind={}, breakdown={})
    scalars = 4.0 * metric_scalars
    if mode == "bf16":
        grad = 2.0 * sum(leaf_sizes)
        return CommCost(by_kind={"all-reduce": grad + scalars},
                        breakdown={"grad_sync": grad, "scalars": scalars})
    if mode not in qcomm.QUANTIZED_MODES:
        return image_comm_bytes(sum(leaf_sizes), dp=dp,
                                metric_scalars=metric_scalars)
    block = qcomm.DEFAULT_BLOCK if block is None else block
    a2a = ag = 0.0
    for size in leaf_sizes:
        padded, nb = qcomm.chunk_layout(int(size), dp, block)
        a2a += padded + 4.0 * dp * nb
        ag += padded + 4.0 * dp * nb
    return CommCost(
        by_kind={"all-to-all": a2a, "all-gather": ag, "all-reduce": scalars},
        breakdown={"grad_sync": a2a + ag, "scalars": scalars})


def image_comm_bytes_zero(
    leaf_sizes: Sequence[int],
    dp: int = 4,
    mode: str = "none",
    block: Optional[int] = None,
    metric_scalars: int = 5,
) -> CommCost:
    """Explicit-collectives image step under ``--zero wus`` weight-update
    sharding (parallel/zero.py): the gradient all-reduce splits into a
    reduce-scatter (grads -> owned 1/N chunk) and an all-gather (parameter
    delta -> full tree), per leaf.  With ``padded = chunk_layout(size, dp,
    block)[0]`` and ``e`` the wire element size (4 f32 / 2 bf16):

    - reduce-scatter: ``e * padded/dp`` per-device result bytes per leaf
    - all-gather:     ``e * padded``   per-device result bytes per leaf

    Wire parity (``zero_wire_parity``): by the EQuARX accounting
    (obs/comms.py) the pair puts ``2*(dp-1)/dp * e * padded`` on the wire —
    exactly the ring all-reduce's cost (padding aside), so WUS reclaims
    (N-1)/N of the optimizer+gradient memory at *equal* wire bytes.

    Quantized modes compose with the qcomm path: stage 1 is the same
    all-to-all as the compressed all-reduce and the delta all-gather
    carries the same quantized payload + scales the compressed stage 2
    would — so the estimate delegates to ``image_comm_bytes_compressed``
    (identical by-kind totals, different *semantics*: the gather moves
    lr-scaled deltas, not re-quantized gradient shards)."""
    from pytorch_distributed_tpu.ops import qcomm

    if dp <= 1:
        return CommCost(by_kind={}, breakdown={})
    if mode in qcomm.QUANTIZED_MODES:
        return image_comm_bytes_compressed(
            leaf_sizes, dp=dp, mode=mode, block=block,
            metric_scalars=metric_scalars)
    elem = 2.0 if mode == "bf16" else 4.0
    block = qcomm.DEFAULT_BLOCK if block is None else block
    rs = ag = 0.0
    for size in leaf_sizes:
        padded, _ = qcomm.chunk_layout(int(size), dp, block)
        rs += elem * padded / dp
        ag += elem * padded
    scalars = 4.0 * metric_scalars
    return CommCost(
        by_kind={"reduce-scatter": rs, "all-gather": ag,
                 "all-reduce": scalars},
        breakdown={"grad_sync": rs + ag, "scalars": scalars})


def comm_cost_wire_bytes(cost: CommCost, n: int) -> float:
    """Total wire bytes for an analytic ``CommCost`` under the EQuARX
    per-device accounting (obs/comms.py ``wire_bytes``) — the common
    currency for comparing layouts whose *result* bytes differ (an
    all-reduce returns the full tree, a reduce-scatter returns 1/N)."""
    from pytorch_distributed_tpu.obs.comms import wire_bytes

    return sum(wire_bytes(kind, b, n) for kind, b in cost.by_kind.items())


def zero_wire_parity(leaf_sizes: Sequence[int], dp: int = 4,
                     mode: str = "none",
                     block: Optional[int] = None) -> Dict[str, float]:
    """The WUS free-lunch check: reduce-scatter + all-gather wire bytes vs
    the one-hop all-reduce for the same gradient tree, same compression
    mode.  Returns ``{"zero": .., "replicated": .., "ratio": ..}``;
    ``ratio <= 1 + pad_overhead`` — tests pin it at ~1 (the ring
    all-reduce IS a reduce-scatter + all-gather, WUS just applies the
    optimizer between the hops)."""
    zero = comm_cost_wire_bytes(
        image_comm_bytes_zero(leaf_sizes, dp=dp, mode=mode, block=block,
                              metric_scalars=0), dp)
    if mode == "bf16":
        repl_cost = image_comm_bytes_compressed(
            leaf_sizes, dp=dp, mode="bf16", metric_scalars=0)
    elif mode == "none":
        repl_cost = image_comm_bytes(sum(int(s) for s in leaf_sizes),
                                     dp=dp, metric_scalars=0)
    else:
        repl_cost = image_comm_bytes_compressed(
            leaf_sizes, dp=dp, mode=mode, block=block, metric_scalars=0)
    repl = comm_cost_wire_bytes(repl_cost, dp)
    return {"zero": zero, "replicated": repl,
            "ratio": zero / repl if repl else 0.0}


def lm_comm_bytes(vocab_size: int, d_model: int, n_layers: int, batch: int,
                  seq_len: int, dp: int = 4, tp: int = 1,
                  fused_ce: bool = False, params: Optional[int] = None,
                  loss_scalars: int = 2) -> CommCost:
    """Transformer-LM train-step collective payload bytes per device.

    DP (``tp == 1``): the gradient all-reduce covers every parameter
    *plus one extra tied-embedding block* — the tied embed's gradient
    arrives as two separately-reduced pieces (the input-embedding
    scatter-add and the output-head ``embed.attend`` matmul transpose),
    so ``V*D`` is counted twice — plus ``loss_scalars`` scalar psums.

    TP (Megatron-style tensor parallelism over a ``dp x tp`` mesh, with
    ``act = (batch/dp) * seq * d_model * 4`` bytes — the per-data-shard
    activation block):

    - 2 forward psums per layer (attn proj out, fc2 out) and 2 backward
      psums per layer (qkv input grad, fc1 input grad): ``4*L*act``;
    - head-sharded attention boundary: 2 permutes of ``act`` forward +
      2 of ``act/2`` backward = ``3*act`` collective-permute bytes;
    - vocab-sharded tied embedding: gather psum ``act`` forward +
      scatter-add psum ``act/2`` backward;
    - gradient sync over the data axis at the *sharded* parameter size:
      ``4*(params + V*D)/tp``.

    The fused-CE chunk loop's per-chunk scalar pmax/psum/pmin carries are
    a few hundred bytes and not modeled.  ``params`` defaults to the
    analytic ``lm_step_cost`` count for the same config."""
    if params is None:
        params = lm_step_cost(vocab_size, d_model, n_layers, batch,
                              seq_len).params
    grad_synced = 4.0 * (params + vocab_size * d_model)
    scalars = 4.0 * loss_scalars
    if tp <= 1:
        if dp <= 1:
            return CommCost(by_kind={}, breakdown={})
        return CommCost(
            by_kind={"all-reduce": grad_synced + scalars},
            breakdown={"grad_sync": grad_synced, "scalars": scalars})
    act = (batch / max(1, dp)) * seq_len * d_model * 4.0
    tp_psums = 4.0 * n_layers * act
    embed = 1.5 * act
    permutes = 3.0 * n_layers * act
    grad = grad_synced / tp
    allreduce = grad + tp_psums + embed + scalars
    return CommCost(
        by_kind={"all-reduce": allreduce, "collective-permute": permutes},
        breakdown={"grad_sync": grad, "tp_psums": tp_psums, "embed": embed,
                   "head_permutes": permutes, "scalars": scalars})


# ----------------------------------------------------------- memory estimates
@dataclasses.dataclass(frozen=True)
class MemCost:
    """Analytic per-device peak-HBM model for one train step.

    The memory-side twin of ``CommCost``: what the state layout and
    activation schedule *should* keep resident at the step's high-water
    mark, cross-checked against the static ledger (obs/memory.py) the
    same way comm estimates are fenced against the measured ledger —
    tests/test_memory.py pins the residual at ±15%.

    The accounting deliberately mirrors ``memory_analysis()``'s naive
    temp + argument + output sum (donated buffers counted on both sides)
    so the number is comparable to both the ledger and the compiler.
    """

    argument_bytes: float
    output_bytes: float
    temp_bytes: float
    breakdown: Dict[str, float]

    @property
    def peak_bytes(self) -> float:
        return self.argument_bytes + self.output_bytes + self.temp_bytes


# Same fence arithmetic for memory as for comms — re-exported under the
# name the memory tests read.
mem_residual_pct = comm_residual_pct


def train_mem_peak(param_bytes: float, act_bytes: float,
                   data_bytes: float = 0.0, *, dp: int = 4,
                   zero: bool = False, explicit_sync: bool = True,
                   metric_bytes: float = 128.0) -> MemCost:
    """Generic train-step peak-HBM model from first principles:

    - **arguments**: params + momentum + the per-device batch shard.
      Under ``--zero wus`` the momentum tree lives as owned 1/dp chunks.
    - **outputs**: the new state (same layout) + the scalar metrics
      tuple.  Donation aliases outputs onto arguments, but the compiler's
      accounting (and so the ledger's) books both sides — so does this.
    - **temps**: the gradient tree (one param-tree copy, live from
      backward until the update consumes it) + the live activation /
      saved-residual bytes at the backward peak.  ``explicit_sync`` adds
      the hand-written grad-sync path's materialized scratch: one synced
      tree for the all-reduce (or the gathered delta under zero), plus
      the owned-chunk stack between the reduce-scatter and all-gather
      hops.  GSPMD steps sync in place — pass ``explicit_sync=False``.
    """
    dp = max(1, int(dp))
    momentum = param_bytes / dp if zero else param_bytes
    state = param_bytes + momentum
    grads = param_bytes
    sync = 0.0
    if explicit_sync and dp > 1:
        sync = param_bytes + (param_bytes / dp if zero else 0.0)
    temp = grads + act_bytes + sync
    return MemCost(
        argument_bytes=state + data_bytes,
        output_bytes=state + metric_bytes,
        temp_bytes=temp,
        breakdown={"params": param_bytes, "momentum": momentum,
                   "data": data_bytes, "grads": grads,
                   "activations": act_bytes, "grad_sync_scratch": sync,
                   "metrics": metric_bytes})


def lm_act_bytes(d_model: int, n_layers: int, n_heads: int, batch: int,
                 seq_len: int, vocab_size: int, *, dp: int = 4,
                 mlp_ratio: int = 4, elem: float = 4.0) -> float:
    """Live activation/saved-residual bytes at the LM backward peak, per
    device (``b = batch/dp`` rows).  Per layer per token the autodiff
    schedule stashes ~9 d-wide tensors (ln1, qkv, attn out, proj out,
    two residual adds, ln2, fc2 out) + 2 mlp-wide (fc1 out, gelu out) +
    the two [H, T, T] score/softmax matrices; the loss head holds the
    logits block plus ~2x for log-softmax and its gradient."""
    b = batch / max(1, int(dp))
    per_token = 9.0 * d_model + 2.0 * mlp_ratio * d_model
    scores = 2.0 * n_heads * seq_len
    stack = b * seq_len * n_layers * (per_token + scores)
    head = 3.0 * b * seq_len * vocab_size
    return elem * (stack + head)


def lm_train_mem_peak(vocab_size: int, d_model: int, n_layers: int,
                      n_heads: int, batch: int, seq_len: int, *,
                      dp: int = 4, zero: bool = False,
                      mlp_ratio: int = 4) -> MemCost:
    """Analytic peak HBM for the GSPMD transformer-LM train step: tied
    embedding + block stack params (f32), momentum (1/dp-sharded under
    ``--zero wus``), the lm_act_bytes schedule, int32 token shard.
    GSPMD derives the grad sync in place, so no explicit scratch term."""
    params = lm_step_cost(vocab_size, d_model, n_layers, batch,
                          seq_len, mlp_ratio=mlp_ratio).params
    act = lm_act_bytes(d_model, n_layers, n_heads, batch, seq_len,
                       vocab_size, dp=dp, mlp_ratio=mlp_ratio)
    tokens = 4.0 * (batch / max(1, dp)) * seq_len + 8.0  # int32 + lr/step
    return train_mem_peak(4.0 * params, act, data_bytes=tokens, dp=dp,
                          zero=zero, explicit_sync=False,
                          metric_bytes=256.0)


# ------------------------------------------------------------------ reporter
class MFUReporter:
    """Turns host-measured step seconds into per-step MFU/HFU fields for
    the metrics JSONL (all-host math — never touches the device)."""

    def __init__(self, cost: StepCost, n_devices: int,
                 peak_per_chip: Optional[float] = None):
        self.cost = cost
        self.n_devices = max(1, int(n_devices))
        self.peak = (peak_per_chip if peak_per_chip is not None
                     else device_peak_flops())
        self._denom = self.peak * self.n_devices

    def fields(self, step_time: float) -> Dict[str, float]:
        dt = max(float(step_time), 1e-9)
        return {
            "mfu": 100.0 * self.cost.model_flops / dt / self._denom,
            "hfu": 100.0 * self.cost.hardware_flops / dt / self._denom,
            "model_tflops": self.cost.model_flops / dt / 1e12,
        }
