"""Per-request tracing + SLO attribution for the serving engine (ISSUE 17).

The obs stack explains every *step* (ledgers, flight recorder, live
gauges) but PR 15's engine emits only aggregate quantiles — when a
``ttft_p99`` fence breaches nobody can say whether the tail came from
queue wait, chunked-prefill compute, preemption-recompute, or defrag
stalls.  This module is the request-scoped plane:

- ``TraceContext`` — the explicit serializable record (trace_id, submit
  clock, hop list) a future router/replica fleet propagates across
  processes unchanged.  The scheduler appends lifecycle hops by duck
  typing (``ctx.hops.append(...)``) so serving/ never imports obs/.
- ``ReqTracer`` — a bounded, lazy-flush span recorder with the
  flight-recorder overhead discipline: every hot-path hook is a tuple
  append (plus a couple of monotonic-clock reads the engine already
  pays); all serialization and I/O happen at the per-step drain.  A
  global span budget caps memory; overflow is *counted*
  (``spans_dropped``), never silently swallowed, and attribution stays
  correct under drops because it runs off per-request scalar state, not
  the span buffer.
- the critical-path analyzer — each completed request's TTFT decomposes
  exactly into ``queue_wait + prefill + preempt_redo + defrag + other``
  (the redo/defrag terms are the overlap of the request's queue window
  with the engine-wide redo-prefill/defrag intervals the tracer keeps),
  and the post-first-token phase into ``decode + redo_own + defrag +
  other`` — both sides on the engine clock, so attributed sums
  reconcile with the engine's measured TTFT/e2e by construction
  (fenced ±5% in tests; see RESULTS_reqtrace.json).
- tail-based sampling — every SLO-violating trace keeps its full span
  list; non-violators keep spans at a deterministic ``sample`` rate
  (rid-hash, no RNG state).  Attribution aggregates are computed for
  *all* requests regardless of sampling.
- ``tail_attribution()`` — the rollup behind ``obs_trace``/``obs_report``:
  "p99 TTFT = 61% queue wait, 24% preempt-redo, …".

Import-time stdlib-only (no jax, no numpy): ``scripts/obs_trace.py``
path-loads this file and asserts jax stays unimported, like obs_live.
"""

from __future__ import annotations

import dataclasses
import json
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

# TTFT components, in render order (shares of the tail rollup).
TTFT_COMPONENTS = ("queue_wait", "prefill", "preempt_redo", "defrag", "other")


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (obs/metrics.py semantics, re-stated here
    so this module stays import-free for the jax-free CLI)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def _overlap_ms(intervals, lo: float, hi: float) -> float:
    """Overlap of ``[lo, hi)`` with the *union* of an interval list, in
    ms.  The union matters: discarded-tenure and redo-prefill intervals
    from concurrent victims overlap each other, and a plain per-interval
    sum would double-count the covered wall — breaking the components-
    sum-to-TTFT contract."""
    clipped = sorted((max(lo, a), min(hi, b)) for a, b in intervals
                     if b > lo and a < hi)
    tot = 0.0
    end = lo
    for a, b in clipped:
        a = max(a, end)
        if b > a:
            tot += b - a
            end = b
    return tot * 1e3


@dataclasses.dataclass
class TraceContext:
    """The propagatable identity of one request.

    ``hops`` is the lifecycle/topology path ("engine:0", "queue",
    "admit", "requeue", …); a router prepends its own hop and ships the
    record unchanged — ``to_wire``/``from_wire`` is the cross-process
    format (plain dict, json-safe).
    """

    trace_id: str
    rid: int
    submit_t: float            # engine clock, seconds
    hops: List[str] = dataclasses.field(default_factory=list)

    def to_wire(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "rid": self.rid,
                "submit_t": round(self.submit_t, 6),
                "hops": list(self.hops)}

    @classmethod
    def from_wire(cls, d: Dict[str, Any]) -> "TraceContext":
        return cls(trace_id=str(d["trace_id"]), rid=int(d["rid"]),
                   submit_t=float(d["submit_t"]),
                   hops=[str(h) for h in d.get("hops", [])])


class _ReqState:
    """Scalar per-request attribution state (survives span drops)."""

    __slots__ = ("ctx", "submit_t", "admit_t", "first_token_t",
                 "prefill_ms", "redo_prefill_ms", "decode_ms",
                 "requeue_raw_ms", "requeue_defrag_ms", "preempt_t",
                 "tenure_t", "preempts", "spans", "dropped")

    def __init__(self, ctx: TraceContext):
        self.ctx = ctx
        self.submit_t = ctx.submit_t
        self.admit_t: Optional[float] = None
        self.tenure_t: Optional[float] = None  # current admission's start
        self.first_token_t: Optional[float] = None
        self.prefill_ms = 0.0        # first-pass prefill (pre-first-token)
        self.redo_prefill_ms = 0.0   # recompute-redo prefill after preempt
        self.decode_ms = 0.0         # this request's share of decode calls
        self.requeue_raw_ms = 0.0    # preempt -> re-admit wall
        self.requeue_defrag_ms = 0.0  # defrag overlap of requeue windows
        self.preempt_t: Optional[float] = None
        self.preempts = 0
        self.spans: List[Tuple] = []  # (kind, t0, t1, aux) — bounded
        self.dropped = 0


class ReqTracer:
    """Bounded per-request span recorder + attribution aggregator.

    Hook methods are called by the engine/scheduler on the serving hot
    path; each is a few scalar ops and at most one tuple append.  All
    derived work (attribution math, JSON encoding) runs at request
    completion / drain time, never per token.
    """

    def __init__(self, *, slo_ms: Optional[float] = None,
                 sample: float = 0.05, max_spans: int = 65536,
                 max_intervals: int = 1024, max_pending: int = 8192,
                 window: int = 512, hop: str = "engine:0"):
        self.slo_ms = None if slo_ms is None else float(slo_ms)
        self.sample = max(0.0, min(1.0, float(sample)))
        self.max_spans = int(max_spans)
        self.max_pending = int(max_pending)
        self.hop = hop
        self._state: Dict[int, _ReqState] = {}
        self._nspans = 0
        self._pending: List[Dict[str, Any]] = []
        # engine-wide interval lists (bounded): what the queue window
        # overlaps against.  deque(maxlen) drops the OLDEST interval —
        # old intervals can only matter to requests that have been
        # queued longer than the window covers, which under-attributes
        # (falls back to queue_wait), never mis-attributes.
        self._redo_iv: deque = deque(maxlen=int(max_intervals))
        self._defrag_iv: deque = deque(maxlen=int(max_intervals))
        # rolling attribution windows feeding the live gauges/alerts
        self._q_share: deque = deque(maxlen=int(window))
        self._redo_ms: deque = deque(maxlen=int(window))
        # counters
        self.completed = 0
        self.violations = 0
        self.sampled_kept = 0
        self.spans_dropped = 0
        self.records_dropped = 0
        self.redo_prefills = 0

    # ------------------------------------------------------------- span ring
    def _span(self, st: _ReqState, kind: str, t0: float, t1: float,
              aux: int = 0) -> None:
        if self._nspans >= self.max_spans:
            st.dropped += 1
            self.spans_dropped += 1
            return
        st.spans.append((kind, t0, t1, aux))
        self._nspans += 1

    # ----------------------------------------------------------- engine hooks
    def on_submit(self, rid: int, t: float, priority: int = 0
                  ) -> TraceContext:
        ctx = TraceContext(trace_id=f"ptd-{self.hop}-{rid:08x}", rid=rid,
                           submit_t=t, hops=[self.hop])
        st = _ReqState(ctx)
        self._state[rid] = st
        self._span(st, "submit", t, t, priority)
        return ctx

    def on_admit(self, rid: int, t: float) -> None:
        st = self._state.get(rid)
        if st is None:
            return
        st.tenure_t = t
        if st.admit_t is None:
            st.admit_t = t
            self._span(st, "queue", st.submit_t, t)
        else:                      # re-admission after a preemption
            if st.preempt_t is not None:
                raw = t - st.preempt_t
                st.requeue_raw_ms += raw * 1e3
                st.requeue_defrag_ms += _overlap_ms(
                    self._defrag_iv, st.preempt_t, t)
                self._span(st, "requeue_wait", st.preempt_t, t)
                st.preempt_t = None

    def on_prefill(self, rid: int, t_marks: Sequence[float], redo: bool
                   ) -> None:
        """``t_marks``: chunk boundaries, first = prefill start, last =
        post-sync (the engine's first-token stamp).  One span per chunk;
        the last chunk absorbs the host sync."""
        st = self._state.get(rid)
        if st is None or len(t_marks) < 2:
            return
        kind = "redo_prefill" if redo else "prefill"
        for i in range(len(t_marks) - 1):
            self._span(st, kind, t_marks[i], t_marks[i + 1], i)
        dur_ms = (t_marks[-1] - t_marks[0]) * 1e3
        if redo:
            st.redo_prefill_ms += dur_ms
            self.redo_prefills += 1
            self._redo_iv.append((t_marks[0], t_marks[-1]))
        else:
            st.prefill_ms += dur_ms
            st.first_token_t = t_marks[-1]

    def on_decode(self, rid: int, t0: float, t1: float,
                  n_tokens: int) -> None:
        st = self._state.get(rid)
        if st is None:
            return
        st.decode_ms += (t1 - t0) * 1e3
        self._span(st, "decode", t0, t1, n_tokens)

    def on_emit(self, rid: int, t: float, first: bool) -> None:
        st = self._state.get(rid)
        if st is None:
            return
        self._span(st, "emit", t, t, 1 if first else 0)

    def on_preempt(self, rid: int, t: float) -> None:
        st = self._state.get(rid)
        if st is None:
            return
        st.preempts += 1
        st.preempt_t = t
        # everything this lane computed since (re-)admission is discarded
        # and will be recomputed — the whole tenure is preempt-redo wall,
        # not just the later redo prefill.
        if st.tenure_t is not None:
            self._redo_iv.append((st.tenure_t, t))
            st.tenure_t = None
        self._span(st, "preempt", t, t, st.preempts)

    def on_defrag(self, t0: float, t1: float) -> None:
        self._defrag_iv.append((t0, t1))

    # ------------------------------------------------------------ completion
    def _keep_spans(self, rid: int, violated: bool) -> bool:
        if violated:
            return True             # tail-based sampling: keep every violator
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        # deterministic, stateless: Knuth multiplicative hash of the rid
        return ((rid * 2654435761) & 0xFFFFFFFF) / 2**32 < self.sample

    def on_complete(self, rid: int, t: float, tokens: int,
                    preemptions: int) -> None:
        st = self._state.pop(rid, None)
        if st is None:
            return
        self._span(st, "complete", t, t, tokens)
        self._nspans -= len(st.spans)   # spans leave the buffer with the record
        self.completed += 1

        admit_t = st.admit_t if st.admit_t is not None else st.submit_t
        ftt = st.first_token_t if st.first_token_t is not None else t
        ttft_ms = (ftt - st.submit_t) * 1e3
        e2e_ms = (t - st.submit_t) * 1e3

        # --- TTFT window: queue_wait + prefill + preempt_redo + defrag
        #     + other == ttft, exactly (engine clock on both sides).
        redo_wait_ms = _overlap_ms(self._redo_iv, st.submit_t, admit_t)
        defrag_wait_ms = _overlap_ms(self._defrag_iv, st.submit_t, admit_t)
        queue_wait_ms = max(
            0.0, (admit_t - st.submit_t) * 1e3 - redo_wait_ms
            - defrag_wait_ms)
        other_wait_ms = max(0.0, ttft_ms - queue_wait_ms - redo_wait_ms
                            - defrag_wait_ms - st.prefill_ms)

        # --- post-first-token phase: decode + redo_own + defrag + other
        phase_ms = max(0.0, e2e_ms - ttft_ms)
        redo_own_ms = (st.redo_prefill_ms + st.requeue_raw_ms
                       - st.requeue_defrag_ms)
        defrag_run_ms = _overlap_ms(self._defrag_iv, ftt, t)
        other_run_ms = max(0.0, phase_ms - st.decode_ms - redo_own_ms
                           - defrag_run_ms)

        preempt_redo_ms = redo_wait_ms + redo_own_ms
        q_share = 100.0 * queue_wait_ms / ttft_ms if ttft_ms > 0 else 0.0
        violated = self.slo_ms is not None and ttft_ms > self.slo_ms
        if violated:
            self.violations += 1
        self._q_share.append(q_share)
        self._redo_ms.append(preempt_redo_ms)

        ev: Dict[str, Any] = {
            "rid": rid,
            "trace_id": st.ctx.trace_id,
            "submit_t": round(st.submit_t, 6),
            "ttft_ms": round(ttft_ms, 4),
            "e2e_ms": round(e2e_ms, 4),
            "tokens": int(tokens),
            "preemptions": int(preemptions),
            "queue_wait_ms": round(queue_wait_ms, 4),
            "prefill_ms": round(st.prefill_ms, 4),
            "redo_wait_ms": round(redo_wait_ms, 4),
            "defrag_wait_ms": round(defrag_wait_ms, 4),
            "other_wait_ms": round(other_wait_ms, 4),
            "decode_ms": round(st.decode_ms, 4),
            "redo_own_ms": round(redo_own_ms, 4),
            "defrag_run_ms": round(defrag_run_ms, 4),
            "other_run_ms": round(other_run_ms, 4),
            "preempt_redo_ms": round(preempt_redo_ms, 4),
            "queue_wait_share_pct": round(q_share, 3),
            "violated": 1 if violated else 0,
            "n_spans": len(st.spans),
            "spans_dropped": st.dropped,
            "ctx": json.dumps(st.ctx.to_wire(), sort_keys=True),
        }
        if self._keep_spans(rid, violated):
            self.sampled_kept += 1
            ev["sampled"] = 1
            # spans as a JSON *string*: MetricsLogger.flush float()-casts
            # any non-primitive field, so lists must not leak through.
            ev["spans"] = json.dumps(
                [[k, round(a, 6), round(b - a, 6), x]
                 for (k, a, b, x) in st.spans])
        else:
            ev["sampled"] = 0
        if len(self._pending) < self.max_pending:
            self._pending.append(ev)
        else:
            self.records_dropped += 1

    # ----------------------------------------------------------------- drain
    def drain(self) -> List[Dict[str, Any]]:
        """Completed trace records since the last drain (lazy flush: the
        engine calls this once per step and books each record as one
        ``reqtrace`` ft_event)."""
        out, self._pending = self._pending, []
        return out

    def step_fields(self) -> Dict[str, float]:
        """Rolling attribution gauges for the per-step metrics record
        (→ ``ptd_serving_attr_*`` exposition, alert rules, obs_report)."""
        out: Dict[str, float] = {
            "trace_completed": float(self.completed),
            "trace_spans_dropped": float(self.spans_dropped),
        }
        if self._q_share:
            qs = sorted(self._q_share)
            out["queue_wait_share_p50"] = _percentile(qs, 0.5)
            out["queue_wait_share_p99"] = _percentile(qs, 0.99)
        if self._redo_ms:
            rd = sorted(self._redo_ms)
            out["preempt_redo_ms_p50"] = _percentile(rd, 0.5)
            out["preempt_redo_ms_p99"] = _percentile(rd, 0.99)
        return out


# ---------------------------------------------------------------- analysis
# Pure functions over drained/parsed trace records — shared by
# scripts/obs_trace.py (jax-free), obs_report, and chaoskit.


def trace_records(records: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Filter a parsed metrics-JSONL stream down to reqtrace events."""
    return [r for r in records if r.get("ft_event") == "reqtrace"]


def _ttft_components_ms(r: Dict[str, Any]) -> Dict[str, float]:
    return {
        "queue_wait": float(r.get("queue_wait_ms", 0.0)),
        "prefill": float(r.get("prefill_ms", 0.0)),
        "preempt_redo": float(r.get("redo_wait_ms", 0.0)),
        "defrag": float(r.get("defrag_wait_ms", 0.0)),
        "other": float(r.get("other_wait_ms", 0.0)),
    }


def tail_attribution(trs: Sequence[Dict[str, Any]], q: float = 0.99
                     ) -> Optional[Dict[str, Any]]:
    """Attribute the TTFT tail: among requests at/above the q-quantile
    TTFT, what share of their (mean) TTFT does each component own?"""
    trs = [r for r in trs if "ttft_ms" in r]
    if not trs:
        return None
    ttfts = sorted(float(r["ttft_ms"]) for r in trs)
    cut = _percentile(ttfts, q)
    tail = [r for r in trs if float(r["ttft_ms"]) >= cut]
    mean_ttft = sum(float(r["ttft_ms"]) for r in tail) / len(tail)
    comps = {k: 0.0 for k in TTFT_COMPONENTS}
    for r in tail:
        for k, v in _ttft_components_ms(r).items():
            comps[k] += v
    for k in comps:
        comps[k] /= len(tail)
    denom = max(mean_ttft, 1e-9)
    shares = {k: 100.0 * v / denom for k, v in comps.items()}
    dominant = max(shares, key=lambda k: shares[k])
    return {"q": q, "n_tail": len(tail), "ttft_tail_ms": cut,
            "mean_tail_ttft_ms": mean_ttft, "components_ms": comps,
            "shares_pct": shares, "dominant": dominant}


def attribution_summary(trs: Sequence[Dict[str, Any]]
                        ) -> Optional[Dict[str, Any]]:
    """Aggregate stats over all completed-request trace records."""
    trs = [r for r in trs if "ttft_ms" in r]
    if not trs:
        return None
    def p(field: str, q: float) -> float:
        return _percentile(sorted(float(r.get(field, 0.0)) for r in trs), q)
    recon = [abs(float(r["ttft_ms"])
                 - sum(_ttft_components_ms(r).values()))
             for r in trs]
    out = {
        "requests": len(trs),
        "violations": sum(int(r.get("violated", 0)) for r in trs),
        "sampled_kept": sum(int(r.get("sampled", 0)) for r in trs),
        "spans_dropped": sum(int(r.get("spans_dropped", 0)) for r in trs),
        "preemptions": sum(int(r.get("preemptions", 0)) for r in trs),
        "ttft_p50_ms": p("ttft_ms", 0.5),
        "ttft_p99_ms": p("ttft_ms", 0.99),
        "e2e_p99_ms": p("e2e_ms", 0.99),
        "queue_wait_share_p99": p("queue_wait_share_pct", 0.99),
        "preempt_redo_ms_p99": p("preempt_redo_ms", 0.99),
        "recon_err_ms_max": max(recon),
        "tail": tail_attribution(trs),
    }
    return out


def fleet_trace_records(records: Sequence[Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
    """Filter a parsed metrics-JSONL stream down to the fleet router's
    per-request ``fleettrace`` events (serving/router.py)."""
    return [r for r in records if r.get("ft_event") == "fleettrace"]


def fleet_reconciliation(fleet_trs: Sequence[Dict[str, Any]],
                         engine_trs: Sequence[Dict[str, Any]] = ()
                         ) -> Optional[Dict[str, Any]]:
    """Reconcile the router's latency attribution (ISSUE 19).

    Two exactness contracts, both checked per request:

    1. Decomposition: ``router_ttft_ms == router_wait_ms +
       redispatch_ms + hedge_wait_ms + engine_ttft_ms`` — the router
       books these so the identity holds by construction; any drift
       means double-counted or lost wall clock.
    2. Engine echo: when the same JSONL also holds the replicas'
       ``reqtrace`` events, the ``engine_ttft_ms`` the router echoed
       must match the engine's own ``ttft_ms`` for that rid — the
       router is reporting the engine's truth, not its own estimate.

    Returns None when there are no fleet traces (routerless runs)."""
    fleet_trs = list(fleet_trs)
    if not fleet_trs:
        return None
    decomp = []
    for t in fleet_trs:
        lhs = float(t.get("router_ttft_ms", 0.0))
        rhs = (float(t.get("router_wait_ms", 0.0))
               + float(t.get("redispatch_ms", 0.0))
               + float(t.get("hedge_wait_ms", 0.0))
               + float(t.get("engine_ttft_ms", 0.0)))
        decomp.append(abs(lhs - rhs))
    by_rid: Dict[Any, List[Dict[str, Any]]] = {}
    for r in engine_trs:
        by_rid.setdefault(r.get("rid"), []).append(r)
    matched = 0
    echo = []
    for t in fleet_trs:
        cands = by_rid.get(t.get("rid"))
        if not cands:
            continue
        matched += 1
        echo.append(min(abs(float(t.get("engine_ttft_ms", 0.0))
                            - float(c.get("ttft_ms", 0.0)))
                        for c in cands))
    waits = sorted(float(t.get("router_wait_ms", 0.0)) for t in fleet_trs)
    return {
        "requests": len(fleet_trs),
        "retried": sum(1 for t in fleet_trs
                       if int(t.get("attempts", 1)) > 1),
        "hedged": sum(1 for t in fleet_trs if t.get("hedged")),
        "decomp_err_ms_max": max(decomp),
        "engine_matched": matched,
        "engine_echo_err_ms_max": max(echo) if echo else None,
        "router_wait_p99_ms": _percentile(waits, 0.99),
        "router_ttft_p99_ms": _percentile(
            sorted(float(t.get("router_ttft_ms", 0.0))
                   for t in fleet_trs), 0.99),
    }


def format_tail_line(tail: Dict[str, Any]) -> str:
    """'p99 TTFT 812.4ms = 61% queue_wait, 24% preempt_redo, …'"""
    shares = tail["shares_pct"]
    parts = ", ".join(f"{shares[k]:.0f}% {k}" for k in TTFT_COMPONENTS
                      if shares[k] >= 0.5)
    return (f"p{int(tail['q'] * 100)} TTFT {tail['mean_tail_ttft_ms']:.1f}ms"
            f" = {parts}")


def chrome_events(trs: Sequence[Dict[str, Any]], pid: int = 9000,
                  process_name: str = "serving requests"
                  ) -> List[Dict[str, Any]]:
    """Chrome-trace events for per-request tracks (one tid per request;
    engine-clock seconds → trace µs).  Only records that retained their
    span list (``sampled``) render; aggregate-only records have no
    geometry to draw.  obs/timeline.py merges these into the step
    timeline (``to_chrome_trace(..., req_traces=...)``)."""
    events: List[Dict[str, Any]] = [{
        "ph": "M", "pid": pid, "name": "process_name",
        "args": {"name": process_name}}]
    for r in trs:
        spans = r.get("spans")
        if not spans:
            continue
        if isinstance(spans, str):
            spans = json.loads(spans)
        tid = int(r.get("rid", 0))
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name",
                       "args": {"name": f"req {tid} "
                                        f"({r.get('trace_id', '?')})"}})
        for kind, t0, dur, aux in spans:
            ev = {"ph": "X", "pid": pid, "tid": tid, "name": str(kind),
                  "ts": float(t0) * 1e6, "dur": max(float(dur) * 1e6, 1.0),
                  "args": {"aux": aux}}
            if kind in ("redo_prefill", "requeue_wait", "preempt"):
                ev["cat"] = "preempt"
            events.append(ev)
    return events
