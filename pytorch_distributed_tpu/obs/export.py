"""Per-rank live metric export: Prometheus text exposition over HTTP
(ISSUE 14).

One daemon thread per rank serves the **latest already-buffered**
``MetricsLogger`` record plus heartbeat-class scalars on
``--metrics-port`` (rank *k* binds ``port + k``).  Discipline matches
the flight recorder: the training loop never does exporter work — the
exporter is a flush-time sink (``exporter.update`` sees each drained
record, a dict of host floats), and all rendering, socket I/O, and the
process-memory sample happen on the scrape path inside the HTTP thread.
Overhead is fenced <2% in ``RESULTS_obs_export.json`` with the same A/B
methodology as ``RESULTS_flightrec.json``.

Endpoints:

- ``GET /metrics``  Prometheus text exposition (``ptd_`` prefix, every
  gauge labelled with ``rank``);
- ``GET /healthz``  ``ok`` + last-record age, 200/503.

Stdlib-only and import-time jax-free: the fleet aggregator
(``scripts/obs_live.py``) and the tests parse the same exposition via
``parse_prometheus`` with no jax in the process.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

# record fields promoted to dedicated gauges; everything else numeric is
# exported generically as ptd_metric{field="..."}
_STAT_FIELDS = {
    "step_time": "last",
    "step_time_ema": "ema",
    "step_time_p50": "p50",
    "step_time_p95": "p95",
    "step_time_max": "max",
}
# serving SLO fields (serving/engine.py) promoted to ptd_serving_*
# gauges so dashboards get stable names instead of ptd_metric{field=...}
_SERVING_FIELDS = {
    "ttft_p50_ms": ("ptd_serving_ttft_ms", {"quantile": "p50"}),
    "ttft_p95_ms": ("ptd_serving_ttft_ms", {"quantile": "p95"}),
    "ttft_p99_ms": ("ptd_serving_ttft_ms", {"quantile": "p99"}),
    "itl_p50_ms": ("ptd_serving_itl_ms", {"quantile": "p50"}),
    "itl_p95_ms": ("ptd_serving_itl_ms", {"quantile": "p95"}),
    "itl_p99_ms": ("ptd_serving_itl_ms", {"quantile": "p99"}),
    "queue_depth": ("ptd_serving_queue_depth", {}),
    "active_seqs": ("ptd_serving_active_seqs", {}),
    "kv_occupancy_pct": ("ptd_serving_kv_occupancy_pct", {}),
    "kv_frag_pct": ("ptd_serving_kv_frag_pct", {}),
    "preemptions": ("ptd_serving_preemptions_total", {}),
    "requests_completed": ("ptd_serving_requests_completed_total", {}),
    "tokens_per_s": ("ptd_serving_tokens_per_second", {}),
    # request-trace attribution gauges (obs/reqtrace.py step_fields):
    # the *why* behind a ptd_serving_ttft_ms breach — queue backlog vs
    # preemption-recompute thrash — live on /metrics.
    "queue_wait_share_p50": ("ptd_serving_attr_queue_wait_share_pct",
                             {"quantile": "p50"}),
    "queue_wait_share_p99": ("ptd_serving_attr_queue_wait_share_pct",
                             {"quantile": "p99"}),
    "preempt_redo_ms_p50": ("ptd_serving_attr_preempt_redo_ms",
                            {"quantile": "p50"}),
    "preempt_redo_ms_p99": ("ptd_serving_attr_preempt_redo_ms",
                            {"quantile": "p99"}),
    "trace_completed": ("ptd_serving_attr_traces_total", {}),
    "trace_spans_dropped": ("ptd_serving_attr_spans_dropped_total", {}),
}
# training step-time attribution gauges (obs/stepattr.py, --step-attr):
# the exact "where did my step go" split on /metrics, one gauge family
# labelled by component so dashboards can stack them, plus the
# data-wait share the alert rule watches.
_ATTR_FIELDS = {
    "attr_compute_ms": ("ptd_attr_ms", {"component": "compute"}),
    "attr_exposed_comm_ms": ("ptd_attr_ms", {"component": "exposed_comm"}),
    "attr_host_sync_ms": ("ptd_attr_ms", {"component": "host_sync"}),
    "attr_data_wait_ms": ("ptd_attr_ms", {"component": "data_wait"}),
    "attr_other_ms": ("ptd_attr_ms", {"component": "other"}),
    "attr_device_ms": ("ptd_attr_device_ms", {}),
    "attr_comm_ms": ("ptd_attr_comm_ms", {}),
    "attr_recon_err_ms": ("ptd_attr_recon_err_ms", {}),
    "data_wait_share": ("ptd_attr_data_wait_share_pct", {}),
}
_SKIP_FIELDS = ({"step", "t", "process", "epoch"} | set(_STAT_FIELDS)
                | set(_SERVING_FIELDS) | set(_ATTR_FIELDS))

# fleet-router gauge names (serving/router.py render_fleet_metrics /
# scripts/obs_live.py fleet block).  The router renders these itself —
# this tuple pins the contract so scrapers and the exposition can't
# drift apart silently (asserted in the export selftest family).
FLEET_GAUGES = (
    "ptd_fleet_up",
    "ptd_fleet_inflight",
    "ptd_fleet_requests_total",
    "ptd_fleet_completed_total",
    "ptd_fleet_failed_total",
    "ptd_fleet_retries_total",
    "ptd_fleet_hedges_total",
    "ptd_fleet_hedges_won_total",
    "ptd_fleet_hedges_lost_total",
    "ptd_fleet_duplicates_suppressed_total",
    "ptd_fleet_replica_down_total",
    "ptd_fleet_last_scale",
    "ptd_fleet_replicas",
    "ptd_fleet_quarantined",
    "ptd_fleet_replica_state",
    "ptd_fleet_replica_queue_depth",
    "ptd_fleet_replica_kv_occupancy_pct",
    "ptd_fleet_replica_ttft_p99_ms",
    "ptd_fleet_replica_beat_age_seconds",
    "ptd_fleet_replica_dispatched_total",
    "ptd_fleet_replica_completed_total",
)


def _heartbeat_mod():
    """The sibling heartbeat module, without importing the top-level
    package (whose ``__init__`` imports jax) into a jax-free process —
    same discipline as ``obs/alerts.py``."""
    import importlib
    import importlib.util
    import os
    import sys

    full = "pytorch_distributed_tpu.obs.heartbeat"
    if full in sys.modules:
        return sys.modules[full]
    if "pytorch_distributed_tpu" in sys.modules:
        return importlib.import_module(full)
    alias = "_ptd_obs_heartbeat"
    if alias in sys.modules:
        return sys.modules[alias]
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "heartbeat.py")
    spec = importlib.util.spec_from_file_location(alias, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[alias] = mod
    spec.loader.exec_module(mod)
    return mod


def _esc(v: Any) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _line(name: str, labels: Dict[str, Any], value: float) -> str:
    lab = ",".join(f'{k}="{_esc(v)}"' for k, v in sorted(labels.items()))
    return f"{name}{{{lab}}} {float(value):g}"


class MetricsExporter:
    """Serve the latest drained metrics record on an HTTP port.

    Registered with ``MetricsLogger`` twice: once as an owned sink
    (``start``/``stop`` → the logger starts it at ``register`` and stops
    it at ``close``) and once via ``exporter.update`` as a per-record
    step sink.  ``update`` only swaps a reference and bumps counters;
    rendering happens at scrape time.
    """

    def __init__(self, port: int, host: str = "127.0.0.1", rank: int = 0,
                 engine: Optional[Any] = None):
        self.port = int(port)  # 0 → ephemeral; re-read after start()
        self.host = host
        self.rank = int(rank)
        self.engine = engine  # optional AlertEngine: exposes firing gauges
        self.running = False
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._record: Optional[dict] = None
        self._record_at: float = 0.0
        self._events: Dict[str, int] = {}
        self._last_event: Optional[dict] = None
        self._started_at: float = 0.0

    # ------------------------------------------------------------ sink side
    def update(self, record: dict) -> None:
        """Flush-time step sink: remember the latest step record, count
        ft_events by kind.  No I/O, no rendering."""
        with self._lock:
            if "ft_event" in record:
                kind = str(record["ft_event"])
                self._events[kind] = self._events.get(kind, 0) + 1
                self._last_event = record
            elif "step_time" in record:
                self._record = record
                self._record_at = time.time()

    # --------------------------------------------------------- server side
    def start(self) -> None:
        if self.running:
            return
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802 - silence per-request logs
                pass

            def do_GET(self):  # noqa: N802
                if self.path.split("?")[0] == "/metrics":
                    body = exporter.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                elif self.path.split("?")[0] == "/healthz":
                    age = exporter.record_age()
                    ok = age is not None
                    body = json.dumps(
                        {"ok": ok, "rank": exporter.rank,
                         "record_age_s": age}).encode()
                    self.send_response(200 if ok else 503)
                    self.send_header("Content-Type", "application/json")
                else:
                    body = b"not found\n"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]  # resolve port 0
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"ptd-metrics-exporter-r{self.rank}", daemon=True)
        self._thread.start()
        self._started_at = time.time()
        self.running = True

    def stop(self) -> None:
        if not self.running:
            return
        self.running = False
        srv, self._server = self._server, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def record_age(self) -> Optional[float]:
        with self._lock:
            if self._record is None:
                return None
            return max(0.0, time.time() - self._record_at)

    # ---------------------------------------------------------- exposition
    def render(self) -> str:
        """Prometheus text exposition of the latest record + counters.
        Runs on the scrape thread only."""
        with self._lock:
            rec = dict(self._record) if self._record else None
            rec_at = self._record_at
            events = dict(self._events)
        rank = {"rank": self.rank}
        now = time.time()
        lines = [
            "# TYPE ptd_up gauge",
            _line("ptd_up", rank, 1.0),
            _line("ptd_uptime_seconds", rank,
                  max(0.0, now - self._started_at)),
        ]
        try:
            mem = _heartbeat_mod().sample_process_memory()
            if mem is not None:
                lines.append(_line("ptd_mem_rss_bytes", rank, float(mem)))
        except Exception:
            pass
        if rec is not None:
            lines.append("# TYPE ptd_step gauge")
            lines.append(_line("ptd_step", rank,
                               float(rec.get("step", -1))))
            lines.append(_line("ptd_record_age_seconds", rank,
                               max(0.0, now - rec_at)))
            lines.append("# TYPE ptd_step_time_seconds gauge")
            for field, stat in _STAT_FIELDS.items():
                v = rec.get(field)
                if isinstance(v, (int, float)):
                    lines.append(_line("ptd_step_time_seconds",
                                       dict(rank, stat=stat), float(v)))
            for field, (name, extra_labels) in sorted(
                    list(_SERVING_FIELDS.items())
                    + list(_ATTR_FIELDS.items())):
                v = rec.get(field)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    lines.append(_line(name, dict(rank, **extra_labels),
                                       float(v)))
            for field in sorted(rec):
                if field in _SKIP_FIELDS:
                    continue
                v = rec[field]
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                lines.append(_line("ptd_metric", dict(rank, field=field),
                                   float(v)))
        if events:
            lines.append("# TYPE ptd_ft_events_total counter")
            for kind in sorted(events):
                lines.append(_line("ptd_ft_events_total",
                                   dict(rank, kind=kind),
                                   float(events[kind])))
            lines.append(_line("ptd_alerts_total", rank,
                               float(events.get("alert", 0))))
        engine = self.engine
        if engine is not None:
            try:
                for alert in engine.active():
                    lines.append(_line(
                        "ptd_alert_firing",
                        dict(rank, rule=alert.name,
                             severity=alert.severity), 1.0))
            except Exception:
                pass
        return "\n".join(lines) + "\n"


# ------------------------------------------------------------- scrape side

def parse_prometheus(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse text exposition back into ``(name, labels, value)`` samples.
    Handles exactly what ``render`` emits (and the common subset of the
    format) — shared by ``obs_live`` and the tests."""
    out: List[Tuple[str, Dict[str, str], float]] = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        try:
            head, _, val = line.rpartition(" ")
            labels: Dict[str, str] = {}
            if "{" in head:
                name, _, rest = head.partition("{")
                body = rest.rsplit("}", 1)[0]
                for part in _split_labels(body):
                    k, _, v = part.partition("=")
                    labels[k.strip()] = (
                        v.strip().strip('"')
                        .replace(r"\"", '"').replace(r"\n", "\n")
                        .replace(r"\\", "\\"))
            else:
                name = head
            out.append((name.strip(), labels, float(val)))
        except (ValueError, IndexError):
            continue
    return out


def _split_labels(body: str) -> List[str]:
    """Split ``a="x",b="y,z"`` on commas outside quotes."""
    parts, cur, quoted, escape = [], [], False, False
    for ch in body:
        if escape:
            cur.append(ch)
            escape = False
        elif ch == "\\":
            cur.append(ch)
            escape = True
        elif ch == '"':
            cur.append(ch)
            quoted = not quoted
        elif ch == "," and not quoted:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return [p for p in (s.strip() for s in parts) if p]


def sample_value(samples: List[Tuple[str, Dict[str, str], float]],
                 name: str, **labels: str) -> Optional[float]:
    """First sample matching ``name`` whose labels include ``labels``."""
    for n, lab, v in samples:
        if n == name and all(lab.get(k) == str(w)
                             for k, w in labels.items()):
            return v
    return None


def scrape(url: str, timeout: float = 2.0
           ) -> List[Tuple[str, Dict[str, str], float]]:
    """GET one exporter endpoint and parse it (stdlib urllib)."""
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return parse_prometheus(resp.read().decode("utf-8", "replace"))
