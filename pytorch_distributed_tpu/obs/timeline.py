"""Runtime comm/compute timeline from the profiler's XPlane captures.

``jax.profiler`` writes each capture as an ``*.xplane.pb`` protobuf (the
XSpace schema TensorBoard's profile plugin consumes).  Importing the
TensorFlow converter stack to read ~10 KB of spans is a multi-second tax
on the 1-core CI host, so this module decodes the wire format directly —
a few hundred lines of varint scanning, no proto/TF/jax imports — into
plain ``Span`` records, then answers the questions the static comm
ledger (obs/comms.py) cannot:

- how long each collective *actually took* per step window,
- how much collective time hid under compute (**overlap %**) vs stalled
  the device (**exposed comm** — the number EQuARX-style quantized
  collectives must shrink for the win to be real),
- what the cross-rank picture looks like: per-process captures merged on
  a common clock (heartbeat wall-times estimate per-rank skew) and
  exported as Chrome-trace JSON for Perfetto.

Schema note: field numbers below mirror tensorflow/tsl's xplane.proto
(XSpace{planes=1,hostnames=4}; XPlane{id=1,name=2,lines=3,
event_metadata=4,stat_metadata=5,stats=6}; XLine{id=1,name=2,
timestamp_ns=3,events=4,display_name=11}; XEvent{metadata_id=1,
offset_ps=2,duration_ps=3,stats=4}; XStat{metadata_id=1,double=2,
uint64=3,int64=4,str=5,bytes=6,ref=7}; XEventMetadata{id=1,name=2};
XStatMetadata{id=1,name=2}).  ``encode_xspace`` is the inverse — enough
of an encoder to build test fixtures and the obs_timeline selftest
capture without a live profiler.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import struct
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from pytorch_distributed_tpu.analysis.hlo import COLLECTIVE_OPS

# Host-side / executor bookkeeping spans: never counted as device compute.
_INFRA_PREFIXES = (
    "ThreadpoolListener", "ThunkExecutor", "TfrtCpuExecutable",
    "ParseArguments", "PjitFunction", "$", "Execute", "TransferTo",
    "TransferFrom", "BufferFromHost", "copy_start", "copy_done",
    "infeed", "outfeed",
)


def is_collective_name(name: str) -> bool:
    """``all-reduce`` / ``all-reduce.13`` / ``all-gather-start.2`` ..."""
    base = name.split(".", 1)[0]
    if base.endswith("-start") or base.endswith("-done"):
        base = base.rsplit("-", 1)[0]
    return base in COLLECTIVE_OPS or any(
        name.startswith(op) for op in COLLECTIVE_OPS)


def collective_kind(name: str) -> str:
    for op in COLLECTIVE_OPS:
        if name.startswith(op):
            return op
    return name.split(".", 1)[0]


# ------------------------------------------------------- wire-format decode

def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not (b & 0x80):
            return val, i
        shift += 7


def _iter_fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """Yield ``(field_number, wire_type, value)`` over one message."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        fnum, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wt} at offset {i}")
        yield fnum, wt, v


def _to_signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _decode_metadata_map(entry: bytes) -> Tuple[int, str]:
    """One ``map<int64, X*Metadata>`` entry -> (id, name)."""
    meta_id, name = 0, ""
    for fnum, _wt, v in _iter_fields(entry):
        if fnum == 1:
            meta_id = v
        elif fnum == 2:
            for f2, _w2, v2 in _iter_fields(v):
                if f2 == 1:
                    meta_id = v2
                elif f2 == 2:
                    name = v2.decode("utf-8", "replace")
    return meta_id, name


def _decode_stat(buf: bytes, stat_names: Dict[int, str]) -> Tuple[str, Any]:
    key, val = "", None
    for fnum, _wt, v in _iter_fields(buf):
        if fnum == 1:
            key = stat_names.get(v, str(v))
        elif fnum == 2:
            val = struct.unpack("<d", v)[0]
        elif fnum == 3:
            val = v
        elif fnum == 4:
            val = _to_signed64(v)
        elif fnum == 5:
            val = v.decode("utf-8", "replace")
        elif fnum == 6:
            val = v
        elif fnum == 7:
            val = stat_names.get(v, str(v))
    return key, val


# ---------------------------------------------------------------- the model

@dataclasses.dataclass
class Span:
    """One timed event, absolute-clocked within its capture."""

    name: str
    start_ns: float
    dur_ns: float
    plane: str
    line: str
    stats: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def end_ns(self) -> float:
        return self.start_ns + self.dur_ns

    def is_collective(self) -> bool:
        return is_collective_name(self.name)

    def is_xla_op(self) -> bool:
        """Device-executed HLO op (vs host/python/bookkeeping span)."""
        if any(k in self.stats for k in ("hlo_op", "hlo_module",
                                         "program_id", "hlo_category")):
            return True
        return False


@dataclasses.dataclass
class Timeline:
    """One rank's parsed capture."""

    source: str
    hostname: str = ""
    spans: List[Span] = dataclasses.field(default_factory=list)

    def device_lines(self) -> List[Tuple[str, str]]:
        """(plane, line) pairs that carry XLA op spans."""
        seen: Dict[Tuple[str, str], bool] = {}
        for s in self.spans:
            key = (s.plane, s.line)
            if s.is_xla_op() or s.is_collective():
                seen[key] = True
            else:
                seen.setdefault(key, False)
        return [k for k, has_ops in seen.items() if has_ops]

    def annotations(self, name: str) -> List[Span]:
        """Host TraceAnnotation spans with exactly this name (the step
        markers ``trace.scope`` wrote)."""
        return sorted((s for s in self.spans
                       if s.name == name and not s.is_xla_op()),
                      key=lambda s: s.start_ns)


def parse_xspace_bytes(data: bytes, source: str = "<bytes>") -> Timeline:
    tl = Timeline(source=source)
    for fnum, _wt, v in _iter_fields(data):
        if fnum == 4 and isinstance(v, bytes):
            tl.hostname = v.decode("utf-8", "replace")
        elif fnum == 1:
            _parse_plane(v, tl)
    tl.spans.sort(key=lambda s: s.start_ns)
    return tl


def parse_xspace(path: str) -> Timeline:
    with open(path, "rb") as f:
        return parse_xspace_bytes(f.read(), source=path)


def _parse_plane(buf: bytes, tl: Timeline) -> None:
    plane_name = ""
    lines: List[bytes] = []
    event_names: Dict[int, str] = {}
    stat_names: Dict[int, str] = {}
    for fnum, _wt, v in _iter_fields(buf):
        if fnum == 2:
            plane_name = v.decode("utf-8", "replace")
        elif fnum == 3:
            lines.append(v)
        elif fnum == 4:
            mid, name = _decode_metadata_map(v)
            event_names[mid] = name
        elif fnum == 5:
            mid, name = _decode_metadata_map(v)
            stat_names[mid] = name
    for line_buf in lines:
        _parse_line(line_buf, plane_name, event_names, stat_names, tl)


def _parse_line(buf: bytes, plane: str, event_names: Dict[int, str],
                stat_names: Dict[int, str], tl: Timeline) -> None:
    line_name, ts_ns = "", 0
    events: List[bytes] = []
    for fnum, _wt, v in _iter_fields(buf):
        if fnum == 2 and not line_name:
            line_name = v.decode("utf-8", "replace")
        elif fnum == 11:
            line_name = v.decode("utf-8", "replace")
        elif fnum == 3:
            ts_ns = v
        elif fnum == 4:
            events.append(v)
    for ev in events:
        meta_id = offset_ps = dur_ps = 0
        stats: Dict[str, Any] = {}
        for fnum, _wt, v in _iter_fields(ev):
            if fnum == 1:
                meta_id = v
            elif fnum == 2:
                offset_ps = v
            elif fnum == 3:
                dur_ps = v
            elif fnum == 4:
                k, sv = _decode_stat(v, stat_names)
                if k:
                    stats[k] = sv
        tl.spans.append(Span(
            name=event_names.get(meta_id, str(meta_id)),
            start_ns=ts_ns + offset_ps / 1000.0,
            dur_ns=dur_ps / 1000.0,
            plane=plane, line=line_name, stats=stats))


def find_xplane_files(trace_dir: str) -> List[str]:
    return sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True))


# ------------------------------------------------------------ interval math

def _union(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    if not intervals:
        return []
    intervals = sorted(intervals)
    out = [list(intervals[0])]
    for lo, hi in intervals[1:]:
        if lo <= out[-1][1]:
            out[-1][1] = max(out[-1][1], hi)
        else:
            out.append([lo, hi])
    return [(lo, hi) for lo, hi in out]


def _measure(union: List[Tuple[float, float]]) -> float:
    return sum(hi - lo for lo, hi in union)


def _intersection_measure(a: List[Tuple[float, float]],
                          b: List[Tuple[float, float]]) -> float:
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def _clip(intervals: List[Tuple[float, float]],
          lo: float, hi: float) -> List[Tuple[float, float]]:
    return [(max(a, lo), min(b, hi))
            for a, b in intervals if b > lo and a < hi]


# ------------------------------------------------------------ step analysis

@dataclasses.dataclass
class StepComm:
    """Comm/compute accounting for one step window on one rank stream."""

    step: int
    rank: str                  # "plane/line" stream key
    window_ns: float
    comm_ns: float = 0.0
    compute_ns: float = 0.0
    overlap_ns: float = 0.0
    by_kind: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict)

    @property
    def exposed_ns(self) -> float:
        return max(0.0, self.comm_ns - self.overlap_ns)

    @property
    def overlap_pct(self) -> float:
        return 100.0 * self.overlap_ns / self.comm_ns if self.comm_ns else 0.0

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["exposed_ns"] = self.exposed_ns
        d["overlap_pct"] = self.overlap_pct
        return d


def analyze_steps(tl: Timeline, annotation: Optional[str] = None,
                  annotations: Sequence[str] = ("lm_step", "train_step",
                                                "profile_step"),
                  ) -> List[StepComm]:
    """Per-(step-window, device-stream) comm/compute/overlap accounting.

    Step windows come from the host TraceAnnotation spans ``trace.scope``
    wrote around each step call (``lm_step`` / ``train_step``); with no
    markers in the capture, the whole capture is one window (step -1).

    Per stream: ``comm`` is the union of collective spans, ``compute``
    the union of non-collective XLA op spans, ``overlap`` their
    intersection — so ``exposed = comm - overlap`` is device time where
    communication ran with *no* concurrent compute on that stream: the
    stall a faster (or quantized) collective would actually recover."""
    names = [annotation] if annotation else list(annotations)
    markers: List[Span] = []
    for name in names:
        markers = tl.annotations(name)
        if markers:
            break
    if markers:
        windows = [(i, m.start_ns, m.end_ns) for i, m in enumerate(markers)]
    else:
        ops = [s for s in tl.spans if s.is_xla_op() or s.is_collective()]
        if not ops:
            return []
        windows = [(-1, min(s.start_ns for s in ops),
                    max(s.end_ns for s in ops))]

    out: List[StepComm] = []
    streams = tl.device_lines()
    for plane, line in streams:
        spans = [s for s in tl.spans if s.plane == plane and s.line == line]
        comm = [s for s in spans if s.is_collective() and s.dur_ns > 0]
        comp = [s for s in spans
                if s.is_xla_op() and not s.is_collective() and s.dur_ns > 0]
        comm_iv = _union([(s.start_ns, s.end_ns) for s in comm])
        comp_iv = _union([(s.start_ns, s.end_ns) for s in comp])
        for step, lo, hi in windows:
            c_iv = _clip(comm_iv, lo, hi)
            p_iv = _clip(comp_iv, lo, hi)
            sc = StepComm(step=step, rank=f"{plane}/{line}",
                          window_ns=hi - lo,
                          comm_ns=_measure(c_iv),
                          compute_ns=_measure(p_iv),
                          overlap_ns=_intersection_measure(c_iv, p_iv))
            for s in comm:
                if s.end_ns <= lo or s.start_ns >= hi:
                    continue
                kind = collective_kind(s.name)
                slot = sc.by_kind.setdefault(
                    kind, {"count": 0, "time_ns": 0.0})
                slot["count"] += 1
                slot["time_ns"] += (min(s.end_ns, hi) - max(s.start_ns, lo))
            if sc.comm_ns or sc.compute_ns:
                out.append(sc)
    return out


def aggregate_steps(stats: Sequence[StepComm]) -> Dict[str, Any]:
    """Fold per-(step, stream) records into capture-level numbers: mean
    per-step comm/exposed time (averaged across streams, summed across
    nothing — a step's exposed time is a per-rank stall)."""
    if not stats:
        return {"steps": 0, "streams": 0}
    steps = sorted({s.step for s in stats})
    streams = sorted({s.rank for s in stats})
    comm = [s.comm_ns for s in stats]
    exposed = [s.exposed_ns for s in stats]
    overlap_pct = [s.overlap_pct for s in stats if s.comm_ns]
    by_kind: Dict[str, Dict[str, float]] = {}
    for s in stats:
        for kind, slot in s.by_kind.items():
            agg = by_kind.setdefault(kind, {"count": 0, "time_ns": 0.0})
            agg["count"] += slot["count"]
            agg["time_ns"] += slot["time_ns"]
    return {
        "steps": len(steps),
        "streams": len(streams),
        "comm_ms_mean": sum(comm) / len(comm) / 1e6,
        "exposed_ms_mean": sum(exposed) / len(exposed) / 1e6,
        "overlap_pct_mean": (sum(overlap_pct) / len(overlap_pct)
                             if overlap_pct else 0.0),
        "by_kind": by_kind,
    }


def marry_ledger(stats: Sequence[StepComm], ledger) -> Dict[str, Any]:
    """Join measured per-kind collective time with the static ledger's
    per-kind bytes: effective per-kind bus bandwidth and the count match
    (a measured-count / ledger-count mismatch means the capture windows
    don't line up with whole steps).  ``ledger`` is an obs.comms
    CommLedger."""
    agg = aggregate_steps(stats)
    n_steps = max(1, agg.get("steps", 1))
    n_streams = max(1, agg.get("streams", 1))
    out: Dict[str, Any] = {}
    measured = agg.get("by_kind", {})
    for kind, slot in ledger.by_kind().items():
        m = measured.get(kind, {"count": 0, "time_ns": 0.0})
        # measured counts accumulate over steps AND streams; the ledger is
        # per-step per-device
        per_step_count = m["count"] / (n_steps * n_streams)
        time_s = m["time_ns"] / 1e9 / (n_steps * n_streams)
        bus_gbps = (slot["wire_bytes"] / time_s / 1e9) if time_s else 0.0
        out[kind] = {
            "ledger_count": slot["count"],
            "ledger_bytes": slot["bytes"],
            "wire_bytes": slot["wire_bytes"],
            "measured_count_per_step": per_step_count,
            "measured_ms_per_step": time_s * 1e3,
            "bus_gbps": bus_gbps,
            "count_match": abs(per_step_count - slot["count"]) < 0.5,
        }
    return out


# -------------------------------------------------------- cross-rank merge

def read_heartbeat_steps(hb_dir: str) -> Dict[int, Dict[int, float]]:
    """``{pid: {step: wall_time}}`` from every beat line in a heartbeat
    dir (unlike ``obs.heartbeat.read_heartbeats``, keeps the full per-step
    history — the alignment signal, not just liveness)."""
    out: Dict[int, Dict[int, float]] = {}
    for path in sorted(glob.glob(os.path.join(hb_dir, "heartbeat-*.jsonl"))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    pid, step, t = int(rec["pid"]), int(rec["step"]), \
                        float(rec["t"])
                except (ValueError, KeyError):
                    continue  # torn tail
                out.setdefault(pid, {})[step] = t
    return out


def clock_offsets_from_heartbeats(hb_dir: str) -> Dict[int, float]:
    """Per-process clock offset (seconds) relative to the lowest pid.

    Ranks beat at the same step at (approximately) the same true time;
    the median per-common-step delta between a rank's beat wall-clock and
    the reference rank's estimates the skew between their captures.
    Subtracting the offset aligns the merged timeline."""
    beats = read_heartbeat_steps(hb_dir)
    if not beats:
        return {}
    ref_pid = min(beats)
    ref = beats[ref_pid]
    offsets = {ref_pid: 0.0}
    for pid, steps in beats.items():
        if pid == ref_pid:
            continue
        deltas = sorted(steps[s] - ref[s] for s in steps if s in ref)
        offsets[pid] = deltas[len(deltas) // 2] if deltas else 0.0
    return offsets


def to_chrome_trace(timelines: Sequence[Tuple[int, Timeline]],
                    offsets_s: Optional[Dict[int, float]] = None,
                    mem_ledgers: Optional[Sequence[Any]] = None,
                    req_traces: Optional[Sequence[Dict[str, Any]]] = None,
                    ) -> Dict[str, Any]:
    """Merge per-rank timelines into one Chrome-trace/Perfetto JSON dict.

    ``timelines``: ``(rank, Timeline)`` pairs; ``offsets_s``: per-rank
    clock offsets (``clock_offsets_from_heartbeats``) subtracted before
    merging.  pid = rank, tid = one per (plane, line) stream; times in
    microseconds as the trace-event format requires.

    ``mem_ledgers``: optional ``obs.memory.MemLedger`` list (from
    ``--mem-ledger``); each ledger's watermark curve is stretched over
    every rank's captured span and merged as a Perfetto counter track
    ("ph": "C") so the HBM profile reads against the op timeline.

    ``req_traces``: optional serving trace records (the ``reqtrace``
    ft_events of obs/reqtrace.py) merged as one per-request track group —
    a request's queue/prefill/decode/preempt spans read against the
    engine's step timeline.  Engine-clock seconds; align the capture
    start to the engine clock zero (both start at the first step)."""
    offsets_s = offsets_s or {}
    events: List[Dict[str, Any]] = []
    for rank, tl in timelines:
        off_us = offsets_s.get(rank, 0.0) * 1e6
        events.append({
            "ph": "M", "pid": rank, "name": "process_name",
            "args": {"name": f"rank {rank}"
                     + (f" ({tl.hostname})" if tl.hostname else "")},
        })
        tids: Dict[Tuple[str, str], int] = {}
        for s in tl.spans:
            key = (s.plane, s.line)
            tid = tids.get(key)
            if tid is None:
                tid = tids[key] = len(tids)
                events.append({
                    "ph": "M", "pid": rank, "tid": tid,
                    "name": "thread_name",
                    "args": {"name": f"{s.plane} · {s.line}"},
                })
            if s.dur_ns <= 0:
                continue
            ev: Dict[str, Any] = {
                "ph": "X", "pid": rank, "tid": tid, "name": s.name,
                "ts": s.start_ns / 1e3 - off_us, "dur": s.dur_ns / 1e3,
            }
            if s.is_collective():
                ev["cat"] = "collective"
            args = {k: v for k, v in s.stats.items()
                    if isinstance(v, (int, float, str))}
            if args:
                ev["args"] = args
            events.append(ev)
        if mem_ledgers and tl.spans:
            from . import memory  # local: counter track is opt-in

            t0_us = min(s.start_ns for s in tl.spans) / 1e3 - off_us
            t1_us = max(s.end_ns for s in tl.spans) / 1e3 - off_us
            for led in mem_ledgers:
                events.extend(memory.watermark_counter_events(
                    led, t0_us, t1_us, pid=rank,
                    name=f"hbm_watermark · {led.step}"))
    if req_traces:
        # local import via path so a jax-free caller (scripts/obs_trace)
        # and the package both resolve the same helper.
        import importlib.util as _ilu
        import os as _os
        import sys as _sys

        full = "pytorch_distributed_tpu.obs.reqtrace"
        mod = _sys.modules.get(full) or _sys.modules.get("_ptd_obs_reqtrace")
        if mod is None:
            if "pytorch_distributed_tpu" in _sys.modules:
                import importlib as _il

                mod = _il.import_module(full)
            else:
                spec = _ilu.spec_from_file_location(
                    "_ptd_obs_reqtrace",
                    _os.path.join(_os.path.dirname(_os.path.abspath(
                        __file__)), "reqtrace.py"))
                mod = _ilu.module_from_spec(spec)
                _sys.modules["_ptd_obs_reqtrace"] = mod
                spec.loader.exec_module(mod)
        pid = max((r for r, _ in timelines), default=-1) + 1
        events.extend(mod.chrome_events(req_traces, pid=pid))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# --------------------------------------------------------- fixture encoder

def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(fnum: int, wt: int) -> bytes:
    return _varint((fnum << 3) | wt)


def _len_field(fnum: int, payload: bytes) -> bytes:
    return _tag(fnum, 2) + _varint(len(payload)) + payload


def _stat_msg(meta_id: int, value: Any) -> bytes:
    msg = _tag(1, 0) + _varint(meta_id)
    if isinstance(value, float):
        msg += _tag(2, 1) + struct.pack("<d", value)
    elif isinstance(value, int):
        msg += _tag(3, 0) + _varint(value)
    else:
        msg += _len_field(5, str(value).encode())
    return msg


def encode_xspace(planes: Sequence[Dict[str, Any]],
                  hostname: str = "synthetic") -> bytes:
    """Encode a minimal XSpace: ``planes`` is a list of
    ``{"name", "lines": [{"name", "timestamp_ns", "events": [
    {"name", "offset_ps", "duration_ps", "stats": {key: value}}]}]}``.
    Event/stat metadata tables are built automatically.  The inverse of
    ``parse_xspace_bytes`` for everything this module reads — used for
    checked-in test fixtures and the obs_timeline selftest."""
    space = _len_field(4, hostname.encode())
    for plane in planes:
        event_ids: Dict[str, int] = {}
        stat_ids: Dict[str, int] = {}
        lines_payload = b""
        for line in plane.get("lines", []):
            lp = _len_field(2, line["name"].encode())
            lp += _tag(3, 0) + _varint(int(line.get("timestamp_ns", 0)))
            for ev in line.get("events", []):
                eid = event_ids.setdefault(ev["name"], len(event_ids) + 1)
                ep = _tag(1, 0) + _varint(eid)
                ep += _tag(2, 0) + _varint(int(ev.get("offset_ps", 0)))
                ep += _tag(3, 0) + _varint(int(ev.get("duration_ps", 0)))
                for k, v in (ev.get("stats") or {}).items():
                    sid = stat_ids.setdefault(k, len(stat_ids) + 1)
                    ep += _len_field(4, _stat_msg(sid, v))
                lp += _len_field(4, ep)
            lines_payload += _len_field(3, lp)
        pp = _len_field(2, plane["name"].encode())
        pp += lines_payload
        for name, mid in event_ids.items():
            meta = _tag(1, 0) + _varint(mid) + _len_field(2, name.encode())
            entry = _tag(1, 0) + _varint(mid) + _len_field(2, meta)
            pp += _len_field(4, entry)
        for name, sid in stat_ids.items():
            meta = _tag(1, 0) + _varint(sid) + _len_field(2, name.encode())
            entry = _tag(1, 0) + _varint(sid) + _len_field(2, meta)
            pp += _len_field(5, entry)
        space += _len_field(1, pp)
    return space
