"""Structured per-step metrics: one JSONL record per training step.

The reference's observability story is three ``.item()`` calls per batch
plus a 500 ms nvidia-smi CSV (SURVEY.md §0).  ``MetricsLogger`` is the one
observability entry point replacing the scattered meter/CSV/telemetry
wiring:

- ``log_step`` buffers a structured record — step index, wall time,
  step-time EMA and windowed p50/p95/max, items/s throughput, lr, and any
  on-device scalars (loss, in-graph grad/param norms).  Device scalars
  stay *unconverted* jax arrays until flush time — the same lazy
  discipline as ``train/meters.py``, so the hot loop never blocks on a
  device→host sync;
- records drain to the JSONL file every ``flush_every`` steps and at
  ``close()``;
- other instrumentation registers as sinks of the same logger:
  ``EpochCSVLogger`` (epoch_start/epoch_end pass through it),
  ``TelemetrySampler`` (started at register, stopped at close), or any
  callable invoked once per drained record.
"""

from __future__ import annotations

import collections
import json
import time
from typing import Any, Dict, List, Optional, Sequence

# Every record carries at least these keys — the schema contract
# scripts/obs_report.py and the tests assert against.
REQUIRED_FIELDS = (
    "step", "t", "process", "step_time", "step_time_ema",
    "step_time_p50", "step_time_p95", "step_time_max",
)


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sequence."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def read_metrics(path: str) -> List[dict]:
    """Parse a metrics JSONL file back into records (schema round-trip)."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records


class MetricsLogger:
    """Per-step structured metrics with lazy device-scalar conversion.

    ``path=None`` still works as the observability hub (sink lifecycle,
    epoch events) — it just writes no JSONL.
    """

    def __init__(self, path: Optional[str] = None, process_index: int = 0,
                 flush_every: int = 50, ema_alpha: float = 0.1,
                 window: int = 256):
        self.path = path
        self.process_index = int(process_index)
        self.flush_every = max(1, int(flush_every))
        self.ema_alpha = float(ema_alpha)
        self._pending: List[Dict[str, Any]] = []
        self._times: collections.deque = collections.deque(maxlen=window)
        self._ema: Optional[float] = None
        # Last event kind logged (skip/rollback/preempt/recompile): the
        # heartbeat writer stamps it into beats so the straggler monitor
        # can tell a rank that *said why* it is behind from a silent one.
        self.last_event_kind: Optional[str] = None
        self._file = None
        self._step_sinks: List[Any] = []
        self._epoch_sinks: List[Any] = []
        self._owned: List[Any] = []  # start()ed at register, stop()ped at close

    # ----------------------------------------------------------------- sinks
    def register(self, sink):
        """Attach instrumentation to this logger (duck-typed):

        - ``start``/``stop`` pair (TelemetrySampler): started now, stopped
          at ``close()``;
        - ``epoch_start``/``epoch_end`` pair (EpochCSVLogger): driven by
          this logger's epoch events;
        - plain callable: invoked with each drained record dict.
        Returns the sink for chaining.
        """
        if sink is None:
            return sink
        if hasattr(sink, "start") and hasattr(sink, "stop"):
            sink.start()
            self._owned.append(sink)
            return sink
        if hasattr(sink, "epoch_start") and hasattr(sink, "epoch_end"):
            self._epoch_sinks.append(sink)
            return sink
        if callable(sink):
            self._step_sinks.append(sink)
            return sink
        raise TypeError(
            f"unsupported sink {type(sink).__name__}: expected start/stop, "
            "epoch_start/epoch_end, or a callable")

    def epoch_start(self) -> None:
        for s in self._epoch_sinks:
            s.epoch_start()

    def epoch_end(self) -> Optional[float]:
        """Forward to epoch sinks; returns the last sink's value (the
        EpochCSVLogger convention: elapsed seconds)."""
        out = None
        for s in self._epoch_sinks:
            out = s.epoch_end()
        return out

    # ----------------------------------------------------------------- steps
    @property
    def ema(self) -> Optional[float]:
        """Current step-time EMA (None before the first step) — exported so
        heartbeats can carry it (obs/heartbeat.py slow-vs-dead signal)."""
        return self._ema

    @property
    def enabled(self) -> bool:
        """True when some step sink (JSONL file or callable) consumes
        records; ``log_step`` is a no-op otherwise, so a hub built only for
        epoch/telemetry sinks adds zero per-step work."""
        return bool(self.path or self._step_sinks)

    def log_step(self, step: int, step_time: float,
                 n_items: Optional[float] = None, lr=None,
                 scalars: Optional[Dict[str, Any]] = None,
                 extra: Optional[Dict[str, Any]] = None) -> None:
        """Buffer one step record.

        ``step_time`` is host-measured seconds (already a float);
        ``n_items`` yields ``throughput`` = items/s (images or tokens);
        ``scalars``/``lr`` may be unready device scalars — they are NOT
        converted here (no host sync); conversion happens at flush.
        """
        if not self.enabled:
            return
        st = float(step_time)
        self._ema = (st if self._ema is None
                     else self.ema_alpha * st + (1.0 - self.ema_alpha) * self._ema)
        self._times.append(st)
        ordered = sorted(self._times)
        rec: Dict[str, Any] = {
            "step": int(step),
            "t": time.time(),
            "process": self.process_index,
            "step_time": st,
            "step_time_ema": self._ema,
            "step_time_p50": _percentile(ordered, 0.50),
            "step_time_p95": _percentile(ordered, 0.95),
            "step_time_max": ordered[-1],
        }
        if n_items is not None:
            rec["throughput"] = (float(n_items) / st) if st > 0 else 0.0
        if lr is not None:
            rec["lr"] = lr  # possibly a device scalar; converted at flush
        if scalars:
            rec.update(scalars)
        if extra:
            rec.update(extra)
        self._pending.append(rec)
        if len(self._pending) >= self.flush_every:
            self.flush()

    def log_event(self, kind: str, step: Optional[int] = None,
                  **fields: Any) -> None:
        """Buffer one structured *event* record (``{"ft_event": kind, …}``)
        through the same pending/flush pipeline as step records — the FT
        subsystem's skip/rollback/preemption trail (ft/divergence.py;
        summarized by ``scripts/obs_report.py``).  Events are rare, so they
        flush immediately: a crash right after a preemption event must not
        lose the record that explains the crash."""
        self.last_event_kind = str(kind)  # beats carry it even w/o a sink
        if not self.enabled:
            return
        rec: Dict[str, Any] = {
            "ft_event": str(kind),
            "t": time.time(),
            "process": self.process_index,
        }
        if step is not None:
            rec["step"] = int(step)
        rec.update(fields)
        self._pending.append(rec)
        self.flush()

    def flush(self) -> None:
        """Drain pending records: convert device scalars (the one host sync,
        amortized over ``flush_every`` steps), write JSONL, notify sinks."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        for rec in pending:
            for k, v in rec.items():
                if not isinstance(v, (int, float, str, bool, type(None))):
                    rec[k] = float(v)
        if self.path:
            if self._file is None:
                self._file = open(self.path, "a")
            for rec in pending:
                self._file.write(json.dumps(rec) + "\n")
            self._file.flush()
        for sink in self._step_sinks:
            for rec in pending:
                sink(rec)

    def close(self) -> None:
        """Flush, stop owned sinks, release the file.  Idempotent; the
        logger stays usable (a later ``log_step`` reopens the file)."""
        self.flush()
        owned, self._owned = self._owned, []
        for s in owned:
            s.stop()
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
